#!/usr/bin/env python
"""Benchmark: sustained GossipSub v1.1 heartbeats/sec on the flagship
simulator — the BASELINE.md north-star config (1M peers, 100 topics,
peer scoring + gater enabled).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.md): 10k simulated heartbeats/sec on a 1M-peer,
100-topic GossipSub v1.1 mesh on TPU v5e-8.  vs_baseline = value / 10000
(measured here on ONE chip; the 8-chip target is the reference point).

Topology: 100 independent per-topic random circulants over 1M peers
(topic t = peers ≡ t mod 100), C=16 candidate edges/peer, default
D/Dlo/Dhi mesh params, v1.1 scoring (P1/P2/P4/P5/P6/P7 + thresholds +
RED gater).  Measures STEADY STATE: the mesh converges during warmup,
then timed reps continue the same run with publishes spread over every
rep window (fresh messages keep flowing; mesh maintenance, scoring, and
gossip repair all stay active).

Timing notes for this platform: only host transfers of dependent values
are trustworthy sync points (device completion futures resolve early), so
every rep ends by pulling a value derived from the final state.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"

    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n_peers = 1_000_000 if on_accel else 100_000
    n_topics = 100
    n_msgs = 32
    n_cand = 16
    warmup = 100
    rep_ticks = 100
    reps = 3
    horizon = warmup + reps * rep_ticks

    rng = np.random.default_rng(0)
    offs = gs.make_gossip_offsets(n_topics, n_cand, n_peers, seed=0)
    cfg = gs.GossipSimConfig(offsets=offs, n_topics=n_topics)
    sc = gs.ScoreSimConfig()

    idx = np.arange(n_peers)
    subs = np.zeros((n_peers, n_topics), dtype=bool)
    subs[idx, idx % n_topics] = True
    msg_topic = rng.integers(0, n_topics, n_msgs)
    # origin must be in the topic's residue class
    msg_origin = (rng.integers(0, n_peers // n_topics, n_msgs) * n_topics
                  + msg_topic)
    # publishes spread across the whole horizon: every timed rep carries
    # fresh traffic through the converged mesh
    msg_tick = np.sort(rng.integers(0, horizon, n_msgs)).astype(np.int32)

    params, state = gs.make_gossip_sim(cfg, subs, msg_topic, msg_origin,
                                       msg_tick, score_cfg=sc,
                                       track_first_tick=True)
    params = jax.device_put(params)
    state = jax.device_put(state)
    step = gs.make_gossip_step(cfg, sc)

    # convergence + compile (forces real execution via host transfer)
    state = gs.gossip_run(params, state, warmup, step)
    deg = np.asarray(gs.mesh_degrees(state))[np.asarray(params.subscribed)]
    assert deg.mean() >= cfg.d_lo, f"mesh failed to form: mean deg {deg.mean()}"

    t0 = time.perf_counter()
    for _ in range(reps):
        state = gs.gossip_run(params, state, rep_ticks, step)
        _ = int(np.asarray(state.tick))  # forced sync via dependent value
    dt = time.perf_counter() - t0

    # correctness gate: messages published early enough reached every
    # subscriber in their topic
    reach = np.asarray(gs.reach_counts(params, state))
    settled = msg_tick < horizon - 30
    full = n_peers // n_topics
    assert (reach[settled] == full).all(), \
        f"dissemination failed: reach {reach[settled][:8]} of {full}"

    hb_per_sec = rep_ticks * reps / dt
    result = {
        "metric": (f"sustained_heartbeats_per_sec_gossipsub_v11_"
                   f"{n_peers}peers_{n_topics}topics"),
        "value": round(hb_per_sec, 2),
        "unit": "heartbeats/s",
        "vs_baseline": round(hb_per_sec / 10_000.0, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
