#!/usr/bin/env python
"""Benchmark: sustained GossipSub v1.1 heartbeats/sec on the flagship
simulator — the BASELINE.md north-star config (1M peers on TPU, 100
topics, peer scoring + gater enabled).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.md): 10k simulated heartbeats/sec on a 1M-peer,
100-topic GossipSub v1.1 mesh on TPU v5e-8.  vs_baseline = value / 10000
(measured here on ONE chip; the 8-chip target is the reference point).

Thin wrapper over bench_suite.bench_gossipsub_v11 (the shared harness
holds the platform-specific sync idiom: only host transfers of dependent
values are trustworthy sync points on this platform — device completion
futures resolve early).  `python bench_suite.py` runs all five BASELINE
configs.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_suite  # noqa: E402


if __name__ == "__main__":
    bench_suite.bench_gossipsub_v11()
