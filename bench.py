#!/usr/bin/env python
"""Benchmark: simulated pubsub heartbeats/sec on the flagship simulator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.md): 10k simulated heartbeats/sec on a 1M-peer,
100-topic mesh.  vs_baseline = value / 10000.

Topology: 100 independent per-topic random circulants over 1M peers
(topic t = peers ≡ t mod 100).  Random circulants are expanders with the
same locally-tree-like spread as the random graphs the reference's tests
wire up, and propagation over them is pure rolls — the TPU-native
formulation (tests/test_flood_sim.py proves the roll path bit-identical to
the general gather path on the same topology).

Timing notes for this platform: only host transfers of dependent values
are trustworthy sync points (device completion futures resolve early), so
every rep ends by pulling a value derived from the final state; each rep
also gets distinct publish ticks so no caching layer can reuse results.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    on_accel = platform != "cpu"

    from go_libp2p_pubsub_tpu.models.floodsub import (
        flood_run,
        make_circulant_flood_step,
        make_flood_sim,
        reach_counts,
    )
    from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets

    n_peers = 1_000_000 if on_accel else 100_000
    n_topics = 100
    n_msgs = 32
    degree = 12
    ticks = 100

    rng = np.random.default_rng(0)
    idx = np.arange(n_peers)
    subs = np.zeros((n_peers, n_topics), dtype=bool)
    subs[idx, idx % n_topics] = True
    offsets = make_circulant_offsets(n_topics, degree, n_peers, seed=0)

    msg_topic = rng.integers(0, n_topics, n_msgs)
    # origin must be in the topic's residue class
    msg_origin = (rng.integers(0, n_peers // n_topics, n_msgs) * n_topics
                  + msg_topic)
    msg_tick = np.zeros(n_msgs, dtype=np.int32)

    params, state = make_flood_sim(None, None, subs, None, msg_topic,
                                   msg_origin, msg_tick)
    params = jax.device_put(params)
    state = jax.device_put(state)

    step = make_circulant_flood_step(offsets)
    reach_j = jax.jit(reach_counts)
    offset_j = jax.jit(lambda p, o: p.replace(publish_tick=p.publish_tick + o))

    # correctness gate + warmup (forces real execution via host transfer)
    out = flood_run(params, state, ticks, step)
    counts = np.asarray(reach_j(params, out))
    assert (counts > n_peers // n_topics // 2).all(), \
        f"flood died: reach {counts[:8]} of {n_peers // n_topics}"
    out = flood_run(offset_j(params, jnp.int32(0)), state, ticks, step)
    _ = np.asarray(reach_j(params, out))

    reps = 5
    t0 = time.perf_counter()
    for r in range(reps):
        p_r = offset_j(params, jnp.int32(r + 1))
        out = flood_run(p_r, state, ticks, step)
        _ = np.asarray(reach_j(p_r, out))  # forced sync via dependent value
    dt = time.perf_counter() - t0

    hb_per_sec = ticks * reps / dt
    result = {
        "metric": f"simulated_heartbeats_per_sec_floodsub_{n_peers}peers_{n_topics}topics",
        "value": round(hb_per_sec, 2),
        "unit": "heartbeats/s",
        "vs_baseline": round(hb_per_sec / 10_000.0, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
