#!/usr/bin/env python
"""Benchmark: sustained GossipSub v1.1 heartbeats/sec on the flagship
simulator — the BASELINE.md north-star config (1M peers on TPU, 100
topics, peer scoring + gater enabled).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline target (BASELINE.md): 10k simulated heartbeats/sec on a 1M-peer,
100-topic GossipSub v1.1 mesh on TPU v5e-8.  vs_baseline = value / 10000
(measured here on ONE chip; the 8-chip target is the reference point).

Thin wrapper over bench_suite.bench_gossipsub_v11 (the shared harness
holds the platform-specific sync idiom: only host transfers of dependent
values are trustworthy sync points on this platform — device completion
futures resolve early).  `python bench_suite.py` runs all five BASELINE
configs.

TPU-unavailable resilience: the axon tunnel has been observed wedged for
16+ hours at a stretch, during which any backend init HANGS indefinitely
(round 4's driver bench recorded rc != 0 and no number at all).  The
backend is therefore probed in a bounded SUBPROCESS first; if it hangs
or errors, the bench re-executes itself pinned to CPU and emits the
clearly-distinguishable CPU-scale row (100k peers in the metric name)
instead of dying — a labeled fallback number beats an empty artifact.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if os.environ.get("BENCH_FORCE_CPU") == "1":
    # the environment's site hook pins JAX_PLATFORMS to the TPU tunnel;
    # only a jax.config update before backend init overrides it
    import jax

    jax.config.update("jax_platforms", "cpu")


if __name__ == "__main__":
    from go_libp2p_pubsub_tpu.utils.accel import tpu_reachable

    # None = this process already holds a backend (never probe then);
    # proceed with it as-is
    if (os.environ.get("BENCH_FORCE_CPU") != "1"
            and tpu_reachable(
                float(os.environ.get("BENCH_PROBE_TIMEOUT", "360")))
            is False):
        print("TPU backend unreachable; re-running on CPU (fallback "
              "row, reduced scale)", file=sys.stderr, flush=True)
        env = dict(os.environ, BENCH_FORCE_CPU="1")
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)], env)

    # BENCH_CONFIG.json pins the measured-fastest execution path for
    # the driver's unattended run ({"kernel": true} -> pallas receive
    # kernel; absent/false -> XLA path).  Committed by the measurement
    # pass only when the kernel path actually wins on hardware.
    try:
        import json
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_CONFIG.json")) as f:
            cfg = json.load(f)
            if isinstance(cfg, dict) and cfg.get("kernel"):
                os.environ.setdefault("GOSSIP_BENCH_KERNEL", "1")
    except (OSError, ValueError):
        pass

    import bench_suite  # noqa: E402

    bench_suite.bench_gossipsub_v11()
