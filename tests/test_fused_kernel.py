"""Round-16 tick-resident fused kernel (ops/pallas/receive.py
make_fused_gossip_update + models/gossipsub.py make_fused_window): a
window of T ticks folded into ONE pallas_call with the per-shard carry
resident in VMEM across the sequential ``(ticks,)`` grid is
BIT-IDENTICAL to T per-tick steps — against the per-tick kernel AND
the XLA step — for T in {2, 4, 8}, with telemetry frames, fault
schedules, and cold-restart rejoin armed; the sharded window (round
17) runs RESIDENT with the ring-halo exchange inside the kernel
(remote DMA between grid ticks, double-buffered halo slots) and stays
bit-identical on the virtual mesh at D in {2, 4} — including faults +
telemetry, the runner twins, and the ckpt composition; and every
configuration where residency is impossible (scored carry, delay
lines, unpadded layout, shard tiles/halo reach, carry past the VMEM
budget) is refused by a named ``kernel_ticks_fused:`` reason that
reports the working-set bytes.

Identity is exact array equality over the full state pytree plus the
delivered words and every telemetry-frame leaf — the same contract the
round-9 kernel parity and round-14 sharding tests hold."""

import functools

import numpy as np
import pytest

import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.telemetry as tl
from go_libp2p_pubsub_tpu.models.delays import DelayConfig
from go_libp2p_pubsub_tpu.models.faults import FaultSchedule

# FUSED_ALIGN: the resident lane rolls need n_true % 1024 == 0 and
# n_true == n_pad, so the whole matrix runs at the smallest legal ring
N, T_TOP, M, C, BLOCK, TICKS = 1024, 4, 8, 16, 1024, 8


def teardown_module(module):
    import jax
    _sim.cache_clear()
    _kernel_ref.cache_clear()
    _tel_ref.cache_clear()
    _fault_sched.cache_clear()
    jax.clear_caches()


@functools.lru_cache(maxsize=None)
def _sim():
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T_TOP, C, N, seed=0),
        n_topics=T_TOP)
    subs = np.zeros((N, T_TOP), dtype=bool)
    subs[np.arange(N), np.arange(N) % T_TOP] = True
    topic = rng.integers(0, T_TOP, M)
    origin = rng.integers(0, N // T_TOP, M) * T_TOP + topic
    tick0 = np.sort(rng.integers(0, 6, M)).astype(np.int32)
    return cfg, subs, topic, origin, tick0


@functools.lru_cache(maxsize=None)
def _fault_sched(cold=False):
    rng = np.random.default_rng(7)
    downs = []
    for p in rng.choice(N, 40, replace=False):
        s0 = int(rng.integers(0, TICKS - 4))
        downs.append((int(p), s0, s0 + int(rng.integers(2, 4))))
    return FaultSchedule(
        n_peers=N, horizon=TICKS, down_intervals=tuple(sorted(downs)),
        drop_prob=0.05, seed=3, cold_restart=cold)


def _build(padded=True, **kw):
    cfg, subs, topic, origin, tick0 = _sim()
    pad = {"pad_to_block": BLOCK} if padded else {}
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                       tick0, **pad, **kw)
    return cfg, params, state


def _window(cfg, Tw, tel=None, **kw):
    return gs.make_fused_window(
        cfg, None, ticks_fused=Tw, receive_block=BLOCK,
        receive_interpret=True, telemetry=tel, on_refusal="raise",
        **kw)


def _run_steps(cfg, params, state, n_ticks, tel=None, kernel=True):
    """Reference trajectory: n_ticks per-tick steps (kernel or XLA),
    returning (state, delivered [n_ticks, W, N], frames|None)."""
    import jax.numpy as jnp
    step = gs.make_gossip_step(
        cfg, None, receive_interpret=True, receive_block=BLOCK,
        use_pallas_receive=kernel, telemetry=tel)
    s, dl, fr = state, [], []
    for _ in range(n_ticks):
        out = step(params, s)
        s = out[0]
        dl.append(out[1])
        if tel is not None:
            fr.append(out[2])
    return s, jnp.stack(dl), fr


def _run_windows(cfg, params, state, n_ticks, Tw, tel=None):
    import jax
    import jax.numpy as jnp
    win = _window(cfg, Tw, tel=tel)
    assert win.capability(params, state) is None
    s, dl, frs = state, [], []
    for _ in range(n_ticks // Tw):
        out = win(params, s)
        s = out[0]
        dl.append(out[1])
        if tel is not None:
            frs.append(out[2])
    frames = None
    if tel is not None:
        frames = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs), *frs)
    return s, jnp.concatenate(dl), frames


def _trees_equal(a, b):
    import jax
    fa, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, a))
    fb, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, b))
    assert len(fa) == len(fb)
    return all(np.array_equal(x, y) for x, y in zip(fa, fb))


def _state_equal(a, b):
    # compare state-by-field so a failure names the diverging leaf
    for name in ("have", "recent", "mesh", "fanout", "last_pub",
                 "backoff", "first_tick"):
        va, vb = getattr(a, name), getattr(b, name)
        if va is None or vb is None:
            assert va is None and vb is None, name
            continue
        assert np.array_equal(np.asarray(va), np.asarray(vb)), name
    for i, (ga, gb) in enumerate(zip(a.gates or (), b.gates or ())):
        assert np.array_equal(np.asarray(ga), np.asarray(gb)), \
            f"gates[{i}]"
    return True


# -- references (one compile+run each, shared across T values) -------------

@functools.lru_cache(maxsize=None)
def _kernel_ref(faults=False, cold=False):
    kw = {}
    if faults or cold:
        kw["fault_schedule"] = _fault_sched(cold)
    cfg, params, state = _build(**kw)
    s, d, _ = _run_steps(cfg, params, state, TICKS)
    return s, np.asarray(d)


@functools.lru_cache(maxsize=None)
def _tel_ref(faults=False):
    tel = tl.TelemetryConfig(counters=True, wire=True, mesh=True,
                             degree_hist=True, latency_hist=True,
                             faults=True)
    kw = {"fault_schedule": _fault_sched()} if faults else {}
    cfg, params, state = _build(**kw)
    s, d, fr = _run_steps(cfg, params, state, TICKS, tel=tel)
    import jax
    import jax.numpy as jnp
    frames = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *fr)
    return s, np.asarray(d), frames


# -- resident-path parity: fused T ticks == T per-tick steps ---------------

@pytest.mark.parametrize(
    "Tw", [pytest.param(2, marks=pytest.mark.slow), 4, 8])
def test_fused_matches_per_tick_kernel(Tw):
    s_ref, d_ref = _kernel_ref()
    cfg, params, state = _build()
    s, d, _ = _run_windows(cfg, params, state, TICKS, Tw)
    assert np.array_equal(np.asarray(d), d_ref)
    assert _state_equal(s, s_ref)


def test_fused_matches_xla_step():
    """The XLA step refuses padded layouts, but at N % BLOCK == 0 the
    padded build IS the unpadded build (pad adds nothing) — so the
    unpadded twin's XLA trajectory is the same-scenario reference."""
    cfg, params, state = _build(padded=False)
    s_x, d_x, _ = _run_steps(cfg, params, state, TICKS, kernel=False)
    s_ref, d_ref = _kernel_ref()
    assert np.array_equal(np.asarray(d_x), d_ref)
    assert _state_equal(s_x, s_ref)


@pytest.mark.parametrize("Tw", [2, 8])
@pytest.mark.slow
def test_fused_telemetry_frames_bit_identical(Tw):
    tel = tl.TelemetryConfig(counters=True, wire=True, mesh=True,
                             degree_hist=True, latency_hist=True,
                             faults=True)
    s_ref, d_ref, fr_ref = _tel_ref()
    cfg, params, state = _build()
    s, d, fr = _run_windows(cfg, params, state, TICKS, Tw, tel=tel)
    assert np.array_equal(np.asarray(d), d_ref)
    assert _state_equal(s, s_ref)
    assert _trees_equal(fr, fr_ref)


@pytest.mark.parametrize("Tw", [4])
@pytest.mark.slow
def test_fused_with_faults(Tw):
    s_ref, d_ref = _kernel_ref(faults=True)
    cfg, params, state = _build(fault_schedule=_fault_sched())
    s, d, _ = _run_windows(cfg, params, state, TICKS, Tw)
    assert np.array_equal(np.asarray(d), d_ref)
    assert _state_equal(s, s_ref)


@pytest.mark.slow
def test_fused_with_faults_and_telemetry():
    tel = tl.TelemetryConfig(counters=True, wire=True, mesh=True,
                             degree_hist=True, latency_hist=True,
                             faults=True)
    s_ref, d_ref, fr_ref = _tel_ref(faults=True)
    cfg, params, state = _build(fault_schedule=_fault_sched())
    s, d, fr = _run_windows(cfg, params, state, TICKS, 4, tel=tel)
    assert np.array_equal(np.asarray(d), d_ref)
    assert _state_equal(s, s_ref)
    assert _trees_equal(fr, fr_ref)


@pytest.mark.slow
def test_fused_cold_restart_rejoin():
    s_ref, d_ref = _kernel_ref(faults=True, cold=True)
    cfg, params, state = _build(fault_schedule=_fault_sched(True))
    s, d, _ = _run_windows(cfg, params, state, TICKS, 4)
    assert np.array_equal(np.asarray(d), d_ref)
    assert _state_equal(s, s_ref)


# -- fused runners ---------------------------------------------------------

def test_gossip_run_fused_matches_run():
    cfg, params, state = _build()
    step = gs.make_gossip_step(cfg, None, receive_interpret=True,
                               receive_block=BLOCK,
                               use_pallas_receive=True)
    s_ref = gs.gossip_run(params, state, TICKS, step)
    cfg, params, state = _build()
    win = _window(cfg, 4)
    s = gs.gossip_run_fused(params, state, TICKS, win)
    assert _state_equal(s, s_ref)


def test_gossip_run_curve_fused_matches_curve():
    cfg, params, state = _build()
    step = gs.make_gossip_step(cfg, None, receive_interpret=True,
                               receive_block=BLOCK,
                               use_pallas_receive=True)
    s_ref, c_ref = gs.gossip_run_curve(params, state, TICKS, step, M)
    cfg, params, state = _build()
    win = _window(cfg, 4)
    s, c = gs.gossip_run_curve_fused(params, state, TICKS, win, M)
    assert np.array_equal(np.asarray(c), np.asarray(c_ref))
    assert _state_equal(s, s_ref)


def test_gossip_run_frames_fused_matches_telemetry_run():
    tel = tl.TelemetryConfig(counters=True, wire=True, mesh=True,
                             degree_hist=True, latency_hist=True,
                             faults=True)
    s_ref, _d, fr_ref = _tel_ref()
    cfg, params, state = _build()
    win = _window(cfg, 4, tel=tel)
    s, fr = gs.gossip_run_frames_fused(params, state, TICKS, win)
    assert _state_equal(s, s_ref)
    assert _trees_equal(fr, fr_ref)


def test_fused_horizon_not_divisible_raises_by_name():
    cfg, params, state = _build()
    win = _window(cfg, 4)
    with pytest.raises(ValueError,
                       match="scan horizon not divisible by the fused "
                             "window"):
        gs.gossip_run_fused(params, state, TICKS - 2, win)


def test_fused_window_length_validated():
    cfg, _, _ = _build()
    with pytest.raises(ValueError, match="ticks_fused must be >= 1"):
        gs.make_fused_window(cfg, ticks_fused=0)


# -- checkpoint composition: segment boundaries align to the window --------

def test_ckpt_fused_misaligned_segment_refused_by_name(tmp_path):
    from go_libp2p_pubsub_tpu.parallel import checkpoint as ck

    cfg, params, state = _build()
    win = _window(cfg, 4)
    ckc = ck.CheckpointConfig(directory=str(tmp_path / "snaps"),
                              every=6)
    with pytest.raises(ValueError,
                       match="ckpt segment boundary mid-window"):
        ck.ckpt_gossip_run_fused(params, state, TICKS, win, ckc)


def test_ckpt_fused_aligned_bit_identity(tmp_path):
    """Aligned segments (every % ticks_fused == 0) compose: the
    segmented fused run — async writer and delta snapshots on — equals
    the per-tick kernel reference, resident path engaged."""
    from go_libp2p_pubsub_tpu.parallel import checkpoint as ck

    s_ref, _d = _kernel_ref()
    cfg, params, state = _build()
    win = _window(cfg, 4)
    assert win.capability(params, state) is None
    ckc = ck.CheckpointConfig(directory=str(tmp_path / "snaps"),
                              every=4, keep=10, async_write=True,
                              full_every=2)
    s = ck.ckpt_gossip_run_fused(params, state, TICKS, win, ckc)
    assert _state_equal(s, s_ref)


# -- sharded dispatch (round 17): in-kernel halo, resident at D in {2,4} --

@pytest.mark.parametrize(
    "D", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_sharded_window_resident_bit_identity(D):
    """The COMPOSED path: fused window × shard_map with the ring-halo
    boundary exchange inside the kernel (remote DMA between grid
    ticks) — capability accepts, and the sharded resident trajectory
    equals the single-device per-tick kernel bit for bit."""
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    s_ref, d_ref = _kernel_ref()
    mesh = pm.make_mesh(D)
    cfg, params, state = _build()
    params_s, state_s, _sh = ps.shard_sim(params, state, mesh, N)
    win = gs.make_fused_window(cfg, None, ticks_fused=4,
                               receive_block=BLOCK,
                               receive_interpret=True,
                               shard_mesh=mesh, on_refusal="raise")
    assert win.capability(params_s, state_s) is None
    s, dl = state_s, []
    for _ in range(2):
        out = win(params_s, s)
        s = out[0]
        dl.append(np.asarray(out[1]))
    assert np.array_equal(np.concatenate(dl), d_ref)
    assert _state_equal(s, s_ref)


def test_sharded_window_faults_telemetry_bit_identity():
    """Faults + cold-restart rejoin + the full telemetry surface on
    the composed path at D=2: states, delivered words, and every
    frame leaf equal the single-device fused window's (which round-16
    pinned to the scanned per-tick step)."""
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    tel = tl.TelemetryConfig(counters=True, wire=True, mesh=True,
                             degree_hist=True, latency_hist=True,
                             faults=True)
    cfg, params, state = _build(fault_schedule=_fault_sched(True))
    s_ref, d_ref, fr_ref = _run_windows(cfg, params, state, TICKS, 4,
                                        tel=tel)
    mesh = pm.make_mesh(2)
    cfg, params, state = _build(fault_schedule=_fault_sched(True))
    params_s, state_s, _sh = ps.shard_sim(params, state, mesh, N)
    win = gs.make_fused_window(cfg, None, ticks_fused=4,
                               receive_block=BLOCK,
                               receive_interpret=True, telemetry=tel,
                               shard_mesh=mesh, on_refusal="raise")
    assert win.capability(params_s, state_s) is None
    import jax
    import jax.numpy as jnp
    s, dl, frs = state_s, [], []
    for _ in range(TICKS // 4):
        out = win(params_s, s)
        s = out[0]
        dl.append(np.asarray(out[1]))
        frs.append(out[2])
    frames = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs), *frs)
    assert np.array_equal(np.concatenate(dl), np.asarray(d_ref))
    assert _state_equal(s, s_ref)
    assert _trees_equal(frames, fr_ref)


def test_sharded_window_double_buffer_hand_off():
    """Halo double-buffer correctness: a T=4 window alternates halo
    slots (t mod 2) and reads at tick t+1 exactly the boundary words
    written at tick t; four T=1 windows only ever touch slot 0 with a
    fresh exchange per dispatch.  Bit-identity between the two pins
    the slot hand-off."""
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    mesh = pm.make_mesh(2)
    cfg, params, state = _build()
    params_s, state_s, _sh = ps.shard_sim(params, state, mesh, N)

    def run(Tw, n_ticks):
        win = gs.make_fused_window(cfg, None, ticks_fused=Tw,
                                   receive_block=BLOCK,
                                   receive_interpret=True,
                                   shard_mesh=mesh,
                                   on_refusal="raise")
        s, dl = state_s, []
        for _ in range(n_ticks // Tw):
            out = win(params_s, s)
            s = out[0]
            dl.append(np.asarray(out[1]))
        return s, np.concatenate(dl)

    s4, d4 = run(4, 4)
    s1, d1 = run(1, 4)
    assert np.array_equal(d4, d1)
    assert _state_equal(s4, s1)


def test_sharded_runner_twins_bit_identity():
    """sharded_gossip_run_fused / _curve_fused (carry-pinned mesh
    runners) equal the single-device fused runners."""
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    cfg, params, state = _build()
    win1 = _window(cfg, 4)
    s_ref, c_ref = gs.gossip_run_curve_fused(params, state, TICKS,
                                             win1, M)
    mesh = pm.make_mesh(2)
    cfg, params, state = _build()
    winD = gs.make_fused_window(cfg, None, ticks_fused=4,
                                receive_block=BLOCK,
                                receive_interpret=True,
                                shard_mesh=mesh, on_refusal="raise")
    params_s, state_s, sh = ps.shard_sim(params, state, mesh, N)
    s, c = ps.sharded_gossip_run_curve_fused(params_s, state_s, TICKS,
                                             winD, sh, M)
    assert np.array_equal(np.asarray(c), np.asarray(c_ref))
    assert _state_equal(s, s_ref)


def test_ckpt_sharded_fused_composes(tmp_path):
    """ckpt × sharded × fused: aligned segments resume bit-identical;
    a mid-window segment length is refused by name."""
    from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    s_ref, _d = _kernel_ref()
    mesh = pm.make_mesh(2)
    cfg, params, state = _build()
    winD = gs.make_fused_window(cfg, None, ticks_fused=4,
                                receive_block=BLOCK,
                                receive_interpret=True,
                                shard_mesh=mesh, on_refusal="raise")
    params_s, state_s, sh = ps.shard_sim(params, state, mesh, N)
    ckc = ck.CheckpointConfig(directory=str(tmp_path / "snaps"),
                              every=4)
    s = ck.ckpt_sharded_gossip_run_fused(params_s, state_s, TICKS,
                                         winD, sh, ckc)
    assert _state_equal(s, s_ref)
    ckc2 = ck.CheckpointConfig(directory=str(tmp_path / "snaps2"),
                               every=6)
    with pytest.raises(ValueError,
                       match="ckpt segment boundary mid-window"):
        ck.ckpt_sharded_gossip_run_fused(params_s, state_s, TICKS,
                                         winD, sh, ckc2)


# -- halo geometry: spec unit cases + named sharded refusals ---------------

def test_fused_halo_spec_geometry():
    from go_libp2p_pubsub_tpu.ops.pallas.receive import fused_halo_spec

    offs = [3, -5, 0, 130, -200]
    S, D = 128, 4
    spec = fused_halo_spec(offs, S, D)
    assert spec["p_l"] == 200 and spec["p_r"] == 130
    # ctrl segments: one per nonzero offset, sum(|o|) words total
    assert spec["ctl_words"] == sum(abs(o) for o in offs)
    assert [j for j, _, _, _ in spec["ctl_segs"]] == [0, 1, 3, 4]
    # payload hops tile each side in <= S-word pieces
    for side, h, take, pos in spec["pay_hops"]:
        assert 1 <= take <= S
        p = spec["p_l"] if side == "l" else spec["p_r"]
        assert 0 <= pos and pos + take <= p
    assert spec["max_hop"] == 2          # 200 reaches 2 shards over
    assert spec["n_dmas"] == len(spec["pay_hops"]) + sum(
        len(h) for _, _, _, h in spec["ctl_segs"])


def test_fused_halo_spec_overreach_refused_by_name():
    from go_libp2p_pubsub_tpu.ops.pallas.receive import fused_halo_spec

    with pytest.raises(ValueError, match="halo reach .* spans"):
        fused_halo_spec([500], 128, 2)   # hop 4 >= D=2


def test_refusal_sharded_tile_and_divisibility():
    cfg, params, state = _build()
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 4, sharded=True, devices=16)
    assert r is not None and "whole 128-lane tiles per shard" in r
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 4, sharded=True, devices=3)
    assert r is not None and "divisible by devices" in r
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 4, sharded=True, devices=1)
    assert r is not None and "known device count" in r


def test_refusal_sharded_delays_stays_per_tick():
    """fused-sharded × delays: the honest hole that remains — the
    K-slot dequeue runs between kernel ticks, so delay-armed sims keep
    the per-tick refusal under shard_map too."""
    cfg, params, state = _build(
        delays=DelayConfig(base=2, jitter=1, k_slots=4))
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 4, sharded=True, devices=2)
    assert r is not None and "delay-armed sims stay per-tick" in r


def test_refusal_sharded_vmem_reports_per_shard_set():
    """The sharded VMEM refusal reports the PER-SHARD working set
    including the halo/stage bytes, and a budget that refuses D=2
    can accept D=4 (the per-shard carry halves)."""
    cfg, params, state = _build()
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 8, sharded=True, devices=2,
        vmem_budget_bytes=1 << 16)
    assert r is not None
    assert "halo/stage" in r and "devices=2 (per-shard)" in r
    assert gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 8, sharded=True, devices=2) is None


# -- named refusals: every impossible residency reports WHY ---------------

def test_refusal_unpadded_layout():
    cfg, params, state = _build(padded=False)
    r = gs.kernel_ticks_fused_capability(cfg, None, params, state, 4)
    assert r is not None and "padded pallas layout" in r


def test_refusal_scored_reports_accumulator_bytes():
    sc = gs.ScoreSimConfig()
    cfg, subs, topic, origin, tick0 = _sim()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                       tick0, pad_to_block=BLOCK,
                                       score_cfg=sc)
    r = gs.kernel_ticks_fused_capability(cfg, sc, params, state, 4)
    assert r is not None and "scored configs stay per-tick" in r
    assert "bytes" in r


def test_refusal_delays_report_line_bytes():
    cfg, params, state = _build(
        delays=DelayConfig(base=2, jitter=1, k_slots=4))
    r = gs.kernel_ticks_fused_capability(cfg, None, params, state, 4)
    assert r is not None and "delay-armed sims stay per-tick" in r
    assert "bytes" in r


def test_refusal_vmem_budget_reports_working_set():
    cfg, params, state = _build()
    r = gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 8, vmem_budget_bytes=1 << 16)
    assert r is not None
    assert "resident carry past the VMEM budget" in r
    assert "working set" in r and "bytes" in r
    # and the full budget accepts the same config
    assert gs.kernel_ticks_fused_capability(
        cfg, None, params, state, 8) is None


def test_refusal_fallback_dispatch_still_runs():
    """on_refusal="fallback" (the default): a refused config silently
    takes the scan-of-steps window and stays bit-identical."""
    cfg, params, state = _build(padded=False)
    step = gs.make_gossip_step(cfg, None)
    s_ref = gs.gossip_run(params, state, 4, step)
    cfg, params, state = _build(padded=False)
    win = gs.make_fused_window(cfg, None, ticks_fused=4)
    assert win.capability(params, state) is not None
    s = win(params, state)[0]
    assert _state_equal(s, s_ref)
