"""Round-15 preemption tolerance (parallel/checkpoint.py): segmented
checkpointed runs are BIT-IDENTICAL to the single uninterrupted scan on
every execution path — XLA combined and split, the pallas kernel, flood
circulant and gather, randomsub circulant and dense — with faults,
event-driven delays, attacks, and telemetry armed; resume after a
deleted tail snapshot, after a deferred-SIGTERM interrupt (in-process
and as a real killed subprocess), and across a device-count change
(save at D=4, resume at D=8) reproduces the same trajectory; and every
unusable snapshot — truncated, bit-flipped, wrong magic, wrong config
fingerprint, wrong peer layout, stale horizon — is rejected BY NAME.

Scan splitting is exact (the tick index rides in the carry and the
step is deterministic), so segmentation must never cost fidelity:
identity here is exact array equality over the full state pytree, the
same contract as tests/test_sharded.py."""

import functools
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import go_libp2p_pubsub_tpu.models.floodsub as fs
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.randomsub as rs
import go_libp2p_pubsub_tpu.models.telemetry as tl
from go_libp2p_pubsub_tpu.models.delays import DelayConfig
from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets
from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
from go_libp2p_pubsub_tpu.parallel import mesh as pm
from go_libp2p_pubsub_tpu.parallel import sharded as ps

N, T, M, TICKS, BLOCK = 512, 4, 8, 10, 64


def teardown_module(module):
    """Release this module's cached sims/steps AND the executables
    compiled against them: at ~500 tests in one pytest process the
    suite's cumulative compile cache is big enough that the largest
    compile later in the run (test_trace_export's probe runner) can
    segfault XLA's CPU backend — freeing our share keeps the whole
    run at its pre-round-15 footprint."""
    import jax
    _armed.cache_clear()
    _armed_ref.cache_clear()
    _kernel_parts.cache_clear()
    _flood_inputs.cache_clear()
    jax.clear_caches()

#: segment lengths under test: every=5 -> 2 equal segments,
#: every=3 -> 4 segments (3+3+3+1, the remainder case)
EVERIES = (5, 3)


def _scenario(seed=0):
    rng = np.random.default_rng(seed)
    subs = np.zeros((N, T), dtype=bool)
    subs[np.arange(N), np.arange(N) % T] = True
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, N // T, M) * T + topic
    tick0 = np.sort(rng.integers(0, 6, M)).astype(np.int32)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T, 16, N, seed=7), n_topics=T)
    return cfg, subs, topic, origin, tick0


def _faults():
    return FaultSchedule(
        n_peers=N, horizon=TICKS, drop_prob=0.05, seed=5,
        down_intervals=tuple((int(p), 2, 5) for p in range(0, N, 41)))


def _trees_equal(a, b):
    import jax
    fa, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, a))
    fb, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, b))
    assert len(fa) == len(fb)
    return all(np.array_equal(x, y) for x, y in zip(fa, fb))


def _ckpt(tmp_path, every, **kw):
    return ck.CheckpointConfig(directory=str(tmp_path / "snaps"),
                               every=every, **kw)


# -- gossip XLA, everything armed (delays + faults + sybil) ----------------

@functools.lru_cache(maxsize=None)
def _armed():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig(sybil_ihave_spam=True)
    sybil = (np.arange(N) % 37 == 0)
    tcfg = tl.TelemetryConfig(
        counters=False, wire=False, mesh=False, scores=False,
        faults=False, latency_hist=True, latency_buckets=TICKS)

    def build(split=False):
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            delays=DelayConfig(base=2, jitter=1, k_slots=4),
            delays_split=split,   # the split path needs its own line
            fault_schedule=_faults(), sybil=sybil,
            track_first_tick=False)

    steps = {
        "combined": gs.make_gossip_step(cfg, sc),
        "split": gs.make_gossip_step(cfg, sc, force_split=True),
        "tel": gs.make_gossip_step(cfg, sc, telemetry=tcfg),
    }
    return cfg, sc, build, steps


@functools.lru_cache(maxsize=None)
def _armed_ref(which):
    cfg, sc, build, steps = _armed()
    params, state = build(which == "split")
    if which == "tel":
        s_ref, fr = tl.telemetry_run(params, state, TICKS, steps["tel"])
        return s_ref, tl.frames_to_arrays(fr)
    return gs.gossip_run(params, state, TICKS, steps[which])


@pytest.mark.parametrize("every", EVERIES)
@pytest.mark.parametrize("which", ["combined", "split"])
def test_gossip_xla_segmented_bit_identity(which, every, tmp_path):
    """Both XLA formulations, delays + faults + sybil spam armed."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref(which)
    params, state = build(which == "split")
    s_seg = ck.ckpt_gossip_run(params, state, TICKS, steps[which],
                               _ckpt(tmp_path, every))
    assert _trees_equal(s_ref, s_seg)


@pytest.mark.parametrize("every", EVERIES)
def test_telemetry_segmented_bit_identity(every, tmp_path):
    """telemetry_run segmented: the per-tick frame blocks concatenate
    across segments (riding through the snapshots), so BOTH the state
    and every frame array must match the single scan exactly."""
    cfg, sc, build, steps = _armed()
    s_ref, fr_ref = _armed_ref("tel")
    params, state = build()
    s_seg, fr_seg = ck.ckpt_telemetry_run(
        params, state, TICKS, steps["tel"], _ckpt(tmp_path, every))
    assert _trees_equal(s_ref, s_seg)
    dev = tl.frames_to_arrays(fr_seg)
    assert set(fr_ref) == set(dev)
    for k in fr_ref:
        assert np.array_equal(np.asarray(fr_ref[k]),
                              np.asarray(dev[k])), k


@pytest.mark.parametrize("every", EVERIES)
def test_curve_segmented_bit_identity(every, tmp_path):
    cfg, sc, build, steps = _armed()
    params, state = build()
    s_ref, c_ref = gs.gossip_run_curve(params, state, TICKS,
                                       steps["combined"], M)
    params, state = build()
    s_seg, c_seg = ck.ckpt_gossip_run_curve(
        params, state, TICKS, steps["combined"],
        _ckpt(tmp_path, every), M)
    assert _trees_equal(s_ref, s_seg)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c_seg))


def test_knob_batch_segmented_bit_identity(tmp_path):
    """The sweepd device side, segmented: stacked seed-replicas, final
    honest-masked reach computed once at the end of the horizon."""
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()

    def build():
        builds = [gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=r, score_cfg=sc,
            fault_schedule=_faults(), sim_knobs={},
            track_first_tick=False) for r in range(3)]
        return (gs.stack_trees([b[0] for b in builds]),
                gs.stack_trees([b[1] for b in builds]))

    step = gs.make_gossip_step(cfg, sc)
    params, state = build()
    s_ref, r_ref = gs.gossip_run_knob_batch(params, state, TICKS, step)
    params, state = build()
    s_seg, r_seg = ck.ckpt_gossip_run_knob_batch(
        params, state, TICKS, step, _ckpt(tmp_path, 3))
    assert _trees_equal(s_ref, s_seg)
    assert np.array_equal(np.asarray(r_ref), np.asarray(r_seg))


# -- pallas kernel path ----------------------------------------------------

@functools.lru_cache(maxsize=None)
def _kernel_parts():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()

    def build():
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            fault_schedule=_faults(), track_first_tick=False,
            pad_to_block=BLOCK)

    step = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                               receive_interpret=True)
    params, state = build()
    s_ref = gs.gossip_run(params, state, TICKS, step)
    return build, step, s_ref


@pytest.mark.parametrize(
    "every", [5, pytest.param(3, marks=pytest.mark.slow)])
def test_kernel_segmented_bit_identity(every, tmp_path):
    build, step, s_ref = _kernel_parts()
    params, state = build()
    s_seg = ck.ckpt_gossip_run(params, state, TICKS, step,
                               _ckpt(tmp_path, every))
    assert _trees_equal(s_ref, s_seg)


# -- flood + randomsub, both variants --------------------------------------

@functools.lru_cache(maxsize=None)
def _flood_inputs():
    rng = np.random.default_rng(1)
    subs = np.zeros((N, T), dtype=bool)
    subs[np.arange(N), np.arange(N) % T] = True
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, N // T, M) * T + topic
    tick0 = np.sort(rng.integers(0, 6, M)).astype(np.int32)
    offs = tuple(int(o) for o in make_circulant_offsets(T, 16, N,
                                                        seed=1))
    return subs, topic, origin, tick0, offs


@pytest.mark.parametrize("every", EVERIES)
@pytest.mark.parametrize("variant", ["circulant", "gather"])
def test_flood_segmented_bit_identity(variant, every, tmp_path):
    subs, topic, origin, tick0, offs = _flood_inputs()
    if variant == "circulant":
        def build():
            return fs.make_flood_sim(
                None, None, subs, None, topic, origin, tick0,
                fault_schedule=_faults(), fault_offsets=offs,
                delays=DelayConfig(base=2, jitter=1, k_slots=4))
        core = fs.make_circulant_step_core(offs)
    else:
        nbrs = np.stack([(np.arange(N) + o) % N for o in offs], axis=1)
        mask = np.ones_like(nbrs, dtype=bool)

        def build():
            return fs.make_flood_sim(
                nbrs, mask, subs, None, topic, origin, tick0,
                fault_schedule=_faults())
        core = fs.make_gather_step_core()

    params, state = build()
    s_ref, c_ref = fs.flood_run_curve(params, state, TICKS, core, M)
    params, state = build()
    s_seg, c_seg = ck.ckpt_flood_run_curve(
        params, state, TICKS, core, _ckpt(tmp_path, every), M)
    assert _trees_equal(s_ref, s_seg)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c_seg))


@pytest.mark.parametrize("every", EVERIES)
@pytest.mark.parametrize("variant", ["circulant", "dense"])
def test_randomsub_segmented_bit_identity(variant, every, tmp_path):
    subs, topic, origin, tick0, _ = _flood_inputs()
    rcfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(T, 16, N, seed=1),
        n_topics=T, d=3)
    if variant == "circulant":
        def build():
            return rs.make_randomsub_sim(
                rcfg, subs, topic, origin, tick0,
                fault_schedule=_faults(),
                delays=DelayConfig(base=2, jitter=1, k_slots=4))
        step = rs.make_randomsub_step(rcfg)
    else:
        def build():
            return rs.make_randomsub_sim(
                rcfg, subs, topic, origin, tick0, dense=True,
                fault_schedule=_faults())
        step = rs.make_randomsub_dense_step(rcfg)

    params, state = build()
    s_ref = rs.randomsub_run(params, state, TICKS, step)
    params, state = build()
    s_seg = ck.ckpt_randomsub_run(params, state, TICKS, step,
                                  _ckpt(tmp_path, every))
    assert _trees_equal(s_ref, s_seg)


# -- resume: crash, kill flag, killed subprocess ---------------------------

def test_resume_after_losing_tail_snapshot(tmp_path):
    """Delete the final snapshot after a completed segmented run (the
    mid-run-crash stand-in): re-running the same call resumes from the
    surviving snapshot and lands on the identical final state."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3, keep=10)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    snaps = sorted(os.listdir(ckc.directory))
    assert len(snaps) == 4   # 3+3+3+1 ticks
    os.unlink(os.path.join(ckc.directory, snaps[-1]))
    params, state = build()
    s_res = ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
    assert _trees_equal(s_ref, s_res)


def test_kill_flag_interrupts_then_resumes(tmp_path):
    """The deferred-kill contract in-process: with the stop flag up,
    the engine finishes the CURRENT segment, flushes its snapshot, and
    raises CheckpointInterrupt naming it; after clear_stop() the same
    call resumes from that snapshot to the identical final state."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3)
    ck.request_stop()
    try:
        params, state = build()
        with pytest.raises(ck.CheckpointInterrupt) as ei:
            ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
        assert ei.value.ticks_done == 3
        assert os.path.exists(ei.value.path)
    finally:
        ck.clear_stop()
    params, state = build()
    s_res = ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
    assert _trees_equal(s_ref, s_res)


_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import go_libp2p_pubsub_tpu.models.gossipsub as gs
from go_libp2p_pubsub_tpu.parallel import checkpoint as ck

N, T, M, TICKS = 256, 4, 6, 400
rng = np.random.default_rng(0)
subs = np.zeros((N, T), dtype=bool)
subs[np.arange(N), np.arange(N) % T] = True
topic = rng.integers(0, T, M)
origin = rng.integers(0, N // T, M) * T + topic
tick0 = np.zeros(M, dtype=np.int32)
cfg = gs.GossipSimConfig(
    offsets=gs.make_gossip_offsets(T, 16, N, seed=7), n_topics=T)
sc = gs.ScoreSimConfig()
step = gs.make_gossip_step(cfg, sc)
params, state = gs.make_gossip_sim(
    cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
    track_first_tick=False)
ckc = ck.CheckpointConfig(directory={snapdir!r}, every=1)
try:
    ck.ckpt_gossip_run(params, state, TICKS, step, ckc)
    print("DONE", flush=True)
except ck.CheckpointInterrupt as e:
    print(f"INTERRUPTED ticks_done={{e.ticks_done}}", flush=True)
    raise SystemExit(0)
"""


@pytest.mark.slow
def test_sigterm_killed_subprocess_resumes_identically(tmp_path):
    """A REAL SIGTERM against a running child process: the installed
    handlers defer it, the child finishes its in-flight segment,
    flushes the snapshot, and exits 0; resuming in-process from the
    child's snapshot directory reproduces the uninterrupted digest."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    snapdir = str(tmp_path / "snaps")
    script = _CHILD.format(repo=repo, snapdir=snapdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    child = subprocess.Popen([sys.executable, "-c", script],
                             stdout=subprocess.PIPE, text=True,
                             env=env)
    try:
        # wait for the run to be demonstrably mid-flight (2 snapshots
        # out of 400 segments), then deliver the real signal
        deadline = time.time() + 120
        while time.time() < deadline:
            if (os.path.isdir(snapdir)
                    and len(os.listdir(snapdir)) >= 2):
                break
            time.sleep(0.01)
        else:
            pytest.fail("child never produced snapshots")
        child.send_signal(signal.SIGTERM)
        out, _ = child.communicate(timeout=120)
    finally:
        if child.poll() is None:
            child.kill()
    assert child.returncode == 0, out
    assert "INTERRUPTED" in out, out

    # uninterrupted reference, then resume from the child's snapshots
    def build():
        rng = np.random.default_rng(0)
        n, t, m = 256, 4, 6
        subs = np.zeros((n, t), dtype=bool)
        subs[np.arange(n), np.arange(n) % t] = True
        topic = rng.integers(0, t, m)
        origin = rng.integers(0, n // t, m) * t + topic
        tick0 = np.zeros(m, dtype=np.int32)
        cfg = gs.GossipSimConfig(
            offsets=gs.make_gossip_offsets(t, 16, n, seed=7),
            n_topics=t)
        sc = gs.ScoreSimConfig()
        step = gs.make_gossip_step(cfg, sc)
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            track_first_tick=False)
        return params, state, step

    params, state, step = build()
    s_ref = gs.gossip_run(params, state, 400, step)
    params, state, step = build()
    s_res = ck.ckpt_gossip_run(
        params, state, 400, step,
        ck.CheckpointConfig(directory=snapdir, every=1))
    assert _trees_equal(s_ref, s_res)


# -- sharded: D -> D' re-placement -----------------------------------------

@pytest.mark.slow
def test_sharded_save_d4_resume_d8_bit_identity(tmp_path):
    """Snapshots hold host-side full arrays, so restore re-places them
    under ANY shard_sim layout: save under a 4-device mesh, resume
    under 8, final state identical to the single-device reference."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3)
    step = steps["combined"]

    mesh4 = pm.make_mesh(4)
    params, state = build()
    p4, s4, sh4 = ps.shard_sim(params, state, mesh4, N)
    ck.request_stop()   # interrupt after the first segment
    try:
        with pytest.raises(ck.CheckpointInterrupt):
            ck.ckpt_sharded_gossip_run(p4, s4, TICKS, step, sh4, ckc)
    finally:
        ck.clear_stop()

    mesh8 = pm.make_mesh(8)
    params, state = build()
    p8, s8, sh8 = ps.shard_sim(params, state, mesh8, N)
    s_res = ck.ckpt_sharded_gossip_run(p8, s8, TICKS, step, sh8, ckc)
    assert _trees_equal(s_ref, s_res)


# -- rejection by name -----------------------------------------------------

def _one_snapshot(tmp_path, fingerprint=0):
    """A completed 2-segment run's newest snapshot path + its config."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 5, fingerprint=fingerprint)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    found = ck.latest_snapshot(ckc.directory, ckc.tag)
    assert found is not None
    return found[1], ckc


def test_truncated_snapshot_rejected_by_name(tmp_path):
    path, _ = _one_snapshot(tmp_path)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-64])
    with pytest.raises(ValueError, match="truncated snapshot"):
        ck.snapshot_read(path)


def test_bitflipped_snapshot_rejected_by_name(tmp_path):
    path, _ = _one_snapshot(tmp_path)
    blob = bytearray(open(path, "rb").read())
    blob[-100] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="CRC32 mismatch"):
        ck.snapshot_read(path)


def test_non_snapshot_file_rejected_by_name(tmp_path):
    p = tmp_path / "junk.ckpt"
    p.write_bytes(b'{"magic": "something-else"}\n')
    with pytest.raises(ValueError, match="not a checkpoint snapshot"):
        ck.snapshot_read(str(p))
    p.write_bytes(b"no header here")
    with pytest.raises(ValueError, match="no header line"):
        ck.snapshot_read(str(p))


def test_fingerprint_mismatch_rejected_through_runner(tmp_path):
    """The engine-level wiring: a runner resuming over a snapshot
    written under a different config fingerprint must refuse by name,
    never silently re-run."""
    cfg, sc, build, steps = _armed()
    fp = ck.config_fingerprint(cfg, sc)
    _, ckc = _one_snapshot(tmp_path, fingerprint=fp)
    params, state = build()
    bad = ck.CheckpointConfig(directory=ckc.directory, every=5,
                              fingerprint=fp + 1)
    with pytest.raises(ValueError, match="fingerprint"):
        ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           bad)


def test_layout_mismatch_rejected_by_name(tmp_path):
    """Resuming a 512-peer snapshot into a 256-peer sim must name the
    offending leaf and the layout contract, not crash in XLA."""
    path, ckc = _one_snapshot(tmp_path)
    n2, t = 256, 4
    rng = np.random.default_rng(0)
    subs = np.zeros((n2, t), dtype=bool)
    subs[np.arange(n2), np.arange(n2) % t] = True
    topic = rng.integers(0, t, M)
    origin = rng.integers(0, n2 // t, M) * t + topic
    cfg2 = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n2, seed=7), n_topics=t)
    sc2 = gs.ScoreSimConfig()
    step2 = gs.make_gossip_step(cfg2, sc2)
    params, state = gs.make_gossip_sim(
        cfg2, subs, topic, origin, np.zeros(M, np.int32), seed=3,
        score_cfg=sc2, track_first_tick=False)
    with pytest.raises(ValueError, match="peer-axis layout or sim "
                                         "configuration mismatch"):
        ck.ckpt_gossip_run(params, state, TICKS, step2, ckc)


def test_stale_horizon_rejected_by_name(tmp_path):
    """A snapshot further along than the requested horizon is a config
    error, not something to silently truncate."""
    cfg, sc, build, steps = _armed()
    _, ckc = _one_snapshot(tmp_path)
    params, state = build()
    with pytest.raises(ValueError, match="requested horizon"):
        ck.ckpt_gossip_run(params, state, TICKS - 5,
                           steps["combined"], ckc)


def test_completed_aux_run_rejected_by_name(tmp_path):
    """An aux-carrying runner (curve/telemetry) re-invoked over an
    ALREADY COMPLETE snapshot chain cannot reconstruct its aux stream
    — it must say so, not return half data."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 5)
    params, state = build()
    ck.ckpt_gossip_run_curve(params, state, TICKS, steps["combined"],
                             ckc, M)
    params, state = build()
    with pytest.raises(ValueError, match="already complete"):
        ck.ckpt_gossip_run_curve(params, state, TICKS,
                                 steps["combined"], ckc, M)


def test_config_fingerprint_discriminates():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()
    a = ck.config_fingerprint(cfg, sc)
    assert a == ck.config_fingerprint(cfg, sc)
    cfg2 = gs.GossipSimConfig(offsets=cfg.offsets, n_topics=T, d=5)
    assert a != ck.config_fingerprint(cfg2, sc)
    assert a != ck.config_fingerprint(
        cfg, gs.ScoreSimConfig(sybil_ihave_spam=True))


# -- round 16: async double-buffered writer --------------------------------

def test_async_write_bit_identity(tmp_path):
    """async_write=True overlaps segment k's encode+CRC+write with
    segment k+1's compute — pure pipelining, so the trajectory AND the
    on-disk snapshots must equal the synchronous writer's."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    params, state = build()
    s = ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           _ckpt(tmp_path, 3, async_write=True))
    assert _trees_equal(s_ref, s)


def test_async_kill_drains_inflight_buffer(tmp_path):
    """The deferred-kill contract under the async writer: the engine
    DRAINS the in-flight write before raising CheckpointInterrupt, so
    the interrupt's named snapshot is durable (readable, correct
    ticks_done) the moment the exception escapes."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, async_write=True)
    ck.request_stop()
    try:
        params, state = build()
        with pytest.raises(ck.CheckpointInterrupt) as ei:
            ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
        assert os.path.exists(ei.value.path)
        header, _ = ck.snapshot_read(ei.value.path)
        assert header["ticks_done"] == ei.value.ticks_done == 3
    finally:
        ck.clear_stop()
    params, state = build()
    s_res = ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
    assert _trees_equal(_armed_ref("combined"), s_res)


def test_async_write_failure_surfaces(tmp_path, monkeypatch):
    """A background write failure is never dropped: it re-raises on
    the next submit or at the drain."""
    cfg, sc, build, steps = _armed()
    params, state = build()

    def boom(path, header, by_key):
        raise OSError("disk gone mid-write")
    monkeypatch.setattr(ck, "snapshot_save", boom)
    with pytest.raises(OSError, match="disk gone mid-write"):
        ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           _ckpt(tmp_path, 3, async_write=True))


# -- round 16: delta snapshots ---------------------------------------------

def test_delta_chain_bit_identity_and_headers(tmp_path):
    """full_every=3 over 4 segments: kinds are full/delta/delta/full,
    the run matches the reference, and a resume that lands ON a delta
    snapshot (tail full deleted) reconstructs the chain and still
    reproduces the uninterrupted digest."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=3)
    params, state = build()
    s = ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)
    assert _trees_equal(s_ref, s)
    kinds = {}
    for name in sorted(os.listdir(ckc.directory)):
        h, _ = ck.snapshot_read(os.path.join(ckc.directory, name))
        kinds[h["segment"]] = h["kind"]
    assert kinds == {1: "full", 2: "delta", 3: "delta", 4: "full"}
    os.unlink(os.path.join(ckc.directory, "sim-seg000004.ckpt"))
    params, state = build()
    s_res = ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
    assert _trees_equal(s_ref, s_res)


def test_delta_async_curve_aux_bit_identity(tmp_path):
    """Deltas + async together, with per-tick aux riding the
    snapshots: the concatenating curve blocks change shape every
    segment (the full-store fallback inside the delta encoder), and
    the resumed [TICKS, M] curve is bit-identical."""
    cfg, sc, build, steps = _armed()
    params, state = build()
    s_ref, c_ref = gs.gossip_run_curve(params, state, TICKS,
                                       steps["combined"], M)
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=2, async_write=True)
    params, state = build()
    s, c = ck.ckpt_gossip_run_curve(params, state, TICKS,
                                    steps["combined"], ckc, M)
    assert _trees_equal(s_ref, s)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c))


def test_delta_async_chain_links_ordered(tmp_path):
    """async_write=True + full_every=3 TOGETHER: the writer thread
    must serialize snapshots in segment order, because each delta is
    encoded against the previous snapshot's payload CRC — if segment
    k+1's write ever overtook segment k's, the on-disk base_crc32
    links would break.  Pins the header chain: kinds
    full/delta/delta/full and every delta's base_crc32 equal to the
    PREVIOUS on-disk snapshot's payload_crc32."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=3, async_write=True)
    params, state = build()
    s = ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)
    assert _trees_equal(s_ref, s)
    headers = {}
    for name in sorted(os.listdir(ckc.directory)):
        h, _ = ck.snapshot_read(os.path.join(ckc.directory, name))
        headers[h["segment"]] = h
    assert {i: h["kind"] for i, h in headers.items()} == {
        1: "full", 2: "delta", 3: "delta", 4: "full"}
    for i, h in headers.items():
        if h["kind"] == "delta":
            assert h["base_crc32"] == headers[i - 1]["payload_crc32"], \
                (i, h)
    # and a resume landing ON the mid-chain delta reconstructs it
    h3, _ = ck.read_snapshot_chain(ckc.directory, "sim", 3)
    assert h3["ticks_done"] == 9


def test_delta_async_kill_drains_chain_then_resumes(tmp_path):
    """The deferred-kill contract with BOTH round-16 flags up: the
    drain must flush the in-flight DELTA write before
    CheckpointInterrupt escapes, so the named snapshot's whole chain
    is durable and readable at that instant; resuming from it
    reproduces the uninterrupted trajectory bit-identically."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=3, async_write=True)
    ck.request_stop()
    try:
        params, state = build()
        with pytest.raises(ck.CheckpointInterrupt) as ei:
            ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
        assert os.path.exists(ei.value.path)
        header, _ = ck.read_snapshot_chain(
            ckc.directory, "sim", 1)
        assert header["ticks_done"] == ei.value.ticks_done == 3
    finally:
        ck.clear_stop()
    params, state = build()
    s_res = ck.ckpt_gossip_run(params, state, TICKS,
                               steps["combined"], ckc)
    assert _trees_equal(_armed_ref("combined"), s_res)


def test_prune_protects_delta_chain_async(tmp_path):
    """keep=2 pruning under the async writer: the background thread's
    prune must floor at the governing full exactly as the synchronous
    writer does — segment 3 is a delta rooted at the segment-1 full,
    so segments 1-4 all survive and the mid-chain read reconstructs."""
    cfg, sc, build, steps = _armed()
    s_ref = _armed_ref("combined")
    ckc = _ckpt(tmp_path, 3, keep=2, full_every=3, async_write=True)
    params, state = build()
    s = ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)
    assert _trees_equal(s_ref, s)
    names = sorted(os.listdir(ckc.directory))
    assert names == [f"sim-seg{i:06d}.ckpt" for i in (1, 2, 3, 4)]
    h3, _ = ck.read_snapshot_chain(ckc.directory, "sim", 3)
    assert h3["ticks_done"] == 9


def test_unusable_delta_chain_missing_full_rejected(tmp_path):
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=4)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    os.unlink(os.path.join(ckc.directory, "sim-seg000001.ckpt"))
    params, state = build()
    with pytest.raises(ValueError, match="unusable delta chain"):
        ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)


def test_unusable_delta_chain_corrupt_link_rejected(tmp_path):
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=4)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    p2 = os.path.join(ckc.directory, "sim-seg000002.ckpt")
    blob = bytearray(open(p2, "rb").read())
    blob[-3] ^= 0x40
    open(p2, "wb").write(bytes(blob))
    params, state = build()
    with pytest.raises(ValueError, match="unusable delta chain"):
        ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)


def test_unusable_delta_chain_divergent_base_rejected(tmp_path):
    """A base snapshot that is VALID on its own but is not the one the
    next delta was encoded against (base_crc32 mismatch) poisons the
    chain — rewriting seg2 self-consistently must not let seg3 resume
    against the wrong bits."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, keep=10, full_every=4)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    p2 = os.path.join(ckc.directory, "sim-seg000002.ckpt")
    h2, k2 = ck.snapshot_read(p2)
    key = sorted(k2)[0]
    arr = k2[key].copy()
    arr.reshape(-1).view(np.uint8)[0] ^= 1
    k2[key] = arr
    ck.snapshot_save(p2, h2, k2)
    params, state = build()
    with pytest.raises(ValueError, match="unusable delta chain"):
        ck.ckpt_gossip_run(params, state, TICKS, steps["combined"],
                           ckc)


def test_prune_protects_delta_chain(tmp_path):
    """keep=2 would retain only segments 3-4, but segment 3 is a delta
    rooted at the segment-1 full — pruning floors at the governing
    full so every kept snapshot stays reconstructable."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 3, keep=2, full_every=3)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    names = sorted(os.listdir(ckc.directory))
    assert names == [f"sim-seg{i:06d}.ckpt" for i in (1, 2, 3, 4)]
    h3, _ = ck.read_snapshot_chain(ckc.directory, "sim", 3)
    assert h3["ticks_done"] == 9


def test_full_every_one_headers_stay_full(tmp_path):
    """The default full_every=1 never writes deltas — back-compat with
    every pre-round-16 snapshot consumer."""
    cfg, sc, build, steps = _armed()
    ckc = _ckpt(tmp_path, 5, keep=10)
    params, state = build()
    ck.ckpt_gossip_run(params, state, TICKS, steps["combined"], ckc)
    for name in sorted(os.listdir(ckc.directory)):
        h, _ = ck.snapshot_read(os.path.join(ckc.directory, name))
        assert h["kind"] == "full"


def test_full_every_validated():
    with pytest.raises(ValueError, match="full_every"):
        ck.CheckpointConfig(directory="x", full_every=0)
