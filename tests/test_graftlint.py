"""graftlint (tools/graftlint): the AST pass runs clean on the tree
and flags every seeded fixture violation; pragmas suppress per line;
the abstract-eval audit covers the full declared config matrix without
compiling (= without executing) a single sim program; the config
contracts' refusal and build-time claims hold.

The full threaded-probe contract sweep (~40 s of step traces) runs in
``python -m tools.graftlint`` (measure_all step 0.5) and in the @slow
test here; tier-1 keeps the fast invariants.
"""

import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import RULES, check_file, run_paths
from tools.graftlint import jaxpr_audit as ja
from tools.graftlint.pragmas import pragma_lines, scope_override

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tools" / "graftlint" / "fixtures"


# --------------------------------------------------------------------------
# AST pass
# --------------------------------------------------------------------------


def test_tree_is_clean():
    """The whole repo (fixtures excluded) has zero findings — the
    tier-1 smoke that runs the AST pass on every file."""
    findings = run_paths([REPO], root=REPO)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_fixture_corpus_seeds_every_rule():
    """>= 1 seeded violation per rule, each named with file:line."""
    findings = run_paths([FIXTURES], root=REPO, include_fixtures=True)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
        assert f.line > 0 and f.path.endswith(".py")
    missing = set(RULES) - set(by_rule)
    assert not missing, f"rules with no seeded fixture: {missing}"


def test_cli_nonzero_on_fixtures_naming_rule_and_line():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         str(FIXTURES / "bare_except.py")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1
    assert "bare_except.py:9: graftlint[bare-except]" in out.stdout


def test_pragmas_suppress_per_line():
    """pragma_ok.py seeds the same violations as its twins but every
    line carries a pragma — zero findings."""
    assert check_file(FIXTURES / "pragma_ok.py", root=REPO) == []
    # and the pragma really is per-LINE: the same violation without a
    # pragma in the same file still fires
    src = ('# graftlint: scope=tools\n'
           'import sys\n'
           'sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]\n'
           'sys.path.insert(0, "x")\n')
    findings = check_file(Path("inline.py"), root=REPO, src=src)
    assert [f.line for f in findings] == [4]
    assert findings[0].rule == "sys-path-insert"


def test_unknown_pragma_rule_rejected_by_name():
    """A typo'd ignore[rule] used to be silently accepted — a
    suppression guarding nothing.  Now it is a pragma-directive
    finding at its file:line naming the unknown rule, and the finding
    it failed to silence still fires on the same line."""
    findings = check_file(FIXTURES / "pragma_unknown.py", root=REPO)
    rules = {f.rule for f in findings}
    assert rules == {"pragma-directive", "sys-path-insert"}
    bad = next(f for f in findings if f.rule == "pragma-directive")
    assert bad.line == 13
    assert "sys-path-insrt" in bad.message
    # a KNOWN rule name in the same position is not flagged
    src = ('# graftlint: scope=tools\n'
           'import sys\n'
           'sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]\n')
    assert check_file(Path("inline.py"), root=REPO, src=src) == []


def test_pragma_parsing_forms():
    src = ("a()  # graftlint: ignore[rule-a]\n"
           "b()  # graftlint: ignore[rule-a, rule-b]\n"
           "c()  # graftlint: ignore\n")
    p = pragma_lines(src)
    assert p[1] == frozenset({"rule-a"})
    assert p[2] == frozenset({"rule-a", "rule-b"})
    assert p[3] is None


def test_scope_directive_overrides_path():
    assert scope_override("# graftlint: scope=model\nx = 1\n") == "model"
    with pytest.raises(ValueError, match="unknown graftlint scope"):
        scope_override("# graftlint: scope=bogus\n")
    # a typo'd directive in a scanned file is a LOCATED finding, not a
    # crash of the whole lint run
    findings = check_file(Path("tools/x.py"), root=REPO,
                          src="x = 1\n# graftlint: scope=modle\n")
    assert [(f.rule, f.line) for f in findings] == [
        ("scope-directive", 2)]
    # nondeterminism is model-scoped: the same source flags under the
    # directive and stays silent without it (tools scope)
    bad = "import time\n\n\ndef f():\n    return time.time()\n"
    silent = check_file(Path("tools/x.py"), root=REPO, src=bad)
    assert silent == []
    loud = check_file(Path("tools/x.py"), root=REPO,
                      src="# graftlint: scope=model\n" + bad)
    assert {f.rule for f in loud} == {"nondeterminism"}


def test_except_rule_covers_evasive_forms():
    """BaseException and tuple-hidden Exception are the same hazards
    as their plain spellings — the rules must see through them."""
    base = ("def f():\n    try:\n        pass\n"
            "    except BaseException:\n        pass\n")
    findings = check_file(Path("m.py"), root=REPO,
                          src="# graftlint: scope=model\n" + base)
    assert {f.rule for f in findings} == {"bare-except"}
    tup = ("def f():\n    try:\n        pass\n"
           "    except (Exception, ValueError):\n        pass\n")
    findings = check_file(Path("tools/x.py"), root=REPO, src=tup)
    assert {f.rule for f in findings} == {"broad-except"}


def test_missing_donate_positions():
    findings = check_file(FIXTURES / "missing_donate.py", root=REPO)
    flagged = {f.line for f in findings
               if f.rule == "missing-donate"}
    assert flagged == {9, 14, 19}     # run_ok (donated) not flagged
    # donate_argnames string form is verifiable too: naming 'state'
    # passes, naming another arg is flagged
    good = ("from functools import partial\nimport jax\n\n\n"
            "@partial(jax.jit, donate_argnames=('state',))\n"
            "def run(params, state):\n    return state\n")
    assert check_file(Path("m.py"), root=REPO, src=good) == []
    bad = good.replace("('state',)", "('params',)")
    findings = check_file(Path("m.py"), root=REPO, src=bad)
    assert {f.rule for f in findings} == {"missing-donate"}


def test_pragma_in_docstring_not_honored():
    """Only real comment tokens carry pragmas/directives — a file that
    QUOTES one in a docstring keeps its path-derived scope and its
    findings."""
    src = ('"""Docs showing the syntax:\n\n'
           '    # graftlint: scope=model\n'
           '    x()  # graftlint: ignore[broad-except]\n'
           '"""\n\n\n'
           'def f():\n'
           '    try:\n'
           '        pass\n'
           '    except Exception:\n'
           '        pass\n')
    assert scope_override(src) is None
    findings = check_file(Path("tools/x.py"), root=REPO, src=src)
    assert {f.rule for f in findings} == {"broad-except"}


def test_only_graftlint_fixture_dir_is_exempt(tmp_path):
    """A directory merely NAMED fixtures elsewhere stays under the
    tree-clean gate."""
    from tools.graftlint.astpass import iter_target_files

    other = tmp_path / "tests" / "fixtures"
    other.mkdir(parents=True)
    (other / "f.py").write_text("x = 1\n")
    corpus = tmp_path / "tools" / "graftlint" / "fixtures"
    corpus.mkdir(parents=True)
    (corpus / "seeded.py").write_text("x = 1\n")
    scanned = {p.relative_to(tmp_path).as_posix()
               for p in iter_target_files(tmp_path)}
    assert "tests/fixtures/f.py" in scanned
    assert "tools/graftlint/fixtures/seeded.py" not in scanned


# --------------------------------------------------------------------------
# Abstract-eval audit: full declared matrix, zero execution
# --------------------------------------------------------------------------


def test_declared_matrix_shape():
    combos = ja.declared_matrix()
    assert len(combos) == 74
    # base 32: all three sims x telemetry x faults x batched; split
    # axis only on gossipsub.  Round-10 variants: gather/dense
    # (tel x faults), rpc (tel, faulted), hist (faults, scored).
    # Round-11 variants: inv (the in-scan invariant checker — gossip
    # on both fault axes, flood/randomsub faulted) and attack (the
    # eclipse+byzantine+knobs+cold-restart surface, sequential + the
    # batched tournament runner).  Round-12 variant: knobs (the
    # config-as-data surface — heterogeneous SimKnobs points,
    # sequential + the knob-batched sweep runner).  Round-13 variant:
    # delays (event-driven time — delayed gossip sequential/knob-
    # batched/split, delayed flood + randomsub ring replay).
    # Round-14 variants: sharded (GSPMD whole-sim carry on a CPU
    # 'peers' mesh, sequential + knob-batched) and sharded-kernel /
    # sharded-kernel-delays (shard_map pallas dispatch — the former
    # asserts ppermute+psum halos, the latter the halo-free delay
    # mode).  Round-15 variant: ckpt (the segmented checkpoint
    # engine's dispatch table traced at the split horizon — gossip
    # sequential + knob-batched, flood sequential).  Round-16
    # variant: fused (the tick-resident window through
    # gossip_run_fused, plain + faulted, traced at the 1024-aligned
    # fused shape).  Round-17 variant: fused-sharded (the COMPOSED
    # dispatch — one resident pallas call per shard under shard_map
    # with the in-kernel remote-DMA ring halo; telemetry x faults,
    # the telemetry cases additionally asserting the cross-mesh
    # frame psum).  Round-19 delays additions: four counter-armed
    # delay cases (gossip combined faulted + split, flood + randomsub
    # replay) — the lifted delays[telemetry-counters] refusal traced.
    key = lambda c: (c["sim"], c["split"], c["telemetry"],  # noqa: E731
                     c["faults"], c["batched"], c["variant"])
    assert len({key(c) for c in combos}) == 74
    assert sum(not c["variant"] for c in combos) == 32
    for sim, n in (("gossipsub", 43), ("floodsub", 16),
                   ("randomsub", 15)):
        assert sum(c["sim"] == sim for c in combos) == n
    for var, n in (("gather", 4), ("dense", 4), ("rpc", 2),
                   ("hist", 2), ("inv", 4), ("attack", 2),
                   ("knobs", 2), ("delays", 9), ("sharded", 2),
                   ("sharded-kernel", 1), ("sharded-kernel-delays", 1),
                   ("ckpt", 3), ("fused", 2), ("fused-sharded", 4)):
        assert sum(c["variant"] == var for c in combos) == n
    axes = {ax: {c[ax] for c in combos}
            for ax in ("telemetry", "faults", "batched")}
    assert all(v == {False, True} for v in axes.values())


@pytest.mark.slow
def test_audit_covers_matrix_without_compiling_a_sim():
    """The audit traces/lowers every declared combo and passes — under
    a backend-compile guard (the dispatch-count trace guard): building
    the tiny sims may compile trivial array ops, but the audit phase
    itself must never reach the compiler, which is what 'asserted
    without executing a sim tick' means mechanically."""
    import jax._src.compiler as _compiler

    cases = ja.build_cases()           # builds arrays; may compile
    declared = {(c["sim"], c["split"], c["telemetry"], c["faults"],
                 c["batched"], c["variant"])
                for c in ja.declared_matrix()}
    built = {(c.sim, c.split, c.telemetry, c.faults, c.batched,
              c.variant) for c in cases}
    assert built == declared

    compiled = []
    orig = _compiler.backend_compile

    def guard(*args, **kw):
        compiled.append(args)
        return orig(*args, **kw)

    _compiler.backend_compile = guard
    try:
        problems = ja.run_audit(cases)
    finally:
        _compiler.backend_compile = orig
    assert problems == [], "\n".join(problems)
    assert compiled == [], (
        f"audit phase reached the compiler {len(compiled)} time(s) — "
        "it must trace/lower only")


def test_audit_catches_a_seeded_64bit_widening():
    """The checks are live, not vacuous: a case whose trace contains a
    float64 convert / aval must fail the audit."""
    import jax
    import jax.numpy as jnp

    def bad_runner(params, state, n_ticks, step):
        return state.astype(jnp.float64)

    case = ja.AuditCase(
        sim="gossipsub", split=False, telemetry=False, faults=False,
        batched=False)
    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(bad_runner, static_argnums=(2, 3))(
            jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.float32), 1,
            None)
    case.trace = lambda: closed
    case.lower = lambda: ""
    case.n_carry_leaves = 0
    problems = ja.audit_case(case)
    assert any("no-64bit" in p for p in problems)
    assert any("no-widening-convert" in p for p in problems)


def test_audit_catches_a_seeded_callback_and_missing_donation():
    import jax
    import jax.numpy as jnp

    def cb_runner(params, state, n_ticks, step):
        jax.debug.callback(lambda: None)
        return state

    case = ja.AuditCase(
        sim="floodsub", split=False, telemetry=False, faults=False,
        batched=False)
    case.trace = lambda: jax.make_jaxpr(
        cb_runner, static_argnums=(2, 3))(
            jnp.zeros(4), jnp.zeros(4), 1, None)
    case.lower = lambda: "module { }"      # zero aliased buffers
    case.n_carry_leaves = 3
    problems = ja.audit_case(case)
    assert any("no-host-callback" in p for p in problems)
    assert any("donation" in p for p in problems)


# --------------------------------------------------------------------------
# Config contracts
# --------------------------------------------------------------------------


def test_contract_declarations_complete():
    """Every field of the contracted configs is declared, for every
    declared path — no probes run (fast completeness gate)."""
    import dataclasses
    from go_libp2p_pubsub_tpu.models.delays import DelayConfig
    from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        GossipSimConfig, ScoreSimConfig)
    from go_libp2p_pubsub_tpu.models.invariants import InvariantConfig
    from go_libp2p_pubsub_tpu.models.telemetry import TelemetryConfig

    for cls in (GossipSimConfig, ScoreSimConfig, TelemetryConfig,
                FaultSchedule, InvariantConfig, DelayConfig):
        fields = {f.name for f in dataclasses.fields(cls)}
        assert set(cls.CONTRACT) == fields, cls.__name__
        for fld, spec in cls.CONTRACT.items():
            per_path = (dict.fromkeys(cls.PATHS, spec)
                        if isinstance(spec, str) else spec)
            assert set(per_path) == set(cls.PATHS), (cls.__name__, fld)


def test_contract_refusals_and_build_time_hold():
    """The build-time reject claims verified directly (the fast,
    no-trace subset).  _REFUSALS — emptied in round 10 — carries the
    round-11 CAPABILITY refusals now: the mesh-less simulators refuse
    cold-restart schedules, and the pallas kernel refuses the
    P3/byzantine score family.  The cheap (build-only) cold-restart
    probes run here; the kernel refusal probe traces a step and is
    exercised by test_attacks.py + the @slow full sweep."""
    from tools.graftlint import contracts as ct

    assert set(ct._REFUSALS) == {
        ("FaultSchedule", "flood-circulant"),
        ("FaultSchedule", "flood-gather"),
        ("FaultSchedule", "randomsub-circulant"),
        ("FaultSchedule", "randomsub-dense"),
        ("ScoreSimConfig", "kernel"),
        # round 12: the one XLA-only knob — gossip_retransmission on
        # iwant-spam configs refuses the kernel path by name
        ("SimKnobs", "kernel"),
    }
    for key, (probe, match) in ct._REFUSALS.items():
        if key[0] != "FaultSchedule":
            continue
        assert ct._expect_raise(probe, match, label=str(key)) == [], key
    for key, (probe, match) in ct._BUILD_TIME.items():
        assert ct._expect_raise(probe, match, label=str(key)) == [], key
    # and the match is load-bearing: the right exception with the
    # WRONG message does not vacuously prove a refusal
    def wrong_reason():
        raise ValueError("some incidental validation error")
    assert ct._expect_raise(wrong_reason, r"refuses fault configs",
                            label="x") != []
    # probe-refusal registry (round 11): the remaining rpc_probe
    # capability gaps stay named and live — NotImplementedError by
    # default; round-12 entries may carry an explicit exception class
    # (the sim_knobs static-field ratchet is ValueError-typed)
    for label, spec in ct._PROBE_REFUSALS.items():
        probe, match = spec[0], spec[1]
        exc = spec[2] if len(spec) > 2 else NotImplementedError
        assert ct._expect_raise(probe, match, label=label,
                                exc=exc) == [], label


def test_contract_fault_threading_fast():
    """FaultSchedule data fields provably reach the device params on
    all three circulant paths, the round-9 pallas kernel path, AND
    the round-10 gather/dense paths (value-diff probes on the build,
    no tracing).  drop_prob on gather/dense is scalar-only, so the
    per-edge form is exercised on the circulant paths only."""
    from tools.graftlint import contracts as ct

    for field in ("down_intervals", "drop_prob", "partition_group",
                  "partition_windows", "seed"):
        for path in ("gossip-xla", "gossip-kernel", "flood-circulant",
                     "randomsub-circulant", "flood-gather",
                     "randomsub-dense"):
            assert ct._fault_threaded(field, path), (field, path)


def test_contract_telemetry_kernel_threaded_fast():
    """One kernel-path telemetry threading probe in the fast subset:
    the ``counters`` group must change the KERNEL step's jaxpr (the
    in-kernel tally output appearing/disappearing) — the round-9
    flip from refused to threaded, proven.  The full field sweep runs
    in the @slow check_contracts pass."""
    from tools.graftlint import contracts as ct

    assert ct._tel_probe("counters", "gossip-kernel", False)


@pytest.mark.slow
def test_contract_detects_an_undeclared_field(monkeypatch):
    """Adding a config field without a contract entry is a finding —
    the ratchet the checker exists for."""
    from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
    from tools.graftlint import contracts as ct

    pruned = {k: v for k, v in FaultSchedule.CONTRACT.items()
              if k != "seed"}
    monkeypatch.setattr(FaultSchedule, "CONTRACT", pruned)
    monkeypatch.setattr(
        ct, "_contracted_classes", lambda: (FaultSchedule,))
    problems = ct.check_contracts()
    assert any("FaultSchedule.seed has no thread-or-refuse" in p
               for p in problems)


@pytest.mark.slow
def test_full_contract_sweep():
    """The complete threaded/inert probe matrix (what the CLI runs)."""
    from tools.graftlint.contracts import check_contracts

    problems = check_contracts()
    assert problems == [], "\n".join(problems)
