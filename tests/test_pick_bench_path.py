"""The bench-path picker is part of the unattended recovery chain
(tools/tpu_watch.sh): it decides which execution path the driver's
end-of-round bench runs.  Pin its decision logic."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PICK = REPO / "tools" / "pick_bench_path.py"

XLA_ROW = ('{"metric": "gossipsub_v11_1000000peers_100topics_'
           'heartbeats_per_sec", "value": %s, "unit": "heartbeats/s"}')
KERN_ROW = ('{"metric": "gossipsub_v11_1024000peers_100topics_kernel_'
            'heartbeats_per_sec", "value": %s, "unit": "heartbeats/s"}')
CPU_ROW = ('{"metric": "gossipsub_v11_100000peers_100topics_'
           'heartbeats_per_sec", "value": %s, "unit": "heartbeats/s"}')


def run_pick(tmp_path, lines):
    log = tmp_path / "m.log"
    log.write_text("\n".join(lines) + "\n")
    out = subprocess.run(
        [sys.executable, str(PICK), str(log)], cwd=tmp_path,
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    cfg = tmp_path / "BENCH_CONFIG.json"
    return json.loads(cfg.read_text()) if cfg.exists() else None


def test_kernel_win_pins(tmp_path):
    cfg = run_pick(tmp_path, [XLA_ROW % 160.0, KERN_ROW % 250.0])
    assert cfg and cfg["kernel"] is True


def test_kernel_loss_no_pin(tmp_path):
    assert run_pick(tmp_path, [XLA_ROW % 160.0, KERN_ROW % 150.0]) is None


def test_margin_under_2pct_no_pin(tmp_path):
    assert run_pick(tmp_path, [XLA_ROW % 160.0, KERN_ROW % 162.0]) is None


def test_stale_pin_cleared_on_loss(tmp_path):
    (tmp_path / "BENCH_CONFIG.json").write_text('{"kernel": true}\n')
    assert run_pick(tmp_path, [XLA_ROW % 160.0, KERN_ROW % 150.0]) is None


def test_cpu_fallback_rows_ignored(tmp_path):
    # a 100k CPU-fallback row must not stand in for the 1M XLA row
    cfg = run_pick(tmp_path, [CPU_ROW % 15.9, KERN_ROW % 250.0])
    assert cfg is None          # no comparable XLA row -> no decision


def test_truncated_line_survived(tmp_path):
    cfg = run_pick(tmp_path, [
        XLA_ROW % 160.0,
        KERN_ROW % 250.0,
        # killed bench mid-write: cut AFTER the metric name so the
        # regex matches and the json.loads guard is what's exercised
        (KERN_ROW % 999.0)[:90],
    ])
    assert cfg and cfg["kernel"] is True


def test_missing_log_untouched(tmp_path):
    (tmp_path / "BENCH_CONFIG.json").write_text('{"kernel": true}\n')
    out = subprocess.run(
        [sys.executable, str(PICK), str(tmp_path / "absent.log")],
        cwd=tmp_path, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    # a missing log is not evidence the pin is stale
    assert (tmp_path / "BENCH_CONFIG.json").exists()


def test_missing_kernel_row_preserves_pin(tmp_path):
    # an aborted pass (or forced-XLA-only rerun) lacks the kernel row:
    # that is NOT a completed comparison — the hardware-measured pin
    # must survive
    (tmp_path / "BENCH_CONFIG.json").write_text('{"kernel": true}\n')
    assert run_pick(tmp_path, [XLA_ROW % 160.0]) == {"kernel": True}


def test_missing_xla_row_preserves_pin(tmp_path):
    # a CPU-fallback flagship run leaves only the kernel row behind
    (tmp_path / "BENCH_CONFIG.json").write_text('{"kernel": true}\n')
    assert run_pick(tmp_path,
                    [CPU_ROW % 15.9, KERN_ROW % 250.0]) == {"kernel": True}


def test_alias_rows_ignored(tmp_path):
    # bench_suite re-emits a kernel measurement under the plain
    # historical name (alias_of tag) for exact-name consumers; the
    # picker must not read it as an XLA measurement (here it would
    # otherwise see xla=250 vs kernel=250 and clear the pin)
    (tmp_path / "BENCH_CONFIG.json").write_text('{"kernel": true}\n')
    alias = ('{"metric": "gossipsub_v11_1024000peers_100topics_'
             'heartbeats_per_sec", "value": 250.0, "unit": '
             '"heartbeats/s", "alias_of": "gossipsub_v11_1024000peers_'
             '100topics_kernel_heartbeats_per_sec"}')
    cfg = run_pick(tmp_path, [KERN_ROW % 250.0, alias])
    assert cfg == {"kernel": True}   # pin untouched (no true XLA row)
