"""Multi-host helpers (single-process validation: process_count == 1;
the same code path drives real pods via jax.distributed)."""

import numpy as np

from go_libp2p_pubsub_tpu.parallel.multihost import (
    make_global_mesh,
    process_local_peer_slice,
)
from go_libp2p_pubsub_tpu.parallel.mesh import shard_peer_tree


def test_global_mesh_spans_all_devices():
    import jax
    mesh = make_global_mesh()
    assert mesh.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("peers",)


def test_sharded_run_on_global_mesh():
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    n, t = 512, 2
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 8, n, seed=1), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    params, state = gs.make_gossip_sim(
        cfg, subs, np.array([0]), np.array([4]),
        np.zeros(1, dtype=np.int32), score_cfg=gs.ScoreSimConfig())
    mesh = make_global_mesh()
    params = shard_peer_tree(params, mesh, n)
    state = shard_peer_tree(state, mesh, n)
    out = gs.gossip_run(params, state, 15, gs.make_gossip_step(
        cfg, gs.ScoreSimConfig()))
    assert int(np.asarray(gs.reach_counts(params, out))[0]) == n // t


def test_process_local_slice_partitions():
    s = process_local_peer_slice(1000)
    assert s == slice(0, 1000)   # single process owns everything


def test_process_local_slice_matches_actual_shards():
    """The helper's slice must cover exactly the union of this process's
    device shards of a really-sharded array (per-device split, 1000/8 =
    125 each)."""
    import jax
    import jax.numpy as jnp

    from go_libp2p_pubsub_tpu.parallel.mesh import (
        make_mesh, peer_sharding)

    n = 1000
    mesh = make_mesh(8)
    arr = jax.device_put(jnp.arange(n), peer_sharding(mesh, 1))
    spans = sorted((s.index[0].start or 0,
                    (s.index[0].start or 0) + s.data.shape[0])
                   for s in arr.addressable_shards)
    assert spans == [(k * 125, (k + 1) * 125) for k in range(8)]

    s = process_local_peer_slice(n, mesh)
    assert (s.start, s.stop) == (spans[0][0], spans[-1][1]) == (0, n)


def test_process_local_slice_multidevice_processes():
    """Multi-device processes own n/n_devices-sized shards per device,
    NOT n/process_count peers: 1008 peers on 2 procs x 8 devs -> 63
    peers/device, so process 0 owns [0, 504)."""
    from types import SimpleNamespace
    from unittest import mock

    import jax
    import numpy as np
    import pytest

    fake = SimpleNamespace(devices=np.array(
        [SimpleNamespace(process_index=k // 8) for k in range(16)]))
    with mock.patch.object(jax, "process_index", return_value=0):
        s0 = process_local_peer_slice(1008, fake)
    with mock.patch.object(jax, "process_index", return_value=1):
        s1 = process_local_peer_slice(1008, fake)
    assert s0 == slice(0, 504)       # 8 devices x 63 peers
    assert s1 == slice(504, 1008)

    # uneven peer counts are refused up front (device_put would reject
    # the sharding anyway) ...
    with mock.patch.object(jax, "process_index", return_value=0), \
         pytest.raises(ValueError, match="divide evenly"):
        process_local_peer_slice(1000, fake)
    # ... and so is non-contiguous device ownership
    interleaved = SimpleNamespace(devices=np.array(
        [SimpleNamespace(process_index=k % 2) for k in range(16)]))
    with mock.patch.object(jax, "process_index", return_value=0), \
         pytest.raises(ValueError, match="contiguous"):
        process_local_peer_slice(1008, interleaved)
