"""Multi-host helpers (single-process validation: process_count == 1;
the same code path drives real pods via jax.distributed)."""

import numpy as np

from go_libp2p_pubsub_tpu.parallel.multihost import (
    make_global_mesh,
    process_local_peer_slice,
)
from go_libp2p_pubsub_tpu.parallel.mesh import shard_peer_tree


def test_global_mesh_spans_all_devices():
    import jax
    mesh = make_global_mesh()
    assert mesh.size == len(jax.devices()) == 8
    assert mesh.axis_names == ("peers",)


def test_sharded_run_on_global_mesh():
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    n, t = 512, 2
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 8, n, seed=1), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    params, state = gs.make_gossip_sim(
        cfg, subs, np.array([0]), np.array([4]),
        np.zeros(1, dtype=np.int32), score_cfg=gs.ScoreSimConfig())
    mesh = make_global_mesh()
    params = shard_peer_tree(params, mesh, n)
    state = shard_peer_tree(state, mesh, n)
    out = gs.gossip_run(params, state, 15, gs.make_gossip_step(
        cfg, gs.ScoreSimConfig()))
    assert int(np.asarray(gs.reach_counts(params, out))[0]) == n // t


def test_process_local_slice_partitions():
    s = process_local_peer_slice(1000)
    assert s == slice(0, 1000)   # single process owns everything
