"""Round-14 whole-sim sharding (ROADMAP direction 1): the sharded
trajectory is BIT-IDENTICAL to the single-device run on the virtual
CPU mesh (conftest forces 8 host devices), on BOTH execution paths —
the XLA step under GSPMD placement and the pallas kernel under
shard_map — with faults, telemetry, event-driven delays, and the
attack surface on, sequential and batched-over-seeds.  Identity is
exact array equality over the whole state pytree: the sharding layer
is a layout contract, never an arithmetic change."""

import functools

import numpy as np
import pytest

import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.telemetry as tl
from go_libp2p_pubsub_tpu.models.delays import DelayConfig
from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
from go_libp2p_pubsub_tpu.parallel import mesh as pm
from go_libp2p_pubsub_tpu.parallel import sharded as ps
from go_libp2p_pubsub_tpu.parallel.mesh import (
    check_peer_divisible, shard_peer_tree)

N, T, M, TICKS, BLOCK = 512, 4, 8, 10, 64


def _scenario(seed=0):
    rng = np.random.default_rng(seed)
    subs = np.zeros((N, T), dtype=bool)
    subs[np.arange(N), np.arange(N) % T] = True
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, N // T, M) * T + topic
    tick0 = np.sort(rng.integers(0, 6, M)).astype(np.int32)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T, 16, N, seed=7), n_topics=T)
    return cfg, subs, topic, origin, tick0


def _faults():
    return FaultSchedule(
        n_peers=N, horizon=TICKS, drop_prob=0.05, seed=5,
        down_intervals=tuple((int(p), 2, 5) for p in range(0, N, 41)))


def _trees_equal(a, b):
    import jax
    fa, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, a))
    fb, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, b))
    assert len(fa) == len(fb)
    return all(np.array_equal(x, y) for x, y in zip(fa, fb))


# -- XLA path: everything on -----------------------------------------------

# The armed scenario (delays + faults + sybil ihave-spam) and its
# single-device references are module-cached: every D parametrization
# reuses ONE reference compile+run and ONE step object, so each extra
# device count only pays its own sharded executable (tier-1 budget).

@functools.lru_cache(maxsize=None)
def _armed():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig(sybil_ihave_spam=True)
    sybil = (np.arange(N) % 37 == 0)
    tcfg = tl.TelemetryConfig(
        counters=False, wire=False, mesh=False, scores=False,
        faults=False, latency_hist=True, latency_buckets=TICKS)

    def build():
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            delays=DelayConfig(base=2, jitter=1, k_slots=4),
            fault_schedule=_faults(), sybil=sybil,
            track_first_tick=False)

    tel_step = gs.make_gossip_step(cfg, sc, telemetry=tcfg)
    run_step = gs.make_gossip_step(cfg, sc)
    return build, tel_step, run_step


@functools.lru_cache(maxsize=None)
def _armed_tel_ref():
    build, tel_step, _ = _armed()
    params, state = build()
    s_ref, fr_ref = tl.telemetry_run(params, state, TICKS, tel_step)
    return s_ref, np.asarray(tl.frames_to_arrays(fr_ref)["latency_hist"])


@functools.lru_cache(maxsize=None)
def _armed_run_ref():
    build, _, run_step = _armed()
    params, state = build()
    return gs.gossip_run(params, state, TICKS, run_step)


@pytest.mark.parametrize("D", [2, 4, 8])
@pytest.mark.slow
def test_xla_everything_on_bit_identity(D):
    """GSPMD placement + telemetry_run: delays + faults + sybil
    ihave-spam + latency-hist telemetry, state AND frames identical."""
    build, tel_step, _ = _armed()
    s_ref, h_ref = _armed_tel_ref()

    mesh = pm.make_mesh(D)
    params, state = build()
    params_s, state_s, _ = ps.shard_sim(params, state, mesh, N)
    s_D, fr_D = tl.telemetry_run(params_s, state_s, TICKS, tel_step)
    assert _trees_equal(s_ref, s_D)
    assert np.array_equal(
        h_ref, np.asarray(tl.frames_to_arrays(fr_D)["latency_hist"]))


@pytest.mark.parametrize("D", [2, 4, 8])
@pytest.mark.slow
def test_xla_pinned_runner_bit_identity(D):
    """The carry-pinned sharded_gossip_run (with_sharding_constraint
    every tick) against single-device gossip_run — delays + faults +
    attacks, no telemetry."""
    build, _, run_step = _armed()
    s_ref = _armed_run_ref()

    mesh = pm.make_mesh(D)
    params, state = build()
    params_s, state_s, shardings = ps.shard_sim(params, state, mesh, N)
    s_D = ps.sharded_gossip_run(params_s, state_s, TICKS, run_step,
                                shardings)
    assert _trees_equal(s_ref, s_D)


# -- pallas kernel path under shard_map ------------------------------------

@functools.lru_cache(maxsize=None)
def _kernel_tel_parts():
    blk = 128
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()
    tcfg = tl.TelemetryConfig()

    def build():
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            fault_schedule=_faults(), track_first_tick=False,
            pad_to_block=blk)

    step1 = gs.make_gossip_step(cfg, sc, receive_block=blk,
                                receive_interpret=True, telemetry=tcfg)
    params, state = build()
    s_ref, fr_ref = tl.telemetry_run(params, state, TICKS, step1)
    return blk, cfg, sc, tcfg, build, s_ref, fr_ref


@pytest.mark.parametrize(
    "D", [2, pytest.param(4, marks=pytest.mark.slow)])
@pytest.mark.slow
def test_kernel_faults_telemetry_bit_identity(D):
    """shard_map kernel dispatch (ring-halo ppermutes + telemetry
    psum) with faults on: identical to the single-device kernel.
    block=128, not the usual 64: the in-kernel telemetry fold tallies
    into 128 lanes, so the telemetry kernel needs blocks >= 128 (a
    pre-existing kernel-path constraint, not a sharding one)."""
    blk, cfg, sc, tcfg, build, s_ref, fr_ref = _kernel_tel_parts()

    mesh = pm.make_mesh(D)
    stepD = gs.make_gossip_step(cfg, sc, receive_block=blk,
                                receive_interpret=True,
                                shard_mesh=mesh, telemetry=tcfg)
    params, state = build()
    params_s, state_s, _ = ps.shard_sim(params, state, mesh, N,
                                        block=blk)
    s_D, fr_D = tl.telemetry_run(params_s, state_s, TICKS, stepD)
    assert _trees_equal(s_ref, s_D)
    ref, dev = tl.frames_to_arrays(fr_ref), tl.frames_to_arrays(fr_D)
    assert set(ref) == set(dev)
    for k in ref:
        a, b = np.asarray(ref[k]), np.asarray(dev[k])
        if np.issubdtype(a.dtype, np.floating):
            # float SUMMARIES (score_mean & co) reduce over the peer
            # axis in shard order — last-ULP tolerance; the integer
            # tallies and the state trajectory itself stay exact
            assert np.allclose(a, b, rtol=1e-6, atol=0), k
        else:
            assert np.array_equal(a, b), k


@functools.lru_cache(maxsize=None)
def _kernel_delay_parts():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()

    def build():
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3, score_cfg=sc,
            delays=DelayConfig(base=2, jitter=1, k_slots=4),
            fault_schedule=_faults(), track_first_tick=False,
            pad_to_block=BLOCK)

    step1 = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                                receive_interpret=True)
    params, state = build()
    s_ref = gs.gossip_run(params, state, TICKS, step1)
    return cfg, sc, build, s_ref


@pytest.mark.parametrize(
    "D", [2, pytest.param(4, marks=pytest.mark.slow)])
@pytest.mark.slow
def test_kernel_delays_bit_identity(D):
    """The round-14 lift: delays x sharded kernel (previously a named
    refusal).  The delay-mode kernel has no sender streams, so the
    sharded dispatch needs no halo — per-receiver blocked operands
    only — and stays bit-identical, faults included."""
    cfg, sc, build, s_ref = _kernel_delay_parts()

    mesh = pm.make_mesh(D)
    stepD = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                                receive_interpret=True,
                                shard_mesh=mesh)
    params, state = build()
    params_s, state_s, shardings = ps.shard_sim(params, state, mesh,
                                                N, block=BLOCK)
    s_D = ps.sharded_gossip_run(params_s, state_s, TICKS, stepD,
                                shardings)
    assert _trees_equal(s_ref, s_D)


# -- fused x sharded (round 17): resident windows with in-kernel halo ------

@pytest.mark.parametrize(
    "D", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_fused_sharded_resident_bit_identity(D):
    """The round-17 lift: fused windows x sharded dispatch (previously
    a named refusal) — the in-kernel remote-DMA halo keeps the
    per-shard carry VMEM-resident across the window, and the composed
    trajectory equals the single-device per-tick XLA step bit for bit,
    faults included.  Note the composition also EXTENDS coverage: at
    N=512 the single-device fused window is refused (n % 1024), but
    the per-shard tile constraint (S % 128) admits D in {2, 4}."""
    cfg, subs, topic, origin, tick0 = _scenario()

    def build():
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=3,
            fault_schedule=_faults(), track_first_tick=False,
            pad_to_block=BLOCK)

    step1 = gs.make_gossip_step(cfg, None, receive_block=BLOCK,
                                receive_interpret=True)
    params, state = build()
    s_ref = gs.gossip_run(params, state, 8, step1)

    mesh = pm.make_mesh(D)
    win = gs.make_fused_window(cfg, None, ticks_fused=4,
                               receive_block=BLOCK,
                               receive_interpret=True,
                               shard_mesh=mesh, on_refusal="raise")
    params, state = build()
    params_s, state_s, shardings = ps.shard_sim(params, state, mesh, N)
    assert win.capability(params_s, state_s) is None
    s_D = ps.sharded_gossip_run_fused(params_s, state_s, 8, win,
                                      shardings)
    assert _trees_equal(s_ref, s_D)


# -- batched over seeds -----------------------------------------------------

@pytest.mark.slow
def test_knob_batch_over_seeds_bit_identity():
    """sweepd's device side on the mesh: B seed-replicas stacked on a
    leading axis, peer axis still sharded, one carry-pinned scan of
    the vmapped step — states and reach identical to the
    single-device knob-batch runner."""
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()

    def build():
        builds = [gs.make_gossip_sim(
            cfg, subs, topic, origin, tick0, seed=r, score_cfg=sc,
            fault_schedule=_faults(), sim_knobs={}, track_first_tick=False)
            for r in range(3)]
        return (gs.stack_trees([b[0] for b in builds]),
                gs.stack_trees([b[1] for b in builds]))

    step = gs.make_gossip_step(cfg, sc)
    params, state = build()
    s_ref, r_ref = gs.gossip_run_knob_batch(params, state, TICKS, step)

    mesh = pm.make_mesh(4)
    params, state = build()
    params_s, state_s, shardings = ps.shard_sim(params, state, mesh, N)
    s_D, r_D = ps.sharded_gossip_run_knob_batch(params_s, state_s,
                                                TICKS, step, shardings)
    assert _trees_equal(s_ref, s_D)
    assert np.array_equal(np.asarray(r_ref), np.asarray(r_D))


def test_curve_runner_bit_identity():
    cfg, subs, topic, origin, tick0 = _scenario()
    sc = gs.ScoreSimConfig()

    def build():
        return gs.make_gossip_sim(cfg, subs, topic, origin, tick0,
                                  seed=3, score_cfg=sc,
                                  track_first_tick=False)

    step = gs.make_gossip_step(cfg, sc)
    params, state = build()
    s_ref, c_ref = gs.gossip_run_curve(params, state, TICKS, step, M)

    mesh = pm.make_mesh(8)
    params, state = build()
    params_s, state_s, shardings = ps.shard_sim(params, state, mesh, N)
    s_D, c_D = ps.sharded_gossip_run_curve(params_s, state_s, TICKS,
                                           step, shardings, M)
    assert _trees_equal(s_ref, s_D)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c_D))


# -- placement rule + hardening --------------------------------------------

def test_peer_spec_square_matrix_picks_last_axis():
    """[N, N] arrays shard the trailing (receiver) axis, matching the
    kernel's per-receiver blocking; [N] shards axis 0; peer-free
    shapes replicate."""
    from jax.sharding import PartitionSpec as P
    assert ps.peer_spec((N, N), N) == P(None, pm.PEER_AXIS)
    assert ps.peer_spec((3, N, N), N) == P(None, None, pm.PEER_AXIS)
    assert ps.peer_spec((N,), N) == P(pm.PEER_AXIS)
    assert ps.peer_spec((N, 7), N) == P(pm.PEER_AXIS, None)
    assert ps.peer_spec((3, 5), N) == P()


def test_shard_peer_tree_square_matrix_shards_receiver_axis():
    import jax
    mesh = pm.make_mesh(8)
    arr = shard_peer_tree(np.arange(16 * 16).reshape(16, 16), mesh, 16)
    spans = sorted(
        (s.index[1].start or 0, s.data.shape) for s in
        jax.device_put(arr, arr.sharding).addressable_shards)
    assert [sp[0] for sp in spans] == [k * 2 for k in range(8)]
    assert all(sp[1] == (16, 2) for sp in spans)


def test_check_peer_divisible_named_errors():
    mesh = pm.make_mesh(4)
    assert check_peer_divisible(N, mesh) == 4
    assert check_peer_divisible(N, mesh, block=BLOCK) == 4
    with pytest.raises(ValueError, match="divide evenly over the"):
        check_peer_divisible(N - 2, mesh)
    with pytest.raises(ValueError, match="whole receive blocks"):
        check_peer_divisible(N, mesh, block=96)


def test_shard_sim_refuses_indivisible():
    cfg, subs, topic, origin, tick0 = _scenario()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                       tick0, track_first_tick=False)
    mesh = pm.make_mesh(4)
    with pytest.raises(ValueError, match="whole receive blocks"):
        ps.shard_sim(params, state, mesh, N, block=96)


# -- collective accounting --------------------------------------------------

def test_collective_stats_parses_hlo():
    hlo = """
  %x = u32[16,125]{1,0} collective-permute(%a), source_target_pairs=...
  %y = (f32[8]{0}, f32[8]{0}) all-reduce-start(%b, %c), replica_groups=...
  %z = s32[4,2]{1,0} all-gather(%d), dimensions={1}
"""
    st = ps.collective_stats(hlo)
    assert st["collective-permute"] == {"count": 1, "bytes": 16 * 125 * 4}
    assert st["all-reduce"] == {"count": 1, "bytes": 2 * 8 * 4}
    assert st["all-gather"] == {"count": 1, "bytes": 4 * 2 * 4}
    assert st["total_bytes"] == 16 * 125 * 4 + 64 + 32
    assert ps.collective_stats("%r = f32[2]{0} add(%a, %b)") == {
        "total_bytes": 0}
