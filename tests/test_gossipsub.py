"""GossipSub end-to-end and adversarial tests.

Mirrors the reference suite's core scenarios (/root/reference/
gossipsub_test.go, gossipsub_spam_test.go): mesh formation and delivery,
fanout, gossip recovery via IHAVE/IWANT, GRAFT/PRUNE handling including
unknown-topic hardening and IWANT-spam cutoff, peer exchange, mixed-protocol
networks, and RPC fragmentation.  The scripted wire-level adversary
(MockPeer) speaks raw protobuf frames like the reference's newMockGS."""

import asyncio
import random

import pytest

from go_libp2p_pubsub_tpu.core import (
    FLOODSUB_ID,
    GOSSIPSUB_ID_V11,
    GossipSubParams,
    InProcNetwork,
    create_floodsub,
    create_gossipsub,
    fragment_rpc,
)
from go_libp2p_pubsub_tpu.core.crypto import make_signed_record
from go_libp2p_pubsub_tpu.pb import (
    RPC,
    ControlGraft,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    PeerInfo,
    PubMessage,
    SubOpts,
)
from go_libp2p_pubsub_tpu.pb.proto import write_delimited
from helpers import (connect, connect_all, dense_connect, get_hosts, settle,
                     settle_until)

def fast_params(**kw):
    p = GossipSubParams(heartbeat_initial_delay=0.01, heartbeat_interval=0.05)
    for k, v in kw.items():
        setattr(p, k, v)
    return p


async def make_gossipsubs(hosts, params_factory=fast_params, **kwargs):
    out = []
    for i, h in enumerate(hosts):
        ps = await create_gossipsub(
            h, router_rng=random.Random(1000 + i),
            gossipsub_params=params_factory(), **kwargs)
        out.append(ps)
    return out


async def close_all(pubsubs, net):
    for ps in pubsubs:
        await ps.close()
    await net.close()


class MockPeer:
    """Scripted wire-level peer speaking the gossipsub protocol directly
    (reference gossipsub_spam_test.go:711-757)."""

    def __init__(self, net, protocol=GOSSIPSUB_ID_V11, refuse_grafts=False):
        self.host = net.new_host()
        self.protocol = protocol
        self.received: list[RPC] = []
        self.refuse_grafts = refuse_grafts
        self.host.set_stream_handler(protocol, self._reader)
        self._stream = None

    async def _reader(self, stream):
        try:
            while True:
                size = await stream.read_uvarint()
                frame = await stream.read_exact(size)
                rpc = RPC.decode(frame)
                self.received.append(rpc)
                if (self.refuse_grafts and rpc.control is not None
                        and rpc.control.graft and self._stream is not None):
                    # stay out of the mesh: answer every GRAFT with PRUNE
                    self.send(RPC(control=ControlMessage(prune=[
                        ControlPrune(topic_id=g.topic_id, backoff=1)
                        for g in rpc.control.graft])))
        except Exception:
            pass

    async def connect_and_open(self, target_host):
        await self.host.connect(target_host)
        await asyncio.sleep(0.05)
        self._stream = await self.host.new_stream(target_host.id, [self.protocol])
        return self._stream

    def send(self, rpc: RPC) -> None:
        self._stream.write(write_delimited(rpc))

    def control_msgs(self, kind: str):
        out = []
        for rpc in self.received:
            if rpc.control is not None:
                out.extend(getattr(rpc.control, kind))
        return out

    def messages(self):
        return [m for rpc in self.received for m in rpc.publish]


async def test_gossipsub_basic_delivery():
    net = InProcNetwork()
    hosts = get_hosts(net, 20)
    psubs = await make_gossipsubs(hosts)
    subs = []
    for ps in psubs:
        topic = await ps.join("foobar")
        subs.append(await topic.subscribe())
    await dense_connect(hosts)
    await settle(0.4)  # several heartbeats: let meshes form

    for i in (0, 7, 13):
        data = f"gossip payload {i}".encode()
        t = await psubs[i].join("foobar")
        await t.publish(data)
        for sub in subs:
            msg = await asyncio.wait_for(sub.next(), 5)
            assert msg.data == data
    await close_all(psubs, net)


async def test_mesh_degree_bounds():
    net = InProcNetwork()
    hosts = get_hosts(net, 20)
    psubs = await make_gossipsubs(hosts)
    for ps in psubs:
        topic = await ps.join("mesh-topic")
        await topic.subscribe()
    await connect_all(hosts)

    def converged():
        for ps in psubs:
            mesh = ps.router.mesh.get("mesh-topic", set())
            if not (ps.router.params.d_lo <= len(mesh)
                    <= ps.router.params.d_hi):
                return False
        return True

    # Heartbeats fire late under suite load; poll for convergence instead
    # of a fixed sleep.  20-host meshes have been observed to need >8s
    # of wall clock on a loaded machine (the poll returns as soon as
    # the meshes settle, so the generous ceiling costs nothing when
    # the box is idle).
    await settle_until(converged, timeout=30.0)
    for ps in psubs:
        mesh = ps.router.mesh.get("mesh-topic", set())
        assert len(mesh) >= ps.router.params.d_lo
        assert len(mesh) <= ps.router.params.d_hi
    await close_all(psubs, net)


async def test_fanout_publish_without_join():
    net = InProcNetwork()
    hosts = get_hosts(net, 8)
    psubs = await make_gossipsubs(hosts)
    subs = []
    for ps in psubs[1:]:
        topic = await ps.join("news")
        subs.append(await topic.subscribe())
    await connect_all(hosts)
    await settle(0.3)

    # host 0 publishes without subscribing: fanout path
    t0 = await psubs[0].join("news")
    await t0.publish(b"fanout delivery")
    for sub in subs:
        msg = await asyncio.wait_for(sub.next(), 5)
        assert msg.data == b"fanout delivery"
    assert "news" in psubs[0].router.fanout
    assert "news" not in psubs[0].router.mesh

    # subscribing converts fanout into mesh
    await t0.subscribe()
    await settle(0.2)
    assert "news" not in psubs[0].router.fanout
    assert "news" in psubs[0].router.mesh
    await close_all(psubs, net)


async def test_gossip_ihave_iwant_recovery():
    # a non-mesh subscriber recovers a message via IHAVE -> IWANT
    net = InProcNetwork()
    hosts = get_hosts(net, 3)
    psubs = await make_gossipsubs(hosts)
    topics = [await ps.join("g") for ps in psubs]
    for t in topics:
        await t.subscribe()
    await connect_all(hosts)
    await settle(0.3)

    mock = MockPeer(net, refuse_grafts=True)
    await mock.connect_and_open(hosts[0])
    # announce subscription but refuse GRAFTs: mock stays out of the mesh
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid="g")]))
    await settle(0.2)

    # publish fresh messages until an IHAVE for topic g arrives
    ihaves = []
    for i in range(30):
        await topics[1].publish(b"gossiped message")
        await settle(0.1)
        ihaves = [ih for ih in mock.control_msgs("ihave") if ih.topic_id == "g"]
        if ihaves:
            break
    assert ihaves, "mock never received IHAVE gossip"

    # ask for it and receive the full message
    mids = ihaves[0].message_ids
    mock.send(RPC(control=ControlMessage(iwant=[ControlIWant(message_ids=list(mids))])))
    for _ in range(20):
        await settle(0.05)
        if mock.messages():
            break
    msgs = mock.messages()
    assert msgs and msgs[0].data == b"gossiped message"
    await close_all(psubs, net)


async def test_graft_unknown_topic_gets_prune_without_px():
    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    psubs = await make_gossipsubs(hosts, do_px=True)
    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(control=ControlMessage(graft=[ControlGraft(topic_id="nope")])))
    await settle(0.3)
    # spam hardening: GRAFT for unknown topic is ignored entirely
    assert not mock.control_msgs("prune")
    await close_all(psubs, net)


async def test_graft_gets_pruned_when_not_subscribed_backoff():
    # GRAFT into a topic the router joined, then GRAFT again during backoff
    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    psubs = await make_gossipsubs(hosts)
    topic = await psubs[0].join("t")
    await topic.subscribe()
    await settle(0.1)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid="t")]))
    await settle(0.1)
    # legit graft: accepted into mesh
    mock.send(RPC(control=ControlMessage(graft=[ControlGraft(topic_id="t")])))
    await settle(0.2)
    assert mock.host.id in psubs[0].router.mesh["t"]
    await close_all(psubs, net)


async def test_iwant_spam_cutoff():
    # after GossipRetransmission requests for the same message id, the
    # router stops responding (reference gossipsub_spam_test.go:24)
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    # slower heartbeat so the message stays in the cache window while the
    # spam loop runs (history shifts once per heartbeat)
    psubs = await make_gossipsubs(
        hosts, params_factory=lambda: fast_params(heartbeat_interval=0.5))
    topics = [await ps.join("s") for ps in psubs]
    subs = [await t.subscribe() for t in topics]
    await connect(hosts[0], hosts[1])
    await settle(0.2)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid="s")]))
    await settle(0.1)

    await topics[0].publish(b"wanted")
    await settle(0.1)
    mid = psubs[0].msg_id(
        [m for m in psubs[0].router.mcache.msgs.values()][0])

    got = 0
    for i in range(6):
        before = len(mock.messages())
        mock.send(RPC(control=ControlMessage(
            iwant=[ControlIWant(message_ids=[mid])])))
        await settle(0.15)
        if len(mock.messages()) > before:
            got += 1
    # 3 retransmissions allowed (GossipRetransmission), then cutoff
    assert got == psubs[0].router.params.gossip_retransmission
    await close_all(psubs, net)


async def test_px_connects_to_exchanged_peer():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)  # host0 = victim, host1 = PX target
    psubs = await make_gossipsubs(hosts)
    t0 = await psubs[0].join("px")
    await t0.subscribe()
    await settle(0.1)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid="px")]))
    mock.send(RPC(control=ControlMessage(graft=[ControlGraft(topic_id="px")])))
    await settle(0.2)
    assert not hosts[0].connectedness(hosts[1].id)

    # mock prunes us, handing over host1 via PX with a valid signed record
    record = make_signed_record(hosts[1].key)
    mock.send(RPC(control=ControlMessage(prune=[ControlPrune(
        topic_id="px",
        peers=[PeerInfo(peer_id=bytes(hosts[1].id), signed_peer_record=record)],
        backoff=1)])))
    for _ in range(20):
        await settle(0.05)
        if hosts[0].connectedness(hosts[1].id):
            break
    assert hosts[0].connectedness(hosts[1].id)
    await close_all(psubs, net)


async def test_px_rejects_bogus_record():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_gossipsubs(hosts)
    t0 = await psubs[0].join("px")
    await t0.subscribe()
    await settle(0.1)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid="px")]))
    await settle(0.1)
    # signed record from the WRONG key (mock's own) claiming host1's ID
    bogus = make_signed_record(mock.host.key)
    mock.send(RPC(control=ControlMessage(prune=[ControlPrune(
        topic_id="px",
        peers=[PeerInfo(peer_id=bytes(hosts[1].id), signed_peer_record=bogus)],
        backoff=1)])))
    await settle(0.4)
    assert not hosts[0].connectedness(hosts[1].id)
    await close_all(psubs, net)


async def test_mixed_floodsub_gossipsub():
    # floodsub peers interoperate: gossipsub always floods to them
    net = InProcNetwork()
    hosts = get_hosts(net, 4)
    gs = await make_gossipsubs(hosts[:3])
    fs = await create_floodsub(hosts[3])
    psubs = gs + [fs]
    subs = []
    for ps in psubs:
        topic = await ps.join("mixed")
        subs.append(await topic.subscribe())
    await connect_all(hosts)
    await settle(0.4)

    t = await psubs[0].join("mixed")
    await t.publish(b"to everyone")
    for sub in subs:
        msg = await asyncio.wait_for(sub.next(), 5)
        assert msg.data == b"to everyone"
    # the floodsub peer speaks /floodsub/1.0.0 to the gossipsub node
    assert gs[0].router.peers[hosts[3].id] == FLOODSUB_ID
    await close_all(psubs, net)


def test_fragment_rpc_unit():
    limit = 1 << 10
    big = RPC(
        publish=[PubMessage(data=bytes([i]) * 300, topic="frag") for i in range(8)],
        control=ControlMessage(
            ihave=[ControlIHave(topic_id="frag",
                                message_ids=[bytes([i, j]) * 8 for j in range(80)])
                   for i in range(3)],
            graft=[ControlGraft(topic_id="frag")],
        ),
    )
    frags = fragment_rpc(big, limit)
    assert len(frags) > 1
    for f in frags:
        assert f.byte_size() < limit
    # no payload lost
    all_msgs = [m.data for f in frags for m in f.publish]
    assert all_msgs == [m.data for m in big.publish]
    all_ihave_ids = [mid for f in frags if f.control
                     for ih in f.control.ihave for mid in ih.message_ids]
    orig_ids = [mid for ih in big.control.ihave for mid in ih.message_ids]
    assert sorted(all_ihave_ids) == sorted(orig_ids)
    grafts = [g for f in frags if f.control for g in f.control.graft]
    assert len(grafts) == 1


def test_fragment_oversize_single_message_errors():
    limit = 1 << 10
    big = RPC(publish=[PubMessage(data=b"x" * 2048, topic="frag")])
    with pytest.raises(ValueError):
        fragment_rpc(big, limit)


def test_gossipsub_params_validation():
    with pytest.raises(ValueError):
        GossipSubParams(d=20).validate()  # D > Dhi
    with pytest.raises(ValueError):
        GossipSubParams(d_out=5).validate()  # Dout >= Dlo
    with pytest.raises(ValueError):
        GossipSubParams(history_gossip=9, history_length=5).validate()


async def test_direct_peers_always_receive():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    # mutual direct peering: always forward, never mesh
    ps0 = await create_gossipsub(hosts[0], router_rng=random.Random(1),
                                 gossipsub_params=fast_params(),
                                 direct_peers=[hosts[1].id])
    ps1 = await create_gossipsub(hosts[1], router_rng=random.Random(2),
                                 gossipsub_params=fast_params(),
                                 direct_peers=[hosts[0].id])
    t0 = await ps0.join("d")
    await t0.subscribe()
    t1 = await ps1.join("d")
    sub1 = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.3)

    await t0.publish(b"direct delivery")
    msg = await asyncio.wait_for(sub1.next(), 5)
    assert msg.data == b"direct delivery"
    # direct peers never enter the mesh
    assert hosts[1].id not in ps0.router.mesh.get("d", set())
    assert hosts[0].id not in ps1.router.mesh.get("d", set())
    await close_all([ps0, ps1], net)


async def test_flood_publish_reaches_all_topic_peers():
    net = InProcNetwork()
    hosts = get_hosts(net, 10)
    psubs = await make_gossipsubs(hosts, flood_publish=True)
    subs = []
    for ps in psubs[1:]:
        topic = await ps.join("f")
        subs.append(await topic.subscribe())
    await connect_all(hosts)
    await settle(0.1)  # do NOT wait for mesh formation

    # flood publish sends to ALL topic peers immediately, mesh or not
    t0 = await psubs[0].join("f")
    await t0.publish(b"flooded")
    for sub in subs:
        msg = await asyncio.wait_for(sub.next(), 5)
        assert msg.data == b"flooded"
    await close_all(psubs, net)
