"""Round-18 fault-tolerant multi-tenant serving
(go_libp2p_pubsub_tpu/serving + the tools/sweepd.py capability lift).

The front end's contracts, each pinned:

* shape bucketing — requests quantize UP into a bounded bucket-spec
  set; the compile counter equals the number of DISTINCT traced
  bucket shapes, and LRU eviction + rebuild adds zero (the jit cache
  is process-global, step closures memoized by identity);
* request lifecycle — admission past the queue cap is an EXPLICIT
  ``overloaded`` rejection row, expired deadlines are named timeout
  rows, transient dispatch failures retry with exponential backoff
  and then fail with named rows: every admitted request ends in
  exactly one terminal row (the no-silent-drop accounting identity);
* crash hardening — CRC'd journal lines survive a torn tail (the
  mid-append kill) on both sweepd and the front end; an interrupted
  LONG scenario parks in the journal and a restarted server resumes
  it from its snapshot to the BIT-IDENTICAL digest;
* AOT executables — a bucket's batched dispatch round-trips through
  jax.export serialization and serves bit-identical rows with zero
  jit-cache growth;
* capability dispatch — the kernel-path/--devices and kernel-path/
  batch>1 combinations are refused BY NAME through
  ``server_capability`` (the sweepd face of ``kernel_capability``),
  and an unarmed server names ``--k-slots`` when refusing delay
  knobs.
"""

import io
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
from go_libp2p_pubsub_tpu.serving import (
    BucketLRU, BucketSpec, FrontendConfig, ScenarioFrontend,
    quantize_shape)
from tools.sweepd import SweepServer, server_capability

#: one tiny serving shape shared by the fast tests (the trace is paid
#: once per (spec, batch, server_kw) triple — distinct seeds below
#: keep per-test compile counting honest)
TINY = {"n": 64, "t": 2, "m": 4, "ticks": 8}


def _cfg(seed, **kw):
    kw.setdefault("batch", 2)
    kw.setdefault("max_buckets", 4)
    kw.setdefault("default_shape", (64, 2, 4, 8))
    kw.setdefault("server_kw", {"seed": seed})
    return FrontendConfig(**kw)


def _req(i, seed=0, **kw):
    r = dict(TINY, id=f"r{i}", seed=seed)
    r.update(kw)
    return r


# -- bucket quantization / LRU ---------------------------------------------


def test_quantize_shape_rounds_up_only():
    spec = quantize_shape(200, 3, 5, 13)
    assert spec == BucketSpec(n=256, t=4, m=8, ticks=16)
    # floors: tiny requests still get a workable sim
    assert quantize_shape(1, 1, 1, 1) == BucketSpec(64, 1, 1, 8)
    # a request never lands in a smaller bucket than itself
    for n, t, m, ticks in ((64, 2, 4, 8), (65, 2, 4, 9), (1000, 7, 9, 33)):
        s = quantize_shape(n, t, m, ticks)
        assert s.n >= n and s.t >= t and s.m >= m and s.ticks >= ticks
    assert quantize_shape(64, 2, 4, 8, 5).k_slots == 8
    assert quantize_shape(64, 2, 4, 8, tick_quantum=16).ticks == 16


@pytest.mark.parametrize("bad", [
    {"n": 0}, {"t": -1}, {"m": "x"}, {"ticks": 1.5}, {"n": True},
    {"k_slots": -1},
])
def test_quantize_shape_rejects_by_name(bad):
    kw = dict(n=64, t=2, m=4, ticks=8)
    kw.update(bad)
    with pytest.raises(ValueError, match="shape:"):
        quantize_shape(**kw)


def test_bucket_lru_eviction_order():
    lru = BucketLRU(2)
    a, b, c = (BucketSpec(64, 1, 1, 8), BucketSpec(128, 1, 1, 8),
               BucketSpec(256, 1, 1, 8))
    assert lru.put(a, "A") == [] and lru.put(b, "B") == []
    assert lru.get(a) == "A"          # refreshes a's recency
    evicted = lru.put(c, "C")         # b is now the LRU
    assert evicted == [(b, "B")] and lru.evictions == 1
    assert lru.specs() == [a, c] and lru.get(b) is None
    with pytest.raises(ValueError, match="max_buckets"):
        BucketLRU(0)


# -- capability dispatch (satellite: the --devices lift) -------------------


def test_server_capability_refusals_by_name():
    assert server_capability() is None
    assert server_capability(kernel=True, batch=1) is None
    assert server_capability(batch=4, devices=2) is None
    assert "use batch=1" in server_capability(kernel=True, batch=4)
    assert ("sequential demonstration"
            in server_capability(kernel=True, batch=1, devices=2))


def test_sweepd_kernel_devices_refused_by_name():
    """The constructor raises server_capability's reason VERBATIM —
    the string graftlint's probe-refusal registry pins."""
    with pytest.raises(ValueError,
                       match="sequential demonstration"):
        SweepServer(n=64, t=2, m=4, ticks=8, batch=1, kernel=True,
                    devices=2)
    with pytest.raises(ValueError, match="use batch=1"):
        SweepServer(n=64, t=2, m=4, ticks=8, batch=4, kernel=True)


def test_sweepd_cli_multi_refuses_kernel_by_name(capsys):
    """``--multi --kernel`` is a clean exit 2 with the same named
    reason, before any jax work."""
    import tools.sweepd as sweepd
    assert sweepd.main(["--multi", "--kernel"]) == 2
    assert "sequential demonstration" in capsys.readouterr().err


# -- front-end config validation -------------------------------------------


def test_frontend_config_validated_by_name():
    with pytest.raises(ValueError, match="batch=1 is sweepd's"):
        FrontendConfig(batch=1)
    with pytest.raises(ValueError, match="needs ckpt_dir"):
        FrontendConfig(long_ticks=8)


# -- admission: overload, deadlines, bad requests --------------------------


def test_overload_rejection_rows_are_explicit(monkeypatch):
    fe = ScenarioFrontend(_cfg(seed=101, queue_cap=2))
    monkeypatch.setattr(SweepServer, "submit",
                        lambda self, reqs: [{"id": r.get("id"),
                                             "ok": True}
                                            for r in reqs])
    rej = [fe.admit(_req(i)) for i in range(4)]
    assert rej[0] is None and rej[1] is None
    for row in rej[2:]:
        assert row["overloaded"] and not row["ok"]
        assert "rejected explicitly" in row["error"]
    assert fe.rejected_overload == 2 and fe.admitted == 2
    rows = fe.drain()
    assert [r["ok"] for r in rows] == [True, True]
    # the accounting identity: nothing silently dropped
    st = fe.stats()
    assert st["admitted"] == (st["served"] + st["errors"]
                              + st["timeouts"]
                              + st["transient_failures"]
                              + st["queued"] + st["parked"])


def test_deadline_cull_emits_named_timeout_rows():
    fe = ScenarioFrontend(_cfg(seed=102))
    t0 = time.monotonic()
    assert fe.admit(_req(0, deadline_s=0.5), now=t0) is None
    assert fe.admit(_req(1), now=t0) is None          # no deadline
    rows = fe.dispatch_ready(now=t0 + 5.0)
    assert len(rows) == 1 and rows[0]["timeout"]
    assert "deadline exceeded" in rows[0]["error"]
    assert "deadline_s=0.5" in rows[0]["error"]
    assert fe.timeouts == 1 and fe.queued() == 1


def test_bad_requests_come_back_as_error_rows():
    fe = ScenarioFrontend(_cfg(seed=103))
    row = fe.admit([1, 2])
    assert not row["ok"] and "JSON object" in row["error"]
    row = fe.admit(_req(0, n=-5))
    assert not row["ok"] and "positive integer" in row["error"]
    assert fe.errors == 2 and fe.admitted == 0


def test_priority_dispatches_first(monkeypatch):
    fe = ScenarioFrontend(_cfg(seed=104))
    monkeypatch.setattr(SweepServer, "submit",
                        lambda self, reqs: [{"id": r.get("id"),
                                             "ok": True}
                                            for r in reqs])
    fe.admit(_req(0))
    fe.admit(_req(1, priority=5))
    fe.admit(_req(2, priority=5))
    rows = fe.drain()
    assert [r["id"] for r in rows] == ["r1", "r2", "r0"]


# -- bounded retry / transient failure rows --------------------------------


def test_transient_failures_retry_with_backoff(monkeypatch):
    fe = ScenarioFrontend(_cfg(seed=105, max_retries=2,
                               backoff_base_s=0.001))
    calls = {"n": 0}

    def flaky(self, reqs):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("device briefly gone")
        return [{"id": r.get("id"), "ok": True} for r in reqs]
    monkeypatch.setattr(SweepServer, "submit", flaky)
    fe.admit(_req(0))
    fe.admit(_req(1))
    rows = fe.drain()
    assert all(r["ok"] for r in rows) and calls["n"] == 3
    assert fe.retries == 2 and fe.transient_failures == 0


def test_transient_failure_terminal_rows_after_retries(monkeypatch):
    fe = ScenarioFrontend(_cfg(seed=106, max_retries=1,
                               backoff_base_s=0.001))

    def dead(self, reqs):
        raise RuntimeError("device gone for good")
    monkeypatch.setattr(SweepServer, "submit", dead)
    fe.admit(_req(0))
    fe.admit(_req(1))
    rows = fe.drain()
    assert len(rows) == 2
    for r in rows:
        assert not r["ok"] and r["transient"]
        assert "after 2 attempts" in r["error"]
    assert fe.transient_failures == 2 and fe.retries == 1
    st = fe.stats()
    assert st["admitted"] == 2 and st["served"] == 2  # terminal rows


def test_validation_errors_never_retry(monkeypatch):
    fe = ScenarioFrontend(_cfg(seed=107, max_retries=5))
    calls = {"n": 0}

    def reject(self, reqs):
        calls["n"] += 1
        raise ValueError("scenario: unknown field(s) ['bogus']")
    monkeypatch.setattr(SweepServer, "submit", reject)
    fe.admit(_req(0, bogus=1))
    fe.admit(_req(1, bogus=1))
    rows = fe.drain()
    assert calls["n"] == 1            # terminal on the first attempt
    assert all("unknown field" in r["error"] for r in rows)
    assert fe.errors == 2 and fe.retries == 0


# -- journal CRC helpers + torn-tail replay --------------------------------


def test_journal_codec_roundtrip_and_torn_detection():
    raw = json.dumps({"id": "x", "seed": 3})
    enc = ck.journal_encode_line(raw)
    assert ck.journal_decode_line(enc) == raw
    # torn inside the suffix: the CRC (or its hex) fails
    assert ck.journal_decode_line(enc[:-1]) is None
    assert ck.journal_decode_line(enc[:-2] + "zz") is None
    # legacy (pre-round-18) journals have no CRC suffix: passthrough
    assert ck.journal_decode_line(raw) == raw
    with pytest.raises(ValueError, match="newline"):
        ck.journal_encode_line("two\nlines")


def test_read_journal_drops_torn_tail_keeps_intact(tmp_path):
    p = tmp_path / "j"
    lines = [json.dumps({"id": f"s{i}"}) for i in range(3)]
    enc = [ck.journal_encode_line(x) for x in lines]
    p.write_text(enc[0] + "\n" + enc[1] + "\n" + enc[2][:-4])
    payloads, torn = ck.read_journal(str(p))
    assert payloads == lines[:2] and torn == 1
    # a tail cut BEFORE the separator: legacy-shaped, but the file's
    # other lines prove a CRC-aware writer — torn, not legacy
    p.write_text(enc[0] + "\n" + enc[1][: len(lines[1]) // 2])
    payloads, torn = ck.read_journal(str(p))
    assert payloads == lines[:1] and torn == 1
    # an all-legacy journal replays unchanged
    p.write_text("".join(x + "\n" for x in lines))
    assert ck.read_journal(str(p)) == (lines, 0)
    assert ck.read_journal(str(tmp_path / "missing")) == ([], 0)


def test_sweepd_replays_intact_lines_past_torn_tail(tmp_path, capsys,
                                                    monkeypatch):
    """A sweepd journal with a torn tail (the writer died mid-append)
    replays every intact line and names the drop on stderr instead of
    burning a bad-JSON error row."""
    monkeypatch.setattr(SweepServer, "submit",
                        lambda self, reqs: [{"id": r.get("id"),
                                             "ok": True}
                                            for r in reqs])
    journal = tmp_path / "sweepd.journal"
    raws = [json.dumps({"id": f"s{i}", "seed": i}) for i in range(2)]
    torn = ck.journal_encode_line(json.dumps({"id": "torn"}))[:-4]
    journal.write_text("".join(ck.journal_encode_line(r) + "\n"
                               for r in raws) + torn)
    srv = SweepServer(n=64, t=2, m=4, ticks=8, batch=2, seed=108)
    out = io.StringIO()
    srv.serve_lines([], out, journal=str(journal))
    err = capsys.readouterr().err
    assert "dropping 1 torn journal line(s)" in err
    rows = [json.loads(x) for x in out.getvalue().splitlines()]
    assert [r["id"] for r in rows if r.get("ok")] == ["s0", "s1"]
    assert not any("bad JSON" in str(r.get("error")) for r in rows)


def test_frontend_replays_intact_lines_past_torn_tail(tmp_path, capsys,
                                                      monkeypatch):
    monkeypatch.setattr(SweepServer, "submit",
                        lambda self, reqs: [{"id": r.get("id"),
                                             "ok": True}
                                            for r in reqs])
    journal = tmp_path / "serve.journal"
    raws = [json.dumps(_req(i)) for i in range(2)]
    torn = ck.journal_encode_line(json.dumps(_req(9)))[:-4]
    journal.write_text("".join(ck.journal_encode_line(r) + "\n"
                               for r in raws) + torn)
    fe = ScenarioFrontend(_cfg(seed=109))
    out = io.StringIO()
    fe.serve_lines([], out, journal=str(journal))
    err = capsys.readouterr().err
    assert "dropping 1 torn journal line(s)" in err
    rows = [json.loads(x) for x in out.getvalue().splitlines()]
    assert [r["id"] for r in rows if r.get("ok")] == ["r0", "r1"]
    stats = rows[-1]
    assert stats["stats"] and stats["admitted"] == 2
    # served, so the journal compacted to empty
    assert journal.read_text() == ""


# -- compile == buckets, eviction, delay-armed buckets ---------------------


def test_compile_count_equals_buckets_and_eviction_is_free():
    """Two distinct shapes -> two compiles; evicting one (max_buckets
    = 1) and re-serving it rebuilds the bucket WITHOUT a new compile
    (process-global jit cache + the step memo)."""
    fe = ScenarioFrontend(_cfg(seed=110, max_buckets=1))
    fe.admit(_req(0))
    fe.admit(_req(1))
    rows = fe.drain()
    fe.admit(_req(2, n=128))           # second shape evicts the first
    fe.admit(_req(3, n=128))
    rows += fe.drain()
    fe.admit(_req(4))                  # first shape again: rebuild
    fe.admit(_req(5))
    rows += fe.drain()
    assert all(r["ok"] for r in rows), rows
    st = fe.stats()
    assert st["compiles"] == st["traced_buckets"] == 2
    assert st["evictions"] == 2 and st["bucket_count"] == 1
    assert {r["bucket"] for r in rows} == {
        "n64-t2-m4-ticks8-k0", "n128-t2-m4-ticks8-k0"}


def test_delay_knobs_need_a_k_armed_bucket():
    """A request carrying delay knobs against a k_slots=0 bucket gets
    the named refusal row pointing at --k-slots; the same request
    with k_slots set routes to a delay-armed bucket and serves."""
    fe = ScenarioFrontend(_cfg(seed=111))
    fe.admit(_req(0, knobs={"delay_base": 2}))
    fe.admit(_req(1))
    rows = fe.drain()
    bad = next(r for r in rows if r["id"] == "r0")
    assert not bad["ok"] and "--k-slots" in bad["error"]
    fe.admit(_req(2, k_slots=4, knobs={"delay_base": 2}))
    fe.admit(_req(3, k_slots=4))
    rows = fe.drain()
    assert all(r["ok"] for r in rows), rows
    assert all(r["bucket"].endswith("-k4") for r in rows)


# -- AOT export/load -------------------------------------------------------


def test_aot_roundtrip_serves_bit_identical_rows(tmp_path):
    """Export on first build, load on the next: the AOT bucket serves
    the exact rows of the traced bucket with zero jit-cache growth
    and no traced buckets."""
    aot = str(tmp_path / "aot")
    fe1 = ScenarioFrontend(_cfg(seed=112, aot_dir=aot))
    fe1.admit(_req(0))
    fe1.admit(_req(1, knobs={"d": 3, "d_lo": 2, "d_hi": 6}))
    ref = fe1.drain()
    st1 = fe1.stats()
    # the jit cache keys steps structurally, so an earlier same-shape
    # bucket anywhere in the process makes fe1's dispatch a cache hit
    # (compiles() == 0); all this side asserts is export + traced serve
    assert st1["aot_exports"] == 1 and st1["aot_loads"] == 0
    assert st1["traced_buckets"] == 1 and st1["compiles"] <= 1
    assert len(os.listdir(aot)) == 1

    fe2 = ScenarioFrontend(_cfg(seed=112, aot_dir=aot))
    fe2.admit(_req(0))
    fe2.admit(_req(1, knobs={"d": 3, "d_lo": 2, "d_hi": 6}))
    got = fe2.drain()
    st2 = fe2.stats()
    assert st2["aot_loads"] == 1 and st2["compiles"] == 0
    assert st2["traced_buckets"] == 0
    strip = lambda rows: [{k: v for k, v in r.items()
                           if k != "queue_s"} for r in rows]
    assert strip(got) == strip(ref)


# -- preemption-surviving long scenarios -----------------------------------


def _long_cfg(tmp_path, seed, tag):
    return _cfg(seed=seed, long_ticks=16,
                ckpt_dir=str(tmp_path / f"ckpt_{tag}"), ckpt_every=4)


def test_long_scenario_parks_on_interrupt_and_resumes_bit_identical(
        tmp_path):
    """The full preemption story in-process: a deferred kill lands
    mid-long-scenario -> CheckpointInterrupt -> the request's journal
    line PARKS (named interruption row, snapshot flushed); a fresh
    front end over the same journal replays it, resumes from the
    snapshot (resumed=True), and its digest matches an uninterrupted
    reference run bit-identically."""
    raw = json.dumps(dict(TINY, id="long1", ticks=16, seed=5))
    journal = str(tmp_path / "serve.journal")

    ref_fe = ScenarioFrontend(_long_cfg(tmp_path, 113, "ref"))
    buf = io.StringIO()
    ref_fe.serve_lines([raw], buf)
    ref = next(json.loads(x) for x in buf.getvalue().splitlines()
               if json.loads(x).get("long"))
    assert ref["ok"] and not ref["resumed"]

    fe1 = ScenarioFrontend(_long_cfg(tmp_path, 113, "live"))
    ck.request_stop()
    try:
        buf = io.StringIO()
        fe1.serve_lines([raw], buf, journal=journal)
    finally:
        ck.clear_stop()
    rows = [json.loads(x) for x in buf.getvalue().splitlines()]
    parked = next(r for r in rows if r.get("interrupted"))
    assert parked["journaled"] and "bit-identical" in parked["error"]
    assert rows[-1]["parked"] == 1
    assert ck.read_journal(journal)[0] == [raw]

    fe2 = ScenarioFrontend(_long_cfg(tmp_path, 113, "live"))
    buf = io.StringIO()
    fe2.serve_lines([], buf, journal=journal)
    rows = [json.loads(x) for x in buf.getvalue().splitlines()]
    res = next(r for r in rows if r.get("long"))
    assert res["ok"] and res["resumed"]
    assert res["digest"] == ref["digest"]
    assert rows[-1]["long_resumed"] == 1
    assert ck.read_journal(journal)[0] == []   # compacted after serve


# -- @slow: real SIGKILL subprocess + mini load generator ------------------


_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
from go_libp2p_pubsub_tpu.serving import FrontendConfig, ScenarioFrontend
fe = ScenarioFrontend(FrontendConfig(
    batch=2, max_buckets=2, long_ticks=32, ckpt_dir={ckpt_dir!r},
    ckpt_every=2, default_shape=(64, 2, 4, 8),
    server_kw={{"seed": 114}}))
lines = [{line!r}] if {first} else []
fe.serve_lines(lines, sys.stdout, journal={journal!r})
"""


@pytest.mark.slow
def test_sigkill_mid_long_scenario_resumes_to_identical_digest(
        tmp_path):
    """kill -9 (no deferred-stop courtesy) against a server running a
    journaled long scenario: the restart replays the CRC'd journal,
    resumes from the flushed snapshot, and reproduces the
    uninterrupted digest."""
    import zlib
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    req = dict(TINY, id="kill1", ticks=160, seed=6)
    raw = json.dumps(req, sort_keys=True)
    ckpt_dir = str(tmp_path / "ckpt")
    journal = str(tmp_path / "serve.journal")
    snapdir = os.path.join(ckpt_dir,
                           f"kill1-{zlib.crc32(raw.encode()):08x}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def child(first):
        script = _KILL_CHILD.format(repo=repo, ckpt_dir=ckpt_dir,
                                    line=raw, first=int(first),
                                    journal=journal)
        return subprocess.Popen([sys.executable, "-c", script],
                                stdout=subprocess.PIPE, text=True,
                                env=env)

    c1 = child(first=True)
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            if (os.path.isdir(snapdir)
                    and sum(f.endswith(".ckpt")
                            for f in os.listdir(snapdir)) >= 2):
                break
            assert c1.poll() is None, \
                "child finished before it could be killed: " \
                + (c1.communicate()[0] or "")
            time.sleep(0.01)
        else:
            pytest.fail("child never produced snapshots")
        c1.send_signal(signal.SIGKILL)
        c1.communicate(timeout=60)
    finally:
        if c1.poll() is None:
            c1.kill()

    # uninterrupted reference (same request, separate snapshot root)
    fe_ref = ScenarioFrontend(FrontendConfig(
        batch=2, max_buckets=2, long_ticks=32,
        ckpt_dir=str(tmp_path / "ckpt_ref"), ckpt_every=40,
        default_shape=(64, 2, 4, 8), server_kw={"seed": 114}))
    buf = io.StringIO()
    fe_ref.serve_lines([raw], buf)
    ref = next(json.loads(x) for x in buf.getvalue().splitlines()
               if json.loads(x).get("long"))

    c2 = child(first=False)
    out, _ = c2.communicate(timeout=600)
    assert c2.returncode == 0, out
    rows = [json.loads(x) for x in out.splitlines()]
    res = next(r for r in rows if r.get("long"))
    assert res["resumed"], res
    assert res["digest"] == ref["digest"]


@pytest.mark.slow
def test_mini_loadgen_accounting_identity_holds():
    """A small Zipf/Poisson load through two buckets with tight
    deadlines and a finite queue: every admitted request ends in
    exactly one terminal bucket and the compile count stays at the
    traced-bucket count."""
    rng = np.random.default_rng(7)
    pool = [(64, 2, 4, 8), (128, 2, 4, 8)]
    fe = ScenarioFrontend(_cfg(seed=115, batch=4, queue_cap=16))
    n_reqs, rejected = 120, 0
    rows = []
    for i in range(n_reqs):
        n, t, m, ticks = pool[int(rng.random() < 0.25)]
        req = {"id": f"r{i}", "n": n, "t": t, "m": m, "ticks": ticks,
               "seed": int(i % 8)}
        if i % 15 == 0:
            req["deadline_s"] = 0.001
        rej = fe.admit(req)
        if rej is not None:
            assert rej["overloaded"]
            rejected += 1
        if i % 2:
            rows.extend(fe.dispatch_ready())
    rows.extend(fe.drain())
    st = fe.stats()
    assert st["admitted"] == n_reqs - rejected
    assert st["admitted"] == (st["served"] + st["errors"]
                              + st["timeouts"]
                              + st["transient_failures"])
    assert st["queued"] == 0 and st["parked"] == 0
    assert st["compiles"] == st["traced_buckets"] == 2
    assert len(rows) == st["admitted"]
    assert all(r.get("inv_bits", 0) == 0 for r in rows if r.get("ok"))
