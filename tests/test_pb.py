"""Wire codec tests: round-trips, framing, compat, and cross-checks against
protobuf-canonical byte patterns (computed by hand from the proto2 spec)."""

import pytest

from go_libp2p_pubsub_tpu.pb import (
    RPC,
    CompatMessage,
    ControlGraft,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    PeerInfo,
    PubMessage,
    SubOpts,
    TraceEvent,
    TraceEventBatch,
    TraceType,
    decode_uvarint,
    encode_uvarint,
    iter_delimited,
    read_delimited,
    write_delimited,
)
from go_libp2p_pubsub_tpu.pb import trace as tr


def test_uvarint_roundtrip():
    for v in [0, 1, 127, 128, 300, 2**21, 2**35, 2**63, 2**64 - 1]:
        enc = encode_uvarint(v)
        dec, pos = decode_uvarint(enc)
        assert dec == v and pos == len(enc)


def test_uvarint_known_bytes():
    # canonical protobuf examples
    assert encode_uvarint(1) == b"\x01"
    assert encode_uvarint(300) == b"\xac\x02"


def test_message_known_encoding():
    # field 2 (data, bytes) -> tag 0x12; field 4 (topic, string) -> tag 0x22
    m = PubMessage(data=b"hi", topic="t")
    assert m.encode() == b"\x12\x02hi\x22\x01t"


def test_rpc_roundtrip():
    rpc = RPC(
        subscriptions=[SubOpts(subscribe=True, topicid="foo"),
                       SubOpts(subscribe=False, topicid="bar")],
        publish=[PubMessage(from_peer=b"\x01\x02", data=b"payload",
                            seqno=b"\x00\x00\x00\x00\x00\x00\x00\x07",
                            topic="foo", signature=b"sig", key=b"key")],
        control=ControlMessage(
            ihave=[ControlIHave(topic_id="foo", message_ids=[b"m1", b"\xff\xfe"])],
            iwant=[ControlIWant(message_ids=[b"m2"])],
            graft=[ControlGraft(topic_id="foo")],
            prune=[ControlPrune(topic_id="bar",
                                peers=[PeerInfo(peer_id=b"p1", signed_peer_record=b"rec")],
                                backoff=60)],
        ),
    )
    data = rpc.encode()
    back = RPC.decode(data)
    assert back == rpc
    assert back.publish[0].data == b"payload"
    assert back.control.ihave[0].message_ids == [b"m1", b"\xff\xfe"]
    assert back.control.prune[0].backoff == 60


def test_non_utf8_message_ids_roundtrip():
    # the reference warns go protobuf emits invalid utf8 in string fields;
    # our bytes-typed ids must round-trip arbitrary binary
    ih = ControlIHave(topic_id="t", message_ids=[bytes(range(256))])
    assert ControlIHave.decode(ih.encode()) == ih


def test_compat_single_vs_multi_topic():
    # new single-topic Message and old repeated topicIDs share field tag 4:
    # a single-topic message decodes as a one-element topicIDs list and
    # vice versa (reference compat_test.go:10-83 proves the same property).
    new = PubMessage(from_peer=b"p", data=b"d", topic="topic-a")
    old = CompatMessage.decode(new.encode())
    assert old.topic_ids == ["topic-a"]

    old2 = CompatMessage(from_peer=b"p", data=b"d", topic_ids=["t1", "t2"])
    new2 = PubMessage.decode(old2.encode())
    # last value wins for a non-repeated field per proto2 semantics
    assert new2.topic == "t2"


def test_unknown_fields_skipped():
    # encode an RPC, append an unknown field (num 15, varint), decode fine
    rpc = RPC(publish=[PubMessage(data=b"x", topic="t")])
    raw = rpc.encode() + encode_uvarint((15 << 3) | 0) + encode_uvarint(42)
    assert RPC.decode(raw) == rpc


def test_delimited_framing():
    msgs = [RPC(publish=[PubMessage(data=bytes([i]) * i, topic=f"t{i}")])
            for i in range(5)]
    buf = b"".join(write_delimited(m) for m in msgs)
    out = list(iter_delimited(RPC, buf))
    assert out == msgs


def test_delimited_max_size():
    big = RPC(publish=[PubMessage(data=b"x" * 100, topic="t")])
    buf = write_delimited(big)
    with pytest.raises(ValueError):
        read_delimited(RPC, buf, 0, max_size=10)


def test_trace_event_roundtrip():
    ev = TraceEvent(
        type=TraceType.GRAFT,
        peer_id=b"me",
        timestamp=1234567890,
        graft=tr.GraftEv(peer_id=b"other", topic="t"),
    )
    back = TraceEvent.decode(ev.encode())
    assert back == ev
    assert back.type == TraceType.GRAFT
    assert TraceType.NAMES[back.type] == "GRAFT"


def test_trace_batch_roundtrip():
    evs = [TraceEvent(type=TraceType.JOIN, peer_id=b"p", timestamp=i,
                      join=tr.JoinEv(topic="x")) for i in range(10)]
    batch = TraceEventBatch(batch=evs)
    assert TraceEventBatch.decode(batch.encode()) == batch


def test_negative_int64_timestamp():
    ev = TraceEvent(type=TraceType.JOIN, timestamp=-1)
    back = TraceEvent.decode(ev.encode())
    assert back.timestamp == -1


def test_duplicate_singular_message_merges():
    # proto2: two occurrences of singular `control` merge, not replace
    a = RPC(control=ControlMessage(ihave=[ControlIHave(topic_id="t", message_ids=[b"a"])]))
    b = RPC(control=ControlMessage(iwant=[ControlIWant(message_ids=[b"b"])]))
    merged = RPC.decode(a.encode() + b.encode())
    assert len(merged.control.ihave) == 1 and len(merged.control.iwant) == 1


def test_truncated_unknown_field_rejected():
    rpc = RPC(publish=[PubMessage(data=b"x", topic="t")])
    raw = rpc.encode() + encode_uvarint((15 << 3) | 2) + encode_uvarint(100) + b"short"
    with pytest.raises(ValueError):
        RPC.decode(raw)


def test_varint_overflow_rejected():
    with pytest.raises(ValueError):
        decode_uvarint(b"\xff" * 9 + b"\x7f")
