"""tools/lint_fallback.py — the stdlib lint subset that gates
measurement passes on ruff-less containers — was itself untested.
Fixture sources per enforced rule family (E999 / F401 / F811 /
W291+W293 / E501), the documented exemptions (noqa, __init__
re-exports, __all__), and an agreement test pinning the fallback's
verdicts to real ruff's (with the pinned ruff.toml) when ruff is
installed.
"""

import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools import lint_fallback

REPO = Path(__file__).resolve().parents[1]

#: rule-family fixtures: name -> (source, expected codes in order)
FIXTURES = {
    "syntax_error": ("def broken(:\n    pass\n", ["E999"]),
    "unused_import": ("import os\nimport sys\n\nprint(sys.argv)\n",
                      ["F401"]),
    "unused_from_import": (
        "from pathlib import Path, PurePath\n\nprint(Path())\n",
        ["F401"]),
    "redefined_import": (
        "import os\nimport os\n\nprint(os.sep)\n",
        ["F811"]),
    "trailing_whitespace": (
        "x = 1  \ny = 2\n", ["W291"]),
    "blank_line_whitespace": (
        "x = 1\n   \ny = 2\n", ["W293"]),
    "long_line": ("x = " + "'a' + " * 20 + "'end'  # "
                  + "y" * 60 + "\n", ["E501"]),
    "clean": ("import sys\n\nprint(sys.argv)\n", []),
    "noqa_respected": ("import os  # noqa: F401\n", []),
    "noqa_bare": ("import os  # noqa\n", []),
    "all_export": (
        "import os\n\n__all__ = ['os']\n", []),
}


def _codes(findings):
    return [re.match(r".*?:\d+: (\w+)", f).group(1) for f in findings]


@pytest.mark.parametrize("name", sorted(FIXTURES))
def test_rule_family(tmp_path, name):
    src, expected = FIXTURES[name]
    p = tmp_path / f"{name}.py"
    p.write_text(src)
    assert _codes(lint_fallback.check_file(p)) == expected


def test_init_reexports_exempt(tmp_path):
    """Package __init__ re-exports skip F401 (mirrors ruff.toml's
    per-file-ignores) but keep the whitespace/length rules."""
    p = tmp_path / "__init__.py"
    p.write_text("from os import sep\nx = 1  \n")
    assert _codes(lint_fallback.check_file(p)) == ["W291"]


def test_function_scope_imports_not_module_level(tmp_path):
    p = tmp_path / "scoped.py"
    p.write_text("def f():\n    import os\n    return os.sep\n")
    # function-level imports are out of scope for the fallback's F401
    # (it checks module level only — a deliberate conservative subset)
    assert lint_fallback.check_file(p) == []


def test_main_exit_status(tmp_path, capsys):
    (tmp_path / "bad.py").write_text("import os\n")
    old = sys.argv
    sys.argv = ["lint_fallback.py", str(tmp_path)]
    try:
        with pytest.raises(SystemExit) as e:
            lint_fallback.main()
        assert e.value.code == 1
        assert "F401" in capsys.readouterr().out
        (tmp_path / "bad.py").write_text("import os\n\nprint(os.sep)\n")
        lint_fallback.main()       # clean tree: returns, no SystemExit
    finally:
        sys.argv = old


def _ruff_cmd():
    if shutil.which("ruff"):
        return ["ruff"]
    probe = subprocess.run([sys.executable, "-c", "import ruff"],
                           capture_output=True)
    if probe.returncode == 0:
        return [sys.executable, "-m", "ruff"]
    return None


@pytest.mark.skipif(_ruff_cmd() is None,
                    reason="ruff not installed (fallback-only container)")
def test_fallback_agrees_with_ruff_on_fixtures(tmp_path):
    """Same fixtures, real ruff with the pinned repo config: the
    (file, code) verdict sets must match — the fallback's contract is
    'only findings ruff would also report'."""
    for name, (src, _) in FIXTURES.items():
        (tmp_path / f"{name}.py").write_text(src)
    out = subprocess.run(
        _ruff_cmd() + ["check", "--config", str(REPO / "ruff.toml"),
                       "--output-format", "concise", str(tmp_path)],
        capture_output=True, text=True)
    ruff_verdicts = set()
    for line in out.stdout.splitlines():
        m = re.match(r"(.+?):\d+:\d+: (\w+)", line)
        if m:
            # newer ruff labels syntax errors "SyntaxError" instead of
            # pycodestyle's E999; normalize to the fallback's code
            code = {"SyntaxError": "E999"}.get(m.group(2), m.group(2))
            ruff_verdicts.add((Path(m.group(1)).name, code))
    fb_verdicts = set()
    for p in sorted(tmp_path.glob("*.py")):
        for f in lint_fallback.check_file(p):
            m = re.match(r"(.+?):\d+: (\w+)", f)
            fb_verdicts.add((Path(m.group(1)).name, m.group(2)))
    assert fb_verdicts == ruff_verdicts
