"""Shared test harness: re-exports the package's in-proc cluster tools
(go_libp2p_pubsub_tpu.core.testing), which mirror the reference test
strategy (/root/reference/floodsub_test.go:45-99)."""

from go_libp2p_pubsub_tpu.core.testing import (  # noqa: F401
    connect,
    connect_all,
    connect_some,
    dense_connect,
    get_hosts,
    settle,
    settle_until,
    sparse_connect,
)
