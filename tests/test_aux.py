"""Subscription filters, discovery pipeline, and tracer sinks.

Mirrors reference subscription_filter_test.go, discovery_test.go, and
trace_test.go scenarios."""

from __future__ import annotations

import asyncio
import json
import random

import pytest

from go_libp2p_pubsub_tpu.core import (
    AllowlistSubscriptionFilter,
    DiscoveryPipeline,
    InProcDiscovery,
    InProcNetwork,
    JSONTracer,
    LimitSubscriptionFilter,
    PBTracer,
    RegexpSubscriptionFilter,
    RemoteTracer,
    TooManySubscriptionsError,
    TraceCollector,
    create_floodsub,
    create_gossipsub,
    filter_subscriptions,
    min_topic_size,
)
from go_libp2p_pubsub_tpu.pb import SubOpts
from go_libp2p_pubsub_tpu.pb import trace as tr
from go_libp2p_pubsub_tpu.pb.proto import read_delimited
from go_libp2p_pubsub_tpu.core.types import PeerID
from helpers import connect, get_hosts, settle

from test_gossipsub import close_all, fast_params


# -- subscription filters ---------------------------------------------------


def test_allowlist_filter():
    f = AllowlistSubscriptionFilter("test1", "test2")
    assert f.can_subscribe("test1")
    assert not f.can_subscribe("test3")
    out = f.filter_incoming_subscriptions(PeerID(b"A"), [
        SubOpts(subscribe=True, topicid="test1"),
        SubOpts(subscribe=True, topicid="test3"),
    ])
    assert [s.topicid for s in out] == ["test1"]


def test_regexp_filter():
    f = RegexpSubscriptionFilter("^test[0-9]$")
    assert f.can_subscribe("test1")
    assert not f.can_subscribe("nope")


def test_filter_dedup_and_cancel():
    # conflicting sub/unsub for the same topic cancel out; dups collapse
    subs = [
        SubOpts(subscribe=True, topicid="a"),
        SubOpts(subscribe=False, topicid="a"),
        SubOpts(subscribe=True, topicid="b"),
        SubOpts(subscribe=True, topicid="b"),
    ]
    out = filter_subscriptions(subs, lambda t: True)
    assert [s.topicid for s in out] == ["b"]
    # a later re-statement after a conflict is accepted again
    # (reference subscription_filter.go:104-108 deletes the entry)
    subs = [
        SubOpts(subscribe=True, topicid="a"),
        SubOpts(subscribe=False, topicid="a"),
        SubOpts(subscribe=True, topicid="a"),
    ]
    out = filter_subscriptions(subs, lambda t: True)
    assert [(s.topicid, bool(s.subscribe)) for s in out] == [("a", True)]


def test_limit_filter():
    f = LimitSubscriptionFilter(AllowlistSubscriptionFilter("t"), 2)
    f.filter_incoming_subscriptions(PeerID(b"A"), [
        SubOpts(subscribe=True, topicid="t")])
    with pytest.raises(TooManySubscriptionsError):
        f.filter_incoming_subscriptions(PeerID(b"A"), [
            SubOpts(subscribe=True, topicid="t")] * 3)


async def test_subscription_filter_applied_on_wire():
    """Peer subscriptions for disallowed topics are not tracked, and local
    joins to disallowed topics error (reference pubsub.go:1096)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    ps0 = await create_floodsub(
        hosts[0], subscription_filter=AllowlistSubscriptionFilter("good"))
    ps1 = await create_floodsub(hosts[1])
    t_good = await ps1.join("good")
    await t_good.subscribe()
    t_bad = await ps1.join("bad")
    await t_bad.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.2)

    peers_good = await ps0.list_peers("good")
    peers_bad = await ps0.list_peers("bad")
    assert peers_good == [hosts[1].id]
    assert peers_bad == []
    with pytest.raises(ValueError):
        await ps0.join("bad")
    await close_all([ps0, ps1], net)


# -- discovery --------------------------------------------------------------


async def test_discovery_connects_topic_peers():
    """Hosts sharing a topic find each other through the rendezvous table
    and end up connected (reference discovery_test.go simple scenario)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 4)
    disc = InProcDiscovery()
    psubs = []
    for h in hosts:
        pipeline = DiscoveryPipeline(disc.for_host(h), poll_interval=0.05)
        psubs.append(await create_floodsub(h, discovery=pipeline))
    # nobody is connected yet
    topics = [await ps.join("rendezvous") for ps in psubs]
    subs = [await t.subscribe() for t in topics]
    await settle(0.5)

    # discovery should have dialed: everyone connected to everyone
    for h in hosts:
        assert len(h.peers()) == len(hosts) - 1, h.peers()

    await topics[0].publish(b"found you")
    for s in subs:
        m = await asyncio.wait_for(s.next(), timeout=5)
        assert m.data == b"found you"
    await close_all(psubs, net)


async def test_bootstrap_blocks_until_ready():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    disc = InProcDiscovery()
    psubs = []
    for h in hosts:
        pipeline = DiscoveryPipeline(disc.for_host(h), poll_interval=0.05)
        psubs.append(await create_floodsub(h, discovery=pipeline))
    t0 = await psubs[0].join("boot")
    await t0.subscribe()

    async def late_joiner():
        await asyncio.sleep(0.2)
        t1 = await psubs[1].join("boot")
        await t1.subscribe()

    task = asyncio.ensure_future(late_joiner())
    ok = await asyncio.wait_for(
        psubs[0].disc.bootstrap("boot", min_topic_size(1)), timeout=5)
    assert ok
    await task
    await close_all(psubs, net)


# -- tracer sinks -----------------------------------------------------------


async def test_json_tracer_roundtrip(tmp_path):
    path = str(tmp_path / "trace.json")
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    tracer = JSONTracer(path)
    ps0 = await create_gossipsub(hosts[0], router_rng=random.Random(0),
                                 gossipsub_params=fast_params(),
                                 event_tracer=tracer)
    ps1 = await create_gossipsub(hosts[1], router_rng=random.Random(1),
                                 gossipsub_params=fast_params())
    t0 = await ps0.join("traced")
    s0 = await t0.subscribe()
    t1 = await ps1.join("traced")
    await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.3)
    await t1.publish(b"traced message")
    await asyncio.wait_for(s0.next(), timeout=5)
    await settle(0.2)
    await tracer.close()

    evts = [json.loads(line) for line in open(path)]
    types = {e["type"] for e in evts}
    # joined, peer added, rpcs exchanged, message delivered
    assert tr.TraceType.JOIN in types
    assert tr.TraceType.ADD_PEER in types
    assert tr.TraceType.RECV_RPC in types
    assert tr.TraceType.DELIVER_MESSAGE in types
    await close_all([ps0, ps1], net)


async def test_pb_tracer_roundtrip(tmp_path):
    path = str(tmp_path / "trace.pb")
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    tracer = PBTracer(path)
    ps0 = await create_gossipsub(hosts[0], router_rng=random.Random(0),
                                 gossipsub_params=fast_params(),
                                 event_tracer=tracer)
    ps1 = await create_gossipsub(hosts[1], router_rng=random.Random(1),
                                 gossipsub_params=fast_params())
    t0 = await ps0.join("traced")
    s0 = await t0.subscribe()
    t1 = await ps1.join("traced")
    await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.3)
    await t1.publish(b"pb message")
    await asyncio.wait_for(s0.next(), timeout=5)
    await settle(0.2)
    await tracer.close()

    buf = open(path, "rb").read()
    evts = []
    pos = 0
    while pos < len(buf):
        evt, pos = read_delimited(tr.TraceEvent, buf, pos)
        evts.append(evt)
    types = {e.type for e in evts}
    assert tr.TraceType.DELIVER_MESSAGE in types
    assert all(e.peer_id == bytes(hosts[0].id) for e in evts)
    await close_all([ps0, ps1], net)


async def test_remote_tracer():
    """Events stream to a collector peer over the tracer protocol with
    gzip+delimited framing (reference trace_test.go:301)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 3)
    collector_host = hosts[2]
    collector = TraceCollector(collector_host)

    await hosts[0].connect(collector_host)
    tracer = RemoteTracer(hosts[0], collector_host.id, min_batch=4,
                          batch_deadline=0.2)
    ps0 = await create_gossipsub(hosts[0], router_rng=random.Random(0),
                                 gossipsub_params=fast_params(),
                                 event_tracer=tracer)
    ps1 = await create_gossipsub(hosts[1], router_rng=random.Random(1),
                                 gossipsub_params=fast_params())
    t0 = await ps0.join("remote")
    s0 = await t0.subscribe()
    t1 = await ps1.join("remote")
    await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.3)
    for i in range(5):
        await t1.publish(b"remote %d" % i)
    for _ in range(5):
        await asyncio.wait_for(s0.next(), timeout=5)
    await settle(0.5)
    await tracer.close()
    await settle(0.2)

    types = {e.type for e in collector.events}
    assert tr.TraceType.DELIVER_MESSAGE in types
    assert len(collector.events) >= 5
    await close_all([ps0, ps1], net)


# -- logging (§5.5; reference logs via ipfs/go-log, pubsub.go:37) -----------


async def test_logging_at_core_sites(caplog):
    """Peer lifecycle and drop sites emit records on the package logger,
    and process-loop exceptions are logged instead of printed."""
    import logging

    net = InProcNetwork()
    hosts = get_hosts(net, 3)
    psubs = [await create_gossipsub(h, gossipsub_params=fast_params())
             for h in hosts]
    with caplog.at_level(logging.DEBUG, logger="go_libp2p_pubsub_tpu"):
        await connect(hosts[0], hosts[1])
        await settle(0.2)
        assert any("new peer" in r.message for r in caplog.records)

        # blacklisted connect attempt
        await psubs[0].blacklist_peer(hosts[2].id)
        await connect(hosts[0], hosts[2])
        await settle(0.2)
        assert any("blacklisted" in r.message for r in caplog.records)

        # a crashing thunk is logged, and the loop survives
        psubs[0]._post(lambda: 1 / 0)
        await settle(0.1)
        errors = [r for r in caplog.records if r.levelno >= logging.ERROR]
        assert any("process loop" in r.message for r in errors)
        assert await psubs[0].list_peers("") is not None  # loop alive

    await close_all(psubs, net)
