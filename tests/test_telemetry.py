"""Device-side telemetry (models/telemetry.py): telemetry-off runs are
bit-identical to pre-telemetry behavior, telemetry-on runs leave the
state trajectory untouched, batched frames match sequential exactly,
and the counters/byte estimates are sane against hand-checkable
quantities."""

import numpy as np
import jax
import pytest

import go_libp2p_pubsub_tpu.models.faults as fl
import go_libp2p_pubsub_tpu.models.floodsub as fs
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.randomsub as rs
import go_libp2p_pubsub_tpu.models.telemetry as tl
from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets


def tree_equal(a, b):
    """Exact (bitwise) equality over two pytrees."""
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def gossip_inputs(n=600, t=3, m=8, seed=6):
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=seed), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(seed)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    return cfg, subs, topic, origin, ticks


# --------------------------------------------------------------------------
# Config validation + wire sizes
# --------------------------------------------------------------------------


def test_config_validates():
    with pytest.raises(ValueError, match="wire"):
        tl.TelemetryConfig(counters=False, wire=True)
    with pytest.raises(ValueError, match="msg_id_bytes"):
        tl.TelemetryConfig(msg_id_bytes=0)


def test_wire_sizes_match_pb_encodings():
    """The framing constants come from ACTUAL pb/rpc.py encodings, and
    base + k * per_id tracks the exact k-id encoding."""
    from go_libp2p_pubsub_tpu.pb import rpc as rpcpb
    from go_libp2p_pubsub_tpu.pb.proto import write_delimited

    tcfg = tl.TelemetryConfig()
    ws = tl.wire_sizes(tcfg)
    msg = rpcpb.PubMessage(
        from_peer=b"\x00" * tcfg.peer_id_bytes,
        data=b"\x00" * tcfg.payload_data_bytes,
        seqno=b"\x00" * 8, topic="t" * tcfg.topic_bytes)
    assert ws.payload_frame == len(write_delimited(
        rpcpb.RPC(publish=[msg])))

    def ih(k):
        return len(write_delimited(rpcpb.RPC(
            control=rpcpb.ControlMessage(ihave=[rpcpb.ControlIHave(
                topic_id="t" * tcfg.topic_bytes,
                message_ids=[b"\x00" * tcfg.msg_id_bytes] * k)]))))

    assert ws.ihave_base + 3 * ws.ihave_per_id == ih(3)
    assert ws.graft_frame > 0 and ws.prune_frame > 0
    assert ws.iwant_per_id > tcfg.msg_id_bytes  # id + tag/len overhead


# --------------------------------------------------------------------------
# Bit-identity: telemetry only READS
# --------------------------------------------------------------------------


@pytest.mark.parametrize("scored", [False, True])
@pytest.mark.slow
def test_gossip_state_identical_with_telemetry(scored):
    cfg, subs, topic, origin, ticks = gossip_inputs()
    sc = gs.ScoreSimConfig() if scored else None
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc)
    p2, s2 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc)
    fin_off = gs.gossip_run(p1, s1, 25, gs.make_gossip_step(cfg, sc))
    fin_on, frames = tl.telemetry_run(
        p2, s2, 25, gs.make_gossip_step(cfg, sc,
                                        telemetry=tl.TelemetryConfig()))
    assert tree_equal(fin_off, fin_on)
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].shape == (25,)
    assert arr["payload_sent"].sum() > 0
    assert arr["graft_sends"].sum() > 0


@pytest.mark.slow
def test_gossip_split_path_state_identical_with_telemetry():
    """The force_split (separate mesh/gossip loop) formulation carries
    its own telemetry tallies — state must stay untouched there too."""
    cfg, subs, topic, origin, ticks = gossip_inputs()
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    p2, s2 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    fin_off = gs.gossip_run(
        p1, s1, 20, gs.make_gossip_step(cfg, force_split=True))
    fin_on, frames = tl.telemetry_run(
        p2, s2, 20, gs.make_gossip_step(
            cfg, force_split=True, telemetry=tl.TelemetryConfig()))
    assert tree_equal(fin_off, fin_on)
    assert tl.frames_to_arrays(frames)["payload_sent"].sum() > 0


def test_flood_state_identical_with_telemetry():
    n, t, m = 300, 3, 6
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(2)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, dtype=np.int32)
    offs = tuple(int(o) for o in make_circulant_offsets(t, 12, n, seed=1))
    p1, s1 = fs.make_flood_sim(None, None, subs, None, topic, origin,
                               ticks)
    p2, s2 = fs.make_flood_sim(None, None, subs, None, topic, origin,
                               ticks)
    core_off = fs.make_circulant_step_core(offs)
    core_on = fs.make_circulant_step_core(
        offs, telemetry=tl.TelemetryConfig())
    fin1, counts1 = fs.flood_run_curve(p1, s1, 15, core_off, m)
    fin2, counts2, frames = tl.telemetry_run_curve(p2, s2, 15, core_on,
                                                   m)
    assert tree_equal(fin1, fin2)
    assert np.array_equal(np.asarray(counts1), np.asarray(counts2))
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].sum() > 0
    assert arr["dup_suppressed"].sum() > 0      # floods re-hear a lot
    # gossip-only fields are zero in the floodsub subset
    assert arr["ihave_ids"].sum() == 0
    assert arr["graft_sends"].sum() == 0


def test_randomsub_state_identical_with_telemetry():
    n, t, m = 400, 2, 6
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(3)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, dtype=np.int32)
    cfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(t, 24, n, seed=2), n_topics=t)
    p1, s1 = rs.make_randomsub_sim(cfg, subs, topic, origin, ticks)
    p2, s2 = rs.make_randomsub_sim(cfg, subs, topic, origin, ticks)
    fin1 = rs.randomsub_run(p1, s1, 15, rs.make_randomsub_step(cfg))
    fin2, frames = tl.telemetry_run(
        p2, s2, 15,
        rs.make_randomsub_step(cfg, telemetry=tl.TelemetryConfig()))
    assert tree_equal(fin1, fin2)
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].sum() > 0
    assert arr["ihave_ids"].sum() == 0


def test_pallas_step_accepts_telemetry():
    """Round 9: the kernel path accepts telemetry configs (in-kernel
    counter tallies; frame parity is pinned in
    tests/test_pallas_receive.py) — this pins acceptance where the
    refusal used to be, and that the frames carry live counters."""
    cfg, subs, topic, origin, ticks = gossip_inputs()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       pad_to_block=1024)
    step = gs.make_gossip_step(cfg, receive_block=1024,
                               receive_interpret=True,
                               telemetry=tl.TelemetryConfig())
    _, frames = tl.telemetry_run(params, state, 12, step)
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].sum() > 0
    assert arr["bytes_payload"].sum() > 0


# --------------------------------------------------------------------------
# Batched == sequential, per replica, bit-for-bit
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_batched_frames_match_sequential():
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300, t=3, m=6)
    sc = gs.ScoreSimConfig()
    tcfg = tl.TelemetryConfig()
    step = gs.make_gossip_step(cfg, sc, telemetry=tcfg)
    specs = [dict(subs=subs, msg_topic=topic, msg_origin=origin,
                  msg_publish_tick=ticks, seed=r, score_cfg=sc)
             for r in range(3)]
    params_b, state_b = gs.stack_sims(cfg, specs)
    fin_b, frames_b = tl.telemetry_run_batch(params_b, state_b, 20,
                                             step)
    arr_b = tl.frames_to_arrays(frames_b)          # each [T, B]
    for i, spec in enumerate(specs):
        p_i, s_i = gs.make_gossip_sim(cfg, **spec)
        fin_i, frames_i = tl.telemetry_run(p_i, s_i, 20, step)
        arr_i = tl.frames_to_arrays(frames_i)      # each [T]
        assert tree_equal(gs.index_trees(fin_b, i), fin_i)
        for name, col in arr_b.items():
            assert np.array_equal(col[:, i], arr_i[name]), name


# --------------------------------------------------------------------------
# Counter semantics against hand-checkable quantities
# --------------------------------------------------------------------------


def test_gossip_counters_and_bytes_consistent():
    cfg, subs, topic, origin, ticks = gossip_inputs()
    tcfg = tl.TelemetryConfig()
    ws = tl.wire_sizes(tcfg)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    _, frames = tl.telemetry_run(
        params, state, 25, gs.make_gossip_step(cfg, telemetry=tcfg))
    a = tl.frames_to_arrays(frames)
    # no withholding: every requested id is served
    assert (a["iwant_ids_requested"] == a["iwant_ids_served"]).all()
    # degree ordering holds tick-wise once meshes exist
    assert (a["mesh_deg_min"] <= a["mesh_deg_max"]).all()
    live = a["mesh_deg_max"] > 0
    assert (a["mesh_deg_mean"][live]
            <= a["mesh_deg_max"][live] + 1e-6).all()
    # byte estimates are exact functions of the counters
    np.testing.assert_allclose(
        a["bytes_payload"],
        (a["payload_sent"] + a["iwant_ids_served"]).astype(np.float64)
        * ws.payload_frame, rtol=1e-6)
    expect_ctl = (a["ihave_rpcs"] * ws.ihave_base
                  + a["ihave_ids"] * ws.ihave_per_id
                  + a["iwant_rpcs"] * ws.iwant_base
                  + a["iwant_ids_requested"] * ws.iwant_per_id
                  + a["graft_sends"] * ws.graft_frame
                  + a["prune_sends"] * ws.prune_frame)
    np.testing.assert_allclose(a["bytes_control"],
                               expect_ctl.astype(np.float64), rtol=1e-6)
    # unscored run: score summary group stays zero
    assert (a["score_mean"] == 0).all() and (a["score_min"] == 0).all()


def test_gossip_score_summary_live_when_scored():
    cfg, subs, topic, origin, ticks = gossip_inputs()
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       score_cfg=sc)
    _, frames = tl.telemetry_run(
        params, state, 25,
        gs.make_gossip_step(cfg, sc, telemetry=tl.TelemetryConfig()))
    a = tl.frames_to_arrays(frames)
    # honest steady traffic: P1/P2 accrue, so the mean goes positive
    # and nobody sinks below the gossip threshold
    assert a["score_mean"][-1] > 0
    assert (a["score_min"] <= a["score_mean"] + 1e-6).all()
    assert (a["score_frac_below_gossip"] == 0).all()


def test_fault_counters_exact():
    """down_peers tracks the churn table exactly; with partitions only
    (drop_prob=0) dropped_edge_ticks equals the cross-edge count during
    the window and 0 outside."""
    cfg, subs, topic, origin, ticks = gossip_inputs()
    n = subs.shape[0]
    grp = (np.arange(n) < n // 2).astype(np.int64)
    sched = fl.FaultSchedule(
        n_peers=n, horizon=30,
        down_intervals=[(7, 3, 9), (11, 5, 30)],
        partition_group=grp, partition_windows=[(10, 14)], seed=4)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       fault_schedule=sched)
    _, frames = tl.telemetry_run(
        params, state, 30,
        gs.make_gossip_step(cfg, telemetry=tl.TelemetryConfig()))
    a = tl.frames_to_arrays(frames)
    expect_down = np.zeros(30, dtype=np.int64)
    expect_down[3:9] += 1
    expect_down[5:30] += 1
    assert np.array_equal(a["down_peers"], expect_down)
    # cross-edge count from the offsets (both views / 2)
    cross = sum(int((grp != np.roll(grp, -o)).sum())
                for o in cfg.offsets) // 2
    in_window = np.zeros(30, dtype=bool)
    in_window[10:14] = True
    assert (a["dropped_edge_ticks"][in_window] == cross).all()
    assert (a["dropped_edge_ticks"][~in_window] == 0).all()


def test_frame_subset_groups_disable():
    """Disabled groups zero their fields and still compile."""
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300)
    tcfg = tl.TelemetryConfig(counters=False, wire=False, scores=False,
                              faults=False)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    _, frames = tl.telemetry_run(
        params, state, 10, gs.make_gossip_step(cfg, telemetry=tcfg))
    a = tl.frames_to_arrays(frames)
    assert (a["payload_sent"] == 0).all()
    assert (a["bytes_control"] == 0).all()
    assert a["mesh_deg_max"][-1] > 0           # mesh group still on


def test_telemetry_works_with_zero_messages():
    """A mesh-formation-only sim (empty message table, W == 0) runs
    under telemetry wherever the plain step runs — the counters just
    stay zero while the mesh/graft groups stay live."""
    cfg, subs, _, _, _ = gossip_inputs(n=300)
    empty = np.zeros(0, dtype=np.int64)
    params, state = gs.make_gossip_sim(
        cfg, subs, empty, empty, empty.astype(np.int32))
    _, frames = tl.telemetry_run(
        params, state, 10,
        gs.make_gossip_step(cfg, telemetry=tl.TelemetryConfig()))
    a = tl.frames_to_arrays(frames)
    assert (a["payload_sent"] == 0).all()
    assert (a["iwant_ids_requested"] == 0).all()
    assert a["graft_sends"].sum() > 0
    assert a["mesh_deg_max"][-1] > 0


def test_combined_and_split_paths_agree_on_frames():
    """The control-overhead outputs are formulation-invariant: the
    combined (fused-roll) and force_split step emit identical frames
    for every field except dup_suppressed (documented: a merged
    eager+gossip word is one received copy vs the split path's two)."""
    cfg, subs, topic, origin, ticks = gossip_inputs()
    tcfg = tl.TelemetryConfig()
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    p2, s2 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    _, fr_c = tl.telemetry_run(
        p1, s1, 25, gs.make_gossip_step(cfg, telemetry=tcfg))
    _, fr_s = tl.telemetry_run(
        p2, s2, 25,
        gs.make_gossip_step(cfg, force_split=True, telemetry=tcfg))
    a_c, a_s = tl.frames_to_arrays(fr_c), tl.frames_to_arrays(fr_s)
    for name in a_c:
        if name == "dup_suppressed":
            assert (a_s[name] >= a_c[name]).all()
            continue
        assert np.array_equal(a_c[name], a_s[name]), name


def test_summarize_frames():
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    _, frames = tl.telemetry_run(
        params, state, 15,
        gs.make_gossip_step(cfg, telemetry=tl.TelemetryConfig()))
    s = tl.summarize_frames(frames)
    assert s["payload_sent"] > 0
    assert s["bytes_payload"] > 0
    assert 0 < s["control_overhead_ratio"] < 10
    assert s["final_mesh_deg_mean"] > 0


# --------------------------------------------------------------------------
# Round-10 histogram groups: sums pinned to the scalar counters,
# hist-off runs bit-identical, every execution path threads them
# --------------------------------------------------------------------------


def hist_tcfg(**kw):
    base = dict(latency_hist=True, degree_hist=True, score_hist=True,
                latency_buckets=12, degree_buckets=12)
    base.update(kw)
    return tl.TelemetryConfig(**base)


@pytest.mark.slow
def test_histogram_sums_match_scalar_counters():
    """Every histogram sums exactly to its population: latency to the
    tick's delivered-copy count, degree to the subscribed-peer count,
    score to the live candidate-edge count — per tick, every tick."""
    from go_libp2p_pubsub_tpu.ops.graph import expand_bits

    cfg, subs, topic, origin, ticks = gossip_inputs(n=400)
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       score_cfg=sc)
    m = len(topic)
    _, counts, frames = tl.telemetry_run_curve(
        params, state, 15, gs.make_gossip_step(
            cfg, sc, telemetry=hist_tcfg()), m)
    counts = np.asarray(counts)                       # [T, M]
    lat = np.asarray(frames.latency_hist)             # [T, L]
    np.testing.assert_array_equal(lat.sum(axis=1), counts.sum(axis=1))
    assert lat.sum() > 0
    deg = np.asarray(frames.mesh_deg_hist)            # [T, B]
    n_sub = int(np.asarray(params.subscribed).sum())
    np.testing.assert_array_equal(deg.sum(axis=1),
                                  np.full(deg.shape[0], n_sub))
    sco = np.asarray(frames.score_hist)               # [T, E+1]
    # live candidate edges: subscribed candidates of subscribed peers
    sub_all = np.where(np.asarray(params.subscribed), 0xFFFFFFFF, 0)
    mask = np.asarray(expand_bits(
        params.cand_sub_bits & sub_all.astype(np.uint32),
        len(cfg.offsets)))
    np.testing.assert_array_equal(
        sco.sum(axis=1), np.full(sco.shape[0], mask.sum()))


@pytest.mark.slow
def test_histogram_off_trajectory_identical_and_consistent_stats():
    """Enabling histogram groups must not perturb the run: the state
    trajectory AND the scalar frame groups are bit-identical with and
    without the histograms (the buckets are pure readouts)."""
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300)
    sc = gs.ScoreSimConfig()
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc)
    p2, s2 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc)
    fin_off, fr_off = tl.telemetry_run(
        p1, s1, 15, gs.make_gossip_step(
            cfg, sc, telemetry=tl.TelemetryConfig()))
    fin_on, fr_on = tl.telemetry_run(
        p2, s2, 15, gs.make_gossip_step(cfg, sc,
                                        telemetry=hist_tcfg()))
    assert tree_equal(fin_off, fin_on)
    a_off, a_on = (tl.frames_to_arrays(fr_off),
                   tl.frames_to_arrays(fr_on))
    for name in a_off:                    # scalar groups unchanged
        np.testing.assert_array_equal(a_off[name], a_on[name], err_msg=name)
    for name in ("latency_hist", "mesh_deg_hist", "score_hist"):
        assert name in a_on and name not in a_off
    # degree histogram consistent with the scalar min/max gauges
    deg = np.asarray(fr_on.mesh_deg_hist)
    nz = [np.flatnonzero(row) for row in deg]
    mins = np.array([int(ix[0]) for ix in nz])
    maxs = np.array([int(ix[-1]) for ix in nz])
    np.testing.assert_array_equal(
        mins, np.asarray(fr_on.mesh_deg_min).astype(np.int64))
    # max clips into the overflow bucket; below it the match is exact
    cap = deg.shape[1] - 1
    np.testing.assert_array_equal(
        maxs, np.minimum(np.asarray(fr_on.mesh_deg_max), cap))


@pytest.mark.slow
def test_latency_histogram_batched_matches_sequential():
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300)
    spec = dict(subs=subs, msg_topic=topic, msg_origin=origin,
                msg_publish_tick=ticks)
    step = gs.make_gossip_step(cfg, telemetry=hist_tcfg(
        score_hist=False))
    seq_frames = []
    for r in range(2):
        p, s = gs.make_gossip_sim(cfg, seed=r, **spec)
        _, fr = tl.telemetry_run(p, s, 10, step)
        seq_frames.append(np.asarray(fr.latency_hist))
    pb, sb = gs.stack_sims(cfg, [dict(spec, seed=r) for r in range(2)])
    _, frb = tl.telemetry_run_batch(pb, sb, 10, step)
    hist_b = np.asarray(frb.latency_hist)          # [T, B, L]
    for r in range(2):
        np.testing.assert_array_equal(hist_b[:, r], seq_frames[r])


def test_flood_gather_telemetry_subset_with_faults():
    """Round 10: the gather table path emits the floodsub frame subset
    (payload/dup/latency/fault counters; gossip fields zero) and its
    latency histogram sums to the delivered counts."""
    import go_libp2p_pubsub_tpu.models.faults as fl

    n, t, m = 300, 3, 6
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(2)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, dtype=np.int32)
    offs = tuple(int(o) for o in make_circulant_offsets(t, 12, n, seed=1))
    nbrs = np.stack([(np.arange(n) + o) % n for o in offs], axis=1)
    sched = fl.FaultSchedule(n_peers=n, horizon=15,
                             down_intervals=((5, 2, 6),),
                             drop_prob=0.05, seed=3)
    params, state = fs.make_flood_sim(
        nbrs, np.ones_like(nbrs, dtype=bool), subs, None, topic,
        origin, ticks, fault_schedule=sched)
    core = fs.make_gather_step_core(telemetry=tl.TelemetryConfig(
        latency_hist=True, latency_buckets=10))
    fin, counts, frames = tl.telemetry_run_curve(params, state, 15,
                                                 core, m)
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].sum() > 0
    assert arr["dup_suppressed"].sum() > 0
    assert arr["bytes_payload"].sum() > 0
    assert arr["down_peers"].max() == 1
    assert arr["dropped_edge_ticks"].sum() > 0
    assert arr["ihave_ids"].sum() == 0
    np.testing.assert_array_equal(
        np.asarray(frames.latency_hist).sum(axis=1),
        np.asarray(counts).sum(axis=1))
    # telemetry-off gather trajectory identical (pure readout)
    p2, s2 = fs.make_flood_sim(
        nbrs, np.ones_like(nbrs, dtype=bool), subs, None, topic,
        origin, ticks, fault_schedule=sched)
    fin2 = fs.flood_run(p2, s2, 15)
    assert tree_equal(fin, fin2)


def test_randomsub_dense_telemetry_subset_with_faults():
    """Round 10: the dense MXU path emits the randomsub frame subset
    and stays trajectory-identical with telemetry off."""
    import go_libp2p_pubsub_tpu.models.faults as fl

    n, t, m = 120, 2, 6
    cfg = rs.RandomSubSimConfig(
        offsets=tuple(int(o)
                      for o in make_circulant_offsets(t, 8, n, seed=3)),
        n_topics=t, d=3)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(3)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, dtype=np.int32)
    sched = fl.FaultSchedule(n_peers=n, horizon=15,
                             down_intervals=((5, 2, 6),),
                             drop_prob=0.05, seed=3)
    params, state = rs.make_randomsub_sim(
        cfg, subs, topic, origin, ticks, dense=True,
        fault_schedule=sched)
    step = rs.make_randomsub_dense_step(cfg, telemetry=tl.TelemetryConfig(
        latency_hist=True, latency_buckets=10))
    fin, counts, frames = tl.telemetry_run_curve(params, state, 15,
                                                 step, m)
    arr = tl.frames_to_arrays(frames)
    assert arr["payload_sent"].sum() > 0
    assert arr["down_peers"].max() == 1
    assert arr["ihave_ids"].sum() == 0
    np.testing.assert_array_equal(
        np.asarray(frames.latency_hist).sum(axis=1),
        np.asarray(counts).sum(axis=1))
    p2, s2 = rs.make_randomsub_sim(
        cfg, subs, topic, origin, ticks, dense=True,
        fault_schedule=sched)
    fin2 = rs.randomsub_run(p2, s2, 15,
                            rs.make_randomsub_dense_step(cfg))
    assert tree_equal(fin, fin2)


@pytest.mark.slow
def test_latency_hists_by_topic_sum_to_device_hist():
    """The host-side per-topic split adds up to the device-side
    latency_hist frames exactly — two views of the same deliveries."""
    cfg, subs, topic, origin, ticks = gossip_inputs(n=300)
    m = len(topic)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    tcfg = hist_tcfg(degree_hist=False, score_hist=False)
    _, counts, frames = tl.telemetry_run_curve(
        params, state, 15, gs.make_gossip_step(cfg, telemetry=tcfg), m)
    by_topic = tl.latency_hists_by_topic(
        np.asarray(counts), np.asarray(params.publish_tick), topic,
        tcfg.latency_buckets)
    total = np.sum([h for h in by_topic.values()], axis=0)
    np.testing.assert_array_equal(
        total, np.asarray(frames.latency_hist).sum(axis=0))
    assert len(by_topic) == len(set(int(x) for x in topic))


def test_hist_percentiles_match_sorted_sample():
    """hist_percentiles over a unit-bucket histogram equals the sorted
    -sample rank convention of tools/tracestat.py."""
    rng = np.random.default_rng(0)
    sample = rng.integers(0, 12, 500)
    hist = np.bincount(sample, minlength=16)
    out = tl.hist_percentiles(hist)
    srt = np.sort(sample)
    for p in (50, 90, 99):
        k = len(srt)
        assert out[f"p{p}"] == int(srt[min(k - 1, (k * p) // 100)])
    assert out["count"] == 500
    empty = tl.hist_percentiles(np.zeros(8, dtype=np.int64))
    assert empty["count"] == 0 and empty["p99"] is None
