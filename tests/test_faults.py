"""Fault injection (models/faults.py): churn, link loss, partitions.

Pins the three satellite invariants of the fault subsystem:
(a) offline-peer invariant — a peer down for the whole run delivers
    and originates nothing;
(b) batched-vs-sequential bit-identity holds under nontrivial fault
    schedules (replicas carrying DISTINCT fault seeds);
(c) a zero-fault FaultSchedule is trajectory-identical to no schedule
    at all (the masked step degrades to the exact unmasked arithmetic);
plus the acceptance scenario: a partition-heal run reports a FINITE
recovery time to 99% reachability, and the schedule validators fail at
build time naming the offending field.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import go_libp2p_pubsub_tpu.models.faults as fl
import go_libp2p_pubsub_tpu.models.floodsub as fs
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.randomsub as rs
from go_libp2p_pubsub_tpu.models._delivery import (
    delivery_fraction_curve,
    recovery_ticks,
)
from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------


def gossip_build(n=240, t=2, m=8, seed=0, score=False, sched=None,
                 cfg_kw=None, publish_tick=None, origin=None):
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t,
        **(cfg_kw or {}))
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(seed)
    topic = rng.integers(0, t, m)
    if origin is None:
        origin = rng.integers(0, n // t, m) * t + topic
    else:
        topic = (np.asarray(origin) % t).astype(topic.dtype)
    if publish_tick is None:
        publish_tick = rng.integers(0, 10, m).astype(np.int32)
    sc = gs.ScoreSimConfig() if score else None
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, np.asarray(origin), publish_tick, seed=seed,
        score_cfg=sc, fault_schedule=sched)
    return cfg, sc, params, state, topic, np.asarray(origin), publish_tick


def state_leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# --------------------------------------------------------------------------
# FaultSchedule constructor validation (fail at build time, named field)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("kw,field", [
    (dict(down_intervals=[(999, 0, 5)]), "down_intervals"),
    (dict(down_intervals=[(1, -1, 5)]), "down_intervals"),
    (dict(down_intervals=[(1, 5, 3)]), "down_intervals"),
    (dict(down_intervals=[(1, 0, 200)]), "down_intervals"),
    (dict(down_intervals=[(1, 0, 6), (1, 4, 9)]), "down_intervals"),
    (dict(down_intervals=[(1, 8, 9), (1, 0, 6)]), "down_intervals"),
    (dict(drop_prob=1.5), "drop_prob"),
    (dict(drop_prob=-0.1), "drop_prob"),
    (dict(drop_prob=np.full((3,), 0.1)), "drop_prob"),
    (dict(partition_windows=[(0, 5)]), "partition_group"),
    (dict(partition_windows=[(5, 3)],
          partition_group=np.zeros(20, np.int64)), "partition_windows"),
    (dict(partition_windows=[(0, 200)],
          partition_group=np.zeros(20, np.int64)), "partition_windows"),
    (dict(partition_windows=[(0, 6), (4, 9)],
          partition_group=np.zeros(20, np.int64)), "partition_windows"),
    (dict(partition_windows=[(0, 5)],
          partition_group=np.zeros(7, np.int64)), "partition_group"),
    (dict(partition_windows=[(0, 5)],
          partition_group=-np.ones(20, np.int64)), "partition_group"),
])
def test_schedule_validation_names_field(kw, field):
    with pytest.raises(ValueError, match=field):
        fl.FaultSchedule(n_peers=20, horizon=100, **kw)


def test_schedule_per_edge_drop_prob_symmetry_detected():
    # round 13: an asymmetric [C, N] array no longer raises — it
    # selects the per-DIRECTION draw; a symmetric one keeps the
    # shared-coin undirected path (directed_drops stays False)
    n = 60
    offs = tuple(int(o) for o in make_circulant_offsets(1, 4, n, seed=0))
    asym = np.zeros((4, n), dtype=np.float32)
    asym[0, 3] = 0.5     # one view of an edge, not its partner view
    sched = fl.FaultSchedule(n_peers=n, horizon=10, drop_prob=asym)
    assert fl.compile_faults(sched, offs).directed_drops
    # the symmetrized form compiles to the undirected shared-coin path
    sym = np.zeros((4, n), dtype=np.float32)
    idx = {o: i for i, o in enumerate(offs)}
    cinv = [idx[-o] for o in offs]
    sym[0, 3] = 0.5
    sym[cinv[0], (3 + offs[0]) % n] = 0.5
    assert not fl.compile_faults(
        fl.FaultSchedule(n_peers=n, horizon=10, drop_prob=sym),
        offs).directed_drops


def test_directed_drop_prob_per_direction_loss():
    """Asymmetric [C, N] drop_prob: the lossy direction drops at its
    own rate while the reverse view stays (nearly) clean, and the
    symmetric-array path remains bit-identical to the scalar draw."""
    n = 80
    offs = tuple(int(o) for o in make_circulant_offsets(1, 4, n, seed=0))
    idx = {o: i for i, o in enumerate(offs)}
    cinv = [idx[-o] for o in offs]
    # symmetric array == scalar, bit for bit
    sym = np.full((4, n), 0.2, dtype=np.float32)
    fp_a = fl.compile_faults(
        fl.FaultSchedule(n_peers=n, horizon=10, drop_prob=sym), offs)
    fp_s = fl.compile_faults(
        fl.FaultSchedule(n_peers=n, horizon=10, drop_prob=0.2), offs)
    for t in range(5):
        np.testing.assert_array_equal(
            np.asarray(fl.link_ok_bits(fp_a, offs, cinv, jnp.int32(t))),
            np.asarray(fl.link_ok_bits(fp_s, offs, cinv, jnp.int32(t))))
    # directed: direction 0 lossy, everything else clean
    asym = np.zeros((4, n), dtype=np.float32)
    asym[0, :] = 0.9
    fp_d = fl.compile_faults(
        fl.FaultSchedule(n_peers=n, horizon=10, drop_prob=asym), offs)
    ups = np.stack([np.asarray(fl.link_ok_bits(
        fp_d, offs, cinv, jnp.int32(t))) for t in range(20)])
    up0 = ((ups >> 0) & 1).mean()
    up_rev = ((ups >> cinv[0]) & 1).mean()
    assert up0 < 0.25, up0            # ~10% up
    assert up_rev == 1.0, up_rev      # reverse direction never drops
    # the unpacked rows form agrees with the packed form
    rows = fl.link_ok_rows(fp_d, offs, cinv, jnp.int32(3))
    bits = fl.link_ok_bits(fp_d, offs, cinv, jnp.int32(3))
    for c in range(4):
        np.testing.assert_array_equal(
            np.asarray(rows[c]),
            ((np.asarray(bits) >> c) & 1).astype(bool))


def test_link_masks_symmetric_and_seed_dependent():
    n = 120
    offs = tuple(int(o) for o in make_circulant_offsets(1, 6, n, seed=2))
    idx = {o: i for i, o in enumerate(offs)}
    cinv = tuple(idx[-o] for o in offs)
    masks = []
    for sd in (0, 1):
        fp = fl.compile_faults(
            fl.FaultSchedule(n_peers=n, horizon=30, drop_prob=0.3,
                             seed=sd), offs, pack_links=False)
        masks.append(np.asarray(
            fl.link_ok_rows(fp, offs, cinv, jnp.int32(4))))
    assert not np.array_equal(masks[0], masks[1])
    for m in masks:      # one coin per undirected edge: views agree
        for c, o in enumerate(offs):
            assert np.array_equal(m[c], np.roll(m[cinv[c]], -o))


# --------------------------------------------------------------------------
# (c) zero-fault schedule == no schedule, bit for bit
# --------------------------------------------------------------------------


@pytest.mark.parametrize("score", [False, True])
def test_zero_fault_schedule_trajectory_identical(score):
    _, _, p0, s0, *_ = gossip_build(score=score)
    cfg, sc, p1, s1, *_ = gossip_build(
        score=score, sched=fl.FaultSchedule(n_peers=240, horizon=40))
    step = gs.make_gossip_step(cfg, sc)
    out0 = gs.gossip_run(p0, s0, 40, step)
    out1 = gs.gossip_run(p1, s1, 40, step)
    assert state_leaves_equal(out0, out1)


# --------------------------------------------------------------------------
# (a) offline-peer invariant, all three simulators
# --------------------------------------------------------------------------


def test_offline_peer_invariant_gossipsub():
    n, m = 240, 8
    down = 6                      # topic 0 peer, also an origin below
    sched = fl.FaultSchedule(
        n_peers=n, horizon=80, down_intervals=[(down, 0, 80)],
        drop_prob=0.05, seed=3)
    cfg, sc, params, state, topic, origin, _ = gossip_build(
        n=n, m=m, score=True, sched=sched,
        origin=[down, 8, 10, 12, 14, 16, 18, 20])
    step = gs.make_gossip_step(cfg, sc)
    out = gs.gossip_run(params, state, 80, step)
    ft = np.asarray(gs.first_tick_matrix(out, m))
    assert (ft[down] < 0).all(), "down peer must deliver nothing"
    reach = np.asarray(gs.reach_counts(params, out))
    assert reach[0] == 0, "down origin must originate nothing"
    # everything else still flows (gossip repair rides over link loss)
    assert (reach[1:] > 0).all()
    assert int(gs.mesh_degrees(out)[down]) == 0


def test_offline_peer_invariant_floodsub():
    n, m = 120, 4
    offs = tuple(int(o) for o in make_circulant_offsets(1, 6, n, seed=2))
    subs = np.ones((n, 1), dtype=bool)
    origin = np.array([3, 10, 20, 30])
    sched = fl.FaultSchedule(n_peers=n, horizon=30,
                             down_intervals=[(3, 0, 30)], seed=1)
    params, state = fs.make_flood_sim(
        None, None, subs, None, np.zeros(m, np.int64), origin,
        np.zeros(m, np.int32), fault_schedule=sched, fault_offsets=offs)
    core = fs.make_circulant_step_core(offs)
    out = fs.flood_run(params, state, 30, lambda p, s: core(p, s)[0])
    ft = np.asarray(fs.first_tick_matrix(out, m))
    assert (ft[3] < 0).all()
    reach = np.asarray(fs.reach_counts(params, out))
    assert reach[0] == 0 and (reach[1:] == n - 1).all()


def test_offline_peer_invariant_randomsub():
    n, m = 120, 4
    cfg = rs.RandomSubSimConfig(
        offsets=tuple(int(o)
                      for o in make_circulant_offsets(1, 12, n, seed=2)))
    subs = np.ones((n, 1), dtype=bool)
    origin = np.array([3, 10, 20, 30])
    sched = fl.FaultSchedule(n_peers=n, horizon=40,
                             down_intervals=[(3, 0, 40)], seed=1)
    params, state = rs.make_randomsub_sim(
        cfg, subs, np.zeros(m, np.int64), origin, np.zeros(m, np.int32),
        fault_schedule=sched)
    out = rs.randomsub_run(params, state, 40, rs.make_randomsub_step(cfg))
    ft = np.asarray(rs.first_tick_matrix(out, m))
    assert (ft[3] < 0).all()
    assert np.asarray(rs.reach_counts(params, out))[0] == 0


# --------------------------------------------------------------------------
# fast fault smoke (tier-1): churn + loss + partition in one short run
# --------------------------------------------------------------------------


def test_fault_smoke_churned_peer_rejoins_and_recovers():
    """A peer that goes down loses its mesh (PRUNE/backoff semantics),
    rejoins through the normal GRAFT path, and catches up on traffic
    published after its rejoin."""
    n, m = 240, 2
    sched = fl.FaultSchedule(
        n_peers=n, horizon=100, down_intervals=[(4, 5, 15)],
        drop_prob=0.02, seed=2)
    cfg, sc, params, state, *_ = gossip_build(
        n=n, t=2, m=m, sched=sched, origin=[8, 10],
        publish_tick=np.array([30, 40], np.int32),
        cfg_kw=dict(backoff_ticks=10))
    step = gs.make_gossip_step(cfg, sc)
    mid = gs.gossip_run(params, gs.tree_copy(state), 10, step)
    assert int(gs.mesh_degrees(mid)[4]) == 0, "down peer keeps no mesh"
    out = gs.gossip_run(params, state, 100, step)
    assert int(gs.mesh_degrees(out)[4]) >= cfg.d_lo, "rejoin via GRAFT"
    reach = np.asarray(gs.reach_counts(params, out))
    assert (reach == n // 2).all(), "post-rejoin publishes reach everyone"


# --------------------------------------------------------------------------
# (b) batched == sequential under nontrivial fault schedules
# --------------------------------------------------------------------------


def test_batch_matches_sequential_under_faults():
    n, t, m, B = 240, 2, 8, 3
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    grp = (np.arange(n) % 2).astype(np.int64)

    def sched(k):
        # distinct fault seeds AND distinct churn victims per replica
        return fl.FaultSchedule(
            n_peers=n, horizon=60, seed=100 + k,
            down_intervals=[(10 + 2 * k, 5, 25)], drop_prob=0.05,
            partition_group=grp, partition_windows=[(12, 20)])

    specs = [dict(subs=subs, msg_topic=topic, msg_origin=origin,
                  msg_publish_tick=ticks, seed=k, fault_schedule=sched(k))
             for k in range(B)]
    step = gs.make_gossip_step(cfg, None)
    params_b, state_b = gs.stack_sims(cfg, specs)
    fin_b = gs.gossip_run_batch(params_b, state_b, 60, step)
    for k in range(B):
        p, s = gs.make_gossip_sim(cfg, **specs[k])
        fin = gs.gossip_run(p, s, 60, step)
        assert state_leaves_equal(fin, gs.index_trees(fin_b, k)), k


def test_stack_sims_names_mismatched_static_config():
    n, t, m = 240, 2, 4
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    base = dict(subs=subs, msg_topic=np.zeros(m, np.int64),
                msg_origin=(np.arange(m) * t).astype(np.int64),
                msg_publish_tick=np.zeros(m, np.int32))
    with pytest.raises(ValueError, match="score_cfg"):
        gs.stack_sims(cfg, [dict(**base, seed=0),
                            dict(**base, seed=1,
                                 score_cfg=gs.ScoreSimConfig())])
    with pytest.raises(ValueError, match="track_first_tick"):
        gs.stack_sims(cfg, [dict(**base, seed=0),
                            dict(**base, seed=1,
                                 track_first_tick=False)])
    # array-shape mismatches name the offending params field
    other = dict(base, msg_topic=np.zeros(m + 32, np.int64),
                 msg_origin=(np.arange(m + 32) * t % n).astype(np.int64),
                 msg_publish_tick=np.zeros(m + 32, np.int32))
    with pytest.raises(ValueError, match="deliver_words"):
        gs.stack_sims(cfg, [dict(**base, seed=0),
                            dict(**other, seed=1)])


# --------------------------------------------------------------------------
# acceptance: partition heal -> finite recovery time to 99% reachability
# --------------------------------------------------------------------------


def test_partition_heal_reports_finite_recovery():
    n, m = 240, 3
    heal = 50
    grp = (np.arange(n) < n // 2).astype(np.int64)
    sched = fl.FaultSchedule(
        n_peers=n, horizon=120, partition_group=grp,
        partition_windows=[(20, heal)], seed=5)
    # msg 0: published just before heal from side 0 — still inside the
    # IHAVE window (history_gossip) at heal, so gossip repair carries it
    # across and recovery is FINITE.  msg 1: published deep inside the
    # partition — aged out of every mcache by heal, never crosses (the
    # reference has the same bound: gossip only advertises the recent
    # window).  msg 2: published after heal — instant full spread.
    cfg, sc, params, state, *_ = gossip_build(
        n=n, t=1, m=m, sched=sched, origin=[2, 4, 6],
        publish_tick=np.array([heal - 2, 25, heal + 10], np.int32))
    step = gs.make_gossip_step(cfg, sc)
    state, counts = gs.gossip_run_curve(params, state, 120, step, m)
    counts = np.asarray(counts)
    rec = np.asarray(recovery_ticks(jnp.asarray(counts), heal,
                                    jnp.float32(n), frac=0.99))
    assert 0 < rec[0] <= 30, f"near-heal msg must recover, got {rec[0]}"
    assert rec[1] == -1, "mcache-aged msg cannot cross the heal"
    assert 0 < rec[2] <= 30, "post-heal publish spreads"
    frac = np.asarray(delivery_fraction_curve(jnp.asarray(counts),
                                              jnp.float32(n)))
    assert frac[-1, 0] >= 0.99
    # during the partition the near-heal message is confined to its side
    assert frac[heal - 1, 0] <= 0.55


# --------------------------------------------------------------------------
# refusals
# --------------------------------------------------------------------------


def test_pallas_step_accepts_fault_configs():
    """Round 9: fault masks thread through the pallas kernel — a
    faulted config on the kernel path is a CAPABILITY now (the full
    parity matrix is pinned in tests/test_pallas_receive.py; this
    pins acceptance where the refusal used to be).  An UNPADDED state
    with the kernel forced still raises the pad requirement."""
    sched = fl.FaultSchedule(n_peers=240, horizon=10,
                             down_intervals=((0, 0, 5),))
    cfg, sc, params, state, *_ = gossip_build(sched=sched)
    step = gs.make_gossip_step(cfg, sc, use_pallas_receive=True)
    with pytest.raises(ValueError, match="pad_to_block"):
        step(params, state)     # the PAD refusal, not a fault refusal
    n, t = 240, 2
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    p_k, s_k = gs.make_gossip_sim(
        cfg, subs, np.zeros(2, np.int64), np.zeros(2, np.int64),
        np.zeros(2, np.int32), pad_to_block=256, fault_schedule=sched)
    step_k = gs.make_gossip_step(cfg, sc, receive_block=256,
                                 receive_interpret=True)
    out = gs.gossip_run(p_k, s_k, 3, step_k)
    assert int(np.asarray(out.tick)) == 3


def test_dense_randomsub_threads_faults_offline_invariant():
    """Round 10: the dense MXU path THREADS fault schedules
    (compile_faults_dense) — the offline-peer invariant holds on it,
    and the per-edge drop_prob form (circulant-keyed) still rejects
    with a message naming the constraint."""
    n, m = 60, 4
    cfg = rs.RandomSubSimConfig(
        offsets=tuple(int(o)
                      for o in make_circulant_offsets(1, 6, n, seed=0)))
    subs = np.ones((n, 1), dtype=bool)
    origin = np.array([3, 10, 20, 30])
    sched = fl.FaultSchedule(n_peers=n, horizon=40,
                             down_intervals=[(3, 0, 40)], seed=1)
    params, state = rs.make_randomsub_sim(
        cfg, subs, np.zeros(m, np.int64), origin, np.zeros(m, np.int32),
        dense=True, fault_schedule=sched)
    out = rs.randomsub_run(params, state, 40,
                           rs.make_randomsub_dense_step(cfg))
    ft = np.asarray(rs.first_tick_matrix(out, m))
    assert (ft[3] < 0).all()
    assert np.asarray(rs.reach_counts(params, out))[0] == 0
    per_edge = np.full((len(cfg.offsets), n), 0.5, dtype=np.float32)
    with pytest.raises(ValueError, match="circulant"):
        rs.make_randomsub_sim(
            cfg, subs, np.zeros(m, np.int64), origin,
            np.zeros(m, np.int32), dense=True,
            fault_schedule=fl.FaultSchedule(
                n_peers=n, horizon=5, drop_prob=per_edge))


def test_flood_gather_path_threads_faults_offline_invariant():
    """Round 10: the gather table path THREADS fault schedules
    (compile_faults_gather) — flood_step honors churn on a symmetric
    nbrs table, with the same offline-peer invariant as the circulant
    core."""
    n, m = 120, 4
    offs = tuple(int(o) for o in make_circulant_offsets(1, 6, n, seed=2))
    nbrs = np.stack([(np.arange(n) + o) % n for o in offs], axis=1)
    mask = np.ones_like(nbrs, dtype=bool)
    subs = np.ones((n, 1), dtype=bool)
    origin = np.array([3, 10, 20, 30])
    sched = fl.FaultSchedule(n_peers=n, horizon=30,
                             down_intervals=[(3, 0, 30)], seed=1)
    params, state = fs.make_flood_sim(
        nbrs, mask, subs, None, np.zeros(m, np.int64), origin,
        np.zeros(m, np.int32), fault_schedule=sched)
    out = fs.flood_run(params, state, 30)
    ft = np.asarray(fs.first_tick_matrix(out, m))
    assert (ft[3] < 0).all()
    reach = np.asarray(fs.reach_counts(params, out))
    assert reach[0] == 0 and (reach[1:] == n - 1).all()


def test_flood_gather_faulted_matches_circulant_core():
    """The SAME schedule on the same ring must produce the same
    delivery outcome whether the topology is expressed as circulant
    offsets or as an equivalent gather table — churn masks are
    topology-independent (link coins differ by construction, so this
    pins churn + partitions only)."""
    n, m = 120, 4
    offs = tuple(int(o) for o in make_circulant_offsets(1, 6, n, seed=2))
    nbrs = np.stack([(np.arange(n) + o) % n for o in offs], axis=1)
    subs = np.ones((n, 1), dtype=bool)
    origin = np.array([3, 10, 20, 30])
    sched = fl.FaultSchedule(
        n_peers=n, horizon=30, down_intervals=[(3, 2, 9), (50, 0, 30)],
        partition_group=(np.arange(n) % 2).astype(np.int32),
        partition_windows=((4, 8),), seed=1)
    p_g, s_g = fs.make_flood_sim(
        nbrs, np.ones_like(nbrs, dtype=bool), subs, None,
        np.zeros(m, np.int64), origin, np.zeros(m, np.int32),
        fault_schedule=sched)
    out_g = fs.flood_run(p_g, s_g, 30)
    p_c, s_c = fs.make_flood_sim(
        None, None, subs, None, np.zeros(m, np.int64), origin,
        np.zeros(m, np.int32), fault_schedule=sched,
        fault_offsets=offs)
    core = fs.make_circulant_step_core(offs)
    out_c = fs.flood_run(p_c, s_c, 30, lambda p, s: core(p, s)[0])
    np.testing.assert_array_equal(
        np.asarray(fs.first_tick_matrix(out_g, m)),
        np.asarray(fs.first_tick_matrix(out_c, m)))


# --------------------------------------------------------------------------
# metric helpers
# --------------------------------------------------------------------------


def test_recovery_ticks_semantics():
    counts = np.zeros((10, 3), np.int32)
    counts[2, 0] = 100          # msg 0 full before heal -> recovery 0
    counts[7, 1] = 100          # msg 1 recovers 3 ticks after heal
    counts[3, 2] = 50           # msg 2 stuck at 50% -> never
    rec = np.asarray(recovery_ticks(jnp.asarray(counts), 4,
                                    jnp.float32(100), frac=0.99))
    assert rec.tolist() == [0, 3, -1]


# --------------------------------------------------------------------------
# long sweeps (excluded from tier-1)
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_degradation_monotone_in_drop_rate_slow():
    """Delivery latency degrades gracefully (not cliff-like) as the
    link-drop rate rises; final delivery holds while the rate stays
    below the mesh's redundancy."""
    n, m = 600, 12
    finals, mean_ticks = [], []
    for level in (0.0, 0.1, 0.25):
        sched = fl.FaultSchedule(n_peers=n, horizon=200,
                                 drop_prob=level, seed=7)
        cfg, sc, params, state, *_ = gossip_build(
            n=n, t=1, m=m, sched=sched,
            publish_tick=np.full(m, 60, np.int32),
            origin=list(range(0, 2 * m, 2)))
        step = gs.make_gossip_step(cfg, sc)
        out = gs.gossip_run(params, state, 160, step)
        ft = np.asarray(gs.first_tick_matrix(out, m))
        finals.append((ft >= 0).mean())
        mean_ticks.append((ft[ft >= 0] - 60).mean())
    assert finals[0] == 1.0 and finals[-1] >= 0.99
    assert mean_ticks[0] <= mean_ticks[1] <= mean_ticks[2] * 1.05


@pytest.mark.slow
def test_rolling_churn_long_run_slow():
    """A third of the network cycling down/up in staggered waves still
    delivers to every peer that is up from publish to run end."""
    n, m = 600, 6
    ivs = [(p, 40 + (p % 3) * 20, 60 + (p % 3) * 20)
           for p in range(0, n, 3)]
    sched = fl.FaultSchedule(n_peers=n, horizon=260,
                             down_intervals=ivs, drop_prob=0.05, seed=9)
    cfg, sc, params, state, *_ = gossip_build(
        n=n, t=1, m=m, sched=sched,
        publish_tick=np.full(m, 140, np.int32),
        origin=[1, 4, 7, 10, 13, 16])
    step = gs.make_gossip_step(cfg, sc)
    out = gs.gossip_run(params, state, 260, step)
    ft = np.asarray(gs.first_tick_matrix(out, m))
    up_after_publish = np.ones(n, dtype=bool)
    for p, s, e in ivs:
        if e > 140:
            up_after_publish[p] = False
    assert (ft[up_after_publish] >= 0).all()
