"""Vectorized GossipSub simulator tests (models/gossipsub.py).

Mirrors the reference's gossipsub_test.go checks at sim scale: mesh degree
convergence into [Dlo, Dhi], GRAFT/PRUNE handshake symmetry, backoff
enforcement, full dissemination over the mesh, gossip (IHAVE/IWANT) repair
for mesh-less peers, and fanout publishing by unsubscribed peers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    _pack_bits_pm_np,
    index_trees,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
    mesh_degrees,
    mesh_symmetry_fraction,
    gossip_run,
    gossip_run_batch,
    gossip_run_curve,
    gossip_run_curve_batch,
    reach_counts,
    refresh_gates,
    stack_sims,
    tree_copy,
)


def build(n=600, t=3, c=16, n_msgs=8, seed=1, subs_mask=None,
          publish_tick=0, unsubscribe=(), **cfg_kw):
    cfg = GossipSimConfig(
        offsets=make_gossip_offsets(t, c, n, seed=seed), n_topics=t,
        **cfg_kw)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    for p in unsubscribe:
        subs[p] = False
    if subs_mask is not None:
        subs &= subs_mask[:, None]
    rng = np.random.default_rng(seed)
    msg_topic = rng.integers(0, t, n_msgs)
    msg_origin = rng.integers(0, n // t, n_msgs) * t + msg_topic
    ticks = np.full(n_msgs, publish_tick, dtype=np.int32)
    params, state = make_gossip_sim(cfg, subs, msg_topic, msg_origin, ticks)
    return cfg, params, state, msg_topic, msg_origin


def test_mesh_degree_converges():
    cfg, params, state, *_ = build(n_msgs=0)
    # pad a zero-length message table to one word
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 10, step)
    deg = np.asarray(mesh_degrees(out))
    assert (deg[np.asarray(params.subscribed)] >= cfg.d_lo).all()
    assert (deg[np.asarray(params.subscribed)] <= cfg.d_hi).all()


def test_mesh_symmetric_after_each_step():
    cfg, params, state, *_ = build(n_msgs=0)
    step = jax.jit(make_gossip_step(cfg))
    for _ in range(5):
        state, _ = step(params, state)
        frac = float(mesh_symmetry_fraction(state, cfg))
        assert frac == pytest.approx(1.0), frac


def test_unsubscribed_peers_stay_out_of_mesh():
    cfg, params, state, *_ = build(n_msgs=0, unsubscribe=range(0, 60))
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 10, step)
    deg = np.asarray(mesh_degrees(out))
    sub = np.asarray(params.subscribed)
    assert (deg[~sub] == 0).all()
    assert (deg[sub] >= cfg.d_lo).all()


def test_backoff_blocks_regraft():
    cfg, params, state, *_ = build(n_msgs=0, backoff_ticks=1000)
    step = jax.jit(make_gossip_step(cfg))
    for _ in range(3):
        state, _ = step(params, state)
    # force-prune everything: clear mesh, set backoff everywhere
    # (manual surgery -> the carried gate words must be refreshed)
    state = refresh_gates(cfg, None, params, state.replace(
        mesh=jnp.zeros_like(state.mesh),
        backoff=jnp.full_like(state.backoff, 10_000)))
    for _ in range(5):
        state, _ = step(params, state)
    assert int(mesh_degrees(state).sum()) == 0  # nobody can re-graft


def test_full_dissemination_over_mesh():
    cfg, params, state, msg_topic, _ = build(n=600, t=3, n_msgs=8)
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 40, step)
    reach = np.asarray(reach_counts(params, out))
    class_size = 600 // 3
    np.testing.assert_array_equal(reach, class_size)


def test_reach_curve_monotone_and_complete():
    cfg, params, state, *_ = build(n=600, t=3, n_msgs=8)
    step = make_gossip_step(cfg)
    out, counts = gossip_run_curve(params, state, 40, step, 8)
    counts = np.asarray(counts)  # [ticks, M] per-tick deliveries
    total = counts.sum(axis=0)
    np.testing.assert_array_equal(total, 600 // 3)
    # deliveries start at the publish tick and stop once everyone has it
    assert (counts[0] >= 1).all()
    assert (counts[-5:] == 0).all()


def test_gossip_repairs_meshless_peers():
    """Peers that can never graft (eternal backoff both directions) still
    receive every message via IHAVE/IWANT gossip — the lazy-pull repair
    path (reference handleIHave/handleIWant gossipsub.go:610-711)."""
    cfg, params, state, *_ = build(n=600, t=3, n_msgs=8)
    isolated = np.zeros(600, dtype=bool)
    isolated[::10] = True  # 10% of peers
    iso_j = jnp.asarray(isolated)
    # eternal backoff on every edge touching an isolated peer: they never
    # graft out, and partners reject their grafts / never graft to them
    from go_libp2p_pubsub_tpu.models.gossipsub import transfer_mask
    iso_cols = jnp.broadcast_to(iso_j[None, :], state.backoff.shape)
    blocked = iso_cols | transfer_mask(iso_cols, cfg)
    state = refresh_gates(cfg, None, params, state.replace(
        backoff=jnp.where(blocked, 30_000, state.backoff)))
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 40, step)
    deg = np.asarray(mesh_degrees(out))
    assert (deg[isolated] == 0).all()
    # every subscriber — including every mesh-less one — still got it
    reach = np.asarray(reach_counts(params, out))
    np.testing.assert_array_equal(reach, 600 // 3)


def test_fanout_publish_without_subscription():
    """An unsubscribed publisher floods via fanout (gossipsub.go:961-983)
    and its fanout set expires FanoutTTL after the last publish."""
    cfg, params, state, msg_topic, msg_origin = build(
        n=600, t=3, n_msgs=4, publish_tick=5, fanout_ttl_ticks=10,
        unsubscribe=set(int(o) for o in
                        np.random.default_rng(1).integers(0, 200, 4) * 3))
    # re-point all messages at one known unsubscribed origin
    origin = int(np.flatnonzero(~np.asarray(params.subscribed))[0])
    n_msgs = 4
    topic = origin % 3
    import numpy as _np
    origin_bits = _np.zeros((600, n_msgs), dtype=bool)
    origin_bits[origin, :] = True
    deliver = _np.asarray(params.subscribed)[:, None] & (
        (_np.arange(600) % 3 == topic)[:, None])
    from go_libp2p_pubsub_tpu.ops.graph import pack_bits_pm
    params = params.replace(
        origin_words=pack_bits_pm(jnp.asarray(origin_bits)),
        deliver_words=pack_bits_pm(jnp.asarray(
            _np.broadcast_to(deliver, (600, n_msgs)))),
        publish_tick=jnp.full((n_msgs,), 5, dtype=jnp.int32))
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 40, step)
    reach = np.asarray(reach_counts(params, out))
    subscribers = int((np.asarray(params.subscribed)
                       & (np.arange(600) % 3 == topic)).sum())
    np.testing.assert_array_equal(reach, subscribers)
    # fanout expired: TTL (10) past last publish (tick 5) < 40 ticks run
    assert int(jax.lax.population_count(out.fanout).sum()) == 0


def test_sharded_step_matches_single_device():
    """The same step over an 8-device peer-sharded mesh is bit-identical
    to the single-device run (pjit + roll -> collective permutes)."""
    from go_libp2p_pubsub_tpu.parallel.mesh import make_mesh, shard_peer_tree

    cfg, params, state, *_ = build(n=512, t=2, c=8, n_msgs=8, d=3, d_lo=2,
                                   d_hi=6, d_score=2, d_out=1, d_lazy=2)
    step = make_gossip_step(cfg)
    # copy for the single-device run: the runner donates its state, and
    # shard_peer_tree shares non-peer-axis buffers (the PRNG key) with
    # the source tree
    mesh = make_mesh(8)
    params_s = shard_peer_tree(params, mesh, 512)
    state_s = shard_peer_tree(state, mesh, 512)
    out_single = gossip_run(params, tree_copy(state), 12, step)
    out_shard = gossip_run(params_s, state_s, 12, step)

    np.testing.assert_array_equal(np.asarray(out_single.have),
                                  np.asarray(out_shard.have))
    np.testing.assert_array_equal(np.asarray(out_single.mesh),
                                  np.asarray(out_shard.mesh))
    np.testing.assert_array_equal(np.asarray(out_single.first_tick),
                                  np.asarray(out_shard.first_tick))


def test_config_validation():
    with pytest.raises(ValueError):
        GossipSimConfig(offsets=(3, -3), n_topics=2)  # not mult of T
    with pytest.raises(ValueError):
        GossipSimConfig(offsets=(2, 4), n_topics=2)   # not negation-closed
    with pytest.raises(ValueError):
        GossipSimConfig(offsets=tuple(range(-6, 0)) + tuple(range(1, 7)),
                        n_topics=1, d_hi=12)          # C <= Dhi


def test_mixed_protocol_floodsub_peers():
    """Mixed network (feature negotiation, gossipsub_feat.go:11-52):
    30% of peers speak /floodsub/1.0.0 — they receive everything, never
    appear in any mesh, and full dissemination still holds
    (mirrors the mixed-protocol test, gossipsub_test.go:810)."""
    import numpy as np
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        make_gossip_sim as _mgs, make_gossip_offsets as _mgo,
        GossipSimConfig as _Cfg)
    n, t, m = 600, 3, 8
    cfg = _Cfg(offsets=_mgo(t, 16, n, seed=9), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(9)
    flood_proto = rng.random(n) < 0.3
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    params, state = _mgs(cfg, subs, topic, origin,
                         np.zeros(m, dtype=np.int32),
                         flood_proto=flood_proto)
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 40, step)
    # full dissemination including the floodsub peers
    np.testing.assert_array_equal(np.asarray(reach_counts(params, out)),
                                  n // t)
    deg = np.asarray(mesh_degrees(out))
    assert (deg[flood_proto] == 0).all()       # no mesh at flood peers
    # gossipsub peers' meshes exclude flood-proto candidates
    from go_libp2p_pubsub_tpu.models.gossipsub import mesh_matrix
    cand_flood = np.stack([np.roll(flood_proto, -o) for o in cfg.offsets])
    mesh = np.asarray(mesh_matrix(out, cfg))
    assert (mesh & cand_flood).sum() == 0
    # gossipsub-only subnetwork still has healthy degrees
    gs_rows = ~flood_proto
    assert (deg[gs_rows] >= 1).all()


def test_fused_equals_split_scored_no_gossip():
    """The fused one-roll-per-edge path vs the split forward/gossip
    loops (VERDICT r3 weak-5): with lazy gossip off the two
    formulations share the credit policy, so ENTIRE state trajectories
    — possession, mesh, backoff, fanout, and all score counters — must
    match bit-for-bit on a shared seed with scoring on (this pins the
    pair-packed gate transfer and the A-mask handshake)."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n, t, C, m = 600, 3, 8, 10
    rng = np.random.default_rng(2)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=2), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=0,
        gossip_factor=0.0)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.sort(rng.integers(0, 10, m)).astype(np.int32)
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       score_cfg=sc)
    out_f = gs.gossip_run(params, gs.tree_copy(state), 30,
                          gs.make_gossip_step(cfg, sc))
    out_s = gs.gossip_run(params, state, 30,
                          gs.make_gossip_step(cfg, sc, force_split=True))
    for f in ("have", "mesh", "backoff", "fanout", "recent",
              "first_tick"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_f, f)), np.asarray(getattr(out_s, f)),
            err_msg=f)
    for f in ("time_in_mesh", "first_deliveries", "invalid_deliveries",
              "behaviour_penalty"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_f.scores, f)),
            np.asarray(getattr(out_s.scores, f)), err_msg=f)
    assert np.asarray(out_f.have).any()


def test_fused_equals_split_v10_with_gossip():
    """v1.0 (no scoring => no credit-policy divergence): fused and split
    paths match bit-for-bit INCLUDING the lazy-gossip repair traffic."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n, t, C, m = 600, 3, 8, 10
    rng = np.random.default_rng(4)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=4), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=3,
        gossip_factor=0.25)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.sort(rng.integers(0, 10, m)).astype(np.int32)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    out_f = gs.gossip_run(params, gs.tree_copy(state), 30,
                          gs.make_gossip_step(cfg))
    out_s = gs.gossip_run(params, state, 30,
                          gs.make_gossip_step(cfg, force_split=True))
    for f in ("have", "mesh", "backoff", "fanout", "recent",
              "first_tick"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_f, f)), np.asarray(getattr(out_s, f)),
            err_msg=f)
    assert np.asarray(out_f.have).any()


def test_pipelined_gates_match_recompute():
    """The carried gate words (emitted by the previous tick's epilogue)
    must be bit-identical to recomputing the gates at tick start —
    including the v1.1 thresholds, the RED gater draw, and adversarial
    counter dynamics (invalid traffic keeps the gater under pressure)."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n, t, C, m = 600, 3, 16, 10
    rng = np.random.default_rng(5)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=5), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, backoff_ticks=6)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.sort(rng.integers(0, 10, m)).astype(np.int32)
    sc = gs.ScoreSimConfig(sybil_ihave_spam=True)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        sybil=rng.random(n) < 0.2, msg_invalid=rng.random(m) < 0.4,
        app_score=rng.normal(0, 0.1, n).astype(np.float32))
    out_p = gs.gossip_run(params, gs.tree_copy(state), 25,
                          gs.make_gossip_step(cfg, sc))
    out_r = gs.gossip_run(params, state, 25,
                          gs.make_gossip_step(cfg, sc,
                                              pipeline_gates=False))
    for f in ("have", "mesh", "backoff", "fanout", "recent",
              "first_tick"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_p, f)), np.asarray(getattr(out_r, f)),
            err_msg=f)
    for f in ("time_in_mesh", "first_deliveries", "invalid_deliveries",
              "behaviour_penalty"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_p.scores, f)),
            np.asarray(getattr(out_r.scores, f)), err_msg=f)
    # the carried gates themselves equal a fresh recompute on the
    # final state
    np.testing.assert_array_equal(
        np.asarray(out_p.gates),
        np.asarray(gs.compute_gates(
            cfg, sc, params, out_p,
            jax.random.key_data(out_p.key)[-1])))
    assert np.asarray(out_p.scores.behaviour_penalty).max() > 0


def test_gossip_repair_with_exact_sampling():
    """binomial_gossip_sampling=False restores the reference's exact
    uniform k-subset target selection (rank-compare path) — gossip
    repair must work identically well."""
    cfg, params, state, *_ = build(n=600, t=3, n_msgs=8,
                                   binomial_gossip_sampling=False)
    isolated = np.zeros(600, dtype=bool)
    isolated[::10] = True
    iso_j = jnp.asarray(isolated)
    from go_libp2p_pubsub_tpu.models.gossipsub import transfer_mask
    iso_cols = jnp.broadcast_to(iso_j[None, :], state.backoff.shape)
    blocked = iso_cols | transfer_mask(iso_cols, cfg)
    state = refresh_gates(cfg, None, params, state.replace(
        backoff=jnp.where(blocked, 30_000, state.backoff)))
    step = make_gossip_step(cfg)
    out = gossip_run(params, state, 40, step)
    assert (np.asarray(mesh_degrees(out))[isolated] == 0).all()
    np.testing.assert_array_equal(
        np.asarray(reach_counts(params, out)), 600 // 3)


# --------------------------------------------------------------------------
# Batched replica execution (gossip_run_batch / stack_sims) + the
# donated state carry
# --------------------------------------------------------------------------


def _replica_specs(n=300, t=3, c=16, n_msgs=8, seeds=(1, 2, 3)):
    cfg = GossipSimConfig(
        offsets=make_gossip_offsets(t, c, n, seed=1), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(1)
    topic = rng.integers(0, t, n_msgs)
    origin = rng.integers(0, n // t, n_msgs) * t + topic
    ticks = np.zeros(n_msgs, dtype=np.int32)
    specs = [dict(subs=subs, msg_topic=topic, msg_origin=origin,
                  msg_publish_tick=ticks, seed=s) for s in seeds]
    return cfg, specs


def test_batch_matches_sequential():
    """gossip_run_batch over B=3 stacked mesh seeds is bit-identical
    per replica to three sequential gossip_run calls: vmap adds no
    arithmetic, so batching replicas can never change a trajectory."""
    from go_libp2p_pubsub_tpu.models.gossipsub import ScoreSimConfig

    cfg, specs = _replica_specs()
    sc = ScoreSimConfig()
    step = make_gossip_step(cfg, sc)
    params_b, state_b = stack_sims(cfg, specs, score_cfg=sc)
    out_b = gossip_run_batch(params_b, state_b, 20, step)
    for i, spec in enumerate(specs):
        params, state = make_gossip_sim(cfg, **spec, score_cfg=sc)
        out = gossip_run(params, state, 20, step)
        ref = jax.tree_util.tree_leaves(out)
        got = jax.tree_util.tree_leaves(index_trees(out_b, i))
        assert len(ref) == len(got)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_batch_curve_matches_sequential_curve():
    """gossip_run_curve_batch returns [n_ticks, B, M] per-tick counts,
    each replica column equal to its sequential gossip_run_curve."""
    cfg, specs = _replica_specs()
    step = make_gossip_step(cfg)
    params_b, state_b = stack_sims(cfg, specs)
    _, counts_b = gossip_run_curve_batch(params_b, state_b, 25, step, 8)
    counts_b = np.asarray(counts_b)
    assert counts_b.shape == (25, len(specs), 8)
    for i, spec in enumerate(specs):
        params, state = make_gossip_sim(cfg, **spec)
        _, counts = gossip_run_curve(params, state, 25, step, 8)
        np.testing.assert_array_equal(counts_b[:, i, :],
                                      np.asarray(counts))


def test_batch_donated_carry_same_fingerprint():
    """The donated-carry path is value-invisible: running a batch whose
    input buffers are consumed (donated) yields the same final state
    fingerprint as running from an undonated copy, and the donated
    input is actually consumed where the backend supports donation."""
    cfg, specs = _replica_specs()
    step = make_gossip_step(cfg)
    params_b, state_b = stack_sims(cfg, specs)
    keep = tree_copy(state_b)
    out_donated = gossip_run_batch(params_b, state_b, 15, step)
    out_copy = gossip_run_batch(params_b, keep, 15, step)

    def fingerprint(tree):
        import hashlib
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(tree):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        return h.hexdigest()

    assert fingerprint(out_donated) == fingerprint(out_copy)


def test_single_run_donates_its_carry():
    """gossip_run consumes its state argument (donate_argnums): the
    input buffers must be gone after the call on backends that honor
    donation — the memory-amortization contract of the runners."""
    cfg, specs = _replica_specs(seeds=(1,))
    step = make_gossip_step(cfg)
    params, state = make_gossip_sim(cfg, **specs[0])
    _ = gossip_run(params, state, 5, step)
    if jax.default_backend() in ("cpu", "tpu", "gpu"):
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(state.mesh)


def test_stack_sims_rejects_structure_mismatch():
    """Replicas built for different configs (different pytree statics /
    None leaves) must be refused, not silently mis-stacked."""
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        ScoreSimConfig, stack_trees)

    cfg, specs = _replica_specs(seeds=(1, 2))
    _, s_plain = make_gossip_sim(cfg, **specs[0])
    _, s_scored = make_gossip_sim(cfg, **specs[1],
                                  score_cfg=ScoreSimConfig())
    with pytest.raises(ValueError, match="structure"):
        stack_trees([s_plain, s_scored])


def test_pack_bits_pm_np_matches_device():
    """The host-side packer is bit-exact against ops.graph.pack_bits_pm
    across padded and word-aligned widths (and the uint32 view is
    explicitly little-endian — '<u4' — so the equality holds regardless
    of host byte order)."""
    from go_libp2p_pubsub_tpu.ops.graph import pack_bits_pm

    rng = np.random.default_rng(0)
    for n, m in ((7, 1), (5, 24), (3, 32), (4, 40), (2, 64), (6, 65)):
        bits = rng.random((n, m)) < 0.5
        host = _pack_bits_pm_np(bits)
        dev = np.asarray(pack_bits_pm(jnp.asarray(bits)))
        assert host.dtype == np.uint32
        np.testing.assert_array_equal(host, dev, err_msg=f"n={n} m={m}")
