"""In-scan runtime invariant checking (models/invariants.py).

Pins the round-11 acceptance properties:
(a) ``invariants=None`` is bit-identical to the pre-invariant step
    (state pytree and trajectory);
(b) with the checker ON, every pre-existing state field's trajectory
    is bit-identical too (the checker only reads), and all green
    paths — scored, faulted, attacked, flood, randomsub, batched —
    report ZERO violations;
(c) the checker actually FIRES: a deliberately seeded defect (state
    surgery creating an impossible state, and a broken step wrapper)
    trips the right bit and records the first violating tick.
"""

import numpy as np
import pytest

import jax

import go_libp2p_pubsub_tpu.models.faults as fl
import go_libp2p_pubsub_tpu.models.floodsub as fs
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.invariants as iv
import go_libp2p_pubsub_tpu.models.randomsub as rs
from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets


def build(n=240, t=2, m=8, seed=0, score=True, sched=None, cfg_kw=None,
          **sim_kw):
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t,
        **(cfg_kw or {}))
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(seed)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    sc = gs.ScoreSimConfig(**sim_kw.pop("score_kw", {})) if score \
        else None
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, seed=seed, score_cfg=sc,
        fault_schedule=sched, **sim_kw)
    return cfg, sc, params, state


def leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def test_invariants_off_bit_identical():
    """invariants=None compiles the exact pre-invariant step: same
    pytree (the None carry fields contribute no leaves), same
    trajectory."""
    cfg, sc, params, state = build()
    base = gs.gossip_run(params, gs.tree_copy(state), 20,
                         gs.make_gossip_step(cfg, sc))
    off = gs.gossip_run(params, state, 20,
                        gs.make_gossip_step(cfg, sc, invariants=None))
    assert leaves_equal(base, off)
    assert base.inv_viol is None and off.inv_viol is None


def test_invariants_on_trajectory_identical_and_green():
    """Checker ON: every pre-existing field bit-identical (pure
    readout), zero violations, first_tick stays -1."""
    cfg, sc, params, state = build()
    base = gs.gossip_run(params, gs.tree_copy(state), 25,
                         gs.make_gossip_step(cfg, sc))
    on = gs.gossip_run(params, iv.attach(state), 25,
                       gs.make_gossip_step(
                           cfg, sc, invariants=iv.InvariantConfig()))
    assert leaves_equal(base, on.replace(inv_viol=None, inv_first=None))
    assert iv.report(on) == {"violations": [], "bits": 0,
                             "first_tick": -1}


@pytest.mark.parametrize("score", [False, True])
def test_invariants_green_under_faults(score):
    """Churn + link loss + partition + cold restart: still zero
    violations (the checker knows the legitimate clears)."""
    n = 240
    sched = fl.FaultSchedule(
        n_peers=n, horizon=40,
        down_intervals=[(3, 2, 8), (9, 5, 12), (40, 1, 30)],
        drop_prob=0.05,
        partition_group=(np.arange(n) % 2).astype(np.int32),
        partition_windows=[(6, 12)], cold_restart=True, seed=2)
    cfg, sc, params, state = build(n=n, score=score, sched=sched)
    out = gs.gossip_run(params, iv.attach(state), 30,
                        gs.make_gossip_step(
                            cfg, sc, invariants=iv.InvariantConfig()))
    assert iv.report(out)["bits"] == 0


def test_invariants_green_under_attacks():
    """Graft-flood + IHAVE/IWANT spam sybils: the attackers' own
    backoff-bypassing mesh edges are excluded by construction, so a
    green adversarial run stays green."""
    n = 240
    sybil = (np.arange(n) % 5) == 0
    cfg, sc, params, state = build(
        n=n, score=True, sybil=sybil,
        score_kw=dict(sybil_ihave_spam=True, sybil_iwant_spam=True,
                      sybil_graft_flood=True))
    out = gs.gossip_run(params, iv.attach(state), 25,
                        gs.make_gossip_step(
                            cfg, sc, invariants=iv.InvariantConfig()))
    assert iv.report(out)["bits"] == 0


def test_invariants_batched_matches_sequential():
    """vmap over invariant-armed replicas: per-replica carries equal
    the sequential runs bit-for-bit."""
    cfg, sc, params0, state0 = build(seed=0)
    _, _, params1, state1 = build(seed=1)
    step = gs.make_gossip_step(cfg, sc,
                               invariants=iv.InvariantConfig())
    params = gs.stack_trees([params0, params1])
    state = gs.stack_trees([iv.attach(state0), iv.attach(state1)])
    batch = gs.gossip_run_batch(params, state, 15, step)
    for i, (p_i, s_i) in enumerate(((params0, state0),
                                    (params1, state1))):
        seq = gs.gossip_run(p_i, iv.attach(s_i), 15, step)
        assert leaves_equal(seq, gs.index_trees(batch, i))


def test_seeded_mesh_defect_fires():
    """State surgery: a forged mesh bit at an UNSUBSCRIBED candidate
    edge survives the step (existing mesh bits are not re-validated)
    and must trip mesh-subscription on the very first tick."""
    cfg, sc, params, state = build()
    # candidate c of peer p is subscribed iff bit c of cand_sub_bits;
    # find a peer with at least one unsubscribed candidate... with
    # every peer subscribed the unsub edge must be synthesized: mark
    # one peer unsubscribed in a fresh sim instead
    n = 240
    subs = np.zeros((n, 2), dtype=bool)
    subs[np.arange(n), np.arange(n) % 2] = True
    subs[7] = False                      # peer 7 subscribes nothing
    rng = np.random.default_rng(0)
    topic = rng.integers(0, 2, 8)
    origin = rng.integers(0, n // 2, 8) * 2 + topic
    origin = np.where(origin == 7, (origin + 2) % n, origin)
    topic = (origin % 2).astype(topic.dtype)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, rng.integers(0, 5, 8).astype(
            np.int32), score_cfg=sc)
    victim = 7 - int(cfg.offsets[0])     # peer whose candidate 0 is 7
    mesh = np.zeros(n, dtype=np.uint32)
    mesh[victim % n] = 1                 # forged edge at unsub peer 7
    state = state.replace(mesh=gs.jnp.asarray(mesh))
    state = gs.refresh_gates(cfg, sc, params, state)
    out = gs.gossip_run(params, iv.attach(state), 3,
                        gs.make_gossip_step(
                            cfg, sc, invariants=iv.InvariantConfig()))
    rep = iv.report(out)
    assert "mesh-subscription" in rep["violations"]
    assert rep["first_tick"] == 0


def test_seeded_broken_step_fires_delivery_bits():
    """A deliberately broken step — delivering at a DOWN peer and
    shrinking possession — trips the delivery-group bits through the
    same fold the in-step wiring uses."""
    n = 240
    sched = fl.FaultSchedule(n_peers=n, horizon=40,
                             down_intervals=[(5, 0, 30)])
    cfg, sc, params, state = build(n=n, sched=sched)
    icfg = iv.InvariantConfig()
    base = gs.make_gossip_step(cfg, sc)

    def broken(params, state):
        s2, delivered = base(params, state)
        # deliver a copy at down peer 5, and lose every origin's own
        # copy (possession shrinks at peers that HAVE content)
        bad = np.zeros((delivered.shape[0], n), dtype=np.uint32)
        bad[0, 5] = 1
        delivered = delivered | gs.jnp.asarray(bad)
        # shrink = a bit the PREVIOUS state held and the new one lacks
        drop = gs.jnp.where(state.tick >= 3,
                            params.origin_words & state.have,
                            gs.jnp.uint32(0))
        s2 = s2.replace(have=s2.have & ~drop)
        aw = fl.alive_word(fl.alive_mask(params.faults, state.tick))
        bits = iv.delivery_violations(
            icfg, state.have, s2.have, delivered, alive_w=aw,
            invalid_words=params.invalid_words)
        viol, first = iv.fold(state.inv_viol, state.inv_first, bits,
                              state.tick)
        return s2.replace(inv_viol=viol, inv_first=first), delivered

    out = gs.gossip_run(params, iv.attach(state), 14, broken)
    rep = iv.report(out)
    assert "delivery-down" in rep["violations"]
    assert "possession-regression" in rep["violations"]
    assert rep["first_tick"] >= 0


def test_flood_and_randomsub_green_and_armed_guard():
    n, t, m = 120, 2, 6
    subs = np.zeros((n, t), bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, np.int32)
    offs = tuple(int(o) for o in make_circulant_offsets(t, 8, n,
                                                        seed=1))
    sched = fl.FaultSchedule(n_peers=n, horizon=12,
                             down_intervals=((0, 0, 4),),
                             drop_prob=0.1)
    icfg = iv.InvariantConfig()
    p, s = fs.make_flood_sim(None, None, subs, None, topic, origin,
                             ticks, fault_schedule=sched,
                             fault_offsets=offs)
    core = fs.make_circulant_step_core(offs, invariants=icfg)
    with pytest.raises(ValueError, match="attach"):
        jax.eval_shape(core, p, s)       # unarmed state refused
    out, _ = fs.flood_run_curve(p, iv.attach(s), 10, core, m)
    assert iv.report(out)["bits"] == 0

    rcfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(t, 8, n, seed=1),
        n_topics=t, d=3)
    p2, s2 = rs.make_randomsub_sim(rcfg, subs, topic, origin, ticks,
                                   fault_schedule=sched)
    out2 = rs.randomsub_run(p2, iv.attach(s2), 10,
                            rs.make_randomsub_step(rcfg,
                                                   invariants=icfg))
    assert iv.report(out2)["bits"] == 0


def test_invariants_kernel_path_interpret():
    """The pallas path folds the SAME checker in its epilogue:
    green on a faulted scored run, and the carried bits equal the
    XLA path's (both zero, trajectories parity-pinned elsewhere)."""
    n, t, m = 512, 2, 8
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    sc = gs.ScoreSimConfig()
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 5, m).astype(np.int32)
    sched = fl.FaultSchedule(n_peers=n, horizon=20,
                             down_intervals=[(3, 1, 6)],
                             cold_restart=True)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        fault_schedule=sched, pad_to_block=128)
    step = gs.make_gossip_step(cfg, sc, receive_block=128,
                               receive_interpret=True,
                               invariants=iv.InvariantConfig())
    out = gs.gossip_run(params, iv.attach(state), 8, step)
    assert iv.report(out)["bits"] == 0
