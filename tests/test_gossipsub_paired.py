"""Overlapping topic membership (paired-topic mode) for the simulator.

VERDICT r3 missing-4 / weak-7: with one topic per peer the per-topic
score sum and topic_score_cap collapse away and a T-topic flagship is T
disjoint networks.  Paired mode subscribes every peer to TWO topics
(its residue class r and r + T/2), keeps a separate mesh + backoff per
topic slot (the reference keeps per-topic meshes, gossipsub.go:135),
and scores candidates over the summed per-topic contributions with the
TopicScoreCap (score.go:256-268).
"""

import numpy as np
import pytest

import go_libp2p_pubsub_tpu.models.gossipsub as gs


def _build_paired(n=600, t=4, C=12, m=12, seed=2, score=True,
                  score_kw=None, n_ticks=35):
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=seed, paired=True),
        n_topics=t, paired_topics=True,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2)
    rng = np.random.default_rng(seed)
    own = np.arange(n) % t
    second = (own + t // 2) % t
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), own] = True
    subs[np.arange(n), second] = True
    topic = rng.integers(0, t, m)
    # any member of the topic may publish (origin's primary OR secondary)
    members = [np.flatnonzero((own == tau) | (second == tau))
               for tau in range(t)]
    origin = np.array([rng.choice(members[tau]) for tau in topic])
    ticks = np.sort(rng.integers(0, 10, m)).astype(np.int32)
    sc = gs.ScoreSimConfig(**(score_kw or {})) if score else None
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       score_cfg=sc)
    out = gs.gossip_run(params, state, n_ticks,
                        gs.make_gossip_step(cfg, sc))
    return cfg, sc, params, out, topic, own, second


def test_paired_dissemination_and_dual_meshes():
    """Every topic reaches BOTH of its residue classes (the overlapping
    membership is real), and each peer maintains two bounded meshes."""
    n, t = 600, 4
    cfg, sc, params, out, topic, own, second = _build_paired(n=n, t=t)
    reach = np.asarray(gs.reach_counts(params, out))
    # members of topic tau = classes {tau, tau + t/2} = half the network
    assert (reach == n // 2).all(), reach
    deg_a = np.asarray(gs.mesh_degrees(out))
    from go_libp2p_pubsub_tpu.ops.graph import popcount32
    deg_b = np.asarray(popcount32(out.mesh_b))
    assert cfg.d_lo <= deg_a.mean() <= cfg.d_hi
    assert cfg.d_lo <= deg_b.mean() <= cfg.d_hi
    # the two slot meshes are genuinely distinct selections
    assert (np.asarray(out.mesh) != np.asarray(out.mesh_b)).mean() > 0.5
    # per-slot P1 accrues on both meshes
    assert np.asarray(out.scores.time_in_mesh).max() > 5
    assert np.asarray(out.scores.time_in_mesh_b).max() > 5


def test_paired_cross_slot_mesh_symmetry():
    """On edges whose offset is an ODD multiple of T/2, a topic lives in
    the two endpoints' DIFFERENT slots (class(p+o) = class(p) + T/2).
    After the GRAFT/PRUNE handshake settles, a mesh edge in my slot X
    must appear in the partner's matching slot for the SAME topic —
    pinning the cross-slot control routing (a same-slot handshake would
    leave odd-parity edges unilateral)."""
    cfg, sc, params, out, *_ = _build_paired(n_ticks=40)
    t = cfg.n_topics
    mesh_a = np.asarray(out.mesh)
    mesh_b = np.asarray(out.mesh_b)
    agree = total = 0
    odd_edges = 0
    for c, o in enumerate(cfg.offsets):
        cb = cfg.cinv[c]
        even = (o % t) == 0
        odd_edges += int(not even)
        for mine_w, partner_w in (
                (mesh_a, mesh_a if even else mesh_b),
                (mesh_b, mesh_b if even else mesh_a)):
            mine = (mine_w >> c) & 1
            partner = (np.roll(partner_w, -o) >> cb) & 1
            agree += int((mine & partner).sum())
            total += int(mine.sum())
    assert odd_edges > 0          # the topology exercises the odd case
    assert total > 0
    assert agree / total > 0.95, agree / total


def test_multi_topic_score_sum_matches_core():
    """The sim's multi-topic score formula == the protocol core's score
    engine (core/score.py, reference score.go:256-333) for a peer in
    TWO topics: per-topic P1 terms, aggregated equal-weight P2/P4, and
    the TopicScoreCap binding the summed topic contribution."""
    from go_libp2p_pubsub_tpu.core import (
        PeerScore, PeerScoreParams, TopicScoreParams)
    from go_libp2p_pubsub_tpu.core.types import (
        Message, PeerID, REJECT_INVALID_SIGNATURE)
    from go_libp2p_pubsub_tpu.pb import rpc as pb

    w = 0.7
    fd_w, inv_w = 1.3, -2.0
    t1, t2 = 5.0, 3.0          # time in mesh per topic (ticks==seconds)
    k1, k2 = 4, 2              # first deliveries per topic

    def run_core(cap, n_inv):
        class Clock:
            t = 1000.0

            def __call__(self):
                return self.t

        def tp():
            return TopicScoreParams(
                topic_weight=w, time_in_mesh_weight=1.0,
                time_in_mesh_quantum=1.0, time_in_mesh_cap=100.0,
                first_message_deliveries_weight=fd_w,
                first_message_deliveries_decay=1.0 - 1e-12,
                first_message_deliveries_cap=1000.0,
                invalid_message_deliveries_weight=inv_w,
                invalid_message_deliveries_decay=1.0 - 1e-12)

        clock = Clock()
        ps = PeerScore(PeerScoreParams(
            topics={"ta": tp(), "tb": tp()},
            app_specific_score=lambda p: 0.0,
            topic_score_cap=cap,
            decay_interval=1.0, decay_to_zero=1e-9), clock=clock)
        pid = PeerID(b"A")
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, "ta")
        ps.graft(pid, "tb")
        seq = [0]

        def deliver(topic, n_msgs, valid=True):
            for _ in range(n_msgs):
                seq[0] += 1
                msg = Message(pb.PubMessage(
                    from_peer=b"owner", data=b"x", topic=topic,
                    seqno=seq[0].to_bytes(8, "big")))
                msg.received_from = pid
                if valid:
                    ps.validate_message(msg)
                    ps.deliver_message(msg)
                else:
                    ps.reject_message(msg, REJECT_INVALID_SIGNATURE)

        deliver("ta", k1)
        deliver("tb", k2)
        deliver("tb", n_inv, valid=False)
        # graft times differ so the per-topic P1 terms differ
        ps.peer_stats[pid].topics["ta"].graft_time = clock.t - t1
        ps.peer_stats[pid].topics["tb"].graft_time = clock.t - t2
        ps.refresh_scores()
        return ps.score(pid)

    def run_sim(cap, n_inv):
        cfg, sc, params, out, *_ = _build_paired(
            n=96, t=4, C=8, m=4, n_ticks=1,
            score_kw=dict(
                topic_weight=w, time_in_mesh_weight=1.0,
                time_in_mesh_quantum=1, time_in_mesh_cap=100.0,
                first_message_deliveries_weight=fd_w,
                invalid_message_deliveries_weight=inv_w,
                topic_score_cap=cap))
        # overwrite one edge's counters with the core scenario's stats
        s = out.scores
        tim = np.zeros(np.asarray(s.time_in_mesh).shape, np.int16)
        tim_b = np.zeros_like(tim)
        fd = np.zeros(np.asarray(s.first_deliveries).shape, np.float32)
        inv = np.zeros_like(fd)
        tim[2, 7], tim_b[2, 7] = int(t1), int(t2)
        fd[2, 7] = k1 + k2      # equal weights: per-topic P2 aggregates
        inv[2, 7] = n_inv
        st = out.replace(scores=s.replace(
            time_in_mesh=np.asarray(tim),
            time_in_mesh_b=np.asarray(tim_b),
            first_deliveries=fd.astype(s.first_deliveries.dtype),
            invalid_deliveries=inv.astype(s.invalid_deliveries.dtype),
            behaviour_penalty=np.zeros_like(fd)))
        return float(np.asarray(
            gs.compute_scores(sc, params, st))[2, 7])

    # uncapped with invalid penalties; capped with a BINDING cap (the
    # positive topic part 0.7*(8 + 1.3*6) = 11.06 > 4)
    for cap, n_inv in ((0.0, 3), (4.0, 0)):
        core_score = run_core(cap, n_inv)
        sim_score = run_sim(cap, n_inv)
        assert sim_score == pytest.approx(core_score, rel=1e-5), (
            cap, sim_score, core_score)
    # sanity: the binding cap actually changed the value
    assert run_core(4.0, 0) == pytest.approx(4.0)
    assert run_core(0.0, 0) > 10.0

def test_px_candidate_refresh_recovers_starved_peers():
    """PX-driven candidate rotation (gossipsub.go:856-937 approximated
    as active-subset refresh): when graylisted sybils dominate the
    initially-known candidates, rotation replaces pruned/neg-dropped
    addresses with fresh pool entries (the connector dialing PX-learned
    addresses) and the honest out-degree recovers; the frozen-active
    control keeps dead sybil slots forever.  Connectivity is symmetric,
    so delivery still completes either way — the mechanism restores
    DEGREE and latency, which is what mass-pruning recovery means
    here."""
    n, t = 600, 3
    rng = np.random.default_rng(11)
    sybil = rng.random(n) < 0.55

    def run(rotate):
        cfg = gs.GossipSimConfig(
            offsets=gs.make_gossip_offsets(t, 16, n, seed=3), n_topics=t,
            d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2,
            px_rotation=rotate)
        subs = np.zeros((n, t), dtype=bool)
        subs[np.arange(n), np.arange(n) % t] = True
        sy = np.flatnonzero(sybil)
        hon = np.flatnonzero(~sybil)
        n_inv = 60
        origin = np.concatenate([
            np.repeat(sy[:20], 3),
            hon[rng.integers(0, len(hon), 10)]])
        topic = (origin % t).astype(np.int64)
        invalid = np.array([True] * n_inv + [False] * 10)
        ticks = np.concatenate([
            np.arange(n_inv, dtype=np.int32) % 15,
            np.full(10, 30, np.int32)])
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, ticks,
            score_cfg=gs.ScoreSimConfig(), sybil=sybil,
            msg_invalid=invalid, px_candidates=7)
        active0 = np.asarray(state.active)   # before the donated run
        out = gs.gossip_run(params, state, 70,
                            gs.make_gossip_step(cfg, gs.ScoreSimConfig()))
        deg = np.asarray(gs.mesh_degrees(out))[~sybil]
        act = np.asarray(out.active)
        from go_libp2p_pubsub_tpu.ops.graph import popcount32
        hon_cand = np.zeros(n, np.uint32)
        for c, o in enumerate(cfg.offsets):
            hon_cand |= np.roll(~sybil, -o).astype(np.uint32) << c
        useful = np.asarray(popcount32(act & hon_cand))[~sybil]
        rotated = not np.array_equal(act, active0)
        honest_mask = ~sybil
        reach = np.asarray(gs.reach_by_hops(
            params, out, 30, mask=honest_mask))[n_inv:, -1]
        members = np.arange(n) % t
        want = np.array([((~sybil) & (members == topic[n_inv + j])).sum()
                         for j in range(10)])
        return deg, useful, rotated, reach, want

    deg_px, useful_px, rotated, reach_px, want = run(True)
    deg_no, useful_no, rotated_no, reach_no, _ = run(False)
    assert rotated and not rotated_no
    # full honest delivery after the attack with rotation on
    assert (reach_px == want).all(), (reach_px, want)
    # rotation measurably restores the honest out-degree the frozen
    # control loses to dead sybil address slots (measured ~+30%/+15%)
    assert useful_px.mean() > 1.15 * useful_no.mean(), (
        useful_px.mean(), useful_no.mean())
    assert deg_px.mean() > deg_no.mean(), (deg_px.mean(), deg_no.mean())


def test_paired_pipelined_gates_match_recompute():
    """Paired mode carries a seventh gate row (slot-B backoff); the
    pipelined emission must match a tick-start recompute bit-for-bit
    across both meshes."""
    import jax
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(4, 12, 600, seed=3, paired=True),
        n_topics=4, paired_topics=True,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2)
    rng = np.random.default_rng(3)
    own = np.arange(600) % 4
    second = (own + 2) % 4
    subs = np.zeros((600, 4), dtype=bool)
    subs[np.arange(600), own] = True
    subs[np.arange(600), second] = True
    topic = rng.integers(0, 4, 10)
    members = [np.flatnonzero((own == tau) | (second == tau))
               for tau in range(4)]
    origin = np.array([rng.choice(members[tau]) for tau in topic])
    ticks = np.sort(rng.integers(0, 10, 10)).astype(np.int32)
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                       score_cfg=sc)
    assert len(state.gates) == 8
    out_p = gs.gossip_run(params, gs.tree_copy(state), 25,
                          gs.make_gossip_step(cfg, sc))
    out_r = gs.gossip_run(params, state, 25,
                          gs.make_gossip_step(cfg, sc,
                                              pipeline_gates=False))
    for f in ("have", "mesh", "mesh_b", "backoff", "backoff_b",
              "recent"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_p, f)), np.asarray(getattr(out_r, f)),
            err_msg=f)
    np.testing.assert_array_equal(
        np.asarray(out_p.gates),
        np.asarray(gs.compute_gates(
            cfg, sc, params, out_p,
            jax.random.key_data(out_p.key)[-1])))
