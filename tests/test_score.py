"""Peer-score engine unit tests.

Drive the engine directly with synthetic peers and a virtual clock —
the reference's pure-unit-test layer (score_test.go:13-1050): each score
parameter P1..P7 has a dedicated test, plus decay, retention, delivery
records, and parameter validation.
"""

from __future__ import annotations

import pytest

from go_libp2p_pubsub_tpu.core import (
    PeerGaterParams,
    PeerScore,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    score_parameter_decay,
)
from go_libp2p_pubsub_tpu.core.score import (
    DELIVERY_INVALID,
    DELIVERY_VALID,
)
from go_libp2p_pubsub_tpu.core.types import (
    Message,
    PeerID,
    REJECT_INVALID_SIGNATURE,
    REJECT_VALIDATION_IGNORED,
    REJECT_VALIDATION_QUEUE_FULL,
    REJECT_VALIDATION_THROTTLED,
)
from go_libp2p_pubsub_tpu.pb import rpc as pb

TOPIC = "test"


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def mk_params(tp: TopicScoreParams, **kw) -> PeerScoreParams:
    defaults = dict(topics={TOPIC: tp}, app_specific_score=lambda p: 0.0,
                    decay_interval=1.0, decay_to_zero=0.01)
    defaults.update(kw)
    return PeerScoreParams(**defaults)


def mk_msg(seq: int, topic: str = TOPIC, frm: bytes = b"owner") -> Message:
    return Message(pb.PubMessage(from_peer=frm, data=b"x", topic=topic,
                                 seqno=seq.to_bytes(8, "big")))


def test_score_time_in_mesh():
    tp = TopicScoreParams(topic_weight=0.5, time_in_mesh_weight=1.0,
                          time_in_mesh_quantum=1.0, time_in_mesh_cap=3600.0,
                          invalid_message_deliveries_decay=0.5)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    assert ps.score(pid) == 0.0
    ps.graft(pid, TOPIC)
    elapsed = 200.0
    clock.advance(elapsed)
    ps.refresh_scores()
    expected = tp.topic_weight * tp.time_in_mesh_weight * elapsed / tp.time_in_mesh_quantum
    assert ps.score(pid) == pytest.approx(expected)


def test_score_time_in_mesh_cap():
    tp = TopicScoreParams(topic_weight=0.5, time_in_mesh_weight=1.0,
                          time_in_mesh_quantum=1.0, time_in_mesh_cap=10.0,
                          invalid_message_deliveries_decay=0.5)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    clock.advance(1000.0)
    ps.refresh_scores()
    expected = tp.topic_weight * tp.time_in_mesh_weight * tp.time_in_mesh_cap
    assert ps.score(pid) == pytest.approx(expected)


def test_score_first_message_deliveries():
    tp = TopicScoreParams(topic_weight=1.0, first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=1.0 - 1e-9,
                          first_message_deliveries_cap=2000.0,
                          invalid_message_deliveries_decay=0.5)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    n = 100
    for i in range(n):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.deliver_message(msg)
    assert ps.score(pid) == pytest.approx(float(n))


def test_score_first_message_deliveries_cap():
    tp = TopicScoreParams(topic_weight=1.0, first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=1.0 - 1e-9,
                          first_message_deliveries_cap=50.0,
                          invalid_message_deliveries_decay=0.5)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    for i in range(100):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.deliver_message(msg)
    assert ps.score(pid) == pytest.approx(tp.first_message_deliveries_cap)


def test_score_first_message_deliveries_decay():
    tp = TopicScoreParams(topic_weight=1.0, first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=0.9,
                          first_message_deliveries_cap=2000.0,
                          invalid_message_deliveries_decay=0.5)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    for i in range(40):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.deliver_message(msg)
    expected = 40.0
    for _ in range(10):
        ps.refresh_scores()
        expected *= 0.9
    assert ps.score(pid) == pytest.approx(expected)


def test_score_mesh_message_deliveries():
    """P3: peers below the delivery threshold take the squared-deficit
    penalty once the activation window has passed."""
    tp = TopicScoreParams(topic_weight=1.0,
                          mesh_message_deliveries_weight=-1.0,
                          mesh_message_deliveries_decay=1.0 - 1e-9,
                          mesh_message_deliveries_cap=100.0,
                          mesh_message_deliveries_threshold=20.0,
                          mesh_message_deliveries_window=0.01,
                          mesh_message_deliveries_activation=1.0,
                          invalid_message_deliveries_decay=0.5)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    # A delivers enough, B delivers nothing, C inactive (just grafted)
    a, b = PeerID(b"A"), PeerID(b"B")
    for pid in (a, b):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    clock.advance(2.0)
    ps.refresh_scores()  # activates the P3 window for A and B
    c = PeerID(b"C")
    ps.add_peer(c, "/meshsub/1.1.0")
    ps.graft(c, TOPIC)

    for i in range(30):
        msg = mk_msg(i)
        msg.received_from = a
        ps.validate_message(msg)
        ps.deliver_message(msg)

    assert ps.score(a) == 0.0   # above threshold: no penalty
    assert ps.score(c) == 0.0   # not activated yet: no penalty
    deficit = tp.mesh_message_deliveries_threshold
    assert ps.score(b) == pytest.approx(-deficit * deficit)


def test_score_mesh_message_deliveries_window():
    """Duplicates outside the delivery window earn no P3 credit."""
    tp = TopicScoreParams(topic_weight=1.0,
                          mesh_message_deliveries_weight=-1.0,
                          mesh_message_deliveries_decay=1.0 - 1e-9,
                          mesh_message_deliveries_cap=100.0,
                          mesh_message_deliveries_threshold=5.0,
                          mesh_message_deliveries_window=0.5,
                          mesh_message_deliveries_activation=1.0,
                          invalid_message_deliveries_decay=0.5)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    a, b, c = PeerID(b"A"), PeerID(b"B"), PeerID(b"C")
    for pid in (a, b, c):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    clock.advance(2.0)
    ps.refresh_scores()

    for i in range(10):
        msg = mk_msg(i)
        msg.received_from = a
        ps.validate_message(msg)
        ps.deliver_message(msg)
        # B echoes within the window: credited
        dup = mk_msg(i)
        dup.received_from = b
        ps.duplicate_message(dup)
        # C echoes too late: not credited
        clock.advance(1.0)
        dup2 = mk_msg(i)
        dup2.received_from = c
        ps.duplicate_message(dup2)

    assert ps.score(a) == 0.0
    assert ps.score(b) == 0.0
    deficit = tp.mesh_message_deliveries_threshold
    assert ps.score(c) == pytest.approx(-deficit * deficit)


def test_score_mesh_failure_penalty():
    """P3b: pruning an underperforming peer makes the deficit sticky."""
    tp = TopicScoreParams(topic_weight=1.0,
                          mesh_message_deliveries_weight=0.0,
                          mesh_message_deliveries_decay=1.0 - 1e-9,
                          mesh_message_deliveries_cap=100.0,
                          mesh_message_deliveries_threshold=10.0,
                          mesh_message_deliveries_activation=1.0,
                          mesh_failure_penalty_weight=-1.0,
                          mesh_failure_penalty_decay=1.0 - 1e-9,
                          invalid_message_deliveries_decay=0.5)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    a, b = PeerID(b"A"), PeerID(b"B")
    for pid in (a, b):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    clock.advance(2.0)
    ps.refresh_scores()

    # both have a deficit of 10, but only B gets pruned
    ps.prune(b, TOPIC)
    assert ps.score(a) == 0.0  # P3 disabled (weight 0), still in mesh
    deficit = tp.mesh_message_deliveries_threshold
    assert ps.score(b) == pytest.approx(-deficit * deficit)


def test_score_invalid_message_deliveries():
    tp = TopicScoreParams(topic_weight=1.0,
                          invalid_message_deliveries_weight=-1.0,
                          invalid_message_deliveries_decay=0.9)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    n = 100
    for i in range(n):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.reject_message(msg, REJECT_INVALID_SIGNATURE)
    assert ps.score(pid) == pytest.approx(-float(n * n))
    # and it decays quadratically
    ps.refresh_scores()
    assert ps.score(pid) == pytest.approx(-((n * 0.9) ** 2))


def test_score_reject_validation_penalizes_forwarders():
    """A validator reject penalizes both the first deliverer and every peer
    that forwarded a duplicate while validation was pending."""
    tp = TopicScoreParams(topic_weight=1.0,
                          invalid_message_deliveries_weight=-1.0,
                          invalid_message_deliveries_decay=0.9)
    ps = PeerScore(mk_params(tp), clock=Clock())
    a, b = PeerID(b"A"), PeerID(b"B")
    for pid in (a, b):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    msg = mk_msg(1)
    msg.received_from = a
    ps.validate_message(msg)
    dup = mk_msg(1)
    dup.received_from = b
    ps.duplicate_message(dup)
    ps.reject_message(msg, "validation failed")
    assert ps.score(a) == pytest.approx(-1.0)
    assert ps.score(b) == pytest.approx(-1.0)
    # the record is marked invalid: late duplicates penalized directly
    mid = ps.msg_id(msg.rpc)
    assert ps.deliveries.records[mid].status == DELIVERY_INVALID
    dup3 = mk_msg(1)
    dup3.received_from = b
    ps.duplicate_message(dup3)
    assert ps.score(b) == pytest.approx(-4.0)


def test_score_throttled_and_ignored_not_penalized():
    tp = TopicScoreParams(topic_weight=1.0,
                          invalid_message_deliveries_weight=-1.0,
                          invalid_message_deliveries_decay=0.9)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    for i, reason in enumerate([REJECT_VALIDATION_THROTTLED,
                                REJECT_VALIDATION_IGNORED,
                                REJECT_VALIDATION_QUEUE_FULL]):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.reject_message(msg, reason)
    assert ps.score(pid) == 0.0


def test_score_app_specific():
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    params = mk_params(tp, app_specific_score=lambda p: -1000.0,
                       app_specific_weight=0.5)
    ps = PeerScore(params, clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    assert ps.score(pid) == pytest.approx(-500.0)


def test_score_ip_colocation():
    """P6: peers sharing an IP above the threshold take a squared penalty."""
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    params = mk_params(tp, ip_colocation_factor_weight=-1.0,
                       ip_colocation_factor_threshold=1)
    ps = PeerScore(params, clock=Clock())
    peers = [PeerID(bytes([i])) for i in range(4)]
    for pid in peers:
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.peer_stats[pid].ips = ["10.0.0.7"]
        ps.peer_ips.setdefault("10.0.0.7", set()).add(pid)
    surplus = len(peers) - params.ip_colocation_factor_threshold
    for pid in peers:
        assert ps.score(pid) == pytest.approx(-float(surplus * surplus))


def test_score_ip_colocation_whitelist():
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    params = mk_params(tp, ip_colocation_factor_weight=-1.0,
                       ip_colocation_factor_threshold=1,
                       ip_colocation_factor_whitelist=["10.0.0.0/8"])
    ps = PeerScore(params, clock=Clock())
    peers = [PeerID(bytes([i])) for i in range(4)]
    for pid in peers:
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.peer_stats[pid].ips = ["10.0.0.7"]
        ps.peer_ips.setdefault("10.0.0.7", set()).add(pid)
    for pid in peers:
        assert ps.score(pid) == 0.0


def test_score_behaviour_penalty():
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    params = mk_params(tp, behaviour_penalty_weight=-1.0,
                       behaviour_penalty_threshold=1.0,
                       behaviour_penalty_decay=0.99)
    ps = PeerScore(params, clock=Clock())
    pid = PeerID(b"A")
    # unknown peer: no-op
    ps.add_penalty(pid, 1)
    assert ps.score(pid) == 0.0
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.add_penalty(pid, 1)
    assert ps.score(pid) == 0.0  # at threshold: no penalty yet
    ps.add_penalty(pid, 1)
    assert ps.score(pid) == pytest.approx(-1.0)   # (2-1)^2
    ps.add_penalty(pid, 2)
    assert ps.score(pid) == pytest.approx(-9.0)   # (4-1)^2


def test_score_retention():
    """Negative scores survive disconnect for retain_score seconds; positive
    scores are forgotten immediately (anti score-reset)."""
    tp = TopicScoreParams(topic_weight=1.0,
                          invalid_message_deliveries_weight=-1.0,
                          invalid_message_deliveries_decay=1.0 - 1e-9)
    clock = Clock()
    params = mk_params(tp, retain_score=5.0)
    ps = PeerScore(params, clock=clock)
    a, b = PeerID(b"A"), PeerID(b"B")
    for pid in (a, b):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    msg = mk_msg(1)
    msg.received_from = a
    ps.reject_message(msg, REJECT_INVALID_SIGNATURE)
    assert ps.score(a) < 0

    ps.remove_peer(a)   # negative: retained
    ps.remove_peer(b)   # zero: retained too (only >0 is dropped)
    assert ps.score(a) < 0
    clock.advance(1.0)
    ps.refresh_scores()
    assert ps.score(a) < 0  # still within retention; no decay while away
    clock.advance(10.0)
    ps.refresh_scores()
    assert ps.score(a) == 0.0
    assert a not in ps.peer_stats


def test_score_retention_not_positive():
    tp = TopicScoreParams(topic_weight=1.0,
                          first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=0.9,
                          first_message_deliveries_cap=100.0,
                          invalid_message_deliveries_decay=0.9)
    ps = PeerScore(mk_params(tp, retain_score=100.0), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    msg = mk_msg(1)
    msg.received_from = pid
    ps.validate_message(msg)
    ps.deliver_message(msg)
    assert ps.score(pid) > 0
    ps.remove_peer(pid)
    assert pid not in ps.peer_stats  # positive scores are not retained


def test_score_recapping():
    tp = TopicScoreParams(topic_weight=1.0,
                          first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=0.9,
                          first_message_deliveries_cap=100.0,
                          invalid_message_deliveries_decay=0.9)
    ps = PeerScore(mk_params(tp), clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    for i in range(80):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.deliver_message(msg)
    assert ps.score(pid) == pytest.approx(80.0)
    tp2 = TopicScoreParams(topic_weight=1.0,
                           first_message_deliveries_weight=1.0,
                           first_message_deliveries_decay=0.9,
                           first_message_deliveries_cap=50.0,
                           invalid_message_deliveries_decay=0.9)
    ps.set_topic_score_params(TOPIC, tp2)
    assert ps.score(pid) == pytest.approx(50.0)


def test_score_topic_score_cap():
    tp = TopicScoreParams(topic_weight=1.0,
                          first_message_deliveries_weight=1.0,
                          first_message_deliveries_decay=0.9,
                          first_message_deliveries_cap=1000.0,
                          invalid_message_deliveries_decay=0.9)
    params = mk_params(tp, topic_score_cap=10.0)
    ps = PeerScore(params, clock=Clock())
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.graft(pid, TOPIC)
    for i in range(100):
        msg = mk_msg(i)
        msg.received_from = pid
        ps.validate_message(msg)
        ps.deliver_message(msg)
    assert ps.score(pid) == pytest.approx(10.0)


def test_delivery_record_gc():
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    msg = mk_msg(1)
    msg.received_from = pid
    ps.validate_message(msg)
    ps.deliver_message(msg)
    assert len(ps.deliveries.records) == 1
    clock.advance(121.0)  # past TimeCacheDuration
    ps.gc_delivery_records()
    assert len(ps.deliveries.records) == 0


def test_near_first_delivery_credit():
    """Duplicates arriving while validation is pending credit P3
    retroactively when the message validates."""
    tp = TopicScoreParams(topic_weight=1.0,
                          mesh_message_deliveries_weight=-1.0,
                          mesh_message_deliveries_decay=1.0 - 1e-9,
                          mesh_message_deliveries_cap=100.0,
                          mesh_message_deliveries_threshold=2.0,
                          mesh_message_deliveries_window=0.1,
                          mesh_message_deliveries_activation=1.0,
                          invalid_message_deliveries_decay=0.9)
    clock = Clock()
    ps = PeerScore(mk_params(tp), clock=clock)
    a, b = PeerID(b"A"), PeerID(b"B")
    for pid in (a, b):
        ps.add_peer(pid, "/meshsub/1.1.0")
        ps.graft(pid, TOPIC)
    clock.advance(2.0)
    ps.refresh_scores()
    deficit = tp.mesh_message_deliveries_threshold
    assert ps.score(b) == pytest.approx(-deficit * deficit)

    for i in range(2):
        msg = mk_msg(i)
        msg.received_from = a
        ps.validate_message(msg)
        dup = mk_msg(i)
        dup.received_from = b
        ps.duplicate_message(dup)      # near-first: during validation
        ps.deliver_message(msg)         # retroactive credit for B
        mid = ps.msg_id(msg.rpc)
        assert ps.deliveries.records[mid].status == DELIVERY_VALID
    assert ps.score(a) == 0.0
    assert ps.score(b) == 0.0


def test_score_parameter_decay():
    # ~0.01 after (decay / interval) ticks
    d = score_parameter_decay(600.0)
    assert 0.99 < d < 1.0
    v = 1.0
    for _ in range(600):
        v *= d
    assert v == pytest.approx(0.01, rel=1e-6)


def test_score_params_validation():
    def check_bad(**kw):
        tp_kw = dict(topic_weight=1.0, invalid_message_deliveries_decay=0.5)
        with pytest.raises(ValueError):
            p = PeerScoreParams(topics={TOPIC: TopicScoreParams(**tp_kw)},
                                app_specific_score=lambda p: 0.0, **kw)
            p.validate()

    check_bad(topic_score_cap=-1.0)
    check_bad(ip_colocation_factor_weight=1.0)
    check_bad(ip_colocation_factor_weight=-1.0, ip_colocation_factor_threshold=0)
    check_bad(behaviour_penalty_weight=1.0)
    check_bad(behaviour_penalty_weight=-1.0, behaviour_penalty_decay=2.0)
    check_bad(decay_interval=0.1)
    check_bad(decay_to_zero=1.5)
    with pytest.raises(ValueError):
        PeerScoreParams(app_specific_score=None).validate()


def test_topic_params_validation():
    def check_bad(**kw):
        with pytest.raises(ValueError):
            TopicScoreParams(**kw).validate()

    check_bad(topic_weight=-1.0)
    check_bad(time_in_mesh_quantum=0.0)
    check_bad(time_in_mesh_weight=-1.0)
    check_bad(time_in_mesh_weight=1.0, time_in_mesh_quantum=1.0, time_in_mesh_cap=0.0)
    check_bad(first_message_deliveries_weight=-1.0)
    check_bad(first_message_deliveries_weight=1.0, first_message_deliveries_decay=2.0)
    check_bad(mesh_message_deliveries_weight=1.0)
    check_bad(invalid_message_deliveries_decay=0.5,
              mesh_message_deliveries_weight=-1.0,
              mesh_message_deliveries_decay=0.5,
              mesh_message_deliveries_cap=5.0,
              mesh_message_deliveries_threshold=0.0)
    check_bad(mesh_failure_penalty_weight=1.0)
    check_bad(invalid_message_deliveries_weight=1.0)
    check_bad(invalid_message_deliveries_decay=0.0)
    # a fully-populated valid config passes
    TopicScoreParams(
        topic_weight=1.0, time_in_mesh_weight=0.01, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=10.0, first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.5, first_message_deliveries_cap=10.0,
        mesh_message_deliveries_weight=-1.0, mesh_message_deliveries_decay=0.5,
        mesh_message_deliveries_cap=10.0, mesh_message_deliveries_threshold=5.0,
        mesh_message_deliveries_window=0.01,
        mesh_message_deliveries_activation=1.0,
        mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.5,
        invalid_message_deliveries_weight=-1.0,
        invalid_message_deliveries_decay=0.3).validate()


def test_thresholds_validation():
    PeerScoreThresholds(gossip_threshold=-1, publish_threshold=-2,
                        graylist_threshold=-3, accept_px_threshold=1,
                        opportunistic_graft_threshold=2).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(gossip_threshold=1).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(gossip_threshold=-2, publish_threshold=-1).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(publish_threshold=-1, graylist_threshold=-0.5).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(accept_px_threshold=-1).validate()
    with pytest.raises(ValueError):
        PeerScoreThresholds(opportunistic_graft_threshold=-1).validate()


def test_gater_params_validation():
    PeerGaterParams().validate()
    with pytest.raises(ValueError):
        PeerGaterParams(threshold=0.0).validate()
    with pytest.raises(ValueError):
        PeerGaterParams(global_decay=1.5).validate()
    with pytest.raises(ValueError):
        PeerGaterParams(duplicate_weight=0.0).validate()


def test_score_inspect():
    tp = TopicScoreParams(topic_weight=1.0, invalid_message_deliveries_decay=0.9)
    seen = {}
    ps = PeerScore(mk_params(tp), clock=Clock(), inspect=seen.update)
    pid = PeerID(b"A")
    ps.add_peer(pid, "/meshsub/1.1.0")
    ps.inspect_scores()
    assert seen == {pid: 0.0}
