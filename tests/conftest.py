"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host CPU devices instead (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import asyncio
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the environment's site hook pins JAX_PLATFORMS to the TPU tunnel before
# conftest runs; override via jax.config, which wins as long as no backend
# has been initialized yet
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-host cluster tests with wall-clock warm-up "
        "(deselect with '-m \"not slow\"')")


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests under asyncio.run (no plugin dependency)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
