"""Round-12 sweep engine: SimKnobs config-as-data (models/knobs.py),
the knob-batched runner, and the resident scenario server
(tools/sweepd.py).

The load-bearing claims, each pinned here:

- knobbed-defaults == baked BIT-IDENTITY on all six gossip execution
  paths (XLA combined, XLA split, pallas kernel, vmapped batch,
  paired-topic, PX rotation) — arming knobs at the config's own values
  changes nothing;
- heterogeneous-config vmap == the per-config sequential loop,
  bit-identical per replica (ONE compiled executable advances B
  *different* scenarios);
- no retrace across knob values (jaxpr identity), the whole point;
- shape-bearing fields are rejected AS KNOBS with a named error;
- the kernel path consumes the SMEM knob scalars bit-identically to
  the XLA path, and refuses the one XLA-only knob configuration
  (gossip_retransmission under IWANT spam) by name;
- sweepd round-trip: scenarios in, metric rows out, ZERO recompiles
  (compile-counter hook).
"""

import io
import json
import re

import numpy as np
import pytest

import jax

import go_libp2p_pubsub_tpu.models.faults as fl
import go_libp2p_pubsub_tpu.models.gossipsub as gs
from go_libp2p_pubsub_tpu.models import knobs as kn

N, T, M, C = 80, 2, 6, 8
BLOCK = 128
TICKS = 6


def _inputs():
    subs = np.zeros((N, T), dtype=bool)
    subs[np.arange(N), np.arange(N) % T] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, N // T, M) * T + topic
    ticks = np.zeros(M, dtype=np.int32)
    return subs, topic, origin, ticks


def _cfg(paired=False):
    return gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T, C, N, seed=1, paired=paired),
        n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
        d_lazy=2, backoff_ticks=8, paired_topics=paired)


def _paired_subs():
    subs = np.zeros((N, T), dtype=bool)
    own = np.arange(N) % T
    subs[np.arange(N), own] = True
    subs[np.arange(N), (own + T // 2) % T] = True
    return subs


def _state_leaves(state):
    return jax.tree_util.tree_leaves(state)


def _assert_states_equal(a, b, label):
    la, lb = _state_leaves(a), _state_leaves(b)
    assert len(la) == len(lb), label
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y)), label


# -- knobbed-defaults == baked, six execution paths ------------------------

#: (name, sim extra kwargs, step extra kwargs, batched?)
PATHS = [
    ("xla-combined", {}, {}, False),
    ("xla-split", {}, {"force_split": True}, False),
    ("kernel", {"pad_to_block": BLOCK},
     {"receive_block": BLOCK, "receive_interpret": True}, False),
    ("batched", {}, {}, True),
    ("paired", {"paired": True}, {}, False),
    ("px", {"px_candidates": 7}, {}, False),
]


@pytest.mark.parametrize("name,sim_kw,step_kw,batched",
                         PATHS, ids=[p[0] for p in PATHS])
def test_knobbed_defaults_bit_identical(name, sim_kw, step_kw, batched):
    sim_kw = dict(sim_kw)
    paired = sim_kw.pop("paired", False)
    cfg = _cfg(paired=paired)
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    if paired:
        subs = _paired_subs()
    step = gs.make_gossip_step(cfg, sc, **step_kw)

    def build(knobbed):
        kw = dict(sim_kw)
        if knobbed:
            kw["sim_knobs"] = {}
        if batched:
            builds = [gs.make_gossip_sim(cfg, subs, topic, origin,
                                         ticks, score_cfg=sc, seed=r,
                                         **kw) for r in range(2)]
            return (gs.stack_trees([b[0] for b in builds]),
                    gs.stack_trees([b[1] for b in builds]))
        return gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                  score_cfg=sc, **kw)

    run = gs.gossip_run_batch if batched else gs.gossip_run
    p0, s0 = build(False)
    p1, s1 = build(True)
    out0 = run(p0, s0, TICKS, step)
    out1 = run(p1, s1, TICKS, step)
    for field in ("mesh", "fanout", "last_pub", "backoff", "have",
                  "recent", "tick", "mesh_b", "backoff_b", "active"):
        a, b = getattr(out0, field), getattr(out1, field)
        if a is None and b is None:
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            (name, field)
    _assert_states_equal(out0.scores, out1.scores, (name, "scores"))
    for ga, gb in zip(out0.gates, out1.gates):
        assert np.array_equal(np.asarray(ga), np.asarray(gb)), name


def test_knobbed_defaults_unscored():
    cfg = _cfg()
    subs, topic, origin, ticks = _inputs()
    step = gs.make_gossip_step(cfg)
    p0, s0 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                sim_knobs={})
    out0 = gs.gossip_run(p0, s0, TICKS, step)
    out1 = gs.gossip_run(p1, s1, TICKS, step)
    for field in ("mesh", "have", "backoff"):
        assert np.array_equal(np.asarray(getattr(out0, field)),
                              np.asarray(getattr(out1, field))), field


# -- heterogeneous-config vmap == sequential -------------------------------

def test_heterogeneous_vmap_matches_sequential():
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    step = gs.make_gossip_step(cfg, sc)
    points = [{}, {"d": 4, "d_hi": 5},
              {"gossip_factor": 0.5, "d_lazy": 3},
              {"backoff_ticks": 4, "graylist_threshold": -60.0}]
    builds = [gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                 score_cfg=sc, seed=7, sim_knobs=k)
              for k in points]
    params = gs.stack_trees([b[0] for b in builds])
    state = gs.stack_trees([gs.tree_copy(b[1]) for b in builds])
    stateB, reach = gs.gossip_run_knob_batch(params, state, TICKS + 2,
                                             step)
    for i, (p, s) in enumerate(builds):
        s2 = gs.gossip_run(p, gs.tree_copy(s), TICKS + 2, step)
        bi = gs.index_trees(stateB, i)
        for field in ("mesh", "have", "backoff", "fanout"):
            assert np.array_equal(np.asarray(getattr(bi, field)),
                                  np.asarray(getattr(s2, field))), \
                (i, field)
        want = np.asarray(gs.reach_counts_from_have(p, s2))
        assert np.array_equal(np.asarray(reach)[i], want), i


def test_no_retrace_across_knob_values():
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    step = gs.make_gossip_step(cfg, sc)
    a = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=sc,
                           sim_knobs={"d": 4, "gossip_factor": 0.3})
    b = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=sc,
                           sim_knobs={"d": 3, "gossip_factor": 0.9,
                                      "backoff_ticks": 20})
    assert (str(jax.make_jaxpr(step)(*a))
            == str(jax.make_jaxpr(step)(*b)))


# -- validation ------------------------------------------------------------

def test_static_field_as_knob_raises_named_error():
    cfg = _cfg()
    subs, topic, origin, ticks = _inputs()
    for field in ("offsets", "n_topics", "history_length",
                  "history_gossip", "paired_topics"):
        with pytest.raises(kn.KnobStaticFieldError,
                           match=re.escape(repr(field))):
            gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                               sim_knobs={field: 1})


def test_unknown_knob_lists_valid_surface():
    cfg = _cfg()
    subs, topic, origin, ticks = _inputs()
    with pytest.raises(ValueError, match="unknown knob 'dd'"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           sim_knobs={"dd": 4})


def test_knob_point_ordering_invariants():
    cfg = _cfg()
    with pytest.raises(ValueError, match="d_lo <= d <= d_hi"):
        kn.make_sim_knobs(cfg, overrides={"d": 1})
    with pytest.raises(ValueError, match="backoff_ticks"):
        kn.make_sim_knobs(cfg, overrides={"backoff_ticks": 0})
    with pytest.raises(ValueError, match="d_hi < C"):
        kn.make_sim_knobs(cfg, overrides={"d_hi": 8})
    with pytest.raises(ValueError, match="gossip_factor"):
        kn.make_sim_knobs(cfg, overrides={"gossip_factor": 1.5})


def test_drop_prob_knob_requires_schedule():
    cfg = _cfg()
    subs, topic, origin, ticks = _inputs()
    with pytest.raises(ValueError, match="fault_schedule"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           sim_knobs={"drop_prob": 0.1})


def test_one_override_surface_only():
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    with pytest.raises(ValueError, match="ONE surface"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=sc, sim_knobs={},
                           score_knobs={"gossip_threshold": -5.0})


# -- fault drop knob -------------------------------------------------------

def test_drop_prob_knob_matches_schedule_rate():
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    step = gs.make_gossip_step(cfg, sc)
    schedA = fl.FaultSchedule(n_peers=N, horizon=10, drop_prob=0.5,
                              seed=3)
    pA, sA = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, fault_schedule=schedA,
                                sim_knobs={"drop_prob": 0.1})
    schedB = fl.FaultSchedule(n_peers=N, horizon=10, drop_prob=0.1,
                              seed=3)
    pB, sB = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, fault_schedule=schedB,
                                sim_knobs={})
    outA = gs.gossip_run(pA, sA, 8, step)
    outB = gs.gossip_run(pB, sB, 8, step)
    for field in ("mesh", "have", "backoff"):
        assert np.array_equal(np.asarray(getattr(outA, field)),
                              np.asarray(getattr(outB, field))), field


# -- kernel path -----------------------------------------------------------

def test_kernel_knob_parity_non_defaults():
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    knobs = {"d": 4, "d_hi": 5, "gossip_factor": 0.5,
             "backoff_ticks": 5, "d_lazy": 3,
             "graylist_threshold": -60.0,
             "behaviour_penalty_weight": -20.0}
    px, sx = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, sim_knobs=knobs)
    outx = gs.gossip_run(px, sx, TICKS, gs.make_gossip_step(cfg, sc))
    pk, sk = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, sim_knobs=knobs,
                                pad_to_block=BLOCK)
    stepk = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                                receive_interpret=True)
    outk = gs.gossip_run(pk, sk, TICKS, stepk)
    assert np.array_equal(np.asarray(outk.mesh)[:N],
                          np.asarray(outx.mesh))
    assert np.array_equal(np.asarray(outk.have)[:, :N],
                          np.asarray(outx.have))
    assert np.array_equal(np.asarray(outk.backoff)[:, :N],
                          np.asarray(outx.backoff))


def test_kernel_refuses_iwant_spam_knobs_by_name():
    cfg = _cfg()
    sc = gs.ScoreSimConfig(sybil_iwant_spam=True)
    subs, topic, origin, ticks = _inputs()
    p, s = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                              score_cfg=sc,
                              sybil=(np.arange(N) % 5) == 0,
                              sim_knobs={}, pad_to_block=BLOCK)
    step = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                               receive_interpret=True)
    with pytest.raises(ValueError,
                       match="gossip_retransmission stays XLA-only"):
        jax.eval_shape(step, p, s)


def test_kernel_accepts_score_knobs_now():
    """The PR-7 refusal is lifted: a legacy score_knobs build takes
    the kernel path (SMEM scalars), bit-identical to XLA."""
    cfg = _cfg()
    sc = gs.ScoreSimConfig()
    subs, topic, origin, ticks = _inputs()
    skn = {"behaviour_penalty_weight": -20.0,
           "gossip_threshold": -5.0}
    px, sx = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, score_knobs=skn)
    outx = gs.gossip_run(px, sx, TICKS, gs.make_gossip_step(cfg, sc))
    pk, sk = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                score_cfg=sc, score_knobs=skn,
                                pad_to_block=BLOCK)
    stepk = gs.make_gossip_step(cfg, sc, receive_block=BLOCK,
                                receive_interpret=True)
    outk = gs.gossip_run(pk, sk, TICKS, stepk)
    assert np.array_equal(np.asarray(outk.mesh)[:N],
                          np.asarray(outx.mesh))
    assert np.array_equal(np.asarray(outk.have)[:, :N],
                          np.asarray(outx.have))


# -- sweepd ---------------------------------------------------------------

def test_sweepd_round_trip_zero_recompiles():
    from tools.sweepd import SweepServer

    srv = SweepServer(n=200, t=2, m=6, ticks=8, batch=3, seed=0)
    compiles0 = srv.compiles()
    rows = srv.submit([
        {"id": "a", "seed": 1},
        {"id": "b", "knobs": {"d": 5, "gossip_factor": 0.4}},
        {"id": "c", "drop_prob": 0.05},
    ])
    assert [r["id"] for r in rows] == ["a", "b", "c"]
    assert all(r["ok"] for r in rows), rows
    assert all(r["inv_bits"] == 0 for r in rows), rows
    # compile-counter hook: ONE executable total, and a second wave of
    # different configs adds none
    assert compiles0 == 0
    assert srv.compiles() == 1
    n_compiles = srv.compiles()
    rows2 = srv.submit([
        {"id": "d", "knobs": {"backoff_ticks": 4}},
        {"id": "e", "attack": "spam", "attack_frac": 0.1},
        {"id": "f", "churn": True},
    ])
    assert all(r["ok"] for r in rows2), rows2
    assert srv.compiles() == n_compiles, "sweepd recompiled"
    stats = srv.stats()
    assert stats["served"] == 6
    assert stats["configs_per_compile"] >= 6


@pytest.mark.slow
def test_sweepd_devices_round_trip_matches_single():
    """Round 14: a devices=4 server serves the same scenario stream as
    the single-device server with IDENTICAL result rows (the sharded
    knob-batch dispatch is bit-identical per replica), still at one
    compile; indivisible peer counts are refused by name up front."""
    import pytest
    from tools.sweepd import SweepServer

    reqs = [
        {"id": "a", "seed": 1},
        {"id": "b", "knobs": {"d": 5, "gossip_factor": 0.4}},
        {"id": "c", "drop_prob": 0.05},
        {"id": "d", "attack": "spam", "attack_frac": 0.1},
    ]
    srv1 = SweepServer(n=200, t=2, m=6, ticks=8, batch=4, seed=0)
    srvD = SweepServer(n=200, t=2, m=6, ticks=8, batch=4, seed=0,
                       devices=4)
    rows1 = srv1.submit([dict(r) for r in reqs])
    rowsD = srvD.submit([dict(r) for r in reqs])
    assert rows1 == rowsD
    assert srvD.compiles() == 1
    assert srvD.stats()["shape"]["devices"] == 4

    with pytest.raises(ValueError, match="divide evenly over the"):
        SweepServer(n=202, t=2, m=6, ticks=8, batch=2, seed=0,
                    devices=4)
    with pytest.raises(ValueError, match="sequential demonstration"):
        SweepServer(n=200, t=2, m=6, ticks=8, batch=1, seed=0,
                    kernel=True, devices=2)


def test_sweepd_line_protocol_and_errors():
    from tools.sweepd import SweepServer

    srv = SweepServer(n=200, t=2, m=6, ticks=8, batch=2, seed=0)
    lines = [
        json.dumps({"id": "ok1"}),
        json.dumps({"id": "bad", "knobs": {"offsets": [1, -1]}}),
        json.dumps({"id": "ok2", "knobs": {"d_lazy": 4}}),
        json.dumps({"cmd": "stats"}),
    ]
    out = io.StringIO()
    srv.serve_lines(lines, out)
    rows = [json.loads(line) for line in
            out.getvalue().strip().splitlines()]
    by_id = {r.get("id"): r for r in rows if "id" in r}
    assert by_id["ok1"]["ok"] and by_id["ok2"]["ok"]
    assert not by_id["bad"]["ok"]
    assert "offsets" in by_id["bad"]["error"]
    stats_rows = [r for r in rows if r.get("stats")]
    assert stats_rows and stats_rows[0]["compiles"] == 1


# -- tournament integration ------------------------------------------------

def test_tournament_defenses_include_tuned():
    from go_libp2p_pubsub_tpu.models.tournament import (
        DEFENSES, TUNED_DEFENSE)
    assert DEFENSES["tuned"] == TUNED_DEFENSE
    # the tuned point is a valid knob point over the tournament config
    cfg = _cfg()
    kn.make_sim_knobs(cfg, gs.ScoreSimConfig(),
                      overrides=dict(TUNED_DEFENSE))
