"""Round 15: the ``*stat --check`` gate contract on unusable input.

measure_all.sh branches on the exit code of every stat gate: 2 means
"unusable artifact" (bench crashed / file mangled), nonzero-else means
"real regression".  That split only works if a truncated, empty, or
bit-flipped artifact produces a CLEAN exit 2 with a named reason —
never a traceback (which the shell would read as a generic crash) and
never a silent 0.  Round 15 makes every artifact write atomic
(utils/artifacts.py), so a mangled file should no longer occur — but
the gates stay the last line of defense, and this pins all nine of
them, on the artifact operand and on the ``--check`` baseline operand.

The committed baselines double as the valid fixtures: each gate run
against its own committed artifact must come back usable (0 or 1 —
never 2), which keeps the corruption fixtures honest (corrupting an
already-unusable file would prove nothing).
"""

import importlib
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

#: (module, committed baseline artifact) — the artifact the bench
#: writes and the committed baseline share one schema for every gate
GATES = [
    ("tools.tourneystat", "TOURNEY_r12.json"),
    ("tools.sweepstat", "SWEEP_r12.json"),
    ("tools.delaystat", "DELAY_r13.json"),
    ("tools.shardstat", "MULTICHIP_r14.json"),
    ("tools.ckptstat", "CKPT_r15.json"),
    ("tools.servestat", "SERVE_r18.json"),
    ("tools.obsstat", "METRICS_r19.json"),
    ("tools.planstat", "PLAN_r19.json"),
]

MODES = ("truncated", "empty", "bitflip")


def _corrupt(mode: str, data: bytes) -> bytes:
    if mode == "empty":
        return b""
    if mode == "truncated":
        return data[: len(data) // 2]
    flipped = bytearray(data)
    flipped[0] ^= 0x08   # '{' -> 's': structurally fatal, 1 bit
    return bytes(flipped)


def _rc(mod, argv):
    """main(argv)'s exit code whether returned or raised — and any
    OTHER exception is the traceback failure mode this test exists to
    forbid, so let it propagate."""
    try:
        return mod.main(argv)
    except SystemExit as e:
        return e.code if isinstance(e.code, int) else 1


@pytest.mark.parametrize("modname,baseline", GATES,
                         ids=[m.split(".")[1] for m, _ in GATES])
def test_committed_baseline_is_usable(modname, baseline):
    mod = importlib.import_module(modname)
    art = str(REPO / baseline)
    assert _rc(mod, [art, "--check", art]) in (0, 1)


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("modname,baseline", GATES,
                         ids=[m.split(".")[1] for m, _ in GATES])
def test_corrupt_artifact_exits_2(modname, baseline, mode, tmp_path):
    mod = importlib.import_module(modname)
    good = (REPO / baseline).read_bytes()
    bad = tmp_path / f"{mode}.json"
    bad.write_bytes(_corrupt(mode, good))
    assert _rc(mod, [str(bad), "--check",
                     str(REPO / baseline)]) == 2


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("modname,baseline", GATES,
                         ids=[m.split(".")[1] for m, _ in GATES])
def test_corrupt_baseline_exits_2(modname, baseline, mode, tmp_path):
    """The --check operand is an artifact too: a mangled committed
    baseline must be a named unusable verdict, not a crash."""
    mod = importlib.import_module(modname)
    good = (REPO / baseline).read_bytes()
    bad = tmp_path / f"{mode}.json"
    bad.write_bytes(_corrupt(mode, good))
    assert _rc(mod, [str(REPO / baseline), "--check",
                     str(bad)]) == 2


# -- tracestat: sys.argv CLI, binary pb / ndjson artifact -----------------


def _tracestat_rc(monkeypatch, argv):
    import tools.tracestat as ts
    monkeypatch.setattr(sys, "argv", ["tracestat"] + argv)
    try:
        rc = ts.main()
        return 0 if rc is None else rc
    except SystemExit as e:
        return e.code if isinstance(e.code, int) else 1


#: a two-line ndjson trace whose FIRST line is longer than the rest,
#: so the half-cut truncation always lands mid-line
_NDJSON = (
    b'{"type": "PUBLISH_MESSAGE", "publishMessage": {"message_id": '
    b'"AAAA", "topic": "t0"}, "timestamp": 100, "padding": "xxxxxxxx"}\n'
    b'{"type": "GRAFT", "timestamp": 101}\n')


@pytest.mark.parametrize("mode", MODES)
def test_tracestat_corrupt_trace_exits_2(mode, tmp_path, monkeypatch):
    bad = tmp_path / "trace.json"
    bad.write_bytes(_corrupt(mode, _NDJSON))
    assert _tracestat_rc(monkeypatch, [str(bad)]) == 2


@pytest.mark.parametrize("mode", MODES)
def test_tracestat_corrupt_frames_exits_2(mode, tmp_path, monkeypatch):
    """A mangled frames SIDECAR is the same unusable verdict."""
    trace = tmp_path / "trace.json"
    trace.write_bytes(_NDJSON)
    frames = tmp_path / "frames.json"
    frames.write_bytes(_corrupt(
        mode, b'{"latency_hist": [0, 3, 1], "latency_buckets": 3}'))
    assert _tracestat_rc(
        monkeypatch, [str(trace), "--frames", str(frames)]) == 2


def test_tracestat_corrupt_baseline_exits_2(tmp_path, monkeypatch):
    trace = tmp_path / "trace.json"
    trace.write_bytes(_NDJSON)
    bad = tmp_path / "baseline.json"
    bad.write_bytes(b'{"cover')
    assert _tracestat_rc(
        monkeypatch, [str(trace), "--check", str(bad)]) == 2
