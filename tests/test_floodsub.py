"""FloodSub end-to-end tests, mirroring the reference suite's core scenarios
(/root/reference/floodsub_test.go: TestBasicFloodsub, TestMultihops,
TestReconnects, TestSelfReceive, subscription announcements)."""

import asyncio

import pytest

from go_libp2p_pubsub_tpu.core import (
    InProcNetwork,
    MessageSignaturePolicy,
    create_floodsub,
)
from helpers import connect, connect_all, dense_connect, get_hosts, settle


async def make_floodsubs(hosts, **kwargs):
    return [await create_floodsub(h, **kwargs) for h in hosts]


async def close_all(pubsubs, net):
    for ps in pubsubs:
        await ps.close()
    await net.close()


async def test_basic_floodsub():
    # 20 hosts, dense topology, every host publishes; all others receive
    net = InProcNetwork()
    hosts = get_hosts(net, 20)
    psubs = await make_floodsubs(hosts)
    subs = []
    for ps in psubs:
        topic = await ps.join("foobar")
        subs.append(await topic.subscribe())
    await dense_connect(hosts)
    await settle(0.1)

    for i, ps in enumerate(psubs):
        data = f"it's not a floooood {i}".encode()
        topic = await ps.join("foobar")
        await topic.publish(data)
        for j, sub in enumerate(subs):
            msg = await asyncio.wait_for(sub.next(), 5)
            assert msg.data == data
            assert msg.from_peer == hosts[i].id

    await close_all(psubs, net)


async def test_self_receive():
    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    (ps,) = await make_floodsubs(hosts)
    topic = await ps.join("t")
    sub = await topic.subscribe()
    await topic.publish(b"hello self")
    msg = await asyncio.wait_for(sub.next(), 5)
    assert msg.data == b"hello self"
    assert msg.local or msg.received_from == hosts[0].id
    await close_all([ps], net)


async def test_multihop_does_not_forward():
    # floodsub does NOT relay beyond direct topic peers unless the middle
    # node subscribes: A - B - C with only A,C subscribed -> no delivery
    net = InProcNetwork()
    hosts = get_hosts(net, 3)
    psubs = await make_floodsubs(hosts)
    ta = await psubs[0].join("chain")
    tc = await psubs[2].join("chain")
    sub_c = await tc.subscribe()
    _sub_a = await ta.subscribe()
    await connect(hosts[0], hosts[1])
    await connect(hosts[1], hosts[2])
    await settle(0.1)

    await ta.publish(b"hop hop")
    with pytest.raises(asyncio.TimeoutError):
        await asyncio.wait_for(sub_c.next(), 0.3)
    await close_all(psubs, net)


async def test_multihop_with_middle_subscriber():
    # when B also subscribes, the message relays A -> B -> C
    net = InProcNetwork()
    hosts = get_hosts(net, 3)
    psubs = await make_floodsubs(hosts)
    topics = [await ps.join("chain") for ps in psubs]
    subs = [await t.subscribe() for t in topics]
    await connect(hosts[0], hosts[1])
    await connect(hosts[1], hosts[2])
    await settle(0.1)

    await topics[0].publish(b"over the river")
    for sub in subs[1:]:
        msg = await asyncio.wait_for(sub.next(), 5)
        assert msg.data == b"over the river"
    await close_all(psubs, net)


async def test_reconnect():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub1 = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)

    await t0.publish(b"one")
    assert (await asyncio.wait_for(sub1.next(), 5)).data == b"one"

    await hosts[0].disconnect(hosts[1].id)
    await settle(0.1)
    assert await psubs[0].list_peers("t") == []

    await connect(hosts[0], hosts[1])
    await settle(0.2)
    await t0.publish(b"two")
    assert (await asyncio.wait_for(sub1.next(), 5)).data == b"two"
    await close_all(psubs, net)


async def test_no_sign_policy():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(
        hosts, sign_policy=MessageSignaturePolicy.STRICT_NO_SIGN)
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)
    await t0.publish(b"anon")
    msg = await asyncio.wait_for(sub.next(), 5)
    assert msg.data == b"anon"
    # StrictNoSign leaves the author/seqno intact (reference keeps
    # signID = host ID unless WithNoAuthor); only the signature is absent
    assert msg.rpc.signature is None and msg.rpc.from_peer is not None
    await close_all(psubs, net)


async def test_no_author():
    import hashlib
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    # no_author requires a content-based message ID (reference pubsub.go:366)
    psubs = await make_floodsubs(
        hosts, sign_policy=MessageSignaturePolicy.STRICT_NO_SIGN,
        no_author=True,
        msg_id_fn=lambda m: hashlib.sha256(m.data or b"").digest())
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)
    await t0.publish(b"one")
    await t0.publish(b"two")
    got = {(await asyncio.wait_for(sub.next(), 5)).data for _ in range(2)}
    assert got == {b"one", b"two"}
    msg_probe = None
    await t0.publish(b"three")
    msg_probe = await asyncio.wait_for(sub.next(), 5)
    assert msg_probe.rpc.from_peer is None and msg_probe.rpc.seqno is None
    await close_all(psubs, net)


async def test_subscription_announcement_reaches_late_peer():
    # host connects AFTER the subscription exists; hello packet carries it
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t1 = await psubs[1].join("late")
    sub = await t1.subscribe()
    await settle(0.05)
    await connect(hosts[0], hosts[1])
    await settle(0.1)

    assert await psubs[0].list_peers("late") == [hosts[1].id]
    t0 = await psubs[0].join("late")
    await t0.publish(b"hi")
    assert (await asyncio.wait_for(sub.next(), 5)).data == b"hi"
    await close_all(psubs, net)


async def test_unsubscribe_announcement():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)
    assert await psubs[0].list_peers("t") == [hosts[1].id]

    sub.cancel()
    await settle(0.1)
    assert await psubs[0].list_peers("t") == []
    await close_all(psubs, net)


async def test_blacklist_drops_messages():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)

    await psubs[1].blacklist_peer(hosts[0].id)
    await settle(0.05)
    await t0.publish(b"nope")
    with pytest.raises(asyncio.TimeoutError):
        await asyncio.wait_for(sub.next(), 0.3)
    await close_all(psubs, net)


async def test_peer_events():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t0 = await psubs[0].join("evt")
    handler = await t0.event_handler()
    t1 = await psubs[1].join("evt")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    ev = await asyncio.wait_for(handler.next_peer_event(), 5)
    assert ev.peer == hosts[1].id and ev.type.name == "JOIN"

    sub.cancel()
    ev = await asyncio.wait_for(handler.next_peer_event(), 5)
    assert ev.peer == hosts[1].id and ev.type.name == "LEAVE"
    await close_all(psubs, net)


async def test_validator_rejects():
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(hosts)
    t0 = await psubs[0].join("guarded")
    t1 = await psubs[1].join("guarded")
    sub = await t1.subscribe()

    async def validator(src, msg):
        return b"bad" not in msg.data

    await psubs[1].register_topic_validator("guarded", validator)
    await connect(hosts[0], hosts[1])
    await settle(0.1)

    await t0.publish(b"a bad message")
    await t0.publish(b"a good message")
    msg = await asyncio.wait_for(sub.next(), 5)
    assert msg.data == b"a good message"
    await close_all(psubs, net)


async def test_message_signature_verified_on_wire():
    # messages forwarded between hosts carry valid signatures; a host with
    # strict policy accepts them (full sign/verify round over the wire)
    net = InProcNetwork()
    hosts = get_hosts(net, 5)
    psubs = await make_floodsubs(hosts)
    topics = [await ps.join("signed") for ps in psubs]
    subs = [await t.subscribe() for t in topics]
    await connect_all(hosts)
    await settle(0.1)
    await topics[0].publish(b"authenticated")
    for sub in subs[1:]:
        msg = await asyncio.wait_for(sub.next(), 5)
        assert msg.data == b"authenticated"
        assert msg.rpc.signature is not None
    await close_all(psubs, net)


async def test_cancel_wakes_blocked_consumer():
    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    (ps,) = await make_floodsubs(hosts)
    topic = await ps.join("t")
    sub = await topic.subscribe()

    async def consume():
        with pytest.raises(Exception):
            await sub.next()

    task = asyncio.ensure_future(consume())
    await settle(0.05)
    sub.cancel()
    await asyncio.wait_for(task, 2)  # must not hang
    await close_all([ps], net)


async def test_api_raises_after_close():
    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    (ps,) = await make_floodsubs(hosts)
    await ps.close()
    with pytest.raises(RuntimeError):
        await ps.get_topics()
    await net.close()


async def test_peer_error_on_protocol_mismatch():
    """Connecting to a peer with no common protocol routes through the
    peer-error path (reference newPeerError, comm.go:96-101) and forgets
    the peer without killing the event loop."""
    from go_libp2p_pubsub_tpu.core import InProcNetwork, create_floodsub
    from helpers import settle

    net = InProcNetwork()
    h1, h2 = net.new_host(), net.new_host()  # h2 has no handlers at all
    ps = await create_floodsub(h1)
    await h1.connect(h2)
    await settle(0.2)
    assert h2.id not in ps.peers  # negotiation failed: peer forgotten
    # the loop survived: normal API still works
    t = await ps.join("alive")
    await t.subscribe()
    await ps.close()
    await net.close()


async def test_custom_message_author():
    """WithMessageAuthor (reference pubsub.go:352-364): messages carry
    the configured author instead of the host ID.  Signing as a foreign
    author is rejected (no key for it)."""
    from go_libp2p_pubsub_tpu.core.crypto import generate_keypair

    other_id = generate_keypair().public.peer_id()
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(
        hosts, sign_policy=MessageSignaturePolicy.LAX_NO_SIGN,
        message_author=other_id)
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)
    await t0.publish(b"attributed")
    msg = await asyncio.wait_for(sub.next(), 5)
    assert msg.rpc.from_peer == bytes(other_id)
    await close_all(psubs, net)

    from go_libp2p_pubsub_tpu.core import PubSub
    from go_libp2p_pubsub_tpu.core.floodsub import FloodSubRouter
    net2 = InProcNetwork()
    h = get_hosts(net2, 1)[0]
    try:
        with pytest.raises(ValueError, match="foreign author"):
            PubSub(h, FloodSubRouter(),
                   sign_policy=MessageSignaturePolicy.STRICT_SIGN,
                   message_author=other_id)
    finally:
        await net2.close()


async def test_no_author_with_default_policy_still_delivers():
    """WithNoAuthor downgrades the signing bit of the policy
    (pubsub.go:371): two no_author nodes on the DEFAULT StrictSign
    policy must accept each other's unsigned messages rather than
    rejecting them for the missing signature."""
    import hashlib
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_floodsubs(
        hosts, no_author=True,
        msg_id_fn=lambda m: hashlib.sha256(m.data or b"").digest())
    t0 = await psubs[0].join("t")
    t1 = await psubs[1].join("t")
    sub = await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.1)
    await t0.publish(b"unsigned but accepted")
    msg = await asyncio.wait_for(sub.next(), 5)
    assert msg.data == b"unsigned but accepted"
    assert msg.rpc.signature is None
    await close_all(psubs, net)
