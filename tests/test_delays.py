# graftlint: scope=tests
"""Event-driven time (round 13, models/delays.py): per-edge delay
lines, jitter, and the pipelined-gossip regime.

The acceptance pins:

- ``delays=None`` and ``DelayConfig(base=1, jitter=0, k_slots=1)`` are
  BIT-IDENTICAL to the pre-delay step on all six execution paths
  (gossip-xla combined + split + kernel, flood-circulant/gather,
  randomsub-circulant/dense).
- batched-over-heterogeneous-delay-knobs == sequential, with the
  no-retrace jaxpr proof.
- delayed ``latency_hist`` sums still equal the per-tick deliveries,
  and the distribution is genuinely multi-bucket.
- the in-scan invariant checker stays green under delays (delivery
  monotonicity tolerates in-flight slots by construction — arrivals
  only ever ADD possession bits).
- DelayConfig validation names the offending field; the named
  capability refusals are live.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import go_libp2p_pubsub_tpu.models.floodsub as fs
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.invariants as iv
import go_libp2p_pubsub_tpu.models.randomsub as rs
import go_libp2p_pubsub_tpu.models.telemetry as tl
from go_libp2p_pubsub_tpu.models import delays as dly
from go_libp2p_pubsub_tpu.models.delays import DelayConfig
from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
from go_libp2p_pubsub_tpu.models.knobs import KnobStaticFieldError
from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets

N, T, M, C = 80, 2, 6, 8
BLK = 1024
TICKS = 10

IDENTITY = DelayConfig(base=1, jitter=0, k_slots=1)


def _inputs():
    subs = np.zeros((N, T), dtype=bool)
    subs[np.arange(N), np.arange(N) % T] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, N // T, M) * T + topic
    ticks = np.zeros(M, dtype=np.int32)
    return subs, topic, origin, ticks


def _sched(**kw):
    base = dict(n_peers=N, horizon=max(TICKS, 16),
                down_intervals=((0, 2, 5), (3, 1, 3)),
                drop_prob=0.1,
                partition_group=(np.arange(N) % 2).astype(np.int32),
                partition_windows=((4, 6),), seed=0)
    base.update(kw)
    return FaultSchedule(**base)


def _gossip_cfg():
    return gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T, C, N, seed=1), n_topics=T,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2,
        backoff_ticks=8)


def _bits(words):
    return int(np.unpackbits(np.asarray(words).view(np.uint8)).sum())


def _assert_state_equal(a, b, n=None, fields=("have", "mesh", "fanout",
                                              "backoff", "last_pub",
                                              "iwant_serves")):
    # n: compare the first n peer lanes only (padded kernel states —
    # pad-lane ledger rows are garbage-tolerated by contract)
    def cut(v):
        v = np.asarray(v)
        return v if n is None else v[..., :n]

    for f in fields:
        x, y = getattr(a, f, None), getattr(b, f, None)
        if x is None or y is None:
            assert x is None and y is None, f
            continue
        np.testing.assert_array_equal(cut(x), cut(y), err_msg=f)
    if getattr(a, "scores", None) is not None:
        for f in ("time_in_mesh", "first_deliveries",
                  "invalid_deliveries", "behaviour_penalty",
                  "mesh_deliveries", "mesh_failure_penalty"):
            x = getattr(a.scores, f)
            y = getattr(b.scores, f)
            if x is None:
                assert y is None, f
                continue
            np.testing.assert_array_equal(cut(x), cut(y), err_msg=f)


# --------------------------------------------------------------------------
# DelayConfig validation
# --------------------------------------------------------------------------


@pytest.mark.parametrize("kw,field", [
    (dict(base=0), "base"),
    (dict(jitter=-1), "jitter"),
    (dict(k_slots=0), "k_slots"),
    (dict(base=3, jitter=2, k_slots=4), "k_slots"),
])
def test_delay_config_validation_names_field(kw, field):
    with pytest.raises(ValueError, match=field):
        DelayConfig(**kw)


def test_delay_line_k1_is_passthrough():
    """The K=1 circular line: enqueue slot == dequeue slot == 0, so
    every tick's sends dequeue the same tick and the carried line is
    identically zero — the mechanical reason DelayConfig(1, 0, 1) is
    bit-identical."""
    dp = dly.compile_delays(IDENTITY)
    d = dly.edge_delays(dp, (C, 16), jnp.int32(5))
    assert np.all(np.asarray(d) == 1)
    sel = dly.slot_select_words(d, jnp.int32(5), 1)
    assert np.all(np.asarray(sel[0]) == (1 << C) - 1)


def test_edge_delays_range_and_jitter_spread():
    dp = dly.compile_delays(DelayConfig(base=2, jitter=3, k_slots=8))
    d = np.asarray(dly.edge_delays(dp, (C, 4096), jnp.int32(7)))
    assert d.min() >= 2 and d.max() <= 5
    assert len(np.unique(d)) == 4          # all four jitter values hit


# --------------------------------------------------------------------------
# Bit-identity of DelayConfig(1, 0, 1) on all six execution paths
# --------------------------------------------------------------------------


def _run_gossip(delays, *, kernel=False, split=False, score=True,
                faults=True, ticks=TICKS, sim_knobs=None):
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = (gs.ScoreSimConfig(mesh_message_deliveries_weight=(
        -1.0 if split else 0.0)) if score else None)
    kw = dict(score_cfg=sc, delays=delays, sim_knobs=sim_knobs)
    if faults:
        kw["fault_schedule"] = _sched()
    if delays is not None and split:
        kw["delays_split"] = True
    skw = {}
    if kernel:
        kw["pad_to_block"] = BLK
        skw = dict(receive_block=BLK, receive_interpret=True)
    if split and not kernel:
        skw["force_split"] = True
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                                       **kw)
    step = gs.make_gossip_step(cfg, sc, **skw)
    for _ in range(ticks):
        out = step(params, state)
        state = out[0]
    return state


@pytest.mark.slow
def test_identity_gossip_combined():
    _assert_state_equal(_run_gossip(None), _run_gossip(IDENTITY))


@pytest.mark.slow
def test_identity_gossip_split():
    _assert_state_equal(_run_gossip(None, split=True),
                        _run_gossip(IDENTITY, split=True))


@pytest.mark.slow
def test_identity_gossip_kernel_interpret():
    # true lanes only: pad-lane LEDGER rows are garbage-tolerated by
    # contract (iwant_serve_level docstring) and legitimately differ
    # between the stream-view and delay-line kernel formulations
    a = _run_gossip(None, kernel=True)
    b = _run_gossip(IDENTITY, kernel=True)
    _assert_state_equal(a, b, n=N)
    # and the kernel identity run equals the unpadded XLA run on the
    # true lanes
    _assert_state_equal(_run_gossip(None), b, n=N)


def test_identity_flood_circulant_and_gather():
    subs, topic, origin, tks = _inputs()
    offs = tuple(int(o) for o in make_circulant_offsets(T, C, N,
                                                        seed=1))
    nbrs = np.stack([(np.arange(N) + o) % N for o in offs], axis=1)
    mask = np.ones_like(nbrs, dtype=bool)
    for gather in (False, True):
        def run(delays):
            if gather:
                p, s = fs.make_flood_sim(nbrs, mask, subs, None,
                                         topic, origin, tks,
                                         fault_schedule=_sched(),
                                         delays=delays)
                core = fs.make_gather_step_core()
            else:
                p, s = fs.make_flood_sim(None, None, subs, None,
                                         topic, origin, tks,
                                         fault_schedule=_sched(),
                                         fault_offsets=offs,
                                         delays=delays)
                core = fs.make_circulant_step_core(offs)
            for _ in range(TICKS):
                s, _d = core(p, s)
            return s
        a, b = run(None), run(IDENTITY)
        np.testing.assert_array_equal(np.asarray(a.have),
                                      np.asarray(b.have))
        np.testing.assert_array_equal(np.asarray(a.first_tick),
                                      np.asarray(b.first_tick))


def test_identity_randomsub_circulant_and_dense():
    subs, topic, origin, tks = _inputs()
    rcfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
        n_topics=T, d=3)
    for dense in (False, True):
        def run(delays):
            p, s = rs.make_randomsub_sim(rcfg, subs, topic, origin,
                                         tks, dense=dense,
                                         fault_schedule=_sched(),
                                         delays=delays)
            step = (rs.make_randomsub_dense_step(rcfg) if dense
                    else rs.make_randomsub_step(rcfg))
            for _ in range(TICKS):
                s, _d = step(p, s)
            return s
        a, b = run(None), run(IDENTITY)
        np.testing.assert_array_equal(np.asarray(a.have),
                                      np.asarray(b.have))
        np.testing.assert_array_equal(np.asarray(a.fresh),
                                      np.asarray(b.fresh))


# --------------------------------------------------------------------------
# Event-driven semantics
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_delays_slow_dissemination_and_kernel_parity():
    """Heterogeneous delays genuinely slow the pipeline (fewer
    possession bits after the same tick budget) and the pallas kernel
    stays bit-identical to the XLA path under them."""
    fast = _run_gossip(IDENTITY)
    slow = _run_gossip(DelayConfig(base=3, jitter=2, k_slots=8))
    assert _bits(slow.have) < _bits(fast.have)
    xla = _run_gossip(DelayConfig(base=3, jitter=2, k_slots=8))
    krn = _run_gossip(DelayConfig(base=3, jitter=2, k_slots=8),
                      kernel=True)
    _assert_state_equal(xla, krn, n=N)


def test_delayed_messages_arrive_exactly_base_late():
    """Deterministic base delay on floodsub: a single publish with
    base=b reaches direct ring neighbors after exactly b ticks —
    first_tick shifts by (b - 1) hops vs the one-hop contract."""
    subs = np.ones((12, 1), dtype=bool)
    topic = np.zeros(1, dtype=np.int64)
    origin = np.zeros(1, dtype=np.int64)
    tks = np.zeros(1, dtype=np.int32)
    offs = (1, -1)
    outs = {}
    for b in (1, 3):
        delays = DelayConfig(base=b, jitter=0, k_slots=4)
        p, s = fs.make_flood_sim(None, None, subs, None, topic,
                                 origin, tks, delays=delays)
        core = fs.make_circulant_step_core(offs)
        for _ in range(13):
            s, _d = core(p, s)
        outs[b] = np.asarray(fs.first_tick_matrix(s, 1))[:, 0]
    # exact per-hop scaling: a distance-h peer first-delivers at
    # t_b(h) = b * h under the b-tick hop (each relay acquires at
    # b*k and sends the following tick, arriving b ticks later)
    for h in (1, 2, 3, 4):
        peers = [h % 12, (12 - h) % 12]
        for p_ in peers:
            assert outs[1][p_] == h, (h, outs[1])
            assert outs[3][p_] == 3 * h, (h, outs[3])


def test_delay_knobs_no_retrace_and_batched_matches_sequential():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig()
    dc = DelayConfig(base=1, jitter=0, k_slots=6)

    def build(knobs):
        return gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                                  score_cfg=sc, delays=dc,
                                  sim_knobs=knobs)

    step = gs.make_gossip_step(cfg, sc)
    ja = str(jax.make_jaxpr(step)(*build({"delay_base": 1})))
    jb = str(jax.make_jaxpr(step)(*build({"delay_base": 4,
                                          "delay_jitter": 2})))
    assert ja == jb, "delay knob values retrace the step"

    points = [{"delay_base": 1}, {"delay_base": 3, "delay_jitter": 2},
              {"delay_base": 5, "delay_jitter": 1}]
    builds = [build(k) for k in points]
    seq = []
    for p, s in builds:
        s2 = gs.gossip_run(p, gs.tree_copy(s), TICKS, step)
        seq.append(np.asarray(s2.have))
    pB = gs.stack_trees([b[0] for b in builds])
    sB = gs.stack_trees([b[1] for b in builds])
    sB2, reach = gs.gossip_run_knob_batch(pB, sB, TICKS, step)
    for i in range(len(points)):
        np.testing.assert_array_equal(np.asarray(sB2.have)[i], seq[i])
    assert reach.shape == (len(points), M)


def test_delay_knob_validation():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    dc = DelayConfig(base=1, jitter=0, k_slots=4)
    with pytest.raises(ValueError, match="k_slots"):
        gs.make_gossip_sim(cfg, subs, topic, origin, tks, delays=dc,
                           sim_knobs={"delay_base": 9})
    with pytest.raises(KnobStaticFieldError, match="delay_k_slots"):
        gs.make_gossip_sim(cfg, subs, topic, origin, tks, delays=dc,
                           sim_knobs={"delay_k_slots": 8})
    with pytest.raises(ValueError, match="DelayConfig alongside"):
        gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                           sim_knobs={"delay_base": 2})


@pytest.mark.slow
def test_delayed_latency_hist_sums_and_multibucket():
    """Under delays the latency histogram is a REAL multi-bucket
    distribution whose per-tick sums still equal the delivered
    counts — on the XLA path and, bit-identically, the kernel."""
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig()
    tcfg = tl.TelemetryConfig(counters=False, wire=False,
                              latency_hist=True, latency_buckets=24)
    frames_by_path = {}
    for kernel in (False, True):
        kw = dict(score_cfg=sc, fault_schedule=_sched(),
                  delays=DelayConfig(base=3, jitter=2, k_slots=8))
        skw = dict(telemetry=tcfg)
        if kernel:
            kw["pad_to_block"] = BLK
            skw.update(receive_block=BLK, receive_interpret=True)
        params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                           tks, **kw)
        step = gs.make_gossip_step(cfg, sc, **skw)
        hist = np.zeros(24, dtype=np.int64)
        delivered = 0
        for _ in range(16):
            state, d, frame = step(params, state)
            hist += np.asarray(frame.latency_hist)
            delivered += _bits(d)
        frames_by_path[kernel] = hist
        assert hist.sum() == delivered
        assert (hist > 0).sum() >= 3, hist     # multi-bucket
        # nothing travels faster than the base delay: bucket 0 is the
        # origins' own inject-tick deliveries, and the earliest
        # relayed copy is a same-tick gossip advert arriving
        # base - 1 = 2 ticks later — bucket 1 must stay empty
        assert hist[1] == 0, hist
        assert hist[3:].sum() > 0, hist
    np.testing.assert_array_equal(frames_by_path[False],
                                  frames_by_path[True])


# --------------------------------------------------------------------------
# Delay-armed telemetry counters (round 19: the lifted refusal)
# --------------------------------------------------------------------------


_COUNTER_FIELDS = ("payload_sent", "ihave_rpcs", "ihave_ids",
                   "iwant_rpcs", "iwant_ids_requested",
                   "iwant_ids_served", "graft_sends", "prune_sends",
                   "dup_suppressed", "bytes_payload", "bytes_control")


def _run_gossip_frames(delays, *, kernel=False, split=False,
                       ticks=TICKS):
    """Counter+wire-armed gossip run; returns summed per-field frame
    totals plus the final state."""
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig(mesh_message_deliveries_weight=(
        -1.0 if split else 0.0))
    kw = dict(score_cfg=sc, delays=delays, fault_schedule=_sched())
    if delays is not None:
        kw["delays_counters"] = True
        if split:
            kw["delays_split"] = True
    skw = dict(telemetry=tl.TelemetryConfig())
    if kernel:
        kw["pad_to_block"] = BLK
        skw.update(receive_block=BLK, receive_interpret=True)
    if split and not kernel:
        skw["force_split"] = True
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                                       **kw)
    step = gs.make_gossip_step(cfg, sc, **skw)
    frames = []
    for _ in range(ticks):
        state, _d, frame = step(params, state)
        frames.append({f: np.asarray(getattr(frame, f))
                       for f in _COUNTER_FIELDS})
    return state, frames


def _assert_frames_equal(a, b):
    assert len(a) == len(b)
    for t, (fa, fb) in enumerate(zip(a, b)):
        for f in _COUNTER_FIELDS:
            np.testing.assert_array_equal(
                fa[f], fb[f], err_msg=f"tick {t}: {f}")


def test_identity_counters_combined():
    """DelayConfig(1, 0, 1) counter frames are bit-identical to the
    pre-delay step's, per tick and per field (combined path)."""
    _, ref = _run_gossip_frames(None)
    _, idn = _run_gossip_frames(IDENTITY)
    _assert_frames_equal(ref, idn)


def test_identity_counters_split():
    _, ref = _run_gossip_frames(None, split=True)
    _, idn = _run_gossip_frames(IDENTITY, split=True)
    _assert_frames_equal(ref, idn)


@pytest.mark.slow
def test_identity_counters_kernel_interpret():
    _, ref = _run_gossip_frames(None, kernel=True)
    _, idn = _run_gossip_frames(IDENTITY, kernel=True)
    _assert_frames_equal(ref, idn)


@pytest.mark.slow
def test_delayed_counters_kernel_matches_xla():
    """Under a REAL heterogeneous delay pipeline the kernel epilogue's
    counter frames stay bit-identical to the XLA delayed path — both
    derive from the same delay_exchange operands."""
    dc = DelayConfig(base=3, jitter=2, k_slots=8)
    _, xla = _run_gossip_frames(dc)
    _, krn = _run_gossip_frames(dc, kernel=True)
    _assert_frames_equal(xla, krn)


def test_delayed_counters_flood_and_randomsub_identity():
    """The flood/randomsub delayed replay paths already thread
    counters; pin their DelayConfig(1, 0, 1) frame identity too."""
    subs, topic, origin, tks = _inputs()
    offs = tuple(int(o) for o in make_circulant_offsets(T, C, N,
                                                        seed=1))
    tcfg = tl.TelemetryConfig()

    def run_flood(delays):
        p, s = fs.make_flood_sim(None, None, subs, None, topic,
                                 origin, tks, fault_schedule=_sched(),
                                 fault_offsets=offs, delays=delays)
        core = fs.make_circulant_step_core(offs, telemetry=tcfg)
        out = []
        for _ in range(TICKS):
            s, _d, frame = core(p, s)
            out.append(np.asarray(frame.payload_sent))
        return out

    def run_rsub(delays):
        rcfg = rs.RandomSubSimConfig(
            offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
            n_topics=T, d=3)
        p, s = rs.make_randomsub_sim(rcfg, subs, topic, origin, tks,
                                     fault_schedule=_sched(),
                                     delays=delays)
        step = rs.make_randomsub_step(rcfg, telemetry=tcfg)
        out = []
        for _ in range(TICKS):
            s, _d, frame = step(p, s)
            out.append(np.asarray(frame.payload_sent))
        return out

    for run in (run_flood, run_rsub):
        a, b = run(None), run(IDENTITY)
        for t, (x, y) in enumerate(zip(a, b)):
            np.testing.assert_array_equal(x, y, err_msg=f"tick {t}")


def test_delays_counters_build_requires_delayconfig():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    with pytest.raises(ValueError, match="needs a DelayConfig"):
        gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                           delays_counters=True)


@pytest.mark.slow
def test_invariants_green_under_delays_with_cold_restart():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig()
    icfg = iv.InvariantConfig()
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, tks, score_cfg=sc,
        fault_schedule=_sched(cold_restart=True),
        delays=DelayConfig(base=2, jitter=2, k_slots=6))
    step = gs.make_gossip_step(cfg, sc, invariants=icfg)
    state = iv.attach(state)
    for _ in range(16):
        state, _d = step(params, state)
    rep = iv.report(state)
    assert rep["bits"] == 0, rep


def test_delayed_attacks_still_contained():
    """The round-11 attack machinery composes with delays: IHAVE-spam
    sybils under a delayed pipeline still accrue P7 at their victims
    (the broken-promise advert rides its own delayed ctrl row)."""
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig(sybil_ihave_spam=True)
    sybil = (np.arange(N) % 5) == 0
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, tks, score_cfg=sc, sybil=sybil,
        delays=DelayConfig(base=2, jitter=1, k_slots=4))
    step = gs.make_gossip_step(cfg, sc)
    for _ in range(12):
        state, _d = step(params, state)
    bp = np.asarray(state.scores.behaviour_penalty, dtype=np.float32)
    # some honest peer recorded broken promises against a sybil edge
    assert bp.sum() > 0.0


def test_directed_drop_prob_one_way_flow():
    """Per-direction link loss end to end: rate-1.0 on every positive
    direction of a 2-regular flood ring means traffic only ever flows
    the negative way (floodsub circulant path)."""
    n = 16
    subs = np.ones((n, 1), dtype=bool)
    offs = (1, -1)
    asym = np.zeros((2, n), dtype=np.float32)
    asym[0, :] = 1.0       # p -> p+1 always down; p -> p-1 clean
    sched = FaultSchedule(n_peers=n, horizon=20, drop_prob=asym)
    p, s = fs.make_flood_sim(None, None, subs, None,
                             np.zeros(1, np.int64),
                             np.zeros(1, np.int64),
                             np.zeros(1, np.int32),
                             fault_schedule=sched, fault_offsets=offs)
    core = fs.make_circulant_step_core(offs)
    for _ in range(6):
        s, _d = core(p, s)
    ft = np.asarray(fs.first_tick_matrix(s, 1))[:, 0]
    # origin 0: peers 15, 14, ... are reached via the surviving -1
    # direction at their ring distance; peers 1, 2, ... can only be
    # reached the long way round (> 6 ticks), so they stay unreached
    for h in (1, 2, 3):
        assert ft[(0 - h) % n] == h, ft      # reached the clean way
        assert ft[h] == -1, ft               # dead direction


def test_refusals_named():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, tks, score_cfg=sc,
        delays=DelayConfig(1, 0, 1))
    # round 19: the counters-group refusal is LIFTED — what remains
    # is the build requirement for the observer delay lines, named
    with pytest.raises(ValueError, match="delays_counters=True"):
        gs.make_gossip_step(cfg, sc,
                            telemetry=tl.TelemetryConfig())(params,
                                                            state)
    # round 20: the rpc-probe refusal is LIFTED — what remains is the
    # build requirement for the probe delay line, named
    with pytest.raises(ValueError, match="delays_probe=True"):
        gs.make_gossip_step(cfg, sc, rpc_probe=True)(params, state)
    # delays + paired refused at BUILD time
    pcfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(T, C, N, seed=1, paired=True),
        n_topics=T, paired_topics=True, d=3, d_lo=2, d_hi=6,
        d_score=2, d_out=1, d_lazy=2, backoff_ticks=8)
    psubs = np.zeros((N, T), dtype=bool)
    own = np.arange(N) % T
    psubs[np.arange(N), own] = True
    psubs[np.arange(N), (own + T // 2) % T] = True
    with pytest.raises(NotImplementedError,
                       match="paired-topic mode is not "
                             "delay-supported"):
        gs.make_gossip_sim(pcfg, psubs, topic, origin, tks,
                           delays=DelayConfig(1, 0, 1))
    # the split path needs its gossip-class line, named
    p2, s2 = gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                                score_cfg=sc,
                                delays=DelayConfig(1, 0, 1))
    with pytest.raises(ValueError, match="delays_split=True"):
        gs.make_gossip_step(cfg, sc, force_split=True)(p2, s2)
    # kernel + iwant-spam under delays stays XLA-only, named
    sc_spam = gs.ScoreSimConfig(sybil_iwant_spam=True)
    p3, s3 = gs.make_gossip_sim(
        cfg, subs, topic, origin, tks, score_cfg=sc_spam,
        sybil=(np.arange(N) % 5) == 0, delays=DelayConfig(1, 0, 1),
        pad_to_block=BLK)
    with pytest.raises(ValueError,
                       match="stays XLA-only on the pallas step "
                             "under delays"):
        jax.eval_shape(gs.make_gossip_step(cfg, sc_spam,
                                           receive_block=BLK),
                       p3, s3)


def test_delays_probe_build_requires_delayconfig():
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    with pytest.raises(ValueError, match="needs a DelayConfig"):
        gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                           delays_probe=True)


def test_identity_delay_probe_parity():
    """Round 20 (the lifted delays[rpc-probe] hole): at the identity
    delay the probe snapshot's shared leaves equal the delays=None
    snapshot bit for bit, and the new ``arr_*`` arrival masks equal
    the same tick's sends in the receiver (transfer) view — the K=1
    probe-line enqueue/dequeue is a value-level pass-through."""
    subs, topic, origin, tks = _inputs()
    cfg = _gossip_cfg()
    step = gs.make_gossip_step(cfg, rpc_probe=True)
    p0, s0 = gs.make_gossip_sim(cfg, subs, topic, origin, tks)
    _, snap0 = gs.gossip_run_rpc_snapshots(p0, s0, TICKS, step)
    p1, s1 = gs.make_gossip_sim(cfg, subs, topic, origin, tks,
                                delays=IDENTITY, delays_probe=True)
    _, snap1 = gs.gossip_run_rpc_snapshots(p1, s1, TICKS, step)
    for k in snap0:
        np.testing.assert_array_equal(
            np.asarray(snap0[k]), np.asarray(snap1[k]), err_msg=k)
    # the arrival leaves: what was sent this tick arrives this tick,
    # receiver-indexed (the edge-duality transfer of the send mask);
    # graft/prune arrivals reuse the ctrl-line dequeue the same way
    for k, send_k in (("arr_fwd", "fwd"), ("arr_ihave", "ihave"),
                      ("arr_flood", "flood"), ("arr_graft", "graft"),
                      ("arr_prune", "prune")):
        got = np.asarray(snap1[k])
        want = np.stack([
            np.asarray(gs.transfer_bits(snap1[send_k][t], cfg))
            for t in range(TICKS)])
        np.testing.assert_array_equal(got, want, err_msg=k)
