# graftlint: scope=tests
"""Round 19: the service observability plane (go_libp2p_pubsub_tpu/
obs) and its serving integration.

The acceptance pins:

- registry semantics: counters are monotonic (``inc``/``set_total``
  both refuse decreases), gauges move freely, histograms keep their
  registration-time buckets, registration is idempotent by name and a
  kind clash is a named error, and ``atomic()`` makes multi-instrument
  updates all-or-nothing under concurrent snapshots.
- render surfaces: the Prometheus text exposition (HELP/TYPE,
  cumulative histogram buckets, escaped labels) and the JSON-lines
  snapshot agree with each other.
- spans: begin/end pairing, never-crash end-without-begin, bounded
  capacity with COUNTED drops, and a Chrome trace export that
  round-trips through json.
- the serving cross-check: a ScenarioFrontend's live metrics scrape
  reproduces its stats() accounting identity on EVERY scrape —
  including mid-flight scrapes taken from another thread during a
  concurrent load burst — and its span ledger covers every admitted
  request (traces == admitted, one terminal event each, nothing open
  or dropped after the drain).
- the sweepd socket loop: thread-per-connection clients against ONE
  resident server, total terminal rows == total requests sent.
"""

import io
import json
import threading
import time

import pytest

from go_libp2p_pubsub_tpu.obs import (MetricsRegistry, Observability,
                                      SpanRecorder)

pytestmark = []


# --------------------------------------------------------------------------
# registry semantics
# --------------------------------------------------------------------------


def test_counter_monotonic():
    m = MetricsRegistry("t")
    c = m.counter("reqs_total")
    c.inc()
    c.inc(4)
    assert c.value() == 5
    c.set_total(9)
    assert c.value() == 9
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    with pytest.raises(ValueError, match="cannot decrease"):
        c.set_total(3)


def test_gauge_and_labels():
    m = MetricsRegistry("t")
    g = m.gauge("depth")
    g.set(7, bucket="a")
    g.add(-2, bucket="a")
    g.set(1, bucket="b")
    assert g.value(bucket="a") == 5
    assert g.value(bucket="b") == 1
    assert g.value(bucket="zzz") == 0
    with pytest.raises(ValueError, match="bad label name"):
        g.set(1, **{"bad-label": "x"})


def test_histogram_buckets_fixed_and_cumulative_render():
    m = MetricsRegistry("t")
    h = m.histogram("lat", (0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    prom = m.render_prometheus()
    assert 't_lat_bucket{le="0.1"} 1' in prom
    assert 't_lat_bucket{le="1.0"} 3' in prom
    assert 't_lat_bucket{le="10.0"} 4' in prom
    assert 't_lat_bucket{le="+Inf"} 5' in prom
    assert "t_lat_count 5" in prom
    with pytest.raises(ValueError, match="strictly-increasing"):
        m.histogram("bad", (1.0, 1.0))
    with pytest.raises(ValueError, match="strictly-increasing"):
        m.histogram("bad2", ())


def test_registration_idempotent_kind_clash_named():
    m = MetricsRegistry("t")
    assert m.counter("x_total") is m.counter("x_total")
    with pytest.raises(ValueError, match="already registered"):
        m.gauge("x_total")
    with pytest.raises(ValueError, match="bad metric name"):
        m.counter("9starts-with-digit")
    with pytest.raises(ValueError, match="bad namespace"):
        MetricsRegistry("no spaces")


def test_atomic_snapshot_all_or_nothing():
    """A scraper racing an atomic() update block must never see the
    identity broken: writer keeps a == b under the lock; reader
    snapshots concurrently and checks every observation."""
    m = MetricsRegistry("t")
    a, b = m.counter("a_total"), m.counter("b_total")
    stop = threading.Event()
    broken = []

    def reader():
        while not stop.is_set():
            snap = {f["name"]: f for f in m.snapshot()}
            va = (snap["t_a_total"]["samples"] or
                  [{"value": 0}])[0]["value"]
            vb = (snap["t_b_total"]["samples"] or
                  [{"value": 0}])[0]["value"]
            if va != vb:
                broken.append((va, vb))
    th = threading.Thread(target=reader)
    th.start()
    for i in range(300):
        with m.atomic():
            a.inc()
            b.inc()
    stop.set()
    th.join()
    assert not broken, broken[:3]
    assert a.value() == b.value() == 300


def test_prometheus_label_escaping_and_json_agreement():
    m = MetricsRegistry("t")
    m.counter("c_total").inc(2, path='a"b\\c')
    prom = m.render_prometheus()
    assert 't_c_total{path="a\\"b\\\\c"} 2' in prom
    fams = [json.loads(ln) for ln in
            m.render_json_lines().splitlines()]
    assert fams[0]["name"] == "t_c_total"
    assert fams[0]["samples"][0]["value"] == 2
    assert fams[0]["samples"][0]["labels"] == {"path": 'a"b\\c'}


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------


def test_span_lifecycle_and_chrome_export(tmp_path):
    rec = SpanRecorder()
    tid = rec.new_trace_id("req/1")
    assert "/" not in tid
    rec.instant(tid, "admit")
    rec.begin(tid, "queue")
    time.sleep(0.002)
    dur = rec.end(tid, "queue")
    assert dur >= 0.002
    rec.instant(tid, "serve", outcome="ok")
    summ = rec.summary()
    assert summ["traces"] == 1 and summ["open_spans"] == 0
    assert summ["phases"] == {"admit": 1, "queue": 1, "serve": 1}
    assert summ["terminal"] == 1
    path = tmp_path / "trace.json"
    rec.write_chrome_trace(str(path))
    trace = json.loads(path.read_text())
    assert len(trace["traceEvents"]) == 3
    x = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert x["name"] == "queue" and x["dur"] >= 2000
    assert x["args"]["trace_id"] == tid


def test_span_end_without_begin_never_crashes():
    rec = SpanRecorder()
    assert rec.end("ghost-0", "dispatch") == 0.0
    assert rec.summary()["phases"] == {"dispatch": 1}


def test_span_capacity_drops_are_counted():
    rec = SpanRecorder(capacity=5)
    for i in range(8):
        rec.instant(f"t-{i}", "admit")
    summ = rec.summary()
    assert summ["events"] == 5 and summ["dropped_events"] == 3
    assert rec.chrome_trace()["otherData"]["dropped_events"] == 3


# --------------------------------------------------------------------------
# scrape server
# --------------------------------------------------------------------------


def test_scrape_server_endpoints():
    import urllib.request
    o = Observability(namespace="t")
    o.metrics.counter("up_total").inc()
    o.spans.instant(o.spans.new_trace_id("r"), "admit")
    srv = o.scrape_server(port=0)
    try:
        with urllib.request.urlopen(srv.url("/metrics")) as r:
            assert b"t_up_total 1" in r.read()
        with urllib.request.urlopen(srv.url("/metrics.json")) as r:
            fams = [json.loads(ln) for ln in
                    r.read().decode().splitlines()]
            assert any(f["name"] == "t_up_total" for f in fams)
        with urllib.request.urlopen(srv.url("/trace.json")) as r:
            assert len(json.loads(r.read())["traceEvents"]) == 1
        with urllib.request.urlopen(srv.url("/healthz")) as r:
            assert r.status == 204
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url("/nope"))
    finally:
        srv.close()


# --------------------------------------------------------------------------
# serving cross-check (the satellite acceptance)
# --------------------------------------------------------------------------


def _mk_frontend(**kw):
    from go_libp2p_pubsub_tpu.serving import (FrontendConfig,
                                              ScenarioFrontend)
    base = dict(max_buckets=2, batch=2, queue_cap=64,
                server_kw={"seed": 0})
    base.update(kw)
    return ScenarioFrontend(FrontendConfig(**base))


def _scrape_identity(metrics):
    """(admitted, accounted, ok) from one atomic snapshot."""
    snap = {f["name"]: f for f in metrics.snapshot()}

    def val(name):
        s = snap["pubsub_" + name]["samples"]
        return s[0]["value"] if s else 0
    admitted = val("serving_admitted_total")
    accounted = (val("serving_served_total")
                 + val("serving_errors_total")
                 + val("serving_deadline_timeouts_total")
                 + val("serving_transient_failures_total")
                 + val("serving_queue_depth")
                 + val("serving_parked"))
    return admitted, accounted, admitted == accounted


def test_frontend_scrape_reproduces_stats_identity():
    """The committed cross-check: drive served + timed-out +
    overload-rejected requests, then assert the live scrape equals
    stats() field by field and the span ledger covers every
    admission."""
    fe = _mk_frontend(queue_cap=5)
    rows = []
    for i in range(12):
        req = {"id": f"r{i}", "n": 64, "t": 1, "m": 2, "ticks": 4,
               "seed": i % 4}
        if i in (2, 3):
            req["deadline_s"] = 0.0
        rej = fe.admit(req)
        if rej is not None:
            rows.append(rej)
        if i % 5 == 4:
            time.sleep(0.005)
            rows.extend(fe.dispatch_ready(force=True))
    rows.extend(fe.drain())
    st = fe.stats()
    assert st["rejected_overload"] > 0 and st["timeouts"] > 0, st

    admitted, accounted, ok = _scrape_identity(fe.obs.metrics)
    assert ok and admitted == st["admitted"]
    snap = {f["name"]: f for f in fe.obs.metrics.snapshot()}

    def val(name):
        s = snap["pubsub_" + name]["samples"]
        return s[0]["value"] if s else 0
    for field, metric in (
            ("admitted", "serving_admitted_total"),
            ("served", "serving_served_total"),
            ("errors", "serving_errors_total"),
            ("timeouts", "serving_deadline_timeouts_total"),
            ("rejected_overload", "serving_overload_rejected_total"),
            ("transient_failures",
             "serving_transient_failures_total"),
            ("queued", "serving_queue_depth"),
            ("parked", "serving_parked"),
            ("compiles", "serving_compiles"),
            ("evictions", "serving_bucket_evictions_total")):
        assert val(metric) == st[field], (field, val(metric),
                                          st[field])

    summ = fe.obs.spans.summary()
    assert summ["traces"] == st["admitted"]
    assert summ["terminal"] == st["admitted"]
    assert summ["open_spans"] == 0 and summ["dropped_events"] == 0
    # every terminal row carries its trace id (rejections never do)
    for r in rows:
        if r.get("overloaded"):
            assert "trace_id" not in r or r["trace_id"] is None
        else:
            assert r.get("trace_id")


def test_frontend_midflight_scrapes_hold_identity():
    """Satellite 1's hard part: scrapes taken CONCURRENTLY with a
    load burst (a scraper thread hammering snapshot() while the
    serving thread admits and dispatches) must satisfy the identity
    on every single observation — the atomic publish contract."""
    fe = _mk_frontend(batch=2)
    stop = threading.Event()
    seen = []

    def scraper():
        while not stop.is_set():
            seen.append(_scrape_identity(fe.obs.metrics))
    th = threading.Thread(target=scraper)
    th.start()
    try:
        for i in range(20):
            rej = fe.admit({"id": f"m{i}", "n": 64, "t": 1, "m": 2,
                            "ticks": 4, "seed": i % 4})
            assert rej is None
            fe.dispatch_ready()
        fe.drain()
    finally:
        stop.set()
        th.join()
    broken = [s for s in seen if not s[2]]
    assert not broken, broken[:3]
    assert len(seen) > 0
    final = _scrape_identity(fe.obs.metrics)
    assert final == (20, 20, True)
    assert fe.obs.spans.summary()["traces"] == 20


def test_serve_lines_metrics_verb_and_journal_replay_counter(
        tmp_path):
    fe = _mk_frontend()
    journal = str(tmp_path / "fe.journal")
    from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
    with open(journal, "w") as f:
        f.write(ck.journal_encode_line(json.dumps(
            {"id": "old1", "n": 64, "t": 1, "m": 2, "ticks": 4}))
            + "\n")
    out = io.StringIO()
    fe.serve_lines([json.dumps({"cmd": "metrics"})], out,
                   journal=journal)
    rows = [json.loads(ln) for ln in out.getvalue().splitlines()]
    met = next(r for r in rows if r.get("metrics"))
    st = next(r for r in rows if r.get("stats"))
    assert st["journal_replays"] == 1 and st["admitted"] == 1
    fam = {f["name"]: f for f in met["families"]}
    assert (fam["pubsub_serving_journal_replays_total"]["samples"]
            [0]["value"] == 1)
    assert met["spans"]["phases"].get("journal") is None  # replayed
    # lines are already journaled — no re-append, no journal instant


def test_sweepd_socket_thread_per_connection(tmp_path):
    """Two concurrent client connections against ONE front end
    through serve_lines with a shared lock (the --socket loop's
    shape, in-process): total terminal rows == total requests, and
    the shared server's scrape identity holds."""
    fe = _mk_frontend(batch=2)
    lock = threading.RLock()
    outs = [io.StringIO(), io.StringIO()]

    def client(k):
        lines = [json.dumps({"id": f"c{k}-{i}", "n": 64, "t": 1,
                             "m": 2, "ticks": 4, "seed": i % 2})
                 for i in range(5)]
        fe.serve_lines(lines, outs[k], lock=lock)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    rows = []
    for o in outs:
        rows += [json.loads(ln) for ln in o.getvalue().splitlines()]
    terminal = [r for r in rows if not r.get("stats")]
    assert len(terminal) == 10, rows
    assert all(r.get("ok") for r in terminal)
    assert _scrape_identity(fe.obs.metrics) == (10, 10, True)


def test_sweepserver_metrics_optional_and_verb():
    """A SweepServer without obs= refuses the metrics verb by name; a
    main()-style obs-armed server publishes sweepd_* families."""
    from tools.sweepd import SweepServer
    srv = SweepServer(n=64, t=1, m=2, ticks=4, batch=2,
                      invariants=False)
    out = io.StringIO()
    srv.serve_lines([json.dumps({"cmd": "metrics"})], out)
    rows = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert "no observability bundle" in rows[0]["error"]

    o = Observability()
    srv2 = SweepServer(n=64, t=1, m=2, ticks=4, batch=2,
                       invariants=False, obs=o)
    out2 = io.StringIO()
    reqs = [json.dumps({"id": f"q{i}", "seed": i}) for i in range(2)]
    srv2.serve_lines(reqs + [json.dumps({"cmd": "metrics"})], out2)
    rows2 = [json.loads(ln) for ln in out2.getvalue().splitlines()]
    met = next(r for r in rows2 if r.get("metrics"))
    fam = {f["name"]: f for f in met["families"]}
    assert fam["pubsub_sweepd_served_total"]["samples"][0]["value"] \
        == 2
    assert fam["pubsub_sweepd_compiles"]["samples"][0]["value"] == 1
