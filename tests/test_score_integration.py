"""Scoring + gater integration over real in-proc gossipsub networks.

Mirrors the reference's score-driven behavioral tests
(gossipsub_test.go:1388-1817 inspector scenarios) and the spam scenarios
that drive score collapse (gossipsub_spam_test.go:349,563)."""

from __future__ import annotations

import asyncio
import random

from go_libp2p_pubsub_tpu.core import (
    AcceptStatus,
    InProcNetwork,
    MessageSignaturePolicy,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    create_gossipsub,
)
from go_libp2p_pubsub_tpu.pb import (
    ControlGraft,
    ControlMessage,
    PubMessage,
    RPC,
    SubOpts,
)
from helpers import connect, dense_connect, get_hosts, settle

from test_gossipsub import MockPeer, close_all, fast_params

TOPIC = "scored"


def score_params(**kw) -> PeerScoreParams:
    tp = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0000001, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=100.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.999,
        first_message_deliveries_cap=100.0,
        invalid_message_deliveries_weight=-1.0,
        invalid_message_deliveries_decay=0.9999)
    defaults = dict(topics={TOPIC: tp}, app_specific_score=lambda p: 0.0,
                    decay_interval=1.0, decay_to_zero=0.01, retain_score=10.0,
                    behaviour_penalty_weight=-1.0,
                    behaviour_penalty_threshold=0.0,
                    behaviour_penalty_decay=0.99)
    defaults.update(kw)
    return PeerScoreParams(**defaults)


def thresholds() -> PeerScoreThresholds:
    return PeerScoreThresholds(
        gossip_threshold=-10.0, publish_threshold=-50.0,
        graylist_threshold=-100.0, accept_px_threshold=10.0,
        opportunistic_graft_threshold=1.0)


async def make_scored(hosts, **kwargs):
    out = []
    for i, h in enumerate(hosts):
        ps = await create_gossipsub(
            h, router_rng=random.Random(7000 + i),
            gossipsub_params=fast_params(),
            score_params=score_params(), score_thresholds=thresholds(),
            **kwargs)
        out.append(ps)
    return out


async def test_delivery_with_scoring_enabled():
    net = InProcNetwork()
    hosts = get_hosts(net, 10)
    psubs = await make_scored(hosts)
    topics = [await ps.join(TOPIC) for ps in psubs]
    subs = [await t.subscribe() for t in topics]
    await dense_connect(hosts)
    await settle(0.3)

    await topics[0].publish(b"hello scored world")
    msgs = await asyncio.gather(
        *[asyncio.wait_for(s.next(), timeout=5) for s in subs])
    assert all(m.data == b"hello scored world" for m in msgs)

    # first deliverers earned positive P2 on someone's books
    any_positive = any(
        ps.router.score.score(p) > 0
        for ps in psubs for p in ps.router.peers)
    assert any_positive
    await close_all(psubs, net)


async def test_invalid_messages_collapse_score_to_graylist():
    """A peer spamming wire-invalid (unsigned) messages collapses its own
    score quadratically until the router graylists it
    (reference gossipsub_spam_test.go:563)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_scored(hosts)
    victim = psubs[0]
    topic = await victim.join(TOPIC)
    sub = await topic.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.2)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid=TOPIC)]))
    await settle(0.1)

    # missing signature under StrictSign => rejected + P4 penalty each
    for i in range(15):
        mock.send(RPC(publish=[PubMessage(
            from_peer=bytes(mock.host.id), data=b"junk %d" % i,
            seqno=i.to_bytes(8, "big"), topic=TOPIC)]))
    await settle(0.3)

    score = victim.router.score.score(mock.host.id)
    assert score < -100.0  # 15^2 over the graylist threshold
    assert victim.router.accept_from(mock.host.id) == AcceptStatus.NONE
    await close_all(psubs, net)


async def test_graft_during_backoff_earns_behaviour_penalty():
    """Re-GRAFTing while in backoff accrues P7 and eventually graylists
    (reference gossipsub_spam_test.go:349)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_scored(hosts)
    victim = psubs[0]
    topic = await victim.join(TOPIC)
    await topic.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(0.2)

    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid=TOPIC)]))
    await settle(0.1)

    # evict from the mesh and impose backoff (what a PRUNE does), then
    # re-GRAFT repeatedly: each graft during backoff is a penalty (double
    # when inside the flood threshold)
    graft = RPC(control=ControlMessage(graft=[ControlGraft(topic_id=TOPIC)]))
    victim.router.mesh[TOPIC].discard(mock.host.id)
    victim.router._add_backoff(mock.host.id, TOPIC)
    for _ in range(5):
        mock.send(graft)
        await settle(0.05)
        victim.router.mesh[TOPIC].discard(mock.host.id)

    assert victim.router.score.score(mock.host.id) < 0
    penalties = victim.router.score.peer_stats[mock.host.id].behaviour_penalty
    assert penalties >= 5
    await close_all(psubs, net)


async def test_score_inspect_callback():
    seen: dict = {}
    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = []
    for i, h in enumerate(hosts):
        psubs.append(await create_gossipsub(
            h, router_rng=random.Random(i),
            gossipsub_params=fast_params(),
            score_params=score_params(), score_thresholds=thresholds(),
            score_inspect=seen.update, score_inspect_period=0.05))
    t0 = await psubs[0].join(TOPIC)
    await t0.subscribe()
    t1 = await psubs[1].join(TOPIC)
    await t1.subscribe()
    await connect(hosts[0], hosts[1])
    await settle(1.2)  # background inspect ticks at >= 1s granularity
    assert hosts[1].id in seen or hosts[0].id in seen
    await close_all(psubs, net)


async def test_gater_integration_throttles_spammer():
    """With a tiny validation queue and the gater enabled, a flood of
    payload triggers throttle events and flips the breaker."""
    from go_libp2p_pubsub_tpu.core import PeerGaterParams

    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = []
    for i, h in enumerate(hosts):
        psubs.append(await create_gossipsub(
            h, router_rng=random.Random(i), gossipsub_params=fast_params(),
            gater_params=PeerGaterParams(),
            sign_policy=MessageSignaturePolicy.LAX_SIGN,
            validate_queue_size=1, validate_workers=1))
    victim = psubs[0]
    topic = await victim.join(TOPIC)
    await topic.subscribe()

    # a slow rejecting validator: overflow pushes trip the breaker
    # (throttle events) while the few validated messages earn rejects,
    # wrecking the spammer's goodput
    async def slow_validator(pid, msg):
        await asyncio.sleep(0.2)
        return False
    await victim.register_topic_validator(TOPIC, slow_validator)

    await connect(hosts[0], hosts[1])
    await settle(0.2)
    mock = MockPeer(net)
    await mock.connect_and_open(hosts[0])
    mock.send(RPC(subscriptions=[SubOpts(subscribe=True, topicid=TOPIC)]))
    await settle(0.1)

    for i in range(50):
        mock.send(RPC(publish=[PubMessage(
            from_peer=bytes(mock.host.id), data=b"flood",
            seqno=i.to_bytes(8, "big"), topic=TOPIC)]))
    await settle(0.3)

    gate = victim.router.gate
    assert gate.throttle > 0  # breaker has tripped at least once
    # statistically the spammer should now be gated at least sometimes
    results = {gate.accept_from(mock.host.id) for _ in range(50)}
    assert AcceptStatus.CONTROL in results
    await close_all(psubs, net)


async def test_topic_set_score_params_recaps_live_counters():
    """Topic.set_score_params re-parameterizes a live topic through the
    router and re-caps existing counters (reference topic.go:36-74 →
    score.go:192-232)."""
    import pytest

    net = InProcNetwork()
    hosts = get_hosts(net, 2)
    psubs = await make_scored(hosts)
    t0 = await psubs[0].join(TOPIC)
    s0 = await t0.subscribe()
    t1 = await psubs[1].join(TOPIC)
    await connect(hosts[0], hosts[1])
    await settle(0.3)

    for i in range(30):
        await t1.publish(b"msg-%d" % i)
    for _ in range(30):
        await asyncio.wait_for(s0.next(), timeout=5)
    await settle(0.1)

    p1 = hosts[1].id
    engine = psubs[0].router.score
    assert engine.score(p1) > 10  # P2 counter built up

    recapped = TopicScoreParams(
        topic_weight=1.0,
        time_in_mesh_weight=0.0000001, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=100.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.999,
        first_message_deliveries_cap=5.0,
        invalid_message_deliveries_weight=-1.0,
        invalid_message_deliveries_decay=0.9999)
    await t0.set_score_params(recapped)
    assert engine.score(p1) <= 5.5  # counter re-capped to the new cap

    # invalid params are rejected before reaching the engine
    with pytest.raises(ValueError):
        await t0.set_score_params(TopicScoreParams(topic_weight=-1.0))
    assert engine.score(p1) <= 5.5
    await close_all(psubs, net)


async def test_topic_set_score_params_requires_scoring():
    """Without peer scoring enabled the API errors rather than silently
    no-opping (reference topic.go:41-44)."""
    import pytest

    net = InProcNetwork()
    hosts = get_hosts(net, 1)
    ps = await create_gossipsub(hosts[0], gossipsub_params=fast_params())
    t = await ps.join(TOPIC)
    with pytest.raises(ValueError):
        await t.set_score_params(TopicScoreParams(topic_weight=1.0))
    await close_all([ps], net)
