"""GossipSub v1.1 hardening tests for the vectorized simulator.

Sim-scale counterparts of the reference's score/attack tests
(score_test.go, gossipsub_spam_test.go): P1-P7 score dynamics, graylist
enforcement, score-ranked prune retention, invalid-message spam collapsing
the spammer's score, IHAVE-spam broken-promise penalties, and
GRAFT-flood backoff violations.
"""

import jax.numpy as jnp
import numpy as np

from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    ScoreSimConfig,
    compute_scores,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
    mesh_degrees,
    gossip_run,
    reach_counts,
    tree_copy,
)

import pytest


def build(n=600, t=3, c=16, n_msgs=8, seed=1, score_kw=None, sim_kw=None,
          msgs_per_tick=False, **cfg_kw):
    cfg = GossipSimConfig(
        offsets=make_gossip_offsets(t, c, n, seed=seed), n_topics=t,
        **cfg_kw)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(seed)
    msg_topic = rng.integers(0, t, n_msgs)
    msg_origin = rng.integers(0, n // t, n_msgs) * t + msg_topic
    ticks = (np.arange(n_msgs, dtype=np.int32) if msgs_per_tick
             else np.zeros(n_msgs, dtype=np.int32))
    sc = ScoreSimConfig(**(score_kw or {}))
    params, state = make_gossip_sim(
        cfg, subs, msg_topic, msg_origin, ticks, score_cfg=sc,
        **(sim_kw or {}))
    return cfg, sc, params, state


def test_scored_run_still_disseminates():
    """Healthy network with scoring on: full delivery, mesh in bounds."""
    cfg, sc, params, state = build()
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 40, step)
    np.testing.assert_array_equal(np.asarray(reach_counts(params, out)),
                                  600 // 3)
    deg = np.asarray(mesh_degrees(out))
    assert (deg >= cfg.d_lo).all() and (deg <= cfg.d_hi).all()


def test_positive_scores_accrue_for_honest_mesh():
    """P1 (time in mesh) + P2 (first deliveries) make healthy mesh edges
    positive (score.go:256-333)."""
    cfg, sc, params, state = build(n_msgs=32, msgs_per_tick=True)
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 30, step)
    from go_libp2p_pubsub_tpu.models.gossipsub import mesh_matrix
    score = np.asarray(compute_scores(sc, params, out))
    mesh = np.asarray(mesh_matrix(out, cfg))
    assert (score[mesh] > 0).mean() > 0.9
    assert float(out.scores.time_in_mesh.max()) > 5


def test_app_score_graylist_blocks_delivery():
    """Peers with catastrophic app-specific score are graylisted: all
    their inbound is dropped (AcceptFrom, gossipsub.go:584-586), so a
    message originated by one never spreads."""
    n = 600
    app = np.zeros(n, dtype=np.float32)
    bad = 3  # peer 3 (topic 0): everyone scores it below graylist
    app[bad] = -1000.0
    cfg, sc, params, state = build(
        n=n, n_msgs=4, sim_kw=dict(app_score=app))
    # all messages originate at the graylisted peer
    from go_libp2p_pubsub_tpu.ops.graph import pack_bits_pm
    ob = np.zeros((n, 4), dtype=bool)
    ob[bad, :] = True
    deliver = ((np.arange(n) % 3) == (bad % 3))[:, None]
    params = params.replace(
        origin_words=pack_bits_pm(jnp.asarray(ob)),
        deliver_words=pack_bits_pm(jnp.asarray(
            np.broadcast_to(deliver, (n, 4)).copy())),
        publish_tick=jnp.zeros((4,), dtype=jnp.int32))
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 30, step)
    reach = np.asarray(reach_counts(params, out))
    assert (reach == 1).all(), reach  # only the origin itself


def test_invalid_spam_collapses_score_and_containment():
    """Sybils publishing invalid messages accrue P4 (squared) and go
    deeply negative at their neighbors (gossipsub_spam_test.go:563);
    invalid messages are never forwarded by honest peers, so they reach
    at most one hop."""
    n, t = 600, 3
    sybil = np.zeros(n, dtype=bool)
    sybil[0:30:3] = True  # 10 sybils in topic 0
    n_msgs = 30
    msg_topic = np.zeros(n_msgs, dtype=np.int64)
    sybil_ids = np.flatnonzero(sybil)
    msg_origin = np.repeat(sybil_ids, 3)
    msg_invalid = np.ones(n_msgs, dtype=bool)
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=1),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    sc = ScoreSimConfig()
    params, state = make_gossip_sim(
        cfg, subs, msg_topic, msg_origin,
        np.arange(n_msgs, dtype=np.int32) % 10, score_cfg=sc, sybil=sybil,
        msg_invalid=msg_invalid)
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 15, step)
    score = np.asarray(compute_scores(sc, params, out))
    cand_sybil = np.asarray(params.cand_sybil)
    # peers that took invalid deliveries score the spammer deeply negative
    # (P4 is squared; decay hasn't washed it out at tick 15)
    assert score[cand_sybil].min() < -5
    assert np.asarray(out.scores.invalid_deliveries).max() > 0.5
    # invalid messages were never delivered to subscribers
    reach = np.asarray(reach_counts(params, out))
    assert (reach == 0).all(), reach
    # sybils end up pruned out of honest meshes
    from go_libp2p_pubsub_tpu.models.gossipsub import mesh_matrix
    mesh_with_sybil = np.asarray(mesh_matrix(out, cfg)) & cand_sybil
    assert mesh_with_sybil.sum() < cand_sybil.sum() * 0.05


def test_ihave_spam_brings_behaviour_penalty():
    """IHAVE-spamming sybils (advertise, never deliver) accrue P7 broken
    promises at every spammed peer and get graylisted
    (gossipsub_spam_test.go:135, gossip_tracer.go)."""
    n, t = 600, 3
    sybil = np.zeros(n, dtype=bool)
    sybil[0:60:3] = True
    cfg, sc, params, state = build(
        n=n, t=t, n_msgs=4,
        score_kw=dict(sybil_ihave_spam=True),
        sim_kw=dict(sybil=sybil))
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 30, step)
    cand_sybil = np.asarray(params.cand_sybil)
    bp = np.asarray(out.scores.behaviour_penalty)
    assert bp[cand_sybil].max() > 1.0
    score = np.asarray(compute_scores(sc, params, out))
    assert np.median(score[cand_sybil]) < sc.gossip_threshold


def test_unflagged_promise_breaker_accrues_p7():
    """P7 is derived from advertised-vs-delivered traffic, not from the
    sybil flag: a STEALTHY spammer (promise_break, not marked sybil)
    that advertises ids and withholds the payload accrues the same
    broken-promise penalty (gossip_tracer.go:48-153 + applyIwantPenalties
    gossipsub.go:1566-1571), while honest peers accrue none."""
    n, t = 600, 3
    breaker = np.zeros(n, dtype=bool)
    breaker[0:60:3] = True
    cfg, sc, params, state = build(
        n=n, t=t, n_msgs=4,
        sim_kw=dict(promise_break=breaker))
    assert params.sybil is not None and not np.asarray(params.sybil).any()
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 30, step)
    bp = np.asarray(out.scores.behaviour_penalty)
    cand_breaker = np.stack(
        [np.roll(breaker, -o) for o in cfg.offsets])
    assert bp[cand_breaker].max() > 0.5      # breakers penalized...
    assert bp[~cand_breaker].max() == 0.0    # ...honest edges never
    score = np.asarray(compute_scores(sc, params, out))
    # the worst breaker edges fall below the gossip threshold (ignored)
    assert score[cand_breaker].min() < sc.gossip_threshold


def test_iwant_flood_retransmission_cutoff():
    """IWANT-flood containment (gossipsub_spam_test.go:24): sybils
    re-request the full advertised window from every candidate every
    tick.  The per-edge retransmission budget (mcache.go:66-80,
    GossipRetransmission) bounds the victim's served load; raising the
    budget to effectively-unbounded measurably raises it.  Honest
    dissemination is unaffected either way."""
    from go_libp2p_pubsub_tpu.models.gossipsub import iwant_serve_level

    n, t = 600, 3
    sybil = np.zeros(n, dtype=bool)
    sybil[np.arange(0, 60, 3)] = True

    def run(retrans):
        # sustained publish stream so the flood reaches steady state
        cfg, sc, params, state = build(
            n=n, t=t, n_msgs=28, msgs_per_tick=True,
            score_kw=dict(sybil_iwant_spam=True),
            sim_kw=dict(sybil=sybil),
            gossip_retransmission=retrans)
        step = make_gossip_step(cfg, sc)
        out = gossip_run(params, state, 26, step)
        level = np.asarray(iwant_serve_level(out, cfg))
        serves = np.asarray(out.iwant_serves)
        # the attack accrues at the sybil requesters' rows (receiver-
        # side ledger); honest rows stay at honest-pull levels
        cand_rows_sybil = np.asarray(out.iwant_serves)[
            :, np.flatnonzero(sybil)]
        assert cand_rows_sybil.max() > 0
        out2 = gossip_run(params, out, 14, step)  # let publishes settle
        reach = np.asarray(reach_counts(params, out2))
        return cfg, reach, level, serves

    cfg, reach_c, level_c, serves_c = run(3)
    _, reach_u, level_u, serves_u = run(1000)
    # defense state exists on the NO-attack path too (unconditional in
    # the reference, mcache.go:66-80): an honest run's ledger is live
    # but stays well below the flood's saturated rows, on the same code
    # path the attack saturates
    hcfg, hsc, hparams, hstate = build(
        n=600, t=3, n_msgs=28, msgs_per_tick=True,
        gossip_retransmission=3)
    assert hstate.iwant_serves is not None      # no attack configured
    hout = gossip_run(hparams, hstate, 26, make_gossip_step(hcfg, hsc))
    hserves = np.asarray(hout.iwant_serves)
    assert hserves.max() > 0                    # ledger is live
    # structural bound: an id is news over an edge at most once, so an
    # honest edge's cumulative (pre-decay) pulls can never exceed the
    # id space — the flood has no such bound without the cutoff
    assert hserves.max() <= 28, hserves.max()
    # sybil rows under sustained flood sit above every honest row
    syb_rows_max = serves_c[:, np.flatnonzero(sybil)].max()
    assert hserves.max() < syb_rows_max, (hserves.max(), syb_rows_max)
    # honest traffic delivered fully in both runs
    assert (reach_c == n // t).all() and (reach_u == n // t).all()
    # the cutoff bounds each edge's served budget: <= (retrans + 1)
    # window loads (the counter can overshoot by one request batch)
    assert serves_c.max() <= 4 * 32
    # and the steady victim-side load is measurably below the uncapped
    # flood (analysis: capped rate = retrans/history_length = 3/5)
    assert level_c.max() > 0
    assert level_c.sum() < 0.8 * level_u.sum(), (
        level_c.sum(), level_u.sum())



def test_gater_shared_ip_fate():
    """Gater stats are keyed by source IP (peer_gater.go:119-151): a
    CLEAN sybil sharing an address with an invalid-spamming one inherits
    its bad goodput, so victims that see both throttle the clean twin's
    payload too.  P6 is disabled to isolate the gater (the colocation
    score term would otherwise graylist the pair on its own).

    Topology: arithmetic-progression offsets (±3k) so a spammer at s and
    its twin at s+3 are co-candidates of most common victims — with
    random circulant offsets IP siblings are almost never visible to the
    same receiver and the grouping has nothing to act on."""
    n, t = 600, 3
    spammer = np.zeros(n, dtype=bool)
    spammer[0:120:12] = True                # 10 spammers (topic 0)
    twin = np.zeros(n, dtype=bool)
    twin[3:123:12] = True                   # 10 clean twins (topic 0)
    offsets = tuple(3 * k for k in range(1, 9)) + tuple(
        -3 * k for k in range(1, 9))

    def run(shared_ip):
        ip = np.arange(n)
        if shared_ip:                       # twin k shares spammer k's IP
            ip[3:123:12] = ip[0:120:12]
        # spammers flood invalid traffic; twins publish valid messages
        n_inv, n_val = 60, 10
        sp_ids = np.flatnonzero(spammer)
        tw_ids = np.flatnonzero(twin)
        origin = np.concatenate([np.repeat(sp_ids, n_inv // 10), tw_ids])
        topic = np.zeros(len(origin), dtype=np.int64)
        invalid = np.array([True] * n_inv + [False] * n_val)
        ticks = np.concatenate([
            np.arange(n_inv, dtype=np.int32) % 12,
            np.full(n_val, 14, dtype=np.int32)])
        cfg = GossipSimConfig(offsets=offsets, n_topics=t)
        subs = np.zeros((n, t), dtype=bool)
        subs[np.arange(n), np.arange(n) % t] = True
        sc = ScoreSimConfig(ip_colocation_factor_weight=0.0)
        params, state = make_gossip_sim(
            cfg, subs, topic, origin, ticks, score_cfg=sc,
            sybil=spammer, peer_ip=ip, msg_invalid=invalid)
        assert (params.cand_same_ip is not None) == shared_ip
        step = make_gossip_step(cfg, sc)
        out = gossip_run(params, state, 20, step)
        # delivery credit earned by twin edges at victims that also see
        # the paired spammer (the edges the IP grouping acts on)
        twin_edges = np.stack([np.roll(twin, -o) for o in offsets])
        spam_sib = np.stack(
            [np.roll(spammer, -(o - 3)) for o in offsets])
        gated = twin_edges & spam_sib
        assert gated.any()
        fd = np.asarray(out.scores.first_deliveries, dtype=np.float64)
        return fd[gated].sum()

    fd_shared = run(True)
    fd_separate = run(False)
    # with separate IPs those same edges earn normal delivery credit...
    assert fd_separate > 0.5, fd_separate
    # ...behind the spammer's IP the gater throttles them hard
    # (measured ~4x suppression on this deterministic seed)
    assert fd_shared < 0.35 * fd_separate, (fd_shared, fd_separate)


def test_graft_flood_penalized_and_rejected():
    """Backoff-violating GRAFT flooders never enter honest meshes and
    accumulate P7 (gossipsub_spam_test.go:349, gossipsub.go:747-765)."""
    n, t = 600, 3
    sybil = np.zeros(n, dtype=bool)
    sybil[0:60:3] = True
    cfg, sc, params, state = build(
        n=n, t=t, n_msgs=4,
        score_kw=dict(sybil_graft_flood=True,
                      behaviour_penalty_weight=-100.0),
        sim_kw=dict(sybil=sybil))
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 40, step)
    cand_sybil = np.asarray(params.cand_sybil)
    honest_rows = ~np.asarray(params.sybil)
    # honest meshes contain (almost) no sybil edges at steady state
    from go_libp2p_pubsub_tpu.models.gossipsub import mesh_matrix
    sybil_mesh_edges = (np.asarray(mesh_matrix(out, cfg))
                        & cand_sybil)[:, honest_rows]
    assert sybil_mesh_edges.mean() < 0.02
    bp = np.asarray(out.scores.behaviour_penalty)
    assert bp[cand_sybil].max() > 0.5


def test_adversarial_network_still_delivers_honest_traffic():
    """20% sybil IWANT/IHAVE-flood network (the BASELINE.md adversarial
    config): honest messages still reach every honest subscriber."""
    n, t = 1000, 5
    rng = np.random.default_rng(0)
    sybil = rng.random(n) < 0.2
    # sybils share one IP per topic class -> P6 colocation
    ip = np.arange(n)
    ip[sybil] = -(np.flatnonzero(sybil) % t) - 1
    n_msgs = 16
    honest_ids = np.flatnonzero(~sybil)
    msg_origin = rng.choice(honest_ids, n_msgs)
    msg_topic = msg_origin % t
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=2),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    sc = ScoreSimConfig(sybil_ihave_spam=True, sybil_graft_flood=True)
    params, state = make_gossip_sim(
        cfg, subs, msg_topic, msg_origin,
        np.full(n_msgs, 10, dtype=np.int32), score_cfg=sc, sybil=sybil,
        peer_ip=ip)
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 60, step)
    # honest subscribers of each topic all got the honest messages
    from go_libp2p_pubsub_tpu.models.gossipsub import first_tick_matrix
    ft = np.asarray(first_tick_matrix(out, n_msgs))
    topics = np.arange(n) % t
    for m in range(n_msgs):
        want = (~sybil) & (topics == msg_topic[m])
        got = ft[:, m] >= 0
        frac = got[want].mean()
        assert frac > 0.99, (m, frac)


def test_mesh_delivery_deficit_penalizes_silent_mesh_edges():
    """With P3 enabled and steady traffic, edges that deliver nothing run
    a deficit; pruning such an edge leaves the sticky P3b penalty
    (score.go:684-818, Prune)."""
    # steady traffic: one message per tick for 40 ticks
    cfg, sc, params, state = build(
        n_msgs=32, msgs_per_tick=True,
        score_kw=dict(mesh_message_deliveries_weight=-1.0,
                      mesh_failure_penalty_weight=-1.0,
                      mesh_message_deliveries_threshold=0.5))
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 40, step)
    # the run must still deliver (P3 calibrated to actual traffic)
    np.testing.assert_array_equal(np.asarray(reach_counts(params, out)),
                                  600 // 3)
    md = np.asarray(out.scores.mesh_deliveries)
    from go_libp2p_pubsub_tpu.models.gossipsub import mesh_matrix
    assert md[np.asarray(mesh_matrix(out, cfg))].max() > 0  # mesh credit
    # sticky penalties exist only where something was pruned while failing
    mfp = np.asarray(out.scores.mesh_failure_penalty)
    assert mfp.min() >= 0


def test_score_config_validation():
    with pytest.raises(ValueError):
        ScoreSimConfig(time_in_mesh_weight=-1.0).validate()
    with pytest.raises(ValueError):
        ScoreSimConfig(invalid_message_deliveries_weight=1.0).validate()
    with pytest.raises(ValueError):
        ScoreSimConfig(first_message_deliveries_decay=1.5).validate()
    with pytest.raises(ValueError):
        ScoreSimConfig(graylist_threshold=-1.0,
                       publish_threshold=-2.0).validate()
    ScoreSimConfig().validate()


def test_score_snapshot_matches_total_and_components():
    """score_snapshot (the sim's WithPeerScoreInspect, score.go:147-175)
    decomposes into components that sum to compute_scores exactly."""
    from go_libp2p_pubsub_tpu.models.gossipsub import score_snapshot
    cfg, sc, params, state = build(n_msgs=16, msgs_per_tick=True)
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 25, step)
    snap = {k: np.asarray(v) for k, v in
            score_snapshot(sc, params, out).items()}
    total = np.asarray(compute_scores(sc, params, out))
    np.testing.assert_allclose(snap["score"], total, rtol=1e-5, atol=1e-5)
    assert (snap["p1_time_in_mesh"] >= 0).all()
    assert snap["p2_first_deliveries"].max() > 0   # deliveries earned credit
    assert (snap["p4_invalid_deliveries"] <= 0).all()


def test_same_tick_credit_uniform_scale():
    """Quantify the sim's all-same-tick-deliverers P2 credit (vs the
    reference's serial first-claim, score.go markFirstMessageDelivery):
    per-peer credit-per-new-message multiplicity is >= 1, bounded by the
    mesh degree bound, and roughly uniform across honest peers — so P2 is
    a uniform scale-up and score *ranking* is preserved (see the module
    docstring's Known deviation note)."""
    cfg, sc, params, state = build(
        n=900, n_msgs=32, msgs_per_tick=True,
        score_kw=dict(first_message_deliveries_decay=0.9999,
                      first_message_deliveries_cap=10000.0))
    step = make_gossip_step(cfg, sc)
    out = gossip_run(params, state, 40, step)

    def popcount(words):  # [W, N] uint32 -> [N] int
        bits = ((words[:, None, :] >> np.arange(32, dtype=np.uint32)
                 [None, :, None]) & 1)
        return bits.sum(axis=(0, 1))

    have = popcount(np.asarray(out.have))
    own = popcount(np.asarray(params.origin_words))
    received = have - own                     # messages delivered by edges
    credit = np.asarray(out.scores.first_deliveries, dtype=np.float64)
    credit_per_peer = credit.sum(axis=0)      # receiver-side issued credit

    mask = received > 4                       # peers with enough samples
    assert mask.sum() > 500
    mult = credit_per_peer[mask] / received[mask]
    # serial first-claim would give exactly 1.0; all-deliverer credit is
    # bounded by the number of same-tick copies <= mesh in-degree <= d_hi
    assert (mult >= 0.99).all()
    assert (mult <= cfg.d_hi + 0.01).all()
    # uniform-scale claim: concentration across honest peers
    assert mult.std() / mult.mean() < 0.35, (mult.mean(), mult.std())


def test_direct_peers_always_forward_never_mesh():
    """Operator-pinned direct peers (gossipsub.go:945-950, 737-745,
    1594-1616): always eager-forwarded, never grafted, graylist/gater
    bypassed.  With gossip disabled (d_lazy=0, factor=0) a fully
    mesh-isolated peer can ONLY receive through its direct edge."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n, t, C, m = 600, 3, 16, 8
    rng = np.random.default_rng(9)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=9), n_topics=t,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
        d_lazy=0, gossip_factor=0.0)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    isolated = np.zeros(n, dtype=bool)
    isolated[::30] = True
    origin_pool = np.flatnonzero(~isolated)
    origin = origin_pool[rng.integers(0, len(origin_pool), m)]
    topic = (origin % t).astype(np.int64)
    ticks = np.zeros(m, dtype=np.int32)

    # every isolated peer gets ONE direct edge (candidate bit 0);
    # operators configure both ends, so the partner's cinv bit mirrors
    o0 = int(cfg.offsets[0])
    cinv0 = cfg.cinv[0]
    de = np.zeros((n, C), dtype=bool)
    de[:, 0] = isolated
    # partner q = p + o0 marks the same edge on bit cinv0:
    # de[q, cinv0] = isolated[q - o0]  (np.roll(x, o)[q] = x[q-o])
    de[:, cinv0] = np.roll(isolated, o0)
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        direct_edges=de)
    # eternal backoff on every edge touching an isolated peer: no
    # mesh membership for them, ever
    iso_cols = jnp.broadcast_to(jnp.asarray(isolated)[None, :],
                                state.backoff.shape)
    blocked = iso_cols | gs.transfer_mask(iso_cols, cfg)
    state = gs.refresh_gates(cfg, sc, params, state.replace(
        backoff=jnp.where(blocked, 30_000, state.backoff)))
    out = gs.gossip_run(params, state, 40, gs.make_gossip_step(cfg, sc))

    # direct edges never entered any mesh
    assert int(jnp.sum(out.mesh & params.cand_direct)) == 0
    # non-isolated subscribers all converged and received everything
    have = np.asarray(out.have)[0]
    members = np.arange(n) % t
    want_bits = np.zeros(n, dtype=np.uint32)
    for j in range(m):
        want_bits[members == topic[j]] |= np.uint32(1 << j)
    ok_honest = (have[~isolated] & want_bits[~isolated]) == \
        want_bits[~isolated]
    assert ok_honest.all()
    # isolated peers: received exactly iff their direct partner exists
    # and subscribes the same topic (always true here: offsets are
    # multiples of t, so partners share the class)
    got = (have[isolated] & want_bits[isolated]) == want_bits[isolated]
    assert got.all(), "direct edge failed to deliver"
    # control: the same scenario WITHOUT direct edges delivers nothing
    # to the isolated peers (no gossip, no mesh)
    params2, state2 = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc)
    state2 = gs.refresh_gates(cfg, sc, params2, state2.replace(
        backoff=jnp.where(blocked, 30_000, state2.backoff)))
    out2 = gs.gossip_run(params2, state2, 40,
                         gs.make_gossip_step(cfg, sc))
    have2 = np.asarray(out2.have)[0]
    assert (have2[isolated] & want_bits[isolated]).max() == 0


def test_static_score_elision_trajectory_identical():
    """The all-zero static-bake elision (GossipParams.static_score_zero)
    must be a pure compiler-level optimization: running the SAME sim
    with the flag forced off (streaming the zero [C, N] array every
    tick) yields a bit-identical trajectory."""
    import jax

    cfg, sc, params, state = build(n=600, n_msgs=8)
    assert params.static_score_zero  # no app scores / unique IPs
    step = make_gossip_step(cfg, sc)
    out_fast = gossip_run(params, tree_copy(state), 40, step)

    forced = params.replace(static_score_zero=False)
    out_ref = gossip_run(forced, state, 40, make_gossip_step(cfg, sc))

    for a, b in zip(jax.tree_util.tree_leaves(out_fast),
                    jax.tree_util.tree_leaves(out_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
