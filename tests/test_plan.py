"""The round-20 capability planner (models/plan.py) and its lattice
audit (tools/graftlint/planaudit.py, tools/planstat.py).

One ExecutionPlan or one named Refusal, statically proven: tier-1
runs the fast lattice subset (planner verdict vs real entry point,
message-matched byte for byte), the golden-matrix round-trip against
the committed PLAN_r19.json, the planstat gate-trip semantics, and
the README table pin.  The full 62-cell sweep (every path x feature
composition, sharded fused included) runs @slow and in
``python -m tools.graftlint`` (measure_all step 0.5).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

import tools.planstat as planstat
from go_libp2p_pubsub_tpu.models import plan
from tools.graftlint import planaudit

REPO = Path(__file__).resolve().parents[1]
GOLDEN = REPO / "PLAN_r19.json"


def _matrix():
    return json.loads(GOLDEN.read_text())


# --------------------------------------------------------------------------
# planner == legacy entry points, message-matched (fast subset tier-1)
# --------------------------------------------------------------------------


def test_fast_lattice_subset_audits_clean():
    """Every fast cell's verdict matches the real entry point: PLAN
    cells trace without compiling with the declared primitives,
    REFUSE cells raise the planner's exact string."""
    problems = planaudit.run_planaudit(fast_only=True)
    assert problems == [], "\n".join(problems)


@pytest.mark.slow
def test_full_lattice_audits_clean():
    problems = planaudit.run_planaudit()
    assert problems == [], "\n".join(problems)


def test_pure_planner_faces_need_no_sim():
    """The host-side faces give verdicts from config alone — the
    serving tier and the mesh-less cold-restart gate call them
    before any build."""
    v = plan.plan_serving(kernel=True, batch=8, devices=0)
    assert isinstance(v, plan.Refusal)
    assert v.code == "serve.kernel-batch"
    assert v.message == plan.MSG_SERVE_KERNEL_BATCH
    v = plan.plan_serving(kernel=True, batch=1, devices=2)
    assert v.code == "serve.kernel-devices"
    v = plan.plan_serving(kernel=False, batch=8, devices=0)
    assert isinstance(v, plan.ExecutionPlan)

    v = plan.plan_circulant("flood-circulant", faults=None)
    assert isinstance(v, plan.ExecutionPlan)
    assert v.path == "flood-circulant"


def test_refusal_is_one_definition_site():
    """The strings legacy call sites used to hand-roll now come FROM
    the planner module — including the two round-20 stragglers
    (fused window arity and the scan-horizon divisibility gate)."""
    assert plan.msg_fused_window(0) == "ticks_fused must be >= 1 (got 0)"
    assert "scan horizon not divisible by the fused window" in \
        plan.msg_fused_horizon(3, 2)
    assert "n_ticks=3" in plan.msg_fused_horizon(3, 2)
    # gossipsub raises these via the plan module, not local literals
    import inspect

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    src = inspect.getsource(gs)
    assert "ticks_fused must be >= 1" not in src
    assert "scan horizon not divisible" not in src


def test_audit_cell_catches_seeded_disagreement():
    """The audit goes nonzero on every way a planner verdict can
    disagree with the entry point — seeded synthetically so the check
    itself is checked."""
    import dataclasses

    import jax
    refuse = plan.Refusal("x.y", "the named message")

    def mk(ctx):
        return planaudit.Cell("seed/x", "gossip-xla", "seed",
                              lambda: dict(ctx))

    # REFUSE but the entry point does not raise
    probs = planaudit.audit_cell(mk(dict(verdict=refuse,
                                         provoke=lambda: None)))
    assert any("did not raise" in p for p in probs)

    # REFUSE but a different string comes out
    def wrong():
        raise ValueError("something else entirely")
    probs = planaudit.audit_cell(mk(dict(verdict=refuse,
                                         provoke=wrong)))
    assert any("drift" in p for p in probs)

    # missing arm / unclassifiable verdict
    probs = planaudit.audit_cell(mk(dict(verdict=refuse)))
    assert any("unclassifiable" in p for p in probs)
    probs = planaudit.audit_cell(mk(dict(verdict=None)))
    assert any("unclassifiable" in p for p in probs)

    # PLAN whose trace lacks a declared primitive
    base = plan.plan_serving(kernel=False, batch=1, devices=0)
    assert isinstance(base, plan.ExecutionPlan)
    lying = dataclasses.replace(base, primitives=("pallas_call",))
    probs = planaudit.audit_cell(mk(dict(
        verdict=lying,
        trace=lambda: jax.make_jaxpr(lambda x: x + 1)(1.0))))
    assert any("declared primitives missing" in p for p in probs)

    # PLAN whose trace contains a forbidden primitive
    lying = dataclasses.replace(base, primitives=(),
                                forbidden=("add",))
    probs = planaudit.audit_cell(mk(dict(
        verdict=lying,
        trace=lambda: jax.make_jaxpr(lambda x: x + 1)(1.0))))
    assert any("forbidden primitives present" in p for p in probs)


# --------------------------------------------------------------------------
# golden-matrix round-trip
# --------------------------------------------------------------------------


def test_golden_matrix_schema_and_coverage():
    m = _matrix()
    assert m["schema"] == planaudit.MATRIX_SCHEMA
    assert m["round"] == planaudit.MATRIX_ROUND
    ids = [r["id"] for r in m["cells"]]
    assert len(ids) == len(set(ids)), "duplicate lattice cell ids"
    for r in m["cells"]:
        assert r["verdict"] in ("PLAN", "REFUSE"), \
            f"unclassified golden cell {r['id']}: {r.get('error')}"
        if r["verdict"] == "REFUSE":
            assert r["code"] and r["message"] and r["exc"]
        else:
            # composed plans extend a base path's name
            # (gossip-kernel-fused[-sharded], serving-*)
            assert any(r["plan_path"].startswith(p)
                       for p in plan.PATHS) or \
                r["plan_path"].startswith("serving"), r["plan_path"]
    # every execution path appears, plus the composition families
    paths = {r["path"] for r in m["cells"]}
    assert paths >= set(plan.PATHS) | {
        "gossip-kernel-fused", "gossip-kernel-fused-sharded",
        "serving"}


def test_golden_matrix_matches_cell_catalog():
    """The committed matrix covers exactly the audit's cell catalog —
    a cell added to planaudit without regenerating PLAN_r19.json (or
    vice versa) is a failure here, not silent drift."""
    cells = planaudit.build_cells()
    assert [c.id for c in cells] == [r["id"] for r in
                                     _matrix()["cells"]]
    fast = [c.id for c in cells if c.fast]
    assert len(fast) >= 12, "fast tier-1 subset shrank"


@pytest.mark.slow
def test_emitted_matrix_matches_committed():
    """capability_matrix() (the --emit-matrix artifact) reproduces
    the committed golden matrix exactly."""
    current = planaudit.capability_matrix()
    assert current == _matrix()


def test_readme_table_is_generated_from_matrix():
    readme = (REPO / "README.md").read_text()
    begin = "<!-- plan-matrix:begin -->\n"
    end = "<!-- plan-matrix:end -->"
    assert begin in readme and end in readme
    block = readme.split(begin, 1)[1].split(end, 1)[0]
    assert block.strip() == planaudit.matrix_markdown(
        _matrix()).strip()


# --------------------------------------------------------------------------
# planstat gate semantics
# --------------------------------------------------------------------------


def _rc(argv):
    try:
        return planstat.main(argv)
    except SystemExit as e:
        return e.code if isinstance(e.code, int) else 1


def test_planstat_clean_on_committed(capsys):
    assert _rc([str(GOLDEN), "--check", str(GOLDEN)]) == 0
    out = capsys.readouterr().out
    assert "100% classified" in out


def test_planstat_trips_on_plan_to_refuse_flip(tmp_path, capsys):
    m = _matrix()
    flipped = next(r for r in m["cells"] if r["verdict"] == "PLAN")
    flipped.update(verdict="REFUSE", code="x.y", message="nope",
                   exc="ValueError")
    art = tmp_path / "flip.json"
    art.write_text(json.dumps(m))
    assert _rc([str(art), "--check", str(GOLDEN)]) == 1
    assert "regressed PLAN -> REFUSE" in capsys.readouterr().err


def test_planstat_trips_on_refusal_message_drift(tmp_path, capsys):
    m = _matrix()
    r = next(r for r in m["cells"] if r["verdict"] == "REFUSE")
    r["message"] += " DRIFTED"
    art = tmp_path / "drift.json"
    art.write_text(json.dumps(m))
    assert _rc([str(art), "--check", str(GOLDEN)]) == 1
    assert "drifted" in capsys.readouterr().err


def test_planstat_trips_on_shrunk_lattice(tmp_path, capsys):
    m = _matrix()
    m["cells"] = m["cells"][1:]
    art = tmp_path / "shrunk.json"
    art.write_text(json.dumps(m))
    assert _rc([str(art), "--check", str(GOLDEN)]) == 1
    assert "lattice shrank" in capsys.readouterr().err


def test_planstat_lift_is_note_not_failure(tmp_path, capsys):
    """REFUSE -> PLAN means capability grew: exit 0 with a note (the
    delays x rpc-probe precedent)."""
    m = _matrix()
    r = next(r for r in m["cells"] if r["verdict"] == "REFUSE")
    for k in ("code", "message"):
        r.pop(k, None)
    r.update(verdict="PLAN", plan_path="gossip-xla", primitives=[],
             forbidden=["pallas_call"])
    art = tmp_path / "lift.json"
    art.write_text(json.dumps(m))
    assert _rc([str(art), "--check", str(GOLDEN)]) == 0
    assert "lifted" in capsys.readouterr().out


def test_planstat_unclassified_cell_is_regression(tmp_path, capsys):
    m = _matrix()
    m["cells"][0] = {"id": m["cells"][0]["id"],
                     "path": m["cells"][0]["path"],
                     "feature": m["cells"][0]["feature"],
                     "verdict": "ERROR", "error": "build exploded"}
    art = tmp_path / "err.json"
    art.write_text(json.dumps(m))
    assert _rc([str(art)]) == 1
    assert "did not classify" in capsys.readouterr().err


def test_planstat_unusable_artifact_exits_2(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(GOLDEN.read_text()[:80])
    assert _rc([str(bad)]) == 2
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"schema": "other-v0",
                                 "cells": [{}]}))
    assert _rc([str(wrong)]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"schema": planstat.SCHEMA,
                                 "cells": []}))
    assert _rc([str(empty)]) == 2


# --------------------------------------------------------------------------
# CLI surfaces
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_emit_matrix_cli_round_trips():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--emit-matrix"],
        capture_output=True, text=True, cwd=REPO,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/root"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert json.loads(out.stdout) == _matrix()
