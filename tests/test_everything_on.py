"""The EVERYTHING-ON configuration: every v1.1 feature active at once.

The reference router runs all features simultaneously by construction
(gossipsub.go:197-297); a sim whose features only exist in mutually-
exclusive modes quietly stops being a model of the real system (VERDICT
r4 weak-3).  This config combines:

- paired-topic overlapping membership (two meshes/peer, TopicScoreCap)
- PX candidate rotation (active-subset refresh on PRUNE)
- operator-pinned direct peers (graylist/gater bypass, never meshed)
- sybil clusters behind shared IPs (P6 colocation + per-IP gater)
- BOTH gossip-repair attacks (IHAVE broken-promise spam + the IWANT
  retransmission flood) plus GRAFT-flood backoff violations
- invalid-message spam from the sybils (P4 + gater pressure)
"""

import numpy as np

import go_libp2p_pubsub_tpu.models.gossipsub as gs


def _build_everything(n=600, t=4, C=16, m=20, seed=5):
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=seed, paired=True),
        n_topics=t, paired_topics=True,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2)
    rng = np.random.default_rng(seed)
    own = np.arange(n) % t
    second = (own + t // 2) % t
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), own] = True
    subs[np.arange(n), second] = True

    sybil = np.zeros(n, dtype=bool)
    sybil[rng.choice(n, n // 10, replace=False)] = True

    # honest origins; sybils additionally inject invalid traffic
    honest_ids = np.flatnonzero(~sybil)
    sybil_ids = np.flatnonzero(sybil)
    n_valid, n_inv = m, m // 2
    origin = np.concatenate([
        honest_ids[rng.integers(0, len(honest_ids), n_valid)],
        sybil_ids[rng.integers(0, len(sybil_ids), n_inv)]])
    topic = (origin % t).astype(np.int64)
    invalid = np.array([False] * n_valid + [True] * n_inv)
    ticks = np.concatenate([
        np.sort(rng.integers(0, 12, n_valid)),
        rng.integers(0, 12, n_inv)]).astype(np.int32)

    # sparse symmetric direct overlay on candidate pair (0, cinv[0])
    f = (np.arange(n) % 53) == 0
    de = np.zeros((n, C), dtype=bool)
    for c_ in (0, cfg.cinv[0]):
        de[:, c_] = f | np.roll(f, -int(cfg.offsets[c_]))

    # sybil pairs share source addresses (P6 + per-IP gater grouping)
    ip = np.arange(n)
    ip[sybil_ids] = n + np.arange(len(sybil_ids)) // 2

    sc = gs.ScoreSimConfig(topic_score_cap=50.0,
                           sybil_ihave_spam=True,
                           sybil_iwant_spam=True,
                           sybil_graft_flood=True)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        sybil=sybil, msg_invalid=invalid, peer_ip=ip,
        px_candidates=10, direct_edges=de)
    return cfg, sc, params, state, sybil, topic, invalid, own, second


def test_everything_on_constructs_and_disseminates():
    """The combined config constructs, runs, and still delivers every
    VALID message to every honest member of its topic pair."""
    (cfg, sc, params, state, sybil, topic, invalid, own,
     second) = _build_everything()
    n, t = len(sybil), cfg.n_topics
    # all features are genuinely wired, not silently dropped
    assert params.cand_direct is not None
    assert params.cand_same_ip is not None
    assert state.active is not None
    assert state.mesh_b is not None
    assert state.iwant_serves is not None

    step = gs.make_gossip_step(cfg, sc)
    out = gs.gossip_run(params, state, 45, step)

    have = np.asarray(out.have)
    honest = ~sybil
    member = lambda tau: (own == tau) | (second == tau)  # noqa: E731
    for j in np.flatnonzero(~invalid):
        w, b = j // 32, np.uint32(1 << (j % 32))
        got = (have[w] & b) != 0
        need = honest & member(topic[j])
        assert (got[need]).all(), f"valid msg {j} failed honest delivery"


def test_everything_on_defenses_live():
    """Each defense observably engages in the combined run: direct
    edges never meshed but pinned in the active set, the serve ledger
    saturates at sybil rows, P7/P6 penalties accrue on attacker edges."""
    (cfg, sc, params, state, sybil, topic, invalid, own,
     second) = _build_everything()
    step = gs.make_gossip_step(cfg, sc)
    mid = gs.gossip_run(params, state, 18, step)
    # pull the mid-run ledger to host BEFORE resuming — the runner
    # donates its state carry, consuming mid's buffers
    serves = np.asarray(mid.iwant_serves)
    out = gs.gossip_run(params, mid, 27, step)

    # direct edges: no HONEST peer ever meshes one (graft-flooding
    # sybils may hold a unilateral delusion — their GRAFT at a direct
    # peer is silently dropped at the graylist, so no PRUNE comes back
    # to retract it, exactly as in the reference) — and pins stay active
    cd = np.asarray(params.cand_direct)
    hon = ~sybil
    assert cd.any()
    assert (np.asarray(out.mesh)[hon] & cd[hon]).max() == 0
    assert (np.asarray(out.mesh_b)[hon] & cd[hon]).max() == 0
    assert ((np.asarray(out.active) & cd) == cd).all(), \
        "PX rotation must never evict pinned direct edges"

    # serve ledger: live mid-run, sybil rows above every honest row
    syb_max = serves[:, sybil].max()
    hon_max = serves[:, ~sybil].max()
    assert syb_max > hon_max, (syb_max, hon_max)

    # P7 (graft flood + broken promises) accrues on sybil edges only
    bp = np.asarray(out.scores.behaviour_penalty)
    cand_sybil = np.stack(
        [np.roll(sybil, -int(o)) for o in cfg.offsets])
    assert bp[cand_sybil].max() > 0
    assert bp[~cand_sybil].max() == 0

    # P6/static score: shared-IP sybil edges carry a colocation penalty
    stat = np.asarray(params.cand_static_score)
    assert stat[cand_sybil].min() < 0

    # the paired gates pipeline stayed consistent throughout
    ref = gs.refresh_gates(cfg, sc, params, out)
    for g_a, g_b in zip(out.gates, ref.gates):
        np.testing.assert_array_equal(np.asarray(g_a), np.asarray(g_b))


def test_everything_on_px_rotation_active():
    """PX rotation actually rotates under the PRUNE churn the attacks
    cause: the active sets at t=18 and t=45 differ somewhere (while
    direct pins never move)."""
    (cfg, sc, params, state, sybil, *_rest) = _build_everything()
    step = gs.make_gossip_step(cfg, sc)
    mid = gs.gossip_run(params, state, 18, step)
    a0 = np.asarray(mid.active)   # before the donated resume eats mid
    out = gs.gossip_run(params, mid, 27, step)
    a1 = np.asarray(out.active)
    assert (a0 != a1).any(), "no PX rotation happened in 45 ticks"
    cd = np.asarray(params.cand_direct)
    assert ((a0 & cd) == cd).all() and ((a1 & cd) == cd).all()
