"""FloodSub simulator tests: semantics against hand-checkable topologies and
cross-validation against the asyncio protocol core; sharded execution on a
virtual 8-device mesh."""

import numpy as np
import jax.numpy as jnp

from go_libp2p_pubsub_tpu.models.floodsub import (
    first_tick_matrix,
    flood_run,
    make_flood_sim,
    reach_by_hops,
    reach_counts,
)
from go_libp2p_pubsub_tpu.ops.graph import (
    build_random_graph,
    pack_bits,
    popcount_words,
    propagate,
    unpack_bits,
)
from go_libp2p_pubsub_tpu.parallel.mesh import make_mesh, shard_peer_tree


def line_graph(n):
    nbrs = np.full((n, 2), n, dtype=np.int32)
    for i in range(n):
        if i > 0:
            nbrs[i, 0] = i - 1
        if i < n - 1:
            nbrs[i, 1] = i + 1
    return nbrs, nbrs != n


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.random((5, 77)) < 0.5
    words = pack_bits(jnp.asarray(bits))
    assert words.shape == (5, 3)
    back = unpack_bits(words, 77)
    np.testing.assert_array_equal(np.asarray(back), bits)


def test_popcount():
    w = jnp.array([[0, 1, 0xFFFFFFFF]], dtype=jnp.uint32)
    np.testing.assert_array_equal(np.asarray(popcount_words(w)), [[0, 1, 32]])


def test_propagate_line():
    n = 5
    nbrs, mask = line_graph(n)
    words = pack_bits(jnp.asarray(np.eye(n, 1, dtype=bool)))  # peer0 has msg0
    heard = propagate(words, jnp.asarray(nbrs), jnp.asarray(mask))
    got = np.asarray(unpack_bits(heard, 1))[:, 0]
    np.testing.assert_array_equal(got, [False, True, False, False, False])


def test_flood_line_hop_timing():
    # message published at tick 0 by peer 0 reaches peer i at tick i
    n = 8
    nbrs, mask = line_graph(n)
    subs = np.ones((n, 1), dtype=bool)
    params, state = make_flood_sim(
        nbrs, mask, subs, None,
        msg_topic=np.array([0]), msg_origin=np.array([0]),
        msg_publish_tick=np.array([0]))
    state = flood_run(params, state, n)
    ft = np.asarray(first_tick_matrix(state, 1))[:, 0]
    np.testing.assert_array_equal(ft, np.arange(n))


def test_unsubscribed_peers_block_flood():
    # middle peer not subscribed -> flood stops (matches the protocol core's
    # multihop semantics, floodsub does not relay through non-subscribers)
    n = 5
    nbrs, mask = line_graph(n)
    subs = np.ones((n, 1), dtype=bool)
    subs[2, 0] = False
    params, state = make_flood_sim(
        nbrs, mask, subs, None,
        msg_topic=np.array([0]), msg_origin=np.array([0]),
        msg_publish_tick=np.array([0]))
    state = flood_run(params, state, n + 2)
    ft = np.asarray(first_tick_matrix(state, 1))[:, 0]
    assert ft[1] == 1
    assert ft[2] == -1 and ft[3] == -1 and ft[4] == -1


def test_relay_peer_forwards_without_delivery():
    n = 5
    nbrs, mask = line_graph(n)
    subs = np.ones((n, 1), dtype=bool)
    subs[2, 0] = False
    relays = np.zeros((n, 1), dtype=bool)
    relays[2, 0] = True
    params, state = make_flood_sim(
        nbrs, mask, subs, relays,
        msg_topic=np.array([0]), msg_origin=np.array([0]),
        msg_publish_tick=np.array([0]))
    state = flood_run(params, state, n + 2)
    ft = np.asarray(first_tick_matrix(state, 1))[:, 0]
    assert ft[2] == -1          # relay never "delivers"
    assert ft[3] == 3 and ft[4] == 4  # but forwards


def test_multi_message_multi_topic():
    n, t = 50, 4
    nbrs, mask = build_random_graph(n, 5, seed=1)
    rng = np.random.default_rng(2)
    subs = rng.random((n, t)) < 0.7
    m = 16
    msg_topic = rng.integers(0, t, m)
    msg_origin = rng.integers(0, n, m)
    ticks = rng.integers(0, 3, m)
    params, state = make_flood_sim(nbrs, mask, subs, None, msg_topic,
                                   msg_origin, ticks)
    state = flood_run(params, state, 30)
    counts = np.asarray(reach_counts(params, state))
    subs_per_topic = subs.sum(axis=0)
    for j in range(m):
        # all subscribed peers in the (connected, dense-enough) graph get it
        assert counts[j] >= 1
        assert counts[j] <= subs_per_topic[msg_topic[j]]
    curve = np.asarray(reach_by_hops(params, state, 30))
    assert curve.shape == (m, 30)
    np.testing.assert_array_equal(curve[:, -1], counts)
    assert (np.diff(curve, axis=1) >= 0).all()


def test_sharded_step_matches_single_device():
    n = 64
    nbrs, mask = build_random_graph(n, 4, seed=3)
    subs = np.ones((n, 2), dtype=bool)
    msg_topic = np.array([0, 1, 0])
    msg_origin = np.array([0, 17, 33])
    ticks = np.array([0, 0, 1])
    params, state = make_flood_sim(nbrs, mask, subs, None, msg_topic,
                                   msg_origin, ticks)
    # copy for the single-device run: the runner donates its state, and
    # shard_peer_tree shares non-peer-axis buffers with the source tree
    from go_libp2p_pubsub_tpu.models.floodsub import tree_copy
    mesh = make_mesh(8)
    assert mesh.size == 8
    params_s = shard_peer_tree(params, mesh, n)
    state_s = shard_peer_tree(state, mesh, n)
    ref = flood_run(params, tree_copy(state), 12)
    out = flood_run(params_s, state_s, 12)
    np.testing.assert_array_equal(np.asarray(ref.first_tick),
                                  np.asarray(out.first_tick))


def test_sim_matches_protocol_core():
    """Cross-validation: the jitted simulator and the asyncio protocol core
    produce identical delivery sets on the same topology."""
    import asyncio
    from go_libp2p_pubsub_tpu.core import InProcNetwork, create_floodsub
    from go_libp2p_pubsub_tpu.core import MessageSignaturePolicy

    n = 10
    rng = np.random.default_rng(7)
    # random connected-ish topology as an edge set
    nbrs, mask = build_random_graph(n, 3, seed=7)
    subs = rng.random((n, 1)) < 0.6
    subs[0, 0] = True  # origin subscribes
    origin = 0

    # --- simulator
    params, state = make_flood_sim(
        nbrs, mask, subs, None, msg_topic=np.array([0]),
        msg_origin=np.array([origin]), msg_publish_tick=np.array([0]))
    state = flood_run(params, state, n + 2)
    sim_delivered = set(np.nonzero(np.asarray(first_tick_matrix(state, 1))[:, 0] >= 0)[0])

    # --- protocol core on the same graph
    async def run_core():
        net = InProcNetwork()
        hosts = [net.new_host() for _ in range(n)]
        psubs = [await create_floodsub(
            h, sign_policy=MessageSignaturePolicy.LAX_NO_SIGN) for h in hosts]
        edges = {(i, int(j)) for i in range(n) for j in nbrs[i] if j < n}
        for i, j in edges:
            if i < j:
                await hosts[i].connect(hosts[j])
        topics, subs_handles = [], {}
        for i, ps in enumerate(psubs):
            topic = await ps.join("t")
            topics.append(topic)
            if subs[i, 0]:
                subs_handles[i] = await topic.subscribe()
        await asyncio.sleep(0.2)
        await topics[origin].publish(b"x")
        await asyncio.sleep(0.3)
        delivered = set()
        for i, sub in subs_handles.items():
            try:
                await asyncio.wait_for(sub.next(), 0.05)
                delivered.add(i)
            except asyncio.TimeoutError:
                pass
        for ps in psubs:
            await ps.close()
        await net.close()
        return delivered

    core_delivered = asyncio.run(run_core())
    assert sim_delivered == core_delivered


def test_circulant_matches_gather_path():
    """The roll-based circulant step and the generic gather step are the
    same protocol over the same topology -> identical first-delivery ticks."""
    from go_libp2p_pubsub_tpu.models.floodsub import make_circulant_flood_step
    from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets

    n, n_classes = 600, 3
    offsets = make_circulant_offsets(n_classes, 6, n, seed=5)
    # explicit neighbor table for the same circulant graph
    idx = np.arange(n)
    nbrs = np.stack([(idx + off) % n for off in offsets], axis=1).astype(np.int32)
    mask = np.ones_like(nbrs, dtype=bool)

    subs = np.zeros((n, n_classes), dtype=bool)
    subs[idx % n_classes == 0, 0] = True
    subs[idx % n_classes == 1, 1] = True
    subs[idx % n_classes == 2, 2] = True
    mt = np.array([0, 1, 2, 0])
    mo = np.array([0, 1, 2, 300])
    pt = np.array([0, 0, 2, 1])

    params_g, state_g = make_flood_sim(nbrs, mask, subs, None, mt, mo, pt)
    out_g = flood_run(params_g, state_g, 25)

    params_c, state_c = make_flood_sim(None, None, subs, None, mt, mo, pt)
    step_c = make_circulant_flood_step(offsets)
    out_c = flood_run(params_c, state_c, 25, step_c)

    np.testing.assert_array_equal(np.asarray(out_g.first_tick),
                                  np.asarray(out_c.first_tick))
    assert (np.asarray(first_tick_matrix(out_c, 4))[idx % n_classes == 0, 0] >= 0).all()
