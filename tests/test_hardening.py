"""Unit tests for the v1.1 hardening engines: peer gater, gossip promise
tracker, and tag tracer (reference peer_gater_test.go, gossip_tracer_test.go,
tag_tracer tests in gossipsub_connmgr_test.go)."""

from __future__ import annotations

import random

from go_libp2p_pubsub_tpu.core import (
    AcceptStatus,
    GossipTracer,
    Message,
    PeerGater,
    PeerGaterParams,
    PeerID,
    TagTracer,
)
from go_libp2p_pubsub_tpu.core.host import ConnManager
from go_libp2p_pubsub_tpu.core.types import (
    REJECT_INVALID_SIGNATURE,
    REJECT_VALIDATION_FAILED,
    REJECT_VALIDATION_IGNORED,
    REJECT_VALIDATION_THROTTLED,
)
from go_libp2p_pubsub_tpu.pb import rpc as pb

TOPIC = "test"


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def mk_msg(seq: int, frm: PeerID, topic: str = TOPIC) -> Message:
    return Message(pb.PubMessage(from_peer=b"owner", data=b"x", topic=topic,
                                 seqno=seq.to_bytes(8, "big")),
                   received_from=frm)


# -- peer gater ------------------------------------------------------------


def mk_gater(clock, rng=None, **kw):
    params = PeerGaterParams(decay_to_zero=0.01, quiet=60.0, **kw)
    return PeerGater(params, clock=clock, rng=rng or random.Random(0),
                     get_ip=lambda p: "1.2.3.4")


def test_gater_inactive_by_default():
    pg = mk_gater(Clock())
    assert pg.accept_from(PeerID(b"A")) == AcceptStatus.ALL


def test_gater_activates_on_throttle_and_gates_bad_peers():
    clock = Clock()
    # rng that always gates (random() -> just below 1)
    class AlwaysGate(random.Random):
        def random(self):
            return 0.999999

    pg = mk_gater(clock, rng=AlwaysGate())
    bad = PeerID(b"B")
    pg.add_peer(bad, "/meshsub/1.1.0")

    # drive the throttle/validate ratio above threshold (0.33)
    for i in range(10):
        pg.validate_message(mk_msg(i, bad))
        pg.reject_message(mk_msg(i, bad), REJECT_VALIDATION_THROTTLED)

    # the bad peer has rejections on its record -> gated to CONTROL
    pg.reject_message(mk_msg(100, bad), REJECT_VALIDATION_FAILED)
    assert pg.accept_from(bad) == AcceptStatus.CONTROL

    # a peer with no stats at its IP...is the same IP here; use a fresh gater
    # for the no-stats case
    pg2 = mk_gater(clock, rng=AlwaysGate())
    for i in range(10):
        pg2.validate_message(mk_msg(i, bad))
        pg2.reject_message(mk_msg(i, bad), REJECT_VALIDATION_THROTTLED)
    clean = PeerID(b"C")
    assert pg2.accept_from(clean) == AcceptStatus.ALL  # total == 0


def test_gater_goodput_probability():
    """A peer with deliveries is accepted with probability
    (1+deliver)/(1+total)."""
    clock = Clock()

    class FixedRng(random.Random):
        value = 0.5

        def random(self):
            return self.value

    rng = FixedRng()
    pg = mk_gater(clock, rng=rng)
    p = PeerID(b"A")
    pg.add_peer(p, "/meshsub/1.1.0")
    for i in range(10):
        pg.validate_message(mk_msg(i, p))
        pg.reject_message(mk_msg(i, p), REJECT_VALIDATION_THROTTLED)
    # 3 deliveries, 1 reject (weight 16): threshold = 4/(1+3+16) = 0.2
    for i in range(3):
        pg.deliver_message(mk_msg(i, p))
    pg.reject_message(mk_msg(50, p), REJECT_VALIDATION_FAILED)

    rng.value = 0.19
    assert pg.accept_from(p) == AcceptStatus.ALL
    rng.value = 0.21
    assert pg.accept_from(p) == AcceptStatus.CONTROL


def test_gater_quiet_period_deactivates():
    clock = Clock()

    class AlwaysGate(random.Random):
        def random(self):
            return 0.999999

    pg = mk_gater(clock, rng=AlwaysGate())
    p = PeerID(b"A")
    pg.add_peer(p, "/meshsub/1.1.0")
    for i in range(10):
        pg.validate_message(mk_msg(i, p))
        pg.reject_message(mk_msg(i, p), REJECT_VALIDATION_THROTTLED)
    pg.reject_message(mk_msg(99, p), REJECT_VALIDATION_FAILED)
    assert pg.accept_from(p) == AcceptStatus.CONTROL
    clock.advance(61.0)  # past quiet
    assert pg.accept_from(p) == AcceptStatus.ALL


def test_gater_ip_shared_fate():
    """Two peers behind one IP share one stats record."""
    pg = mk_gater(Clock())
    a, b = PeerID(b"A"), PeerID(b"B")
    pg.add_peer(a, "/meshsub/1.1.0")
    pg.add_peer(b, "/meshsub/1.1.0")
    pg.deliver_message(mk_msg(1, a))
    assert pg._get_peer_stats(b).deliver == 1.0


def test_gater_decay_and_retention():
    clock = Clock()
    pg = mk_gater(clock)
    p = PeerID(b"A")
    pg.add_peer(p, "/meshsub/1.1.0")
    pg.deliver_message(mk_msg(1, p))
    pg.validate_message(mk_msg(1, p))
    st = pg._get_peer_stats(p)
    before = st.deliver
    pg.decay_stats()
    assert 0 < st.deliver < before
    # disconnected stats expire after retain_stats
    pg.remove_peer(p)
    assert p not in pg.peer_stats
    clock.advance(pg.params.retain_stats + 1)
    pg.decay_stats()
    assert "1.2.3.4" not in pg.ip_stats


def test_gater_ignore_weight():
    clock = Clock()

    class FixedRng(random.Random):
        value = 0.5

        def random(self):
            return self.value

    rng = FixedRng()
    pg = mk_gater(clock, rng=rng)
    p = PeerID(b"A")
    pg.add_peer(p, "/meshsub/1.1.0")
    for i in range(10):
        pg.validate_message(mk_msg(i, p))
        pg.reject_message(mk_msg(i, p), REJECT_VALIDATION_THROTTLED)
    pg.reject_message(mk_msg(20, p), REJECT_VALIDATION_IGNORED)
    # 0 deliveries, 1 ignore (weight 1): threshold = 1/2
    rng.value = 0.49
    assert pg.accept_from(p) == AcceptStatus.ALL
    rng.value = 0.51
    assert pg.accept_from(p) == AcceptStatus.CONTROL


# -- gossip promise tracker ------------------------------------------------


def test_promise_broken_after_followup():
    clock = Clock()
    gt = GossipTracer(follow_up_time=3.0, clock=clock, rng=random.Random(0))
    p = PeerID(b"A")
    mids = [b"m1", b"m2", b"m3"]
    gt.add_promise(p, mids)
    assert gt.get_broken_promises() == {}
    clock.advance(4.0)
    assert gt.get_broken_promises() == {p: 1}
    # and the promise is consumed
    assert gt.get_broken_promises() == {}


def test_promise_fulfilled_by_delivery():
    clock = Clock()
    gt = GossipTracer(follow_up_time=3.0, clock=clock, rng=random.Random(0))
    p = PeerID(b"A")
    msg = mk_msg(1, p)
    mid = gt.msg_id(msg.rpc)
    gt.add_promise(p, [mid])
    gt.deliver_message(msg)
    clock.advance(4.0)
    assert gt.get_broken_promises() == {}


def test_promise_fulfilled_on_validate_even_if_invalid():
    clock = Clock()
    gt = GossipTracer(follow_up_time=3.0, clock=clock, rng=random.Random(0))
    p = PeerID(b"A")
    msg = mk_msg(1, p)
    mid = gt.msg_id(msg.rpc)
    gt.add_promise(p, [mid])
    gt.validate_message(msg)  # began validation: promise kept
    clock.advance(4.0)
    assert gt.get_broken_promises() == {}


def test_promise_not_fulfilled_by_bogus_signature():
    clock = Clock()
    gt = GossipTracer(follow_up_time=3.0, clock=clock, rng=random.Random(0))
    p = PeerID(b"A")
    msg = mk_msg(1, p)
    mid = gt.msg_id(msg.rpc)
    gt.add_promise(p, [mid])
    gt.reject_message(msg, REJECT_INVALID_SIGNATURE)
    clock.advance(4.0)
    assert gt.get_broken_promises() == {p: 1}


def test_promise_voided_on_throttle():
    clock = Clock()
    gt = GossipTracer(follow_up_time=3.0, clock=clock, rng=random.Random(0))
    p = PeerID(b"A")
    gt.add_promise(p, [b"m1"])
    gt.throttle_peer(p)
    clock.advance(4.0)
    assert gt.get_broken_promises() == {}


# -- tag tracer ------------------------------------------------------------


def mk_tag_tracer(clock):
    tt = TagTracer(clock=clock)
    tt.cmgr = ConnManager()
    return tt


def test_tag_tracer_mesh_protection():
    tt = mk_tag_tracer(Clock())
    p = PeerID(b"A")
    tt.graft(p, TOPIC)
    assert f"pubsub:{TOPIC}" in tt.cmgr.protected[p]
    tt.prune(p, TOPIC)
    assert p not in tt.cmgr.protected


def test_tag_tracer_direct_peer_protection():
    tt = mk_tag_tracer(Clock())
    p = PeerID(b"A")
    tt.direct = {p}
    tt.add_peer(p, "/meshsub/1.1.0")
    assert "pubsub:<direct>" in tt.cmgr.protected[p]


def test_tag_tracer_delivery_bump_and_cap():
    tt = mk_tag_tracer(Clock())
    p = PeerID(b"A")
    tt.join(TOPIC)
    for i in range(20):
        msg = mk_msg(i, p)
        tt.validate_message(msg)
        tt.deliver_message(msg)
    assert tt.decaying[TOPIC][p] == 15  # capped
    assert tt.cmgr.tags[p][f"pubsub-deliveries:{TOPIC}"] == 15


def test_tag_tracer_near_first_bump():
    tt = mk_tag_tracer(Clock())
    a, b, late = PeerID(b"A"), PeerID(b"B"), PeerID(b"L")
    tt.join(TOPIC)
    msg = mk_msg(1, a)
    tt.validate_message(msg)
    tt.duplicate_message(mk_msg(1, b))      # during validation: near-first
    tt.deliver_message(msg)
    tt.duplicate_message(mk_msg(1, late))   # after delivery: no credit
    assert tt.decaying[TOPIC] == {a: 1, b: 1}


def test_tag_tracer_reject_clears_tracking():
    tt = mk_tag_tracer(Clock())
    a = PeerID(b"A")
    tt.join(TOPIC)
    msg = mk_msg(1, a)
    tt.validate_message(msg)
    tt.reject_message(msg, REJECT_VALIDATION_FAILED)
    assert tt.near_first == {}


def test_tag_tracer_decay():
    tt = mk_tag_tracer(Clock())
    p = PeerID(b"A")
    tt.join(TOPIC)
    for i in range(3):
        msg = mk_msg(i, p)
        tt.validate_message(msg)
        tt.deliver_message(msg)
    assert tt.decaying[TOPIC][p] == 3
    tt.decay()
    assert tt.decaying[TOPIC][p] == 2
    tt.decay()
    tt.decay()
    assert p not in tt.decaying[TOPIC]
    assert f"pubsub-deliveries:{TOPIC}" not in tt.cmgr.tags.get(p, {})


def test_tag_tracer_leave_clears_tags():
    tt = mk_tag_tracer(Clock())
    p = PeerID(b"A")
    tt.join(TOPIC)
    msg = mk_msg(1, p)
    tt.validate_message(msg)
    tt.deliver_message(msg)
    tt.leave(TOPIC)
    assert TOPIC not in tt.decaying
    assert f"pubsub-deliveries:{TOPIC}" not in tt.cmgr.tags.get(p, {})
