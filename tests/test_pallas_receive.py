"""The pallas receive/update mega-kernel vs the XLA transfer path.

Both paths implement the SAME tick (models/gossipsub.py docstring):
identical uniforms (counter-based lane hash), identical op order in the
counter updates — so entire state trajectories must match bit-for-bit,
padding or not.  Runs the kernel in interpreter mode so CI needs no TPU
(the mosaic lowering itself is exercised by the bench on hardware).
"""

import numpy as np
import pytest

import go_libp2p_pubsub_tpu.models.gossipsub as gs


def _sched(n, seed=5, horizon=40, drop=0.05, partition=True,
           churn_frac=0.1):
    """A FaultSchedule exercising all three fault classes inside the
    test runs' tick windows (staggered churn waves, symmetric link
    loss, one mid-run half/half partition)."""
    import go_libp2p_pubsub_tpu.models.faults as fl

    rng = np.random.default_rng(seed)
    victims = np.flatnonzero(rng.random(n) < churn_frac)
    ivs = tuple((int(p), 3 + int(p % 4), 10 + int(p % 4))
                for p in victims)
    kw = {}
    if partition:
        kw = dict(partition_group=(np.arange(n) % 2).astype(np.int32),
                  partition_windows=((12, 18),))
    return fl.FaultSchedule(n_peers=n, horizon=horizon,
                            down_intervals=ivs, drop_prob=drop,
                            seed=seed ^ 0x9E37, **kw)


def _build(n, n_topics, C, m, *, score, sybil_frac=0.0, spam=False,
           iwant_spam=False, graft_flood=False, invalid_frac=0.0,
           breaker_frac=0.0, pad_block=None, seed=3, exact_k=False,
           direct=False, flood_publish=False, px=None,
           shared_ip=False, faults=None):
    rng = np.random.default_rng(seed)
    offsets = gs.make_gossip_offsets(n_topics, C, n, seed=seed)
    cfg = gs.GossipSimConfig(offsets=offsets, n_topics=n_topics,
                             d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                             d_lazy=2, gossip_factor=0.25,
                             backoff_ticks=8,
                             binomial_gossip_sampling=not exact_k)
    sc = (gs.ScoreSimConfig(sybil_ihave_spam=spam,
                            sybil_iwant_spam=iwant_spam,
                            sybil_graft_flood=graft_flood,
                            flood_publish=flood_publish)
          if score else None)
    idx = np.arange(n)
    subs = np.zeros((n, n_topics), dtype=bool)
    subs[idx, idx % n_topics] = True
    topic = rng.integers(0, n_topics, m)
    origin = rng.integers(0, n // n_topics, m) * n_topics + topic
    ticks = np.sort(rng.integers(0, 12, m)).astype(np.int32)
    kw = {}
    if score:
        sybil = rng.random(n) < sybil_frac
        kw = dict(sybil=sybil,
                  msg_invalid=rng.random(m) < invalid_frac,
                  app_score=rng.normal(0, 0.1, n).astype(np.float32))
        if breaker_frac:
            kw["promise_break"] = rng.random(n) < breaker_frac
    if direct:
        # sparse symmetric direct overlay on candidate pair (0, cinv0)
        f = (np.arange(n) % 29) == 0
        de = np.zeros((n, C), dtype=bool)
        for c_ in (0, cfg.cinv[0]):
            de[:, c_] = f | np.roll(f, -int(offsets[c_]))
        kw["direct_edges"] = de
    if px is not None:
        kw["px_candidates"] = px
    if shared_ip:
        ip = np.arange(n)
        ip[::7] = 0              # broad sharing: cand_same_ip built
        kw["peer_ip"] = ip
        kw.setdefault("app_score",
                      rng.normal(0, 0.1, n).astype(np.float32))
        kw.setdefault("sybil", np.zeros(n, dtype=bool))
        kw.setdefault("msg_invalid", np.zeros(m, dtype=bool))
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        pad_to_block=pad_block, fault_schedule=faults, **kw)
    return cfg, sc, params, state


def _run_pair(n, n_topics, C, m, n_ticks, block, telemetry=None, **kw):
    """XLA (unpadded) and kernel (padded, interpret) trajectories of
    one config.  With ``telemetry`` returns (..., frames_x, frames_k)
    too, run through the telemetry runners."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    cfg, sc, p_x, s_x = _build(n, n_topics, C, m, **kw)
    cfg2, sc2, p_k, s_k = _build(n, n_topics, C, m, pad_block=block,
                                 **kw)
    step_x = gs.make_gossip_step(cfg, sc, telemetry=telemetry)
    step_k = gs.make_gossip_step(cfg2, sc2, receive_block=block,
                                 receive_interpret=True,
                                 telemetry=telemetry)
    if telemetry is not None:
        out_x, fr_x = tl.telemetry_run(p_x, s_x, n_ticks, step_x)
        out_k, fr_k = tl.telemetry_run(p_k, s_k, n_ticks, step_k)
        return cfg, sc, out_x, out_k, fr_x, fr_k
    out_x = gs.gossip_run(p_x, s_x, n_ticks, step_x)
    out_k = gs.gossip_run(p_k, s_k, n_ticks, step_k)
    return cfg, sc, out_x, out_k


def _assert_frames_equal(fr_x, fr_k):
    """Kernel-path frames == XLA-path frames, bit for bit (the int
    counter tallies are exact by construction; the float gauges reduce
    over identical [:n_true] shapes)."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    ax, ak = tl.frames_to_arrays(fr_x), tl.frames_to_arrays(fr_k)
    for name in ax:
        np.testing.assert_array_equal(ax[name], ak[name], err_msg=name)
    return ax


def _assert_state_equal(out_x, out_k, n, sc):
    """Kernel trajectory == XLA trajectory on the true peers."""
    np.testing.assert_array_equal(np.asarray(out_x.mesh),
                                  np.asarray(out_k.mesh)[:n])
    np.testing.assert_array_equal(np.asarray(out_x.have),
                                  np.asarray(out_k.have)[:, :n])
    np.testing.assert_array_equal(np.asarray(out_x.backoff),
                                  np.asarray(out_k.backoff)[:, :n])
    np.testing.assert_array_equal(np.asarray(out_x.fanout),
                                  np.asarray(out_k.fanout)[:n])
    np.testing.assert_array_equal(np.asarray(out_x.recent),
                                  np.asarray(out_k.recent)[:, :, :n])
    np.testing.assert_array_equal(
        np.asarray(out_x.first_tick), np.asarray(out_k.first_tick)
        [:, :, :n])
    if sc is not None:
        for f in ("time_in_mesh", "first_deliveries",
                  "invalid_deliveries", "behaviour_penalty"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out_x.scores, f)),
                np.asarray(getattr(out_k.scores, f))[:, :n], err_msg=f)
        np.testing.assert_array_equal(
            np.asarray(out_x.iwant_serves),
            np.asarray(out_k.iwant_serves)[:, :n],
            err_msg="iwant_serves")


def test_kernel_matches_xla_v10():
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=False)
    _assert_state_equal(out_x, out_k, n, sc)
    # and the run did something: meshes formed, messages moved
    assert np.asarray(gs.mesh_degrees(out_x)).mean() > 0
    assert np.asarray(out_x.have).any()


def test_kernel_matches_xla_v11_scored():
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=True)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.scores.first_deliveries).max() > 0


def test_kernel_matches_xla_serve_ledger_live():
    """The in-kernel gossip-repair serve ledger must match the XLA
    epilogue at a tick where it is LIVE (by tick 30 both paths have
    decayed it to zero, which would make the trajectory-end parity
    check vacuous for this field)."""
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 10, 128, score=True)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.iwant_serves).max() > 0   # non-vacuous


@pytest.mark.slow
def test_kernel_matches_xla_v11_adversarial():
    """IHAVE-spam sybils + invalid traffic: the spam/valid gating and
    broken-promise P7 bookkeeping ride the kernel's ctrl bytes."""
    n = 640
    cfg, sc, out_x, out_k = _run_pair(
        n, 2, 8, 10, 30, 128, score=True, sybil_frac=0.2, spam=True,
        invalid_frac=0.3)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.scores.behaviour_penalty).max() > 0


@pytest.mark.slow
def test_kernel_matches_xla_v11_iwant_flood():
    """BOTH gossip-repair attacks (IHAVE broken-promise spam + the
    IWANT retransmission flood) on the kernel path: the in-kernel
    flood accrual reads the partner's advertised window straight from
    VMEM (the XLA twin rolls adv_count per edge) and must match bit
    for bit, with the sybil rows' serve ledger live."""
    n = 640
    cfg, sc, out_x, out_k = _run_pair(
        n, 2, 8, 10, 12, 128, score=True, sybil_frac=0.2, spam=True,
        iwant_spam=True, invalid_frac=0.3)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.iwant_serves).max() > 0


@pytest.mark.parametrize("score", [True, False])
def test_kernel_matches_xla_px_rotation(score):
    """PX candidate rotation on the kernel path: the kernel emits the
    px_rot word (received PRUNEs/PRUNE-responses), the XLA epilogue
    rotates the active set and re-emits the targets row from the
    POST-rotation actives — trajectories must stay bit-identical, and
    rotation must actually happen."""
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=score,
                                      px=7)
    _assert_state_equal(out_x, out_k, n, sc)
    np.testing.assert_array_equal(np.asarray(out_x.active),
                                  np.asarray(out_k.active)[:n])
    # non-vacuous: the active set rotated somewhere along the run
    cfg2, sc2, p2, s2 = _build(n, 4, 8, 8, score=score, px=7)
    assert (np.asarray(out_x.active) != np.asarray(s2.active)).any()


def test_kernel_matches_xla_flood_publish():
    """WithFloodPublish on the kernel path: own publishes ride a third
    per-edge payload view to every candidate above the publish
    threshold (CTRL_FLOOD), gated by the receiver's payload gate like
    eager forwards — bit-identical to the XLA combined path."""
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=True,
                                      flood_publish=True)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.have).any()


def test_kernel_matches_xla_direct_peers():
    """Operator-pinned direct peers on the kernel path: the direct
    accept/payload bypass and graft exclusions all happen on the gate
    words and selections the kernel consumes (XLA prologue side), so
    the trajectories must stay bit-identical — and direct edges never
    enter a mesh."""
    n = 928                     # multiple of 29: the overlay predicate
    #                             tiles the ring without a seam
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=True,
                                      direct=True)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.have).any()
    # pinned invariant, not just parity: direct edges never meshed
    f = (np.arange(n) % 29) == 0
    cd = np.zeros(n, dtype=np.uint32)
    for c_ in (0, cfg.cinv[0]):
        cd |= (f | np.roll(f, -int(cfg.offsets[c_]))).astype(
            np.uint32) << c_
    assert cd.any()
    assert (np.asarray(out_x.mesh) & cd).max() == 0
    assert (np.asarray(out_k.mesh)[:n] & cd).max() == 0


@pytest.mark.parametrize("score", [True, False])
def test_kernel_matches_xla_exact_k_sampling(score):
    """Exact uniform k-subset gossip targets (the reference's
    emitGossip draw; binomial_gossip_sampling=False) on the kernel
    path: the in-VMEM rank-compare must match ops.graph.select_k_bits
    bit-for-bit."""
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 20, 128, score=score,
                                      exact_k=True)
    assert not cfg.binomial_gossip_sampling
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.have).any()


def test_kernel_matches_xla_v11_promise_breakers():
    """Stealthy (unflagged) promise-breakers: the behavioral P7 rides
    the kernel's ADV-vs-TGT ctrl bits."""
    n = 640
    cfg, sc, out_x, out_k = _run_pair(
        n, 2, 8, 10, 30, 128, score=True, breaker_frac=0.1)
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.scores.behaviour_penalty).max() > 0


def test_kernel_matches_xla_v11_graft_flood():
    n = 640
    cfg, sc, out_x, out_k = _run_pair(
        n, 2, 8, 6, 30, 128, score=True, sybil_frac=0.15,
        graft_flood=True)
    _assert_state_equal(out_x, out_k, n, sc)


def _build_paired(n, t, C, m, *, score, pad_block=None, seed=2,
                  sybil_frac=0.0, spam=False, iwant_spam=False,
                  invalid_frac=0.0, px=None, direct=False,
                  shared_ip=False, flood_publish=False, faults=None):
    rng = np.random.default_rng(seed)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=seed, paired=True),
        n_topics=t, paired_topics=True,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=2,
        gossip_factor=0.25, backoff_ticks=8)
    own = np.arange(n) % t
    second = (own + t // 2) % t
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), own] = True
    subs[np.arange(n), second] = True
    topic = rng.integers(0, t, m)
    members = [np.flatnonzero((own == tau) | (second == tau))
               for tau in range(t)]
    origin = np.array([rng.choice(members[tau]) for tau in topic])
    ticks = np.sort(rng.integers(0, 12, m)).astype(np.int32)
    sc = (gs.ScoreSimConfig(topic_score_cap=25.0,
                            sybil_ihave_spam=spam,
                            sybil_iwant_spam=iwant_spam,
                            flood_publish=flood_publish)
          if score else None)
    kw = {}
    if score:
        sybil = rng.random(n) < sybil_frac
        kw = dict(sybil=sybil,
                  msg_invalid=rng.random(m) < invalid_frac,
                  app_score=rng.normal(0, 0.1, n).astype(np.float32))
        if shared_ip:
            ip = np.arange(n)
            sid = np.flatnonzero(sybil)
            ip[sid] = n + np.arange(len(sid)) // 2
            kw["peer_ip"] = ip
    if direct:
        f = (np.arange(n) % 29) == 0
        de = np.zeros((n, C), dtype=bool)
        for c_ in (0, cfg.cinv[0]):
            de[:, c_] = f | np.roll(f, -int(cfg.offsets[c_]))
        kw["direct_edges"] = de
    if px is not None:
        kw["px_candidates"] = px
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        pad_to_block=pad_block, fault_schedule=faults, **kw)
    return cfg, sc, params, state


@pytest.mark.parametrize("score", [True, False])
def test_kernel_matches_xla_paired(score):
    """Paired-topic mode on the kernel path: two meshes/backoffs per
    peer, slot-B payload view, second ctrl byte with STATIC cross-slot
    routing, per-slot P1 and the 8-row gate emission — all bit-identical
    to the XLA combined path."""
    n = 928
    cfg, sc, p_x, s_x = _build_paired(n, 4, 8, 10, score=score)
    cfg2, sc2, p_k, s_k = _build_paired(n, 4, 8, 10, score=score,
                                        pad_block=128)
    out_x = gs.gossip_run(p_x, s_x, 30, gs.make_gossip_step(cfg, sc))
    out_k = gs.gossip_run(p_k, s_k, 30, gs.make_gossip_step(
        cfg2, sc2, receive_block=128, receive_interpret=True))
    _assert_state_equal(out_x, out_k, n, sc)
    np.testing.assert_array_equal(np.asarray(out_x.mesh_b),
                                  np.asarray(out_k.mesh_b)[:n])
    np.testing.assert_array_equal(np.asarray(out_x.backoff_b),
                                  np.asarray(out_k.backoff_b)[:, :n])
    if sc is not None:
        np.testing.assert_array_equal(
            np.asarray(out_x.scores.time_in_mesh_b),
            np.asarray(out_k.scores.time_in_mesh_b)[:, :n])
    # both slot meshes formed
    assert np.asarray(out_x.mesh_b).any()
    assert np.asarray(out_x.have).any()


@pytest.mark.slow
def test_kernel_matches_xla_everything_on():
    """The EVERYTHING-ON configuration on the kernel path: paired
    topics + PX rotation + direct peers + shared-IP sybils + both
    gossip-repair attacks + flood publish, bit-identical to the XLA
    path — the full feature matrix in one kernel invocation."""
    n = 928
    kw = dict(score=True, sybil_frac=0.15, spam=True, iwant_spam=True,
              invalid_frac=0.25, px=7, direct=True, shared_ip=True,
              flood_publish=True)
    cfg, sc, p_x, s_x = _build_paired(n, 4, 8, 12, **kw)
    cfg2, sc2, p_k, s_k = _build_paired(n, 4, 8, 12, pad_block=128,
                                        **kw)
    assert p_x.cand_same_ip is not None and p_x.cand_direct is not None
    assert s_x.active is not None
    out_x = gs.gossip_run(p_x, s_x, 16, gs.make_gossip_step(cfg, sc))
    out_k = gs.gossip_run(p_k, s_k, 16, gs.make_gossip_step(
        cfg2, sc2, receive_block=128, receive_interpret=True))
    _assert_state_equal(out_x, out_k, n, sc)
    np.testing.assert_array_equal(np.asarray(out_x.mesh_b),
                                  np.asarray(out_k.mesh_b)[:n])
    np.testing.assert_array_equal(np.asarray(out_x.active),
                                  np.asarray(out_k.active)[:n])
    assert np.asarray(out_x.iwant_serves).max() > 0


def test_gate_row_count_single_source():
    """compute_gates' emitted row count must equal the canonical
    n_gate_rows() the kernel and every unpacking site use, for all four
    (scored, paired) combinations — the counts live in two files and
    this pins them in lockstep."""
    from go_libp2p_pubsub_tpu.ops.pallas.receive import n_gate_rows

    for paired in (False, True):
        for score in (False, True):
            if paired:
                cfg, sc, params, state = _build_paired(
                    256, 4, 8, 4, score=score)
            else:
                cfg, sc, params, state = _build(256, 4, 8, 4,
                                                score=score)
            assert len(state.gates) == n_gate_rows(score, paired), \
                (score, paired, len(state.gates))


def test_padded_state_requires_kernel():
    cfg, sc, params, state = _build(900, 4, 8, 8, score=True,
                                    pad_block=128)
    step = gs.make_gossip_step(cfg, sc, use_pallas_receive=False)
    with pytest.raises(ValueError, match="padded"):
        step(params, state)


@pytest.mark.parametrize(
    "score,variant",
    [(True, "plain"), (False, "plain"),
     pytest.param(True, "loaded", marks=pytest.mark.slow),
     pytest.param(True, "paired", marks=pytest.mark.slow)])
def test_sharded_kernel_matches_single_device(score, variant):
    """The shard_map multi-chip kernel dispatch (ring-halo exchange +
    per-shard kernel, ops/pallas/receive.sharded_receive) must produce
    the SAME trajectory as the single-device kernel, bit for bit — the
    in-kernel uniform streams draw by global peer index and the halos
    reproduce extend_wrap's mod-n indexing.  The ``loaded`` variant
    additionally exercises the PX, flood-publish, and shared-IP
    plumbing (extra flats / operands / outputs) under shard_map; the
    ``paired`` variant the second ctrl-byte halo and slot-B payload
    view."""
    import jax
    from jax.sharding import Mesh

    n, D, block = 2048, 8, 128
    assert n % (D * block) == 0
    if variant == "paired":
        cfg, sc, p_k, s_k = _build_paired(n, 4, 8, 8, score=score,
                                          pad_block=block)
    else:
        extra = (dict(px=7, flood_publish=True, shared_ip=True)
                 if variant == "loaded" else {})
        cfg, sc, p_k, s_k = _build(n, 4, 8, 8, score=score,
                                   pad_block=block, **extra)
    if variant == "loaded":
        assert p_k.cand_same_ip is not None and s_k.active is not None
    assert p_k.subscribed.shape[0] == n          # n_pad == n_true
    step_1 = gs.make_gossip_step(cfg, sc, receive_block=block,
                                 receive_interpret=True)
    mesh = Mesh(np.array(jax.devices("cpu")[:D]), ("peers",))
    step_8 = gs.make_gossip_step(cfg, sc, receive_block=block,
                                 receive_interpret=True,
                                 shard_mesh=mesh)
    out_1 = gs.gossip_run(p_k, gs.tree_copy(s_k), 15, step_1)
    out_8 = gs.gossip_run(p_k, s_k, 15, step_8)
    l1 = jax.tree_util.tree_leaves(out_1)
    l8 = jax.tree_util.tree_leaves(out_8)
    assert len(l1) == len(l8)
    for a, b in zip(l1, l8):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # non-vacuous: the run formed meshes and moved messages
    assert np.asarray(gs.mesh_degrees(out_1)).mean() > 0
    assert np.asarray(out_1.have).any()


@pytest.mark.slow
def test_kernel_matches_xla_shared_ip_gater():
    """Shared-IP gater grouping on the kernel path (peer_gater.go:
    119-151): the in-kernel gate emission sums gater stats over
    same-IP siblings exactly as the XLA emission.  Topology mirrors
    test_gater_shared_ip_fate: arithmetic offsets so IP siblings are
    co-candidates of common victims, invalid spam creates real gater
    pressure."""
    n, t = 640, 2
    offsets = tuple(2 * k for k in range(1, 9)) + tuple(
        -2 * k for k in range(1, 9))
    cfg = gs.GossipSimConfig(offsets=offsets, n_topics=t,
                             d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                             d_lazy=2, gossip_factor=0.25,
                             backoff_ticks=8)
    rng = np.random.default_rng(3)
    idx = np.arange(n)
    subs = np.zeros((n, t), dtype=bool)
    subs[idx, idx % t] = True
    spam = np.zeros(n, dtype=bool)
    spam[0:120:12] = True
    ip = np.arange(n)
    ip[2:122:12] = ip[0:120:12]      # clean twins share spammer IPs
    m = 12
    sp_ids = np.flatnonzero(spam)
    origin = np.concatenate([np.repeat(sp_ids, 1),
                             rng.integers(0, n, m - len(sp_ids))])
    topic = (origin % t).astype(np.int64)
    invalid = np.array([True] * len(sp_ids)
                       + [False] * (m - len(sp_ids)))
    ticks = np.sort(rng.integers(0, 8, m)).astype(np.int32)
    sc = gs.ScoreSimConfig(ip_colocation_factor_weight=0.0)

    def build(pad):
        return gs.make_gossip_sim(
            cfg, subs, topic, origin, ticks, score_cfg=sc,
            sybil=spam, msg_invalid=invalid, peer_ip=ip,
            pad_to_block=pad)

    p_x, s_x = build(None)
    p_k, s_k = build(128)
    assert p_x.cand_same_ip is not None
    out_x = gs.gossip_run(p_x, s_x, 25, gs.make_gossip_step(cfg, sc))
    out_k = gs.gossip_run(p_k, s_k, 25, gs.make_gossip_step(
        cfg, sc, receive_block=128, receive_interpret=True))
    _assert_state_equal(out_x, out_k, n, sc)
    # non-vacuous: invalid traffic accrued somewhere
    assert np.asarray(out_x.scores.invalid_deliveries).max() > 0


@pytest.mark.slow
def test_kernel_matches_xla_aligned_wrap():
    """Aligned plan (n divisible by the u8 tile alignment and the
    block): DMA starts computed mod n at run time, composes reduced to
    a small tail — must stay bit-identical to the XLA path."""
    from go_libp2p_pubsub_tpu.ops.pallas.receive import plan

    n = 4096
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 25, 128, score=True,
                                      sybil_frac=0.1, spam=True)
    assert plan(n, cfg.offsets, 128)["aligned"]
    _assert_state_equal(out_x, out_k, n, sc)
    assert np.asarray(out_x.scores.first_deliveries).max() > 0


def test_kernel_slots_env_validated_at_import():
    """A typo'd GOSSIP_KERNEL_SLOTS must fail at import with the env
    var named — not as an opaque Mosaic scratch error mid-sweep."""
    import os
    import subprocess
    import sys

    for bad in ("banana", "0", "33"):
        env = dict(os.environ, GOSSIP_KERNEL_SLOTS=bad,
                   JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-c",
             "import go_libp2p_pubsub_tpu.ops.pallas.receive"],
            capture_output=True, text=True, timeout=120, env=env)
        assert r.returncode != 0, bad
        assert "GOSSIP_KERNEL_SLOTS" in r.stderr, r.stderr[-500:]


# --------------------------------------------------------------------------
# Faulted + observed runs on the fast path: the kernel accepts
# FaultSchedule and TelemetryConfig (round 9) — kernel vs XLA state
# trajectories (and telemetry frames) must stay bit-identical across
# the new config matrix.  Fast subset here; the full sweep is @slow.
# --------------------------------------------------------------------------


@pytest.mark.parametrize("score", [True, False])
@pytest.mark.slow
def test_kernel_matches_xla_faults(score):
    """Churn + link loss + a mid-run partition on the kernel path:
    the per-tick alive/link mask words ride the ctrl bytes (sender
    side) and the alive-word operand (receiver side) — bit-identical
    to the XLA fault masking."""
    n = 900
    cfg, sc, out_x, out_k = _run_pair(n, 4, 8, 8, 30, 128, score=score,
                                      faults=_sched(n))
    _assert_state_equal(out_x, out_k, n, sc)
    # non-vacuous: the faults actually bit — the faulted trajectory
    # differs from a fault-free run of the same seed
    _, _, out_clean, _ = _run_pair(n, 4, 8, 8, 30, 128, score=score)
    assert (np.asarray(out_clean.have) != np.asarray(out_x.have)).any()
    assert np.asarray(out_x.have).any()


@pytest.mark.slow
def test_kernel_matches_xla_telemetry_frames():
    """Telemetry through the kernel: the in-kernel counter tallies
    (RPC sends by type, duplicates, bytes-on-wire) and the epilogue
    gauge groups must reproduce the XLA path's TelemetryFrame stream
    bit for bit, while the state trajectory stays bit-identical to
    the telemetry-free kernel run."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n = 900
    cfg, sc, out_x, out_k, fr_x, fr_k = _run_pair(
        n, 4, 8, 8, 25, 128, score=True, telemetry=tl.TelemetryConfig())
    _assert_state_equal(out_x, out_k, n, sc)
    ax = _assert_frames_equal(fr_x, fr_k)
    assert ax["payload_sent"].sum() > 0
    assert ax["ihave_ids"].sum() > 0
    assert ax["iwant_ids_served"].sum() > 0
    assert ax["dup_suppressed"].sum() > 0
    assert ax["bytes_control"].sum() > 0
    # telemetry only READS: the kernel state trajectory is identical
    # to the telemetry-free kernel run
    _, _, _, out_k_plain = _run_pair(n, 4, 8, 8, 25, 128, score=True)
    np.testing.assert_array_equal(np.asarray(out_k.have),
                                  np.asarray(out_k_plain.have))
    np.testing.assert_array_equal(np.asarray(out_k.mesh),
                                  np.asarray(out_k_plain.mesh))


@pytest.mark.slow
def test_kernel_matches_xla_faults_plus_telemetry():
    """Faults AND telemetry at once on the kernel path — the two
    ROADMAP workloads together: fault counters land in the frames,
    masked tallies match the XLA accumulators exactly."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n = 900
    cfg, sc, out_x, out_k, fr_x, fr_k = _run_pair(
        n, 4, 8, 8, 25, 128, score=True, faults=_sched(n),
        telemetry=tl.TelemetryConfig())
    _assert_state_equal(out_x, out_k, n, sc)
    ax = _assert_frames_equal(fr_x, fr_k)
    assert ax["down_peers"].max() > 0
    assert ax["dropped_edge_ticks"].max() > 0
    assert ax["payload_sent"].sum() > 0


@pytest.mark.slow
def test_kernel_matches_xla_faults_iwant_flood():
    """IWANT-retransmission-flood sybils UNDER faults: the in-kernel
    flood accrual is gated by the send-ok ∧ cand-alive operand (a
    dead sybil requests nothing, a cut link serves nothing) — serve
    ledger bit-identical to the XLA epilogue."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n = 640
    cfg, sc, out_x, out_k, fr_x, fr_k = _run_pair(
        n, 2, 8, 10, 12, 128, score=True, sybil_frac=0.2, spam=True,
        iwant_spam=True, invalid_frac=0.3,
        faults=_sched(n, partition=False),
        telemetry=tl.TelemetryConfig())
    _assert_state_equal(out_x, out_k, n, sc)
    _assert_frames_equal(fr_x, fr_k)
    assert np.asarray(out_x.iwant_serves).max() > 0


@pytest.mark.slow
def test_kernel_matches_xla_batched_fault_seeds():
    """Batched-over-seeds faulted replicas: the XLA batched runner
    (vmapped step, per-replica fault seeds) against the kernel run
    sequentially per replica — every replica's trajectory must agree
    with its kernel twin."""
    n, B = 640, 3
    kw = dict(n_topics=2, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
              d_lazy=2, gossip_factor=0.25, backoff_ticks=8)
    offsets = gs.make_gossip_offsets(2, 8, n, seed=3)
    cfg = gs.GossipSimConfig(offsets=offsets, **kw)
    sc = gs.ScoreSimConfig()
    rng = np.random.default_rng(3)
    idx = np.arange(n)
    subs = np.zeros((n, 2), dtype=bool)
    subs[idx, idx % 2] = True
    topic = rng.integers(0, 2, 8)
    origin = rng.integers(0, n // 2, 8) * 2 + topic
    ticks = np.sort(rng.integers(0, 8, 8)).astype(np.int32)
    specs = [dict(subs=subs, msg_topic=topic, msg_origin=origin,
                  msg_publish_tick=ticks, seed=0,
                  fault_schedule=_sched(n, seed=100 + r))
             for r in range(B)]
    params_b, state_b = gs.stack_sims(cfg, specs, score_cfg=sc)
    out_b = gs.gossip_run_batch(params_b, state_b, 20,
                                gs.make_gossip_step(cfg, sc))
    step_k = gs.make_gossip_step(cfg, sc, receive_block=128,
                                 receive_interpret=True)
    for r in range(B):
        p_k, s_k = gs.make_gossip_sim(cfg, pad_to_block=128,
                                      score_cfg=sc, **specs[r])
        out_k = gs.gossip_run(p_k, s_k, 20, step_k)
        out_r = gs.index_trees(out_b, r)
        np.testing.assert_array_equal(np.asarray(out_r.have),
                                      np.asarray(out_k.have)[:, :n])
        np.testing.assert_array_equal(np.asarray(out_r.mesh),
                                      np.asarray(out_k.mesh)[:n])
        np.testing.assert_array_equal(
            np.asarray(out_r.scores.first_deliveries),
            np.asarray(out_k.scores.first_deliveries)[:, :n])
    # distinct fault seeds actually diverged the replicas
    h = np.asarray(out_b.have)
    assert (h[0] != h[1]).any() or (h[0] != h[2]).any()


@pytest.mark.slow
def test_kernel_zero_fault_schedule_bit_identical():
    """A zero-fault schedule through the kernel == no schedule at all
    (the masks are all-ones; masking with them is the identity) — the
    kernel twin of the XLA pin in test_faults.py."""
    import go_libp2p_pubsub_tpu.models.faults as fl

    n = 900
    empty = fl.FaultSchedule(n_peers=n, horizon=40)
    cfg, sc, p_a, s_a = _build(n, 4, 8, 8, score=True, pad_block=128,
                               faults=empty)
    cfg2, sc2, p_b, s_b = _build(n, 4, 8, 8, score=True, pad_block=128)
    step_f = gs.make_gossip_step(cfg, sc, receive_block=128,
                                 receive_interpret=True)
    step_0 = gs.make_gossip_step(cfg2, sc2, receive_block=128,
                                 receive_interpret=True)
    out_f = gs.gossip_run(p_a, s_a, 20, step_f)
    out_0 = gs.gossip_run(p_b, s_b, 20, step_0)
    for a, b in zip(__import__("jax").tree_util.tree_leaves(out_f),
                    __import__("jax").tree_util.tree_leaves(out_0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_sharded_kernel_faults_telemetry():
    """Faults + telemetry through the SHARDED kernel dispatch: the
    per-peer mask operands shard like any blocked operand, the tel
    tallies psum across the ring — state bit-identical to the
    single-device kernel, int counters exact, float gauges within one
    GSPMD-reduction ulp."""
    import jax
    from jax.sharding import Mesh
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n, D, block = 2048, 8, 128
    sched = _sched(n, seed=7)
    cfg, sc, p_k, s_k = _build(n, 4, 8, 8, score=True, pad_block=block,
                               faults=sched)
    assert p_k.subscribed.shape[0] == n          # n_pad == n_true
    tcfg = tl.TelemetryConfig()
    step_1 = gs.make_gossip_step(cfg, sc, receive_block=block,
                                 receive_interpret=True, telemetry=tcfg)
    mesh = Mesh(np.array(jax.devices("cpu")[:D]), ("peers",))
    step_8 = gs.make_gossip_step(cfg, sc, receive_block=block,
                                 receive_interpret=True,
                                 shard_mesh=mesh, telemetry=tcfg)
    out_1, fr_1 = tl.telemetry_run(p_k, gs.tree_copy(s_k), 12, step_1)
    out_8, fr_8 = tl.telemetry_run(p_k, s_k, 12, step_8)
    for a, b in zip(jax.tree_util.tree_leaves(out_1),
                    jax.tree_util.tree_leaves(out_8)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    a1, a8 = tl.frames_to_arrays(fr_1), tl.frames_to_arrays(fr_8)
    for name in a1:
        if a1[name].dtype.kind == "i":
            np.testing.assert_array_equal(a1[name], a8[name],
                                          err_msg=name)
        else:
            # sharded float reductions use a different tree (per-shard
            # partials + cross-device sum) — value-equal to ~1 ulp
            np.testing.assert_allclose(a1[name], a8[name], rtol=1e-6,
                                       err_msg=name)
    assert a1["payload_sent"].sum() > 0
    assert a1["down_peers"].max() > 0


@pytest.mark.slow
@pytest.mark.parametrize("variant", [
    "paired", "paired_attacks", "px", "flood_publish", "direct",
    "exact_k", "shared_ip"])
def test_kernel_faults_telemetry_full_matrix(variant):
    """@slow full sweep: every kernel feature variant under faults +
    telemetry at once — states AND frames bit-identical to XLA."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    tcfg = tl.TelemetryConfig()
    if variant.startswith("paired"):
        n = 928
        kw = dict(score=True, faults=_sched(n, seed=11))
        if variant == "paired_attacks":
            kw.update(sybil_frac=0.15, spam=True, iwant_spam=True,
                      invalid_frac=0.25)
        cfg, sc, p_x, s_x = _build_paired(n, 4, 8, 10, **kw)
        cfg2, sc2, p_k, s_k = _build_paired(n, 4, 8, 10, pad_block=128,
                                            **kw)
        out_x, fr_x = tl.telemetry_run(
            p_x, s_x, 20, gs.make_gossip_step(cfg, sc, telemetry=tcfg))
        out_k, fr_k = tl.telemetry_run(
            p_k, s_k, 20, gs.make_gossip_step(
                cfg2, sc2, receive_block=128, receive_interpret=True,
                telemetry=tcfg))
        _assert_state_equal(out_x, out_k, n, sc)
        np.testing.assert_array_equal(np.asarray(out_x.mesh_b),
                                      np.asarray(out_k.mesh_b)[:n])
        _assert_frames_equal(fr_x, fr_k)
        return
    n = 900
    kw = dict(score=True, faults=_sched(n, seed=13))
    kw.update({"px": dict(px=7), "flood_publish": dict(flood_publish=True),
               "direct": dict(direct=True), "exact_k": dict(exact_k=True),
               "shared_ip": dict(shared_ip=True)}[variant])
    if variant == "direct":
        n = 928
        kw["faults"] = _sched(n, seed=13)
    cfg, sc, out_x, out_k, fr_x, fr_k = _run_pair(
        n, 4, 8, 8, 25, 128, telemetry=tcfg, **kw)
    _assert_state_equal(out_x, out_k, n, sc)
    _assert_frames_equal(fr_x, fr_k)
    if variant == "px":
        np.testing.assert_array_equal(np.asarray(out_x.active),
                                      np.asarray(out_k.active)[:n])


@pytest.mark.slow
def test_kernel_histogram_frames_bit_identical_to_xla():
    """Round 10: the in-kernel latency-bucket tallies (TEL_ROWS..
    rows of the tel output) and the epilogue degree/score histograms
    equal the XLA path's frames bit for bit on a scored + faulted
    run, and the latency histogram sums to the per-tick delivered
    counts."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n = 900
    sched = _sched(n, seed=7)
    tcfg = tl.TelemetryConfig(latency_hist=True, degree_hist=True,
                              score_hist=True, latency_buckets=12,
                              degree_buckets=10)
    m = 8
    cfg, sc, p_x, s_x = _build(n, 4, 8, m, score=True, faults=sched)
    cfg2, sc2, p_k, s_k = _build(n, 4, 8, m, score=True,
                                 pad_block=128, faults=sched)
    out_x, counts_x, fr_x = tl.telemetry_run_curve(
        p_x, s_x, 20, gs.make_gossip_step(cfg, sc, telemetry=tcfg), m)
    out_k, counts_k, fr_k = tl.telemetry_run_curve(
        p_k, s_k, 20, gs.make_gossip_step(
            cfg2, sc2, receive_block=128, receive_interpret=True,
            telemetry=tcfg), m)
    np.testing.assert_array_equal(np.asarray(counts_x),
                                  np.asarray(counts_k))
    for name in ("latency_hist", "mesh_deg_hist", "score_hist"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fr_x, name)),
            np.asarray(getattr(fr_k, name)), err_msg=name)
    lat = np.asarray(fr_k.latency_hist)
    np.testing.assert_array_equal(lat.sum(axis=1),
                                  np.asarray(counts_k).sum(axis=1))
    assert lat.sum() > 0


@pytest.mark.slow
def test_kernel_latency_hist_without_counters():
    """latency_hist alone (counters off) still routes the kernel's
    tel output: the bucket rows ride without the counter groups and
    match the XLA path bit for bit."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    n = 640
    tcfg = tl.TelemetryConfig(counters=False, wire=False, mesh=False,
                              scores=False, faults=False,
                              latency_hist=True, latency_buckets=8)
    m = 6
    cfg, sc, p_x, s_x = _build(n, 4, 8, m, score=True)
    cfg2, sc2, p_k, s_k = _build(n, 4, 8, m, score=True, pad_block=128)
    out_x, fr_x = tl.telemetry_run(
        p_x, s_x, 15, gs.make_gossip_step(cfg, sc, telemetry=tcfg))
    out_k, fr_k = tl.telemetry_run(
        p_k, s_k, 15, gs.make_gossip_step(
            cfg2, sc2, receive_block=128, receive_interpret=True,
            telemetry=tcfg))
    np.testing.assert_array_equal(np.asarray(fr_x.latency_hist),
                                  np.asarray(fr_k.latency_hist))
    for a, b in zip(__import__("jax").tree_util.tree_leaves(out_x),
                    __import__("jax").tree_util.tree_leaves(out_k)):
        if np.asarray(a).shape == np.asarray(b).shape:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_kernel_rpc_probe_matches_xla_trajectory():
    """rpc_probe on the kernel path: pure readout (trajectory equals
    the probe-free kernel run), and the probe's [:n] leaves equal the
    XLA probe's — one exporter serves both paths."""
    n, m = 640, 6
    cfg, sc, p_x, s_x = _build(n, 4, 8, m, score=True)
    cfg2, sc2, p_k, s_k = _build(n, 4, 8, m, score=True, pad_block=128)
    out_x, snap_x = gs.gossip_run_rpc_snapshots(
        p_x, s_x, 12, gs.make_gossip_step(cfg, sc, rpc_probe=True))
    out_k, snap_k = gs.gossip_run_rpc_snapshots(
        p_k, s_k, 12, gs.make_gossip_step(
            cfg2, sc2, receive_block=128, receive_interpret=True,
            rpc_probe=True))
    for key in snap_x:
        a = np.asarray(snap_x[key])
        b = np.asarray(snap_k[key])
        np.testing.assert_array_equal(a, b[..., :a.shape[-1]],
                                      err_msg=key)
    np.testing.assert_array_equal(np.asarray(out_x.have),
                                  np.asarray(out_k.have)[:, :n])
