"""Round-11 attack formations (models/gossipsub.py + tournament).

Acceptance pins:
- eclipse victim-mesh takeover is BOUNDED by the score defenses at
  reference parameters (weakened defenses measurably worse), honest
  delivery intact;
- Byzantine id-preserving payload mutation: mutated copies are
  rejected (P4 accrues on exactly the mutating edges) and NEVER
  acquired — the trace replay oracle reconstructs the same final
  possession;
- cold-restart churn: a rejoining peer loses aged-out content for
  good and re-requests the still-advertised window via IWANT;
- the batched attack × defense tournament is bit-identical to
  sequential runs, and the defense knobs ride as traced operands
  (validated at build);
- the pallas kernel path runs eclipse bit-identically and refuses
  byzantine/knob configs with the capability message.
"""

import numpy as np
import pytest

import jax

import go_libp2p_pubsub_tpu.models.faults as fl
import go_libp2p_pubsub_tpu.models.gossipsub as gs
import go_libp2p_pubsub_tpu.models.invariants as iv
from go_libp2p_pubsub_tpu.models import tournament as tn


def _inputs(n, t, m, rng, horizon=40, pool_mask=None):
    if pool_mask is None:
        pool_mask = np.ones(n, dtype=bool)
    pool = np.flatnonzero(pool_mask)
    origin = pool[rng.integers(0, len(pool), m)]
    topic = (origin % t).astype(np.int64)
    ticks = np.sort(rng.integers(0, horizon, m)).astype(np.int32)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    return subs, topic, origin, ticks


def _honest_delivery(params, state, honest, topic, n, t):
    reach = np.asarray(gs.reach_counts_from_have(params, state,
                                                 mask=honest))
    members = np.arange(n) % t
    want = np.array([(honest & (members == tau)).sum()
                     for tau in topic])
    return float((reach / want).mean())


# --------------------------------------------------------------------------
# Eclipse formations
# --------------------------------------------------------------------------


def test_eclipse_takeover_bounded_by_score_defense():
    """Coordinated GRAFT pressure on a victim set: under REFERENCE
    score parameters the P7 backoff-violation penalty locks attackers
    out and bounds the victims' mesh takeover measurably below the
    defense-free level; honest traffic still fully delivers."""
    n, t, m = 240, 2, 8
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t,
        backoff_ticks=4, d=4, d_lo=2, d_hi=6, d_score=2, d_out=1)
    rng = np.random.default_rng(0)
    es = np.zeros(n, dtype=bool)
    es[:96] = True
    ev = np.zeros(n, dtype=bool)
    ev[96:120] = True
    subs, topic, origin, ticks = _inputs(n, t, m, rng,
                                         pool_mask=~es & ~ev)
    takeover = {}
    for name, knobs in (("reference", {}),
                        ("weak",
                         dict(invalid_message_deliveries_weight=0.0,
                              behaviour_penalty_weight=0.0))):
        sc = gs.ScoreSimConfig(sybil_eclipse=True)
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, ticks, score_cfg=sc,
            eclipse_sybil=es, eclipse_victim=ev,
            score_knobs=dict(knobs))
        out = gs.gossip_run(params, iv.attach(state), 80,
                            gs.make_gossip_step(
                                cfg, sc,
                                invariants=iv.InvariantConfig()))
        takeover[name] = gs.eclipse_takeover(out, params, cfg)
        assert iv.report(out)["bits"] == 0
        assert _honest_delivery(params, out, ~es, topic, n,
                                t) == 1.0, name
    # measured: ~0.64 reference vs ~0.81 weak on this topology
    assert takeover["reference"] < 0.75, takeover
    assert takeover["reference"] < takeover["weak"] - 0.05, takeover


def test_eclipse_requires_score_cfg_and_disjoint_sets():
    n, t = 120, 2
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    rng = np.random.default_rng(0)
    subs, topic, origin, ticks = _inputs(n, t, 4, rng)
    flags = np.zeros(n, dtype=bool)
    flags[:10] = True
    with pytest.raises(ValueError, match="require"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           eclipse_sybil=flags, eclipse_victim=~flags)
    with pytest.raises(ValueError, match="disjoint"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=gs.ScoreSimConfig(),
                           eclipse_sybil=flags, eclipse_victim=flags)
    with pytest.raises(ValueError, match="BOTH"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=gs.ScoreSimConfig(),
                           eclipse_sybil=flags)


# --------------------------------------------------------------------------
# Byzantine payload mutation
# --------------------------------------------------------------------------


def test_byzantine_mutation_rejected_never_acquired():
    """Mutated copies feed P4 on exactly the mutating edges and never
    enter possession; honest copies still reach every subscriber, and
    the trace replay oracle agrees with the final possession."""
    from go_libp2p_pubsub_tpu.interop import export as ex
    from go_libp2p_pubsub_tpu.interop.replay import (
        possession_from_trace)

    n, t, m = 240, 2, 6
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    sc = gs.ScoreSimConfig(byzantine_mutation=True)
    rng = np.random.default_rng(0)
    bz = (np.arange(n) % 5) == 0
    subs, topic, origin, ticks = _inputs(n, t, m, rng, horizon=3,
                                         pool_mask=~bz)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc, byzantine=bz)
    T = 14
    step = gs.make_gossip_step(cfg, sc)
    out = gs.gossip_run(params, gs.tree_copy(state), T, step)

    # P4 accrues only on edges FROM mutators
    invd = np.asarray(out.scores.invalid_deliveries, dtype=np.float32)
    cand_bz = np.stack([np.roll(bz, -int(o)) for o in cfg.offsets])
    assert invd[cand_bz].max() > 0
    assert invd[~cand_bz].max() == 0
    # honest copies still reach everyone (mutated ones were rejected
    # pre-possession, so clean edges deliver)
    assert _honest_delivery(params, out, np.ones(n, bool), topic, n,
                            t) == 1.0

    # replay oracle: the exported 'acquisition' stream reconstructs
    # the same final possession — no mutated copy snuck in
    peer_topic = (np.arange(n) % t).astype(np.int64)
    ftm = np.asarray(gs.first_tick_matrix(out, m))
    events = ex.events_from_sim(ftm, topic, origin, ticks,
                                peer_topic=peer_topic)
    have_replay = possession_from_trace(events, n, m)
    have_words = np.asarray(out.have)
    shifts = np.arange(32, dtype=np.uint32)
    have_bits = ((have_words[:, None, :] >> shifts[None, :, None])
                 & 1).astype(bool)
    have_sim = have_bits.reshape(-1, n).T[:, :m]
    np.testing.assert_array_equal(have_replay, have_sim)


# --------------------------------------------------------------------------
# Cold-restart churn
# --------------------------------------------------------------------------


def test_cold_restart_loses_aged_content_and_repulls_via_iwant():
    """Victim holds message A (published well before its outage),
    then goes down across message B's publish.  Rejoining COLD it has
    lost A for good (aged out of every IHAVE window) but re-requests
    B — still advertised — via the IWANT pull; rejoining WARM it
    holds both."""
    n, t = 240, 2
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t,
        backoff_ticks=4)
    sc = gs.ScoreSimConfig()
    victim = 8
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    # A published at tick 0 (origin 2), B at tick 7 (origin 4) — both
    # in the victim's residue class (t=2, victim even)
    topic = np.array([0, 0])
    origin = np.array([2, 4])
    pub = np.array([0, 7], dtype=np.int32)
    have = {}
    first = {}
    for cold in (False, True):
        sched = fl.FaultSchedule(
            n_peers=n, horizon=30, down_intervals=[(victim, 6, 10)],
            cold_restart=cold)
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, pub, score_cfg=sc,
            fault_schedule=sched)
        out = gs.gossip_run(params, iv.attach(state), 16,
                            gs.make_gossip_step(
                                cfg, sc,
                                invariants=iv.InvariantConfig()))
        assert iv.report(out)["bits"] == 0
        words = np.asarray(out.have)[0]
        have[cold] = [bool(words[victim] >> b & 1) for b in (0, 1)]
        first[cold] = np.asarray(gs.first_tick_matrix(out, 2))[victim]
    assert have[False] == [True, True]     # warm rejoin keeps A, gets B
    # cold rejoin: A is gone for good (outside every advert window),
    # B recovered through the gossip pull AFTER the rejoin tick
    assert have[True] == [False, True]
    assert first[True][1] >= 10


def test_cold_restart_refused_off_gossipsub():
    import go_libp2p_pubsub_tpu.models.floodsub as fs
    import go_libp2p_pubsub_tpu.models.randomsub as rs
    from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets

    n, t, m = 60, 1, 4
    subs = np.ones((n, t), dtype=bool)
    topic = np.zeros(m, dtype=np.int64)
    origin = np.arange(m)
    ticks = np.zeros(m, dtype=np.int32)
    offs = tuple(int(o) for o in make_circulant_offsets(t, 4, n,
                                                        seed=0))
    sched = fl.FaultSchedule(n_peers=n, horizon=5, cold_restart=True)
    with pytest.raises(ValueError, match="cold_restart"):
        fs.make_flood_sim(None, None, subs, None, topic, origin,
                          ticks, fault_schedule=sched,
                          fault_offsets=offs)
    rcfg = rs.RandomSubSimConfig(offsets=offs, n_topics=t, d=3)
    with pytest.raises(ValueError, match="cold_restart"):
        rs.make_randomsub_sim(rcfg, subs, topic, origin, ticks,
                              fault_schedule=sched)


def test_noop_intervals_pad_replica_tables():
    """start == end intervals are explicit no-ops: they occupy table
    slots (so batched replicas share one [N, K] shape) but never mark
    a peer down."""
    s1 = fl.FaultSchedule(n_peers=20, horizon=10,
                          down_intervals=[(3, 0, 0), (5, 0, 0)])
    s2 = fl.FaultSchedule(n_peers=20, horizon=10,
                          down_intervals=[(3, 2, 6), (5, 1, 4)])
    f1 = fl.compile_faults(s1, (1, -1))
    f2 = fl.compile_faults(s2, (1, -1))
    assert f1.down_start.shape == f2.down_start.shape
    assert bool(np.asarray(fl.alive_mask(f1, 3)).all())
    assert not bool(np.asarray(fl.alive_mask(f2, 3)).all())


# --------------------------------------------------------------------------
# Tournament
# --------------------------------------------------------------------------


def test_tournament_batched_matches_sequential():
    """The one-dispatch tournament is bit-identical to running each
    attack × defense cell sequentially (stacking + vmap adds no
    arithmetic) — final states AND reach reductions."""
    n, t, m, T = 240, 2, 6, 25
    attacks = ("clean", "eclipse", "cold_restart")
    defenses = {"reference": {},
                "weak": {"behaviour_penalty_weight": 0.0}}
    offsets = gs.make_gossip_offsets(t, 16, n, seed=0)
    cfg, sc = tn.tournament_static_config(offsets, t)
    builds, meta, ctx = tn.tournament_grid(n, t, m, T, seed=0,
                                           attacks=attacks,
                                           defenses=defenses)
    pairs = [gs.make_gossip_sim(cfg, score_cfg=sc, **b)
             for b in builds]
    states = [iv.attach(s) for _, s in pairs]
    params = gs.stack_trees([p for p, _ in pairs])
    state = gs.stack_trees(states)
    step = gs.make_gossip_step(cfg, sc,
                               invariants=iv.InvariantConfig())
    honest = np.broadcast_to(~ctx["attackers"],
                             (len(builds), n)).copy()
    batch_state, batch_reach = gs.gossip_run_tournament(
        params, state, T, step, honest)
    for i in range(len(builds)):
        p_i, s_i = gs.make_gossip_sim(cfg, score_cfg=sc, **builds[i])
        seq = gs.gossip_run(p_i, iv.attach(s_i), T, step)
        la = jax.tree_util.tree_leaves(seq)
        lb = jax.tree_util.tree_leaves(gs.index_trees(batch_state, i))
        assert all(np.array_equal(np.asarray(x), np.asarray(y))
                   for x, y in zip(la, lb)), meta[i]
        seq_reach = np.asarray(gs.reach_counts_from_have(
            p_i, seq, mask=~ctx["attackers"]))
        np.testing.assert_array_equal(seq_reach,
                                      np.asarray(batch_reach)[i])


def test_score_knob_validation():
    n, t = 120, 2
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    rng = np.random.default_rng(0)
    subs, topic, origin, ticks = _inputs(n, t, 4, rng)
    sc = gs.ScoreSimConfig()
    with pytest.raises(ValueError, match="unknown knob"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_cfg=sc, score_knobs={"nope": 1.0})
    with pytest.raises(ValueError, match="must be <= 0"):
        gs.make_gossip_sim(
            cfg, subs, topic, origin, ticks, score_cfg=sc,
            score_knobs={"behaviour_penalty_weight": 1.0})
    with pytest.raises(ValueError, match="graylist <= publish"):
        gs.make_gossip_sim(
            cfg, subs, topic, origin, ticks, score_cfg=sc,
            score_knobs={"graylist_threshold": -10.0})
    with pytest.raises(ValueError, match="require score_cfg"):
        gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                           score_knobs={"gossip_threshold": -5.0})


def test_knobbed_defaults_match_baked():
    """ScoreKnobs carrying exactly the config values reproduce the
    baked-constant trajectory bit for bit (the knob read is the same
    arithmetic with a traced scalar)."""
    n, t, m = 240, 2, 6
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    sc = gs.ScoreSimConfig()
    rng = np.random.default_rng(0)
    subs, topic, origin, ticks = _inputs(n, t, m, rng)
    base_p, base_s = gs.make_gossip_sim(cfg, subs, topic, origin,
                                        ticks, score_cfg=sc)
    knob_p, knob_s = gs.make_gossip_sim(cfg, subs, topic, origin,
                                        ticks, score_cfg=sc,
                                        score_knobs={})
    step = gs.make_gossip_step(cfg, sc)
    base = gs.gossip_run(base_p, base_s, 20, step)
    knob = gs.gossip_run(knob_p, knob_s, 20, step)
    for name in ("mesh", "have", "backoff"):
        np.testing.assert_array_equal(
            np.asarray(getattr(base, name)),
            np.asarray(getattr(knob, name)))


# --------------------------------------------------------------------------
# Kernel path
# --------------------------------------------------------------------------


def test_kernel_refuses_byzantine():
    """Byzantine mutation needs the per-edge receive loops the fused
    kernel elides — still refused.  (The round-11 score-knob refusal
    is LIFTED in round 12: the kernel takes ScoreKnobs/SimKnobs as
    SMEM operands — tests/test_knobs.py pins parity.)"""
    n, t, m = 512, 2, 6
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t)
    rng = np.random.default_rng(0)
    subs, topic, origin, ticks = _inputs(n, t, m, rng)
    bz = (np.arange(n) % 7) == 0
    sc = gs.ScoreSimConfig(byzantine_mutation=True)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc,
        pad_to_block=128, byzantine=bz)
    step = gs.make_gossip_step(cfg, sc, receive_block=128,
                               receive_interpret=True)
    with pytest.raises(ValueError,
                       match="not supported by the pallas step"):
        jax.eval_shape(step, params, state)


def test_kernel_eclipse_matches_xla():
    """The eclipse formation lives in the SHARED selection phase, so
    the pallas path runs it bit-identically to XLA (interpret mode,
    n % block == 0 so no pad lanes)."""
    n, t, m = 512, 2, 6
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=1), n_topics=t,
        backoff_ticks=4)
    sc = gs.ScoreSimConfig(sybil_eclipse=True)
    rng = np.random.default_rng(0)
    es = np.zeros(n, dtype=bool)
    es[:100] = True
    ev = np.zeros(n, dtype=bool)
    ev[100:140] = True
    subs, topic, origin, ticks = _inputs(n, t, m, rng, horizon=5,
                                         pool_mask=~es & ~ev)
    kw = dict(score_cfg=sc, eclipse_sybil=es, eclipse_victim=ev)
    xp, xs = gs.make_gossip_sim(cfg, subs, topic, origin, ticks, **kw)
    kp, ks = gs.make_gossip_sim(cfg, subs, topic, origin, ticks,
                                pad_to_block=128, **kw)
    xout = gs.gossip_run(xp, xs, 8, gs.make_gossip_step(cfg, sc))
    kout = gs.gossip_run(kp, ks, 8,
                         gs.make_gossip_step(cfg, sc,
                                             receive_block=128,
                                             receive_interpret=True))
    np.testing.assert_array_equal(np.asarray(xout.mesh),
                                  np.asarray(kout.mesh)[:n])
    np.testing.assert_array_equal(np.asarray(xout.have),
                                  np.asarray(kout.have)[:, :n])


# --------------------------------------------------------------------------
# tourneystat gate
# --------------------------------------------------------------------------


def test_tourneystat_gate_semantics(tmp_path):
    """Exit codes mirror tracestat's: 2 on unusable input, 1 on a
    worst-case regression or any invariant violation, 0 clean."""
    import json
    from tools.tourneystat import main as tstat

    art = {
        "n_peers": 100, "n_topics": 2, "n_msgs": 4, "ticks": 10,
        "replicas": 2, "attacks": ["clean", "spam"],
        "defenses": ["reference"],
        "rows": [
            {"attack": "clean", "defense": "reference",
             "delivery_fraction": 1.0, "inv_bits": 0, "inv_first": -1},
            {"attack": "spam", "defense": "reference",
             "delivery_fraction": 0.9, "inv_bits": 0, "inv_first": -1},
        ],
        "worst_case": {"reference": {"delivery_fraction": 0.9,
                                     "attack": "spam"}},
        "reference_worst_case_delivery": 0.9,
        "invariant_violations": 0,
    }
    base = tmp_path / "base.json"
    base.write_text(json.dumps(art))
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps(art))
    assert tstat([str(cur), "--check", str(base)]) == 0

    worse = dict(art, reference_worst_case_delivery=0.7)
    cur.write_text(json.dumps(worse))
    assert tstat([str(cur), "--check", str(base)]) == 1

    viol = dict(art, invariant_violations=1)
    viol["rows"] = [dict(art["rows"][0], inv_bits=8, inv_first=3),
                    art["rows"][1]]
    cur.write_text(json.dumps(viol))
    assert tstat([str(cur)]) == 1

    shrunk = dict(art, attacks=["clean"],
                  rows=[art["rows"][0]],
                  worst_case={"reference": {"delivery_fraction": 1.0,
                                            "attack": "clean"}},
                  reference_worst_case_delivery=1.0)
    cur.write_text(json.dumps(shrunk))
    assert tstat([str(cur), "--check", str(base)]) == 1

    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"rows": []}))
    with pytest.raises(SystemExit) as ei:
        tstat([str(empty)])
    assert ei.value.code == 2
