"""Pallas select kernel: bit-identical to the XLA rank/pack chain
(interpret mode on CPU; the real mosaic lowering is exercised on TPU)."""

import numpy as np
import jax.numpy as jnp

from go_libp2p_pubsub_tpu.ops.graph import (
    lane_seed,
    lane_uniform,
    select_k_bits,
)
from go_libp2p_pubsub_tpu.ops.pallas.select import select_k_bits_pallas


def test_pallas_select_matches_xla():
    n, c = 5000, 16     # non-multiple of the block: exercises padding
    rng = np.random.default_rng(3)
    elig = jnp.asarray(
        rng.integers(0, 2 ** c, n, dtype=np.int64).astype(np.uint32))
    k = jnp.asarray(rng.integers(0, 8, n).astype(np.int32))
    tick = jnp.int32(11)
    salt = jnp.uint32(99)
    ref = select_k_bits(elig, k, lane_uniform((c, n), tick, 2, salt))
    out = select_k_bits_pallas(elig, k, lane_seed(tick, 2, salt), c,
                               4096, True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
