"""RandomSub simulator tests: sqrt-fanout probabilistic dissemination
(reference randomsub.go; sim-scale counterpart of randomsub_test.go)."""

import numpy as np

from go_libp2p_pubsub_tpu.models.randomsub import (
    RandomSubSimConfig,
    make_randomsub_offsets,
    make_randomsub_sim,
    make_randomsub_step,
    randomsub_run,
    reach_by_hops,
    reach_counts,
)


def build(n=2000, t=1, c=64, n_msgs=8, seed=0, publish_tick=0):
    cfg = RandomSubSimConfig(
        offsets=make_randomsub_offsets(t, c, n, seed=seed), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(seed)
    msg_topic = rng.integers(0, t, n_msgs)
    msg_origin = rng.integers(0, n // t, n_msgs) * t + msg_topic
    ticks = np.full(n_msgs, publish_tick, dtype=np.int32)
    params, state = make_randomsub_sim(cfg, subs, msg_topic, msg_origin,
                                       ticks, seed=seed)
    return cfg, params, state, msg_topic


def test_full_dissemination():
    """sqrt-fanout flood reaches every subscriber (randomsub delivers like
    floodsub on connected networks, randomsub_test.go:19-60)."""
    cfg, params, state, _ = build()
    step = make_randomsub_step(cfg)
    out = randomsub_run(params, state, 12, step)
    np.testing.assert_array_equal(np.asarray(reach_counts(params, out)),
                                  2000)


def test_sqrt_fanout_spread_speed():
    """Fanout k=sqrt(N)~45 covers N=2000 in ~2-3 hops (log_k N); most
    delivery mass lands by hop 3."""
    cfg, params, state, _ = build()
    step = make_randomsub_step(cfg)
    out = randomsub_run(params, state, 12, step)
    curve = np.asarray(reach_by_hops(params, out, 6))   # [M, 6] cumulative
    assert (curve[:, 3] > 0.9 * 2000).all(), curve[:, 3]


def test_send_prob_matches_sqrt_scaling():
    """p = max(D, ceil(sqrt(topic size))) / pool (randomsub.go:124-138)."""
    cfg, params, state, _ = build(n=2000, c=64)
    k = max(cfg.d, int(np.ceil(np.sqrt(2000))))
    pool = np.asarray(params.cand_subscribed).sum(axis=0)
    np.testing.assert_allclose(np.asarray(params.send_prob),
                               np.minimum(1.0, k / np.maximum(pool, 1)),
                               rtol=1e-6)
    # and with a tiny topic the D floor dominates
    cfg2, params2, *_ = build(n=60, c=16, t=1)
    pool2 = np.asarray(params2.cand_subscribed).sum(axis=0)
    np.testing.assert_allclose(np.asarray(params2.send_prob),
                               np.minimum(1.0, 8 / np.maximum(pool2, 1)),
                               rtol=1e-6)  # ceil(sqrt(60))=8 > D=6


def test_multi_topic_isolation():
    """Messages stay inside their topic's residue class."""
    cfg, params, state, msg_topic = build(n=3000, t=3, c=48, n_msgs=6)
    step = make_randomsub_step(cfg)
    out = randomsub_run(params, state, 12, step)
    reach = np.asarray(reach_counts(params, out))
    np.testing.assert_array_equal(reach, 3000 // 3)


def test_dense_mxu_path_full_dissemination():
    """The matmul (MXU) step disseminates like the roll step: full reach
    in log_k(N) hops, same sqrt fanout, all-topic-members pool."""
    from go_libp2p_pubsub_tpu.models.randomsub import (
        make_randomsub_dense_step)
    n, t, m = 1500, 3, 6
    cfg = RandomSubSimConfig(
        offsets=make_randomsub_offsets(t, 12, n, seed=2), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(2)
    msg_topic = rng.integers(0, t, m)
    msg_origin = rng.integers(0, n // t, m) * t + msg_topic
    params, state = make_randomsub_sim(
        cfg, subs, msg_topic, msg_origin, np.zeros(m, dtype=np.int32),
        seed=2, dense=True)
    k = max(cfg.d, int(np.ceil(np.sqrt(n // t))))
    np.testing.assert_allclose(np.asarray(params.send_prob),
                               min(1.0, k / (n // t - 1)), rtol=1e-6)
    step = make_randomsub_dense_step(cfg)
    out = randomsub_run(params, state, 10, step)
    np.testing.assert_array_equal(np.asarray(reach_counts(params, out)),
                                  n // t)
    curve = np.asarray(reach_by_hops(params, out, 6))
    assert (curve[:, 3] > 0.9 * (n // t)).all()


def test_unsubscribed_never_delivered():
    """Unsubscribed peers neither receive nor forward (no relay mode in
    randomsub, randomsub.go:76-100)."""
    n, t = 1200, 1
    cfg = RandomSubSimConfig(
        offsets=make_randomsub_offsets(t, 64, n, seed=1), n_topics=t)
    subs = np.ones((n, t), dtype=bool)
    subs[::4] = False                     # 25% not subscribed
    rng = np.random.default_rng(1)
    origin = int(rng.integers(0, n))
    while not subs[origin, 0]:
        origin += 1
    params, state = make_randomsub_sim(
        cfg, subs, np.array([0]), np.array([origin]),
        np.zeros(1, dtype=np.int32), seed=1)
    step = make_randomsub_step(cfg)
    out = randomsub_run(params, state, 15, step)
    ft = np.asarray(
        __import__("go_libp2p_pubsub_tpu.models.randomsub",
                   fromlist=["first_tick_matrix"]).first_tick_matrix(out, 1)
    )[:, 0]
    assert (ft[~subs[:, 0]] < 0).all()    # never delivered to unsubscribed
    assert (ft[subs[:, 0]] >= 0).all()    # all subscribers reached
