"""Checkpoint/resume: save mid-run, restore, continue bit-identically.

Capability the reference lacks entirely (SURVEY.md §5.4)."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    ScoreSimConfig,
    gossip_run,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
)
from go_libp2p_pubsub_tpu.utils.checkpoint import load_state, save_state


def build(score=True):
    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 40, m).astype(np.int32)
    sc = ScoreSimConfig() if score else None
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks,
                                    score_cfg=sc)
    return cfg, sc, params, state


def assert_tree_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("score", [True, False])
def test_resume_is_bit_identical(tmp_path, score):
    cfg, sc, params, state = build(score)
    step = make_gossip_step(cfg, sc)

    mid = gossip_run(params, state, 25, step)
    path = str(tmp_path / "snap.npz")
    save_state(path, mid)

    uninterrupted = gossip_run(params, mid, 25, step)
    restored = load_state(path, mid)
    assert_tree_equal(mid, restored)
    resumed = gossip_run(params, restored, 25, step)
    assert_tree_equal(uninterrupted, resumed)


def test_template_mismatch_rejected(tmp_path):
    cfg, sc, params, state = build(True)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)
    _, _, _, other = build(False)   # no score state: different tree
    with pytest.raises(ValueError):
        load_state(path, other)
