"""Checkpoint/resume: save mid-run, restore, continue bit-identically.

Capability the reference lacks entirely (SURVEY.md §5.4)."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    ScoreSimConfig,
    gossip_run,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
)
from go_libp2p_pubsub_tpu.utils.checkpoint import load_state, save_state


def build(score=True):
    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 40, m).astype(np.int32)
    sc = ScoreSimConfig() if score else None
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks,
                                    score_cfg=sc)
    return cfg, sc, params, state


def assert_tree_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("score", [True, False])
def test_resume_is_bit_identical(tmp_path, score):
    cfg, sc, params, state = build(score)
    step = make_gossip_step(cfg, sc)

    mid = gossip_run(params, state, 25, step)
    path = str(tmp_path / "snap.npz")
    save_state(path, mid)

    # restore + compare BEFORE resuming: the runner donates its state
    # carry, so mid's buffers are consumed by the continuation run
    restored = load_state(path, mid)
    assert_tree_equal(mid, restored)
    uninterrupted = gossip_run(params, mid, 25, step)
    resumed = gossip_run(params, restored, 25, step)
    assert_tree_equal(uninterrupted, resumed)


def test_template_mismatch_rejected(tmp_path):
    cfg, sc, params, state = build(True)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)
    _, _, _, other = build(False)   # no score state: different tree
    with pytest.raises(ValueError):
        load_state(path, other)


def test_legacy_zero_p3_leaves_load(tmp_path):
    """Snapshots taken before P3/P3b state became None (track_p3-off
    configs) carry all-zero mesh-delivery leaves; they must still load
    into a None-P3 template — nonzero P3 state must still error."""
    cfg, sc, params, state = build(score=True)
    assert state.scores.mesh_deliveries is None
    # fabricate a legacy snapshot: same state with zero P3 arrays
    legacy = state.replace(scores=state.scores.replace(
        mesh_deliveries=np.zeros_like(np.asarray(
            state.scores.first_deliveries), dtype=np.float32),
        mesh_failure_penalty=np.zeros(
            np.asarray(state.scores.first_deliveries).shape,
            dtype=np.float32)))
    path = tmp_path / "legacy.npz"
    save_state(str(path), legacy)
    restored = load_state(str(path), state)
    assert restored.scores.mesh_deliveries is None
    assert int(restored.tick) == int(state.tick)

    # nonzero P3 state in a non-P3 template is a config mismatch
    bad = legacy.replace(scores=legacy.scores.replace(
        mesh_deliveries=np.full_like(
            np.asarray(legacy.scores.mesh_deliveries), 1.0)))
    path2 = tmp_path / "bad.npz"
    save_state(str(path2), bad)
    with pytest.raises(ValueError, match="lacks"):
        load_state(str(path2), state)


def _write_pre_gate_pipeline_snapshot(path, state):
    """Fabricate a pre-gate-pipeline snapshot: no gates leaves, backoff
    as int32 ABSOLUTE expiry ticks (the old format)."""
    import io
    import os

    tick = int(np.asarray(state.tick))
    payload = {}
    import jax

    for p, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        k = "/".join(str(getattr(q, "name", getattr(q, "idx", q)))
                     for q in p)
        if k.startswith("gates"):
            continue
        arr = np.asarray(leaf)
        if k.split("/")[-1].startswith("backoff"):
            # remaining -> absolute expiry (old semantics)
            arr = np.where(arr > 0, arr.astype(np.int32) + tick, 0)
        if arr.dtype.kind not in "biufc?":
            payload["bits:" + arr.dtype.name + ":" + k] = arr.view(
                np.dtype(f"u{arr.dtype.itemsize}"))
        else:
            payload["raw::" + k] = arr
    buf = io.BytesIO()
    np.savez(buf, **payload)
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    return tick


def test_pre_gate_pipeline_snapshot_rejected_and_migrates(tmp_path):
    """A snapshot from before the gate pipeline (no gates leaves, int32
    absolute-expiry backoff) must fail load_state with a targeted error
    — not the generic missing-leaf message, and never a silent
    expiry-as-remaining reinterpretation — and must migrate correctly
    through load_legacy_gossip_state."""
    from go_libp2p_pubsub_tpu.utils.checkpoint import (
        load_legacy_gossip_state,
    )

    cfg, sc, params, state = build(True)
    step = make_gossip_step(cfg, sc)
    mid = gossip_run(params, state, 25, step)
    path = str(tmp_path / "old.npz")
    _write_pre_gate_pipeline_snapshot(path, mid)

    # whichever legacy symptom is hit first (absolute-expiry backoff or
    # missing gates), the error must point at the migration helper
    with pytest.raises(ValueError, match="load_legacy_gossip_state"):
        load_state(path, mid)

    migrated = load_legacy_gossip_state(path, mid, cfg, sc, params)
    # backoff round-trips expiry -> remaining exactly, gates re-emitted
    np.testing.assert_array_equal(np.asarray(migrated.backoff),
                                  np.asarray(mid.backoff))
    assert migrated.gates is not None
    for g_m, g_o in zip(migrated.gates, mid.gates):
        np.testing.assert_array_equal(np.asarray(g_m), np.asarray(g_o))
    # and the migrated state continues bit-identically
    a = gossip_run(params, mid, 10, step)
    b = gossip_run(params, migrated, 10, step)
    assert_tree_equal(a, b)


def test_snapshot_gates_fp_survives_roundtrip(tmp_path):
    """The gates config fingerprint is persisted with the snapshot: a
    same-shape different-threshold template must be rejected at LOAD
    time (the restored gate words are the old config's; re-tagging them
    with the template's fingerprint would bypass the step guard)."""
    cfg, sc, params, state = build(True)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)

    n, t, m = 600, 3, 8
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 40, m).astype(np.int32)
    sc2 = ScoreSimConfig(gossip_threshold=-20.0)
    _, tmpl2 = make_gossip_sim(cfg, subs, topic, origin, ticks,
                               score_cfg=sc2)
    with pytest.raises(ValueError, match="different"):
        load_state(path, tmpl2)
    # the matching template still round-trips
    restored = load_state(path, state)
    assert restored.gates_fp == state.gates_fp


def test_pre_ledger_scored_snapshot_zero_fills(tmp_path):
    """Scored snapshots taken before the serve ledger became always-on
    have no iwant_serves leaf; they must load with a zero-initialized
    ledger (what make_gossip_sim does), not fail."""
    cfg, sc, params, state = build(True)
    assert state.iwant_serves is not None
    path = str(tmp_path / "snap.npz")
    save_state(path, state)
    # strip the ledger leaf, as a pre-change save would have omitted it
    with np.load(path) as z:
        kept = {k: z[k] for k in z.files if "iwant_serves" not in k}
    np.savez(str(tmp_path / "old.npz"), **kept)
    restored = load_state(str(tmp_path / "old.npz"), state)
    assert np.asarray(restored.iwant_serves).max() == 0
    np.testing.assert_array_equal(np.asarray(restored.have),
                                  np.asarray(state.have))


def test_carried_gates_config_fingerprint_guard():
    """A state seeded under one ScoreSimConfig must be rejected by a
    step built with a same-shape but different-threshold config — the
    carried gate words were computed under the old thresholds."""
    cfg, sc, params, state = build(True)
    sc2 = ScoreSimConfig(gossip_threshold=-20.0)
    step2 = make_gossip_step(cfg, sc2)
    with pytest.raises(ValueError, match="refresh_gates"):
        step2(params, state)

    # refresh_gates with the new config clears the mismatch
    from go_libp2p_pubsub_tpu.models.gossipsub import refresh_gates
    state2 = refresh_gates(cfg, sc2, params, state)
    step2(params, state2)   # traces and runs
