"""Checkpoint/resume: save mid-run, restore, continue bit-identically.

Capability the reference lacks entirely (SURVEY.md §5.4)."""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    ScoreSimConfig,
    gossip_run,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
)
from go_libp2p_pubsub_tpu.utils.checkpoint import load_state, save_state


def build(score=True):
    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 40, m).astype(np.int32)
    sc = ScoreSimConfig() if score else None
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks,
                                    score_cfg=sc)
    return cfg, sc, params, state


def assert_tree_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("score", [True, False])
def test_resume_is_bit_identical(tmp_path, score):
    cfg, sc, params, state = build(score)
    step = make_gossip_step(cfg, sc)

    mid = gossip_run(params, state, 25, step)
    path = str(tmp_path / "snap.npz")
    save_state(path, mid)

    uninterrupted = gossip_run(params, mid, 25, step)
    restored = load_state(path, mid)
    assert_tree_equal(mid, restored)
    resumed = gossip_run(params, restored, 25, step)
    assert_tree_equal(uninterrupted, resumed)


def test_template_mismatch_rejected(tmp_path):
    cfg, sc, params, state = build(True)
    path = str(tmp_path / "snap.npz")
    save_state(path, state)
    _, _, _, other = build(False)   # no score state: different tree
    with pytest.raises(ValueError):
        load_state(path, other)


def test_legacy_zero_p3_leaves_load(tmp_path):
    """Snapshots taken before P3/P3b state became None (track_p3-off
    configs) carry all-zero mesh-delivery leaves; they must still load
    into a None-P3 template — nonzero P3 state must still error."""
    cfg, sc, params, state = build(score=True)
    assert state.scores.mesh_deliveries is None
    # fabricate a legacy snapshot: same state with zero P3 arrays
    legacy = state.replace(scores=state.scores.replace(
        mesh_deliveries=np.zeros_like(np.asarray(
            state.scores.first_deliveries), dtype=np.float32),
        mesh_failure_penalty=np.zeros(
            np.asarray(state.scores.first_deliveries).shape,
            dtype=np.float32)))
    path = tmp_path / "legacy.npz"
    save_state(str(path), legacy)
    restored = load_state(str(path), state)
    assert restored.scores.mesh_deliveries is None
    assert int(restored.tick) == int(state.tick)

    # nonzero P3 state in a non-P3 template is a config mismatch
    bad = legacy.replace(scores=legacy.scores.replace(
        mesh_deliveries=np.full_like(
            np.asarray(legacy.scores.mesh_deliveries), 1.0)))
    path2 = tmp_path / "bad.npz"
    save_state(str(path2), bad)
    with pytest.raises(ValueError, match="lacks"):
        load_state(str(path2), state)
