"""Cross-check the hand-rolled codec against protoc-generated code.

Encodes with our codec, decodes with the official protobuf runtime (and the
reverse), proving byte-level interop with any stock protobuf implementation —
which is what the Go reference uses on the wire.
"""

import importlib.util
import subprocess
import sys

import pytest

from go_libp2p_pubsub_tpu.pb import (
    RPC, ControlGraft, ControlIHave, ControlIWant, ControlMessage,
    ControlPrune, PeerInfo, PubMessage, SubOpts,
)

# Same wire contract as the reference (pb/rpc.proto), restated independently.
RPC_PROTO = """
syntax = "proto2";
package interop.pb;

message RPC {
  repeated SubOpts subscriptions = 1;
  repeated Message publish = 2;
  message SubOpts {
    optional bool subscribe = 1;
    optional string topicid = 2;
  }
  optional ControlMessage control = 3;
}
message Message {
  optional bytes from = 1;
  optional bytes data = 2;
  optional bytes seqno = 3;
  optional string topic = 4;
  optional bytes signature = 5;
  optional bytes key = 6;
}
message ControlMessage {
  repeated ControlIHave ihave = 1;
  repeated ControlIWant iwant = 2;
  repeated ControlGraft graft = 3;
  repeated ControlPrune prune = 4;
}
message ControlIHave {
  optional string topicID = 1;
  repeated bytes messageIDs = 2;
}
message ControlIWant {
  repeated bytes messageIDs = 1;
}
message ControlGraft {
  optional string topicID = 1;
}
message ControlPrune {
  optional string topicID = 1;
  repeated PeerInfo peers = 2;
  optional uint64 backoff = 3;
}
message PeerInfo {
  optional bytes peerID = 1;
  optional bytes signedPeerRecord = 2;
}
"""


@pytest.fixture(scope="module")
def pb2(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("interop_proto")
    (tmp / "interop.proto").write_text(RPC_PROTO)
    try:
        subprocess.run(
            ["protoc", f"--proto_path={tmp}", f"--python_out={tmp}", "interop.proto"],
            check=True, capture_output=True,
        )
    except (FileNotFoundError, subprocess.CalledProcessError):
        pytest.skip("protoc unavailable")
    spec = importlib.util.spec_from_file_location("interop_pb2", tmp / "interop_pb2.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["interop_pb2"] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception as e:  # runtime/gencode version mismatch
        pytest.skip(f"protobuf runtime cannot load gencode: {e}")
    return mod


def _sample_rpc() -> RPC:
    return RPC(
        subscriptions=[SubOpts(subscribe=True, topicid="alpha"),
                       SubOpts(subscribe=False, topicid="beta")],
        publish=[PubMessage(from_peer=b"\x12\x20" + bytes(32), data=b"hello world",
                            seqno=(7).to_bytes(8, "big"), topic="alpha",
                            signature=b"\x01" * 64, key=b"\x08\x01\x12\x20" + bytes(32))],
        control=ControlMessage(
            ihave=[ControlIHave(topic_id="alpha", message_ids=[b"id-1", b"\xde\xad\xbe\xef"])],
            iwant=[ControlIWant(message_ids=[b"id-2"])],
            graft=[ControlGraft(topic_id="alpha")],
            prune=[ControlPrune(topic_id="beta",
                                peers=[PeerInfo(peer_id=b"QmPeer", signed_peer_record=b"env")],
                                backoff=60)],
        ),
    )


def test_ours_decodable_by_protobuf(pb2):
    data = _sample_rpc().encode()
    official = pb2.RPC()
    official.ParseFromString(data)
    assert official.subscriptions[0].subscribe is True
    assert official.subscriptions[0].topicid == "alpha"
    assert official.publish[0].data == b"hello world"
    assert official.publish[0].topic == "alpha"
    assert official.control.ihave[0].messageIDs == [b"id-1", b"\xde\xad\xbe\xef"]
    assert official.control.prune[0].backoff == 60
    assert official.control.prune[0].peers[0].peerID == b"QmPeer"


def test_protobuf_decodable_by_ours(pb2):
    official = pb2.RPC()
    s = official.subscriptions.add()
    s.subscribe = True
    s.topicid = "gamma"
    m = official.publish.add()
    m.data = b"payload"
    m.topic = "gamma"
    m.seqno = (99).to_bytes(8, "big")
    ih = official.control.ihave.add()
    ih.topicID = "gamma"
    ih.messageIDs.append(b"\x00\xffmid")
    ours = RPC.decode(official.SerializeToString())
    assert ours.subscriptions[0].topicid == "gamma"
    assert ours.publish[0].data == b"payload"
    assert ours.control.ihave[0].message_ids == [b"\x00\xffmid"]


def test_byte_identical_roundtrip(pb2):
    # protobuf serializes fields in field-number order, as does our codec;
    # re-encoding an official parse of our bytes must reproduce them.
    data = _sample_rpc().encode()
    official = pb2.RPC()
    official.ParseFromString(data)
    assert official.SerializeToString() == data
