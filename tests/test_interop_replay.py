"""Cross-validation: protocol core vs TPU simulator on the SAME topology.

The BASELINE.md contract is reachability-vs-hops curves matching within
1%.  FloodSub is deterministic given the graph (first delivery = BFS
distance), so here the core's traced curves and the simulator's curves
must agree bit-for-bit; the core run uses real varint-delimited frames
over in-proc streams and the sim runs the same padded neighbor table
through the jitted step.
"""

import numpy as np
import pytest

from go_libp2p_pubsub_tpu.pb import trace as tr

from go_libp2p_pubsub_tpu.interop import (
    hops_from_trace,
    reach_by_hops_from_trace,
    run_core_floodsub,
)
from go_libp2p_pubsub_tpu.models.floodsub import (
    flood_run,
    flood_step,
    make_flood_sim,
    reach_by_hops,
)
from go_libp2p_pubsub_tpu.ops.graph import build_random_graph


def test_core_and_sim_agree_on_floodsub_reachability():
    n = 20
    nbrs, mask = build_random_graph(n, 3, seed=11)
    publishers = [0, 7, 13]

    run = run_core_floodsub(nbrs, mask, publishers, settle_s=1.0)
    assert len(run.msg_ids) == len(publishers)

    m = len(publishers)
    subs = np.ones((n, 1), dtype=bool)
    params, state = make_flood_sim(
        nbrs, mask, subs, None,
        np.zeros(m, dtype=np.int64), np.array(publishers),
        np.zeros(m, dtype=np.int32))
    out = flood_run(params, state, 12, flood_step)

    max_hops = 10
    core_curve = reach_by_hops_from_trace(run, max_hops)
    sim_curve = np.asarray(reach_by_hops(params, out, max_hops))
    np.testing.assert_array_equal(core_curve, sim_curve)
    # and the curve is non-trivial: full reach, multiple hops
    assert (core_curve[:, -1] == n).all()
    assert (core_curve[:, 0] == 1).all()


def test_trace_hop_reconstruction_details():
    """Hop counts from the provenance chain are exact BFS distances on a
    line topology (multihop path, floodsub_test.go TestMultihops)."""
    n = 6
    nbrs = np.full((n, 2), n, dtype=np.int32)
    for i in range(n - 1):
        nbrs[i, 0] = i + 1
        nbrs[i + 1, 1] = i
    mask = nbrs != n
    run = run_core_floodsub(nbrs, mask, [0], settle_s=0.8)
    hops = hops_from_trace(run)[:, 0]
    np.testing.assert_array_equal(hops, np.arange(n))


# -- gossipsub / randomsub core<->sim curve validation (VERDICT r1 #3) ------


def _gossip_twin(n, offsets, publishers, pub_tick, n_ticks, *,
                 score=False, sybil=None, msg_invalid=None, d_lazy=0,
                 gossip_factor=0.0):
    """Sim run on the same circulant candidate graph the core cluster
    uses.  Lazy gossip defaults OFF for curve comparisons: the sim
    delivers gossip within the tick that advertises it, while in the
    core (as in the reference) eager mesh forwarding completes in
    milliseconds — long before the next heartbeat's IHAVE — so first-
    delivery curves measure MESH dissemination on both sides; gossip's
    repair role is validated separately (partition tests)."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    m = len(publishers)
    cfg = gs.GossipSimConfig(
        offsets=offsets, n_topics=1, d=3, d_lo=2, d_hi=6, d_score=2,
        d_out=1, d_lazy=d_lazy, gossip_factor=gossip_factor)
    subs = np.ones((n, 1), dtype=bool)
    sc = gs.ScoreSimConfig() if score else None
    params, state = gs.make_gossip_sim(
        cfg, subs, np.zeros(m, np.int64), np.array(publishers),
        np.full(m, pub_tick, np.int32), score_cfg=sc, sybil=sybil,
        msg_invalid=msg_invalid)
    out = gs.gossip_run(params, state, n_ticks, gs.make_gossip_step(cfg, sc))
    return gs, cfg, params, out


@pytest.mark.slow
def test_gossipsub_core_vs_sim_reach_curves():
    """Real gossipsub cluster vs the vectorized sim on the SAME circulant
    candidate graph: once both meshes settle (past the initial
    graft/prune burst and its backoffs), mesh-degree means agree and the
    mean reachability-vs-hops curves match within the BASELINE.md-style
    envelope.  Sim hop h aligns with core hop h+1: the sim's publish
    tick includes the first forwarding hop (fresh = injected | recent).

    Measured on this topology (n=60, C=8, 24 msgs) with matched mesh
    degrees: systematic aligned-curve delta ~0.010 (the 1% envelope).
    The CI tolerance is wider because the 60-host core cluster's
    asyncio timing adds ~±0.02 of run-to-run noise to the mid-curve —
    finite-size sampling, not model disagreement.  Under machine load
    the 60-host cluster's warm-up can be cut short, which shifts the
    whole core curve; the test therefore retries once with a longer
    warm window before declaring a real envelope breach (VERDICT r3
    weak-2: a validation gate must not fail on a correct build)."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, run_core_gossipsub)

    n, C, M = 60, 8, 24
    offsets = gs.make_gossip_offsets(1, C, n, seed=3)
    rng = np.random.default_rng(5)
    publishers = list(rng.integers(0, n, M))

    gsm, cfg, params, out = _gossip_twin(n, offsets, publishers, 90, 110)
    sim_mean = mean_reach_fraction(
        np.asarray(gsm.reach_by_hops(params, out, 12)), n)
    sim_deg = float(np.asarray(gsm.mesh_degrees(out)).mean())
    # deterministic sim invariant first: fail fast (and unambiguously)
    # on a sim regression before spending core-cluster retries
    assert sim_mean[-1] == 1.0, sim_mean

    last = None
    for warm_s, settle_s in ((2.0, 1.2), (3.5, 2.0)):
        run = run_core_gossipsub(offsets, n, publishers,
                                 warm_s=warm_s, settle_s=settle_s)
        core_mean = mean_reach_fraction(
            reach_by_hops_from_trace(run, 13), n)
        core_deg = np.mean(run.extra["mesh_degrees"])
        delta = np.abs(core_mean[1:13] - sim_mean)
        last = (delta.max(), core_mean, sim_mean, core_deg, sim_deg)
        if (abs(core_deg - sim_deg) < 0.6 and delta.max() < 0.075
                and core_mean[-1] == 1.0):
            break
    else:
        raise AssertionError(f"envelope breach after retry: {last}")


@pytest.mark.slow
def test_gossipsub_v11_adversarial_containment_core_vs_sim():
    """Invalid-spam containment, core gater/score engines vs the sim's:
    (a) invalid messages reach zero subscribers on both sides (core:
    rejected at validation under StrictSign; sim: the valid gate), and
    (b) honest traffic still achieves full reach with curves matching
    the clean-run envelope."""
    import random as _random

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, run_core_gossipsub)
    from go_libp2p_pubsub_tpu.pb import PubMessage, RPC, SubOpts
    from test_gossipsub import MockPeer
    from test_score_integration import score_params, thresholds
    import asyncio

    n, C, M = 40, 8, 16
    offsets = gs.make_gossip_offsets(1, C, n, seed=7)
    rng = np.random.default_rng(9)
    publishers = list(rng.integers(0, n, M))

    mocks = []

    async def spam(hosts, net):
        # 4 wire-level spammers, each attached to one victim, pushing
        # unsigned (wire-invalid) publishes (gossipsub_spam_test.go:563)
        for k in range(4):
            mock = MockPeer(net)
            mocks.append(mock)
            await mock.connect_and_open(hosts[k * 7])
            mock.send(RPC(subscriptions=[
                SubOpts(subscribe=True, topicid="interop")]))
            await asyncio.sleep(0.05)
            for i in range(10):
                mock.send(RPC(publish=[PubMessage(
                    from_peer=bytes(mock.host.id), data=b"spam",
                    seqno=(k * 100 + i).to_bytes(8, "big"),
                    topic="interop")]))

    sp = score_params()
    sp.topics = {"interop": sp.topics.pop("scored")}

    def run_core(warm_s, settle_s):
        mocks.clear()
        run = run_core_gossipsub(
            offsets, n, publishers, warm_s=warm_s, settle_s=settle_s,
            score_params=sp, score_thresholds=thresholds(), spam=spam)
        core_mean = mean_reach_fraction(
            reach_by_hops_from_trace(run, 13), n)
        # (a) no spam payload was ever delivered to a subscriber
        valid_ids = set(run.msg_ids)
        spam_deliveries = sum(
            1 for ev in run.events
            if ev.type == tr.TraceType.DELIVER_MESSAGE
            and ev.deliver_message.message_id not in valid_ids)
        assert spam_deliveries == 0
        return core_mean

    core_mean = run_core(2.0, 1.2)
    _ = _random, mocks

    # sim twin: 20% sybils originate only-invalid traffic while honest
    # peers publish the measured messages
    sybil = np.zeros(n, dtype=bool)
    sybil[rng.choice(n, 8, replace=False)] = True
    honest_ids = np.flatnonzero(~sybil)
    honest_pubs = [int(honest_ids[i % len(honest_ids)])
                   for i in range(M)]
    sy_ids = np.flatnonzero(sybil)
    all_pubs = honest_pubs + [int(p) for p in np.repeat(sy_ids, 3)]
    msg_invalid = np.array([False] * M + [True] * (len(all_pubs) - M))
    # gossip repair ON here (d_lazy): with sybils pruned out of honest
    # meshes, a candidate-poor peer may be mesh-isolated and only the
    # IHAVE/IWANT path reaches it — the same role gossip plays in the
    # core cluster
    gsm, cfg, params, out = _gossip_twin(
        n, offsets, all_pubs, 90, 110, score=True, sybil=sybil,
        msg_invalid=msg_invalid, d_lazy=2, gossip_factor=0.25)
    # Honest-only reach on the sim side: the sim's sybils are in-network
    # peers (graylisted, pruned from honest meshes), while the core
    # twin's spammers are out-of-network mocks — so "reach" is stated
    # over honest members on both sides, matching the population
    # semantics of gossipsub_spam_test.go:563-709.
    n_honest = int((~sybil).sum())
    curve = np.asarray(gsm.reach_by_hops(params, out, 12, mask=~sybil))
    sim_mean = mean_reach_fraction(curve[:M], n_honest)
    # (a) sim: invalid messages reached no subscriber
    ft = np.asarray(gsm.first_tick_matrix(out, len(all_pubs)))
    assert (ft[:, M:] < 0).all()
    # (b) honest curves: full reach on both sides, and the sim's curve
    # lies in the band [core aligned, core advanced one hop]: with
    # gossip repair ON the sim delivers IHAVE/IWANT repair within the
    # advertising tick (see _gossip_twin docstring), so its mid-curve
    # runs up to one hop ahead of the core cluster, never behind.
    # Measured: aligned delta ~0.20 at the knee, one-hop-advanced delta
    # ~0.02; core run-to-run noise ~±0.03 (asyncio timing).  Machine
    # load can cut the cluster's warm-up short and shift the whole core
    # curve, so on a band breach the core run retries once with longer
    # windows before declaring real disagreement (same policy as
    # test_gossipsub_core_vs_sim_reach_curves).
    assert sim_mean[-1] == 1.0

    def band_ok(cm):
        lower = cm[1:13] - 0.10
        upper = np.append(cm[2:13], 1.0) + 0.10
        return (cm[-1] == 1.0 and (sim_mean >= lower).all()
                and (sim_mean <= upper).all())

    if not band_ok(core_mean):
        core_mean = run_core(3.5, 2.0)
        assert band_ok(core_mean), (sim_mean, core_mean)


@pytest.mark.slow
def test_randomsub_core_vs_sim_reach_curves():
    """Real randomsub cluster (exact max(D, ceil(sqrt N))-peer sampling,
    randomsub.go:124-138) vs the sim's binomial approximation
    (models/randomsub.py docstring): mean curves align within ~3% at
    n=40 — the measured cost of the CLT approximation, which shrinks
    with scale.  Sim hop h aligns with core hop h+1 (publish tick
    includes the first hop)."""
    import go_libp2p_pubsub_tpu.models.randomsub as rs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, run_core_randomsub)

    n, M = 40, 24
    rng = np.random.default_rng(5)
    publishers = list(rng.integers(0, n, M))

    cfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(1, 8, n, seed=0), n_topics=1)
    subs = np.ones((n, 1), dtype=bool)
    params, state = rs.make_randomsub_sim(
        cfg, subs, np.zeros(M, np.int64), np.array(publishers),
        np.zeros(M, np.int32), dense=True)
    out = rs.randomsub_run(params, state, 15,
                           rs.make_randomsub_dense_step(cfg))
    sim_mean = mean_reach_fraction(
        np.asarray(rs.reach_by_hops(params, out, 9)), n)
    assert sim_mean[-1] == 1.0

    # retry on envelope breach with growing settle windows: machine
    # load can cut the cluster's settle window short (same policy as
    # the gossipsub curve gates; the third rung rides out heavy
    # co-located load, e.g. a parallel compile)
    last = None
    for settle_s in (1.0, 2.0, 4.0, 8.0):
        run = run_core_randomsub(n, publishers, settle_s=settle_s)
        core_mean = mean_reach_fraction(
            reach_by_hops_from_trace(run, 10), n)
        delta = np.abs(core_mean[1:10] - sim_mean)
        last = (delta.max(), core_mean, sim_mean)
        if delta.max() < 0.07 and core_mean[-1] == 1.0:
            break
    else:
        raise AssertionError(f"envelope breach after retry: {last}")


@pytest.mark.slow
def test_gossipsub_multitopic_core_vs_sim_reach_curves():
    """Overlapping topic membership, core vs sim: a real cluster whose
    hosts each join TWO topics (the reference router keeps a mesh per
    topic) against the paired-topic simulator on the SAME multiples-of-
    T/2 circulant.  Every message must reach its topic's full
    membership (both residue classes) on both sides, with the mean
    reach curves matching within the same envelope/retry policy as the
    single-topic gate.  Sim hop h aligns with core hop h+1."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, run_core_gossipsub_multitopic)

    n, T, C, M = 64, 4, 10, 16
    offsets = gs.make_gossip_offsets(T, C, n, seed=6, paired=True)
    rng = np.random.default_rng(8)
    own = np.arange(n) % T
    second = (own + T // 2) % T
    pubs = []
    for j in range(M):
        tau = int(rng.integers(0, T))
        members = np.flatnonzero((own == tau) | (second == tau))
        pubs.append((int(rng.choice(members)), tau))

    cfg = gs.GossipSimConfig(
        offsets=offsets, n_topics=T, paired_topics=True,
        d=3, d_lo=2, d_hi=6, d_score=2, d_out=1, d_lazy=0,
        gossip_factor=0.0)
    subs = np.zeros((n, T), dtype=bool)
    subs[np.arange(n), own] = True
    subs[np.arange(n), second] = True
    params, state = gs.make_gossip_sim(
        cfg, subs, np.array([t for _, t in pubs], np.int64),
        np.array([o for o, _ in pubs]),
        np.full(M, 90, np.int32))
    out = gs.gossip_run(params, state, 110, gs.make_gossip_step(cfg))
    sim_curve = np.asarray(gs.reach_by_hops(params, out, 12))
    sim_mean = mean_reach_fraction(sim_curve, n // 2)
    assert sim_mean[-1] == 1.0, sim_mean    # fail fast on sim regression

    last = None
    for warm_s, settle_s in ((1.5, 1.2), (3.0, 2.0)):
        run = run_core_gossipsub_multitopic(
            offsets, n, T, pubs, warm_s=warm_s, settle_s=settle_s)
        core_mean = mean_reach_fraction(
            reach_by_hops_from_trace(run, 13), n // 2)
        delta = np.abs(core_mean[1:13] - sim_mean)
        last = (delta.max(), core_mean, sim_mean)
        if core_mean[-1] == 1.0 and delta.max() < 0.17:
            break
    else:
        raise AssertionError(f"multitopic envelope breach: {last}")
    # and the reference router really kept two meshes per host
    degs = np.array(run.extra["mesh_degrees"])   # [n, T]
    assert ((degs > 0).sum(axis=1) == 2).mean() > 0.9


@pytest.mark.slow
def test_gossipsub_direct_peers_core_vs_sim():
    """Direct peers twin (WithDirectPeers, gossipsub.go:338): the same
    circulant cluster with pinned direct edges on both sides.  Direct
    edges are never mesh members in either implementation, and the
    reach curves still match within the envelope — direct forwarding
    adds the same always-on links to both."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, run_core_gossipsub)

    n, C, M = 60, 8, 24
    offsets = gs.make_gossip_offsets(1, C, n, seed=3)
    rng = np.random.default_rng(6)
    publishers = list(rng.integers(0, n, M))

    # every third peer pins its offset-0 candidate as a direct peer
    # (both ends configured, as operators would)
    o0 = int(offsets[0])
    cfg_probe = gs.GossipSimConfig(
        offsets=offsets, n_topics=1, d=3, d_lo=2, d_hi=6, d_score=2,
        d_out=1, d_lazy=0, gossip_factor=0.0)
    cinv0 = cfg_probe.cinv[0]
    pinned = np.zeros(n, dtype=bool)
    pinned[::3] = True
    de = np.zeros((n, C), dtype=bool)
    de[:, 0] = pinned
    de[:, cinv0] = np.roll(pinned, o0)

    def direct_index(i):
        out = []
        if pinned[i]:
            out.append((i + o0) % n)
        if pinned[(i - o0) % n]:
            out.append((i - o0) % n)
        return sorted(set(out))

    # sim twin on the same graph + direct set (mesh-only comparison:
    # gossip off, as in the main curve test)
    m = len(publishers)
    sc = gs.ScoreSimConfig()
    params, state = gs.make_gossip_sim(
        cfg_probe, np.ones((n, 1), dtype=bool), np.zeros(m, np.int64),
        np.array(publishers), np.full(m, 90, np.int32), score_cfg=sc,
        direct_edges=de)
    out = gs.gossip_run(params, state, 110,
                        gs.make_gossip_step(cfg_probe, sc))
    assert int(np.asarray(out.mesh & params.cand_direct).sum()) == 0
    sim_mean = mean_reach_fraction(
        np.asarray(gs.reach_by_hops(params, out, 12)), n)
    assert sim_mean[-1] == 1.0, sim_mean

    last = None
    for warm_s, settle_s in ((2.0, 1.2), (3.5, 2.0)):
        run = run_core_gossipsub(offsets, n, publishers,
                                 warm_s=warm_s, settle_s=settle_s,
                                 direct_index=direct_index)
        assert run.extra["direct_in_mesh"] == 0
        core_mean = mean_reach_fraction(
            reach_by_hops_from_trace(run, 13), n)
        delta = np.abs(core_mean[1:13] - sim_mean)
        last = (delta.max(), core_mean, sim_mean)
        if delta.max() < 0.075 and core_mean[-1] == 1.0:
            break
    else:
        raise AssertionError(f"envelope breach after retry: {last}")


# -- faulted cross-validation (round 11): churn on BOTH sides ---------------


def test_core_churn_harness_smoke():
    """Fast harness check: a peer churned across the publish window
    records leave+join, misses messages while down, and the rest of
    the cluster still fully delivers."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import run_core_gossipsub
    from go_libp2p_pubsub_tpu.pb.trace import TraceType

    n, C = 24, 6
    offsets = gs.make_gossip_offsets(1, C, n, seed=3)
    pubs = [0, 3, 7, 11, 15, 19]
    churn = [(5, 0.0, 0.7), (9, 0.05, 0.7)]
    run = run_core_gossipsub(offsets, n, pubs, warm_s=0.8,
                             settle_s=1.0, churn=churn)
    ev = run.extra["churn_events"]
    assert {(p, kind) for p, kind, _ in ev} == {
        (5, "leave"), (5, "join"), (9, "leave"), (9, "join")}
    hops = hops_from_trace(run)
    # churned peers missed at least one publish-window message ...
    assert (hops[5] < 0).any() or (hops[9] < 0).any()
    # ... while every untouched peer got everything
    untouched = np.ones(n, dtype=bool)
    untouched[[5, 9]] = False
    assert (hops[untouched] >= 0).all()


@pytest.mark.slow
def test_gossipsub_churned_core_vs_sim_delivery():
    """BASELINE cross-validation under FAULTS (ROADMAP known gap): the
    asyncio core cluster and the vectorized simulator run the SAME
    FaultSchedule JOIN/LEAVE windows (churn_from_schedule maps ticks
    to heartbeats) and their delivery pictures must agree — full
    delivery at non-churned peers on both sides, and the per-message
    mean delivery fraction matching within a loose asyncio-timing
    envelope."""
    import go_libp2p_pubsub_tpu.models.faults as fl
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        churn_from_schedule, run_core_gossipsub)

    n, C, M = 40, 8, 12
    heartbeat_s = 0.05
    offsets = gs.make_gossip_offsets(1, C, n, seed=3)
    rng = np.random.default_rng(5)
    victims = [4, 9, 17, 23, 31]
    publishers = [int(p) for p in
                  rng.choice(np.setdiff1d(np.arange(n), victims), M)]
    # sim timeline: warm to tick 90, publishes at 90, victims down
    # ticks [88, 106) — across the whole publish burst
    pub_tick, down = 90, (88, 106)
    sched = fl.FaultSchedule(
        n_peers=n, horizon=130,
        down_intervals=[(v, down[0], down[1]) for v in victims])
    cfg = gs.GossipSimConfig(
        offsets=offsets, n_topics=1, d=3, d_lo=2, d_hi=6, d_score=2,
        d_out=1, d_lazy=2, backoff_ticks=8)
    subs = np.ones((n, 1), dtype=bool)
    params, state = gs.make_gossip_sim(
        cfg, subs, np.zeros(M, np.int64), np.array(publishers),
        np.full(M, pub_tick, np.int32), fault_schedule=sched)
    out = gs.gossip_run(params, state, 120,
                        gs.make_gossip_step(cfg))
    ft = np.asarray(gs.first_tick_matrix(out, M))
    sim_frac = (ft >= 0).mean(axis=0)
    untouched = np.ones(n, dtype=bool)
    untouched[victims] = False
    assert (ft[untouched] >= 0).all()

    churn = churn_from_schedule(sched, heartbeat_s,
                                start_tick=pub_tick)
    last = None
    for warm_s, settle_s in ((2.0, 1.6), (3.5, 2.2)):
        run = run_core_gossipsub(offsets, n, publishers,
                                 heartbeat_s=heartbeat_s,
                                 warm_s=warm_s, settle_s=settle_s,
                                 churn=churn)
        hops = hops_from_trace(run)
        core_frac = (hops >= 0).mean(axis=0)
        delta = abs(core_frac.mean() - sim_frac.mean())
        core_untouched_ok = (hops[untouched] >= 0).all()
        last = (delta, core_frac.mean(), sim_frac.mean())
        if core_untouched_ok and delta < 0.15:
            break
    else:
        raise AssertionError(f"churned delivery disagrees: {last}")
    # the fault bit on both sides: churned peers miss SOME deliveries
    assert sim_frac.mean() < 1.0
