"""Cross-validation: protocol core vs TPU simulator on the SAME topology.

The BASELINE.md contract is reachability-vs-hops curves matching within
1%.  FloodSub is deterministic given the graph (first delivery = BFS
distance), so here the core's traced curves and the simulator's curves
must agree bit-for-bit; the core run uses real varint-delimited frames
over in-proc streams and the sim runs the same padded neighbor table
through the jitted step.
"""

import numpy as np

from go_libp2p_pubsub_tpu.interop import (
    hops_from_trace,
    reach_by_hops_from_trace,
    run_core_floodsub,
)
from go_libp2p_pubsub_tpu.models.floodsub import (
    first_tick_matrix,
    flood_run,
    flood_step,
    make_flood_sim,
    reach_by_hops,
)
from go_libp2p_pubsub_tpu.ops.graph import build_random_graph


def test_core_and_sim_agree_on_floodsub_reachability():
    n = 20
    nbrs, mask = build_random_graph(n, 3, seed=11)
    publishers = [0, 7, 13]

    run = run_core_floodsub(nbrs, mask, publishers, settle_s=1.0)
    assert len(run.msg_ids) == len(publishers)

    m = len(publishers)
    subs = np.ones((n, 1), dtype=bool)
    params, state = make_flood_sim(
        nbrs, mask, subs, None,
        np.zeros(m, dtype=np.int64), np.array(publishers),
        np.zeros(m, dtype=np.int32))
    out = flood_run(params, state, 12, flood_step)

    max_hops = 10
    core_curve = reach_by_hops_from_trace(run, max_hops)
    sim_curve = np.asarray(reach_by_hops(params, out, max_hops))
    np.testing.assert_array_equal(core_curve, sim_curve)
    # and the curve is non-trivial: full reach, multiple hops
    assert (core_curve[:, -1] == n).all()
    assert (core_curve[:, 0] == 1).all()


def test_trace_hop_reconstruction_details():
    """Hop counts from the provenance chain are exact BFS distances on a
    line topology (multihop path, floodsub_test.go TestMultihops)."""
    n = 6
    nbrs = np.full((n, 2), n, dtype=np.int32)
    for i in range(n - 1):
        nbrs[i, 0] = i + 1
        nbrs[i + 1, 1] = i
    mask = nbrs != n
    run = run_core_floodsub(nbrs, mask, [0], settle_s=0.8)
    hops = hops_from_trace(run)[:, 0]
    np.testing.assert_array_equal(hops, np.arange(n))
