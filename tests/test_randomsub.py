"""RandomSub router tests (reference randomsub_test.go)."""

from __future__ import annotations

import asyncio
import random

from go_libp2p_pubsub_tpu.core import InProcNetwork, create_floodsub
from go_libp2p_pubsub_tpu.core.randomsub import RANDOMSUB_D, create_randomsub
from helpers import connect_all, connect_some, get_hosts, settle


async def try_receive(sub, timeout=0.1):
    try:
        return await asyncio.wait_for(sub.next(), timeout=timeout)
    except asyncio.TimeoutError:
        return None


async def _run_delivery(psubs, n_publishes=10):
    subs = []
    for ps in psubs:
        topic = await ps.join("test")
        subs.append(await topic.subscribe())
    await settle(0.3)

    count = 0
    for i in range(n_publishes):
        t = await psubs[i].join("test")
        await t.publish(b"message %d" % i)
        for sub in subs:
            if await try_receive(sub) is not None:
                count += 1
    return count


async def test_randomsub_small():
    net = InProcNetwork()
    hosts = get_hosts(net, 10)
    psubs = [await create_randomsub(h, 10, rng=random.Random(i))
             for i, h in enumerate(hosts)]
    await connect_all(hosts)
    count = await _run_delivery(psubs)
    # reference accepts >= 7 * hosts out of 10 * hosts
    assert count >= 7 * len(hosts), count
    for ps in psubs:
        await ps.close()
    await net.close()


async def test_randomsub_big():
    net = InProcNetwork()
    hosts = get_hosts(net, 30)
    psubs = [await create_randomsub(h, 30, rng=random.Random(i))
             for i, h in enumerate(hosts)]
    await connect_some(hosts, 12, random.Random(7))
    count = await _run_delivery(psubs)
    assert count >= 7 * len(hosts), count
    for ps in psubs:
        await ps.close()
    await net.close()


async def test_randomsub_mixed_with_floodsub():
    """FloodSub-protocol peers always receive (randomsub.go:117-121)."""
    net = InProcNetwork()
    hosts = get_hosts(net, 20)
    psubs = [await create_floodsub(h) for h in hosts[:5]]
    psubs += [await create_randomsub(h, 15, rng=random.Random(i))
              for i, h in enumerate(hosts[5:])]
    await connect_some(hosts, 10, random.Random(7))
    count = await _run_delivery(psubs)
    assert count >= 7 * len(hosts), count
    for ps in psubs:
        await ps.close()
    await net.close()


async def test_randomsub_enough_peers():
    net = InProcNetwork()
    hosts = get_hosts(net, 20)
    psubs = [await create_floodsub(h) for h in hosts[:5]]
    psubs += [await create_randomsub(h, 15, rng=random.Random(i))
              for i, h in enumerate(hosts[5:])]
    await connect_some(hosts, 12, random.Random(7))
    for ps in psubs:
        topic = await ps.join("test")
        await topic.subscribe()
    await settle(0.3)
    rs = psubs[-1]
    res = await rs._eval(lambda: rs.router.enough_peers("test"))
    assert res


async def test_randomsub_fanout_bounded():
    """Each publish goes to at most max(D, ceil(sqrt(size))) randomsub
    peers directly — sqrt scaling, not a full flood
    (reference randomsub.go:124-138)."""
    import math

    net = InProcNetwork()
    hosts = get_hosts(net, 30)
    psubs = [await create_randomsub(h, 30, rng=random.Random(i))
             for i, h in enumerate(hosts)]
    await connect_all(hosts)
    subs = []
    for ps in psubs:
        topic = await ps.join("test")
        subs.append(await topic.subscribe())
    await settle(0.3)

    publisher = psubs[0]
    sent: list = []
    orig = publisher.send_rpc_to

    def counting_send(pid, rpc):
        if rpc.publish:
            sent.append(pid)
        return orig(pid, rpc)

    publisher.send_rpc_to = counting_send
    t0 = await publisher.join("test")
    await t0.publish(b"bounded")
    await settle(0.1)

    target = max(RANDOMSUB_D, math.ceil(math.sqrt(30)))
    assert 0 < len(set(sent)) <= target, sent
    for ps in psubs:
        await ps.close()
    await net.close()
