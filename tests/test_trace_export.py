"""Sim -> TraceEvent export: the reference-format trace files round-trip
and reproduce the sim's reachability curves (SURVEY.md §5.1 contract)."""

import json

import pytest

import numpy as np

from go_libp2p_pubsub_tpu.interop.export import (
    events_from_sim,
    msg_id,
    write_json_trace,
    write_pb_trace,
)
from go_libp2p_pubsub_tpu.models.gossipsub import (
    GossipSimConfig,
    first_tick_matrix,
    gossip_run,
    make_gossip_offsets,
    make_gossip_sim,
    make_gossip_step,
    reach_counts,
)
from go_libp2p_pubsub_tpu.pb import trace as tr
from go_libp2p_pubsub_tpu.pb.proto import read_delimited
from go_libp2p_pubsub_tpu.pb.trace import TraceType


def run_sim():
    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=6),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(6)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    out = gossip_run(params, state, 30, make_gossip_step(cfg))
    ft = np.asarray(first_tick_matrix(out, m))
    reach = np.asarray(reach_counts(params, out))
    return ft, topic, origin, ticks, reach


def test_pb_trace_roundtrip(tmp_path):
    ft, topic, origin, ticks, reach = run_sim()
    events = events_from_sim(ft, topic, origin, ticks)
    path = str(tmp_path / "trace.pb")
    write_pb_trace(path, events)

    buf = open(path, "rb").read()
    pos, parsed = 0, []
    while pos < len(buf):
        evt, pos = read_delimited(tr.TraceEvent, buf, pos)
        parsed.append(evt)
    assert len(parsed) == len(events)
    pubs = [e for e in parsed if e.type == TraceType.PUBLISH_MESSAGE]
    assert len(pubs) == len(topic)
    # reach per message from the trace == the sim's own counts (the
    # origin's local publish is traced as a delivery too, matching the
    # reference's publishMessage -> tracer.DeliverMessage)
    for j in range(len(topic)):
        n_deliver = sum(1 for e in parsed
                        if e.type == TraceType.DELIVER_MESSAGE
                        and e.deliver_message.message_id == msg_id(j))
        assert n_deliver == reach[j]
    # timestamps are tick-ordered
    deliver_ts = [e.timestamp for e in parsed
                  if e.type == TraceType.DELIVER_MESSAGE]
    assert deliver_ts == sorted(deliver_ts)


def test_json_trace_has_reference_shape(tmp_path):
    ft, topic, origin, ticks, _ = run_sim()
    events = events_from_sim(ft, topic, origin, ticks)
    path = str(tmp_path / "trace.json")
    write_json_trace(path, events)
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == len(events)
    kinds = {ln["type"] for ln in lines}
    assert kinds == {int(TraceType.PUBLISH_MESSAGE),
                     int(TraceType.DELIVER_MESSAGE)}
    deliver = next(ln for ln in lines
                   if ln["type"] == int(TraceType.DELIVER_MESSAGE))
    assert "deliver_message" in deliver
    assert {"message_id", "topic"} <= set(deliver["deliver_message"])


def test_tracestat_summarizes_both_formats(tmp_path):
    """tools/tracestat.py (the native tracestat analog) computes the
    same aggregate from the ndjson and delimited-pb sinks."""
    import subprocess
    import sys as _sys

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop.export import (
        events_from_sim, write_json_trace, write_pb_trace)

    n, t, m = 300, 3, 6
    rng = np.random.default_rng(2)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=2), n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = np.zeros(m, dtype=np.int32)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, ticks)
    out = gs.gossip_run(params, state, 25, gs.make_gossip_step(cfg))
    ftm = np.asarray(gs.first_tick_matrix(out, m))
    evs = list(events_from_sim(ftm, topic, origin, ticks))
    pj = tmp_path / "t.json"
    pp = tmp_path / "t.pb"
    write_json_trace(str(pj), evs)
    write_pb_trace(str(pp), evs)

    import json as _json
    outs = []
    for p in (pj, pp):
        from pathlib import Path
        repo = Path(__file__).resolve().parents[1]
        r = subprocess.run(
            [_sys.executable, "tools/tracestat.py", str(p), "--json"],
            capture_output=True, text=True, cwd=str(repo))
        assert r.returncode == 0, r.stderr
        outs.append(_json.loads(r.stdout))
    assert outs[0] == outs[1]
    assert outs[0]["messages_published"] == m
    assert outs[0]["total_deliveries"] == m * (n // t)
    assert outs[0]["events"]["DELIVER_MESSAGE"] == m * (n // t)


def test_churn_run_exports_join_leave_events(tmp_path):
    """A churn run's trace carries the reference's JOIN/LEAVE event
    types (trace.proto 9/10) at the down-interval boundaries, merged in
    tick order with the payload events, and the pb file round-trips."""
    import go_libp2p_pubsub_tpu.models.faults as fl

    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=6),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(6)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    sched = fl.FaultSchedule(
        n_peers=n, horizon=30,
        down_intervals=[(9, 2, 12), (12, 4, 30)], seed=3)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks,
                                    fault_schedule=sched)
    out = gossip_run(params, state, 30, make_gossip_step(cfg))
    ft = np.asarray(first_tick_matrix(out, m))
    events = events_from_sim(ft, topic, origin, ticks,
                             fault_schedule=sched,
                             peer_topic=np.arange(n) % t)
    path = str(tmp_path / "churn.pb")
    write_pb_trace(path, events)
    buf = open(path, "rb").read()
    pos, parsed = 0, []
    while pos < len(buf):
        evt, pos = read_delimited(tr.TraceEvent, buf, pos)
        parsed.append(evt)
    assert len(parsed) == len(events)
    leaves = [e for e in parsed if e.type == TraceType.LEAVE]
    joins = [e for e in parsed if e.type == TraceType.JOIN]
    # peer 9 leaves at 2, rejoins at 12; peer 12 leaves at 4 and its
    # interval runs to the horizon -> no JOIN
    assert [(e.peer_id, e.timestamp) for e in leaves] == [
        (b"sim-9", 2 * 10 ** 9), (b"sim-12", 4 * 10 ** 9)]
    assert [(e.peer_id, e.timestamp) for e in joins] == [
        (b"sim-9", 12 * 10 ** 9)]
    assert leaves[0].leave.topic == f"topic-{9 % t}"
    assert joins[0].join.topic == f"topic-{9 % t}"
    # the merged stream stays timestamp-ordered
    ts = [e.timestamp for e in parsed]
    assert ts == sorted(ts)
    # and the churned peers delivered nothing while down
    assert (ft[12] < 0).all()


def _run_tracestat(paths, extra=()):
    import subprocess
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    return subprocess.run(
        [_sys.executable, "tools/tracestat.py",
         *[str(p) for p in paths], *extra],
        capture_output=True, text=True, cwd=str(repo))


def test_mesh_snapshot_diff_emits_graft_prune_events(tmp_path):
    """Per-tick mesh-word snapshots diffed host-side reproduce the
    reference's GRAFT/PRUNE TraceEvents (trace.proto types 11/12):
    replaying the events from the empty mesh reconstructs the final
    mesh exactly, and the merged stream round-trips through BOTH sink
    formats with identical tracestat aggregates — growing the
    tracestat-validated event coverage to 6 types."""
    from go_libp2p_pubsub_tpu.interop.export import (
        mesh_trace_events, merge_event_streams)
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import json as _json

    n, t, m = 600, 3, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=6),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(6)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 10, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    init_mesh = np.asarray(state.mesh)
    fin, snaps = gs.gossip_run_mesh_snapshots(
        params, state, 30, make_gossip_step(cfg))
    mesh_snaps = np.asarray(snaps["mesh"])
    assert mesh_snaps.shape == (30, n)
    events = mesh_trace_events(mesh_snaps, cfg.offsets,
                               np.arange(n) % t, start_tick=0,
                               initial_mesh=init_mesh)
    grafts = [e for e in events if e.type == TraceType.GRAFT]
    prunes = [e for e in events if e.type == TraceType.PRUNE]
    assert grafts and grafts[0].graft.peer_id.startswith(b"sim-")
    assert grafts[0].graft.topic.startswith("topic-")
    # replay: per-peer (grafts - prunes) == final mesh degree
    net = {}
    for e in events:
        net[e.peer_id] = net.get(e.peer_id, 0) + (
            1 if e.type == TraceType.GRAFT else -1)
    final_mesh = np.asarray(fin.mesh)
    for p in range(n):
        deg = int(bin(int(final_mesh[p])).count("1"))
        assert net.get(b"sim-%d" % p, 0) == deg
    # merged payload + mesh stream stays timestamp-ordered and
    # round-trips both sinks with identical aggregates
    ft = np.asarray(first_tick_matrix(fin, m))
    merged = merge_event_streams(
        events_from_sim(ft, topic, origin, ticks), events)
    ts = [e.timestamp for e in merged]
    assert ts == sorted(ts)
    pj, pp = tmp_path / "mesh.json", tmp_path / "mesh.pb"
    write_json_trace(str(pj), merged)
    write_pb_trace(str(pp), merged)
    outs = []
    for p in (pj, pp):
        r = _run_tracestat([p], extra=("--json",))
        assert r.returncode == 0, r.stderr
        outs.append(_json.loads(r.stdout))
    assert outs[0] == outs[1]
    assert outs[0]["events"]["GRAFT"] == len(grafts)
    assert outs[0]["events"]["PRUNE"] == len(prunes)
    # 6 event types covered: publish/deliver (+graft/prune here;
    # join/leave covered by the churn tests)
    assert {"PUBLISH_MESSAGE", "DELIVER_MESSAGE", "GRAFT",
            "PRUNE"} <= set(outs[0]["events"])
    # control-plane rates are reported over the trace span
    assert outs[0]["control"]["total_events"] == (len(grafts)
                                                 + len(prunes))
    assert outs[0]["control"]["events_per_sec"]["GRAFT"] > 0


def test_tracestat_errors_on_empty_file(tmp_path):
    p = tmp_path / "empty.json"
    p.write_bytes(b"")
    r = _run_tracestat([p])
    assert r.returncode != 0
    assert "empty trace file" in r.stderr


def test_tracestat_errors_on_unparseable_file(tmp_path):
    bad_pb = tmp_path / "garbage.pb"
    bad_pb.write_bytes(b"\xff" * 16)        # unterminated varint
    r = _run_tracestat([bad_pb])
    assert r.returncode != 0
    assert "unparseable" in r.stderr

    bad_json = tmp_path / "garbage.json"
    bad_json.write_text('{"type": 0}\nnot json at all {{{\n')
    r = _run_tracestat([bad_json])
    assert r.returncode != 0
    assert "unparseable" in r.stderr

    eventless = tmp_path / "blank.json"
    eventless.write_text("\n\n")
    r = _run_tracestat([eventless])
    assert r.returncode != 0


def test_tracestat_per_topic_latency_percentiles(tmp_path):
    """Hand-built two-topic trace: the per-topic p50/p90/p99 split the
    global distribution correctly (topic-a deliveries at +1s, topic-b
    at +3s)."""
    import json as _json
    from go_libp2p_pubsub_tpu.interop.export import NS_PER_TICK

    events = []
    for j, (tpc, lat) in enumerate((("a", 1), ("a", 1), ("b", 3),
                                    ("b", 3))):
        events.append(tr.TraceEvent(
            type=TraceType.PUBLISH_MESSAGE, peer_id=b"sim-0",
            timestamp=j * NS_PER_TICK,
            publish_message=tr.PublishMessageEv(
                message_id=b"msg-%d" % j, topic=f"topic-{tpc}")))
        events.append(tr.TraceEvent(
            type=TraceType.DELIVER_MESSAGE, peer_id=b"sim-1",
            timestamp=(j + lat) * NS_PER_TICK,
            deliver_message=tr.DeliverMessageEv(
                message_id=b"msg-%d" % j, topic=f"topic-{tpc}")))
    path = tmp_path / "topics.pb"
    write_pb_trace(str(path), events)
    r = _run_tracestat([path], extra=("--json",))
    assert r.returncode == 0, r.stderr
    out = _json.loads(r.stdout)
    by_topic = out["latency_by_topic_ns"]
    assert by_topic["topic-a"]["p50"] == 1 * NS_PER_TICK
    assert by_topic["topic-a"]["p99"] == 1 * NS_PER_TICK
    assert by_topic["topic-b"]["p50"] == 3 * NS_PER_TICK
    assert by_topic["topic-a"]["count"] == 2
    assert out["latency_ns"]["p50"] in (1 * NS_PER_TICK,
                                        3 * NS_PER_TICK)
    assert out["latency_ns"]["p90"] == 3 * NS_PER_TICK


def test_adjacent_churn_intervals_merge_in_trace():
    """Adjacent down intervals ([a, b) + [b, c)) are ONE continuous
    outage to alive_mask; the exported stream must not show a
    same-tick JOIN+LEAVE flicker at the seam."""
    import go_libp2p_pubsub_tpu.models.faults as fl
    from go_libp2p_pubsub_tpu.interop.export import churn_events

    sched = fl.FaultSchedule(
        n_peers=8, horizon=40,
        down_intervals=[(2, 3, 10), (2, 10, 20), (5, 30, 40)])
    evs = churn_events(sched, np.zeros(8, dtype=np.int64))
    kinds = [(e.type, e.peer_id, e.timestamp // 10 ** 9) for e in evs]
    # peer 2: one LEAVE at 3, one JOIN at 20 (seam at 10 merged away);
    # peer 5: LEAVE at 30, interval runs to horizon -> no JOIN
    assert kinds == [(TraceType.LEAVE, b"sim-2", 3),
                     (TraceType.JOIN, b"sim-2", 20),
                     (TraceType.LEAVE, b"sim-5", 30)]


# --------------------------------------------------------------------------
# REJECT_MESSAGE / DUPLICATE_MESSAGE export (round 9: 2 more of the
# reference's 13 event types; the telemetry counters measure them in
# aggregate, these are the per-event streams)
# --------------------------------------------------------------------------


def test_reject_events_match_invalid_acquisitions():
    """Every first acquisition of a validation-failing message is one
    REJECT_MESSAGE event at the exact (peer, tick) — no more, no
    less, and never for valid messages."""
    from go_libp2p_pubsub_tpu.interop.export import reject_events
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        ScoreSimConfig, gossip_run_acq_snapshots)

    n, t, m = 400, 2, 8
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 8, m).astype(np.int32)
    invalid = np.zeros(m, dtype=bool)
    invalid[:3] = True
    # sybil origins forward their own invalid publishes (honest peers
    # drop invalid traffic before forwarding, so it would never move)
    sybil = np.zeros(n, dtype=bool)
    sybil[origin[:3]] = True
    sc = ScoreSimConfig()
    params, state = make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc, sybil=sybil,
        msg_invalid=invalid)
    out, snaps = gossip_run_acq_snapshots(
        params, state, 20, make_gossip_step(cfg, sc))
    have = np.asarray(snaps["have"])
    events = reject_events(have, invalid, topic)
    # ground truth straight from the possession words
    got = {(e.peer_id, e.reject_message.message_id,
            e.timestamp // 10**9) for e in events}
    want = set()
    prev = np.zeros_like(have[0])
    for k in range(have.shape[0]):
        new = have[k] & ~prev
        for mm in np.flatnonzero(invalid):
            w, b = divmod(int(mm), 32)
            for p in np.flatnonzero((new[w] >> np.uint32(b)) & 1):
                want.add((b"sim-%d" % p, b"msg-%d" % mm, k))
        prev = have[k]
    assert got == want and len(events) == len(got)   # no dup events
    assert len(events) > 0                            # non-vacuous
    assert all(e.type == TraceType.REJECT_MESSAGE for e in events)
    valid_ids = {msg_id(int(j)) for j in range(m) if not invalid[j]}
    assert not any(e.reject_message.message_id in valid_ids
                   for e in events)


@pytest.mark.slow
def test_duplicate_events_match_telemetry_dup_counter():
    """The eager-forward replay's per-tick DUPLICATE_MESSAGE count
    EQUALS the telemetry seen-cache counter on a gossip-free,
    fully-subscribed run — the per-event stream and the aggregate
    counter are two views of the same quantity."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.interop.export import duplicate_events
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        gossip_run_acq_snapshots, tree_copy)

    n, t, m = 400, 2, 8
    # gossip disabled (d_lazy=0, factor=0): every received copy is an
    # eager mesh forward, exactly the replay's model
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t, d_lazy=0, gossip_factor=0.0)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 8, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    n_ticks = 20
    step_tel = make_gossip_step(cfg, telemetry=tl.TelemetryConfig(
        wire=False, scores=False))
    _, frames = tl.telemetry_run(params, tree_copy(state), n_ticks,
                                 step_tel)
    dup = np.asarray(tl.frames_to_arrays(frames)["dup_suppressed"])
    out, snaps = gossip_run_acq_snapshots(params, state, n_ticks,
                                          make_gossip_step(cfg))
    events = duplicate_events(np.asarray(snaps["have"]),
                              np.asarray(snaps["mesh"]),
                              cfg.offsets, topic)
    per_tick = np.zeros(n_ticks, dtype=np.int64)
    for e in events:
        assert e.type == TraceType.DUPLICATE_MESSAGE
        assert e.duplicate_message.received_from.startswith(b"sim-")
        per_tick[e.timestamp // 10**9] += 1
    # tick 0 needs pre-run history the snapshots don't carry; every
    # later tick's event count must equal the aggregate counter
    np.testing.assert_array_equal(per_tick[1:], dup[1:])
    assert per_tick.sum() > 0                         # non-vacuous


@pytest.mark.slow
def test_duplicate_events_paired_mode_matches_telemetry():
    """Paired-topic runs: with mesh_b_snapshots + slot_b_words the
    replay splits each sender's fresh set by topic slot and walks
    BOTH meshes — per-tick event counts again equal the telemetry
    seen-cache counter on a gossip-free run."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.interop.export import duplicate_events
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        gossip_run_acq_snapshots, tree_copy)

    n, t, m = 400, 4, 8
    cfg = GossipSimConfig(
        offsets=make_gossip_offsets(t, 8, n, seed=4, paired=True),
        n_topics=t, paired_topics=True, d=3, d_lo=2, d_hi=6,
        d_score=2, d_out=1, d_lazy=0, gossip_factor=0.0)
    own = np.arange(n) % t
    second = (own + t // 2) % t
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), own] = True
    subs[np.arange(n), second] = True
    rng = np.random.default_rng(4)
    topic = rng.integers(0, t, m)
    members = [np.flatnonzero((own == tau) | (second == tau))
               for tau in range(t)]
    origin = np.array([rng.choice(members[tau]) for tau in topic])
    ticks = rng.integers(0, 8, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    n_ticks = 20
    step_tel = make_gossip_step(cfg, telemetry=tl.TelemetryConfig(
        wire=False, scores=False))
    _, frames = tl.telemetry_run(params, tree_copy(state), n_ticks,
                                 step_tel)
    dup = np.asarray(tl.frames_to_arrays(frames)["dup_suppressed"])
    out, snaps = gossip_run_acq_snapshots(params, state, n_ticks,
                                          make_gossip_step(cfg))
    events = duplicate_events(
        np.asarray(snaps["have"]), np.asarray(snaps["mesh"]),
        cfg.offsets, topic,
        mesh_b_snapshots=np.asarray(snaps["mesh_b"]),
        slot_b_words=np.asarray(params.slot_b_words))
    per_tick = np.zeros(n_ticks, dtype=np.int64)
    for e in events:
        per_tick[e.timestamp // 10**9] += 1
    np.testing.assert_array_equal(per_tick[1:], dup[1:])
    assert per_tick.sum() > 0
    # omitting slot_b_words with mesh_b snapshots must refuse loudly
    import pytest
    with pytest.raises(ValueError, match="slot_b_words"):
        duplicate_events(
            np.asarray(snaps["have"]), np.asarray(snaps["mesh"]),
            cfg.offsets, topic,
            mesh_b_snapshots=np.asarray(snaps["mesh_b"]))
    # ...and the mirror: slot_b_words without its mesh would silently
    # drop every slot-B forward from the replay (undercount)
    with pytest.raises(ValueError, match="mesh_b_snapshots"):
        duplicate_events(
            np.asarray(snaps["have"]), np.asarray(snaps["mesh"]),
            cfg.offsets, topic,
            slot_b_words=np.asarray(params.slot_b_words))


# --------------------------------------------------------------------------
# Round 10: 13/13 event-type coverage, per-RPC streams, peer events,
# replay oracle, and the tracestat frames/--check gate
# --------------------------------------------------------------------------


def faulted_run(T=16, n=200, t=2, m=10):
    """One faulted, scored, sybil-invalid gossipsub run plus every
    snapshot collector the 13-type export needs."""
    import go_libp2p_pubsub_tpu.models.faults as fl
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        ScoreSimConfig, gossip_run_acq_snapshots,
        gossip_run_rpc_snapshots, tree_copy)

    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    rng = np.random.default_rng(4)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 6, m).astype(np.int32)
    invalid = np.zeros(m, dtype=bool)
    invalid[:2] = True
    sybil = np.zeros(n, dtype=bool)
    sybil[origin[:2]] = True
    sc = ScoreSimConfig()
    sched = fl.FaultSchedule(
        n_peers=n, horizon=T, down_intervals=((5, 3, 8), (11, 2, 12)),
        drop_prob=0.05, seed=9)
    params, state = make_gossip_sim(
        cfg, subs, topic, origin, ticks, score_cfg=sc, sybil=sybil,
        msg_invalid=invalid, fault_schedule=sched)
    peer_topic = (np.arange(n) % t).astype(np.int64)
    step = make_gossip_step(cfg, sc)
    out, snaps = gossip_run_acq_snapshots(params, tree_copy(state), T,
                                          step)
    step_rpc = make_gossip_step(cfg, sc, rpc_probe=True)
    out2, rsnaps = gossip_run_rpc_snapshots(params, tree_copy(state),
                                            T, step_rpc)
    # the probe is a pure readout: same trajectory
    assert np.array_equal(np.asarray(out.have), np.asarray(out2.have))
    rsnaps = {k: np.asarray(v) for k, v in rsnaps.items()}
    return (cfg, sched, params, out, snaps, rsnaps, topic, origin,
            ticks, invalid, peer_topic, n, m, T)


def all_13_events(run):
    from go_libp2p_pubsub_tpu.interop import export as ex

    (cfg, sched, params, out, snaps, rsnaps, topic, origin, ticks,
     invalid, peer_topic, n, m, T) = run
    ftm = np.asarray(first_tick_matrix(out, m))
    base = ex.events_from_sim(ftm, topic, origin, ticks,
                              fault_schedule=sched,
                              peer_topic=peer_topic)
    meshes = ex.mesh_trace_events(np.asarray(snaps["mesh"]),
                                  cfg.offsets, peer_topic)
    rejects = ex.reject_events(np.asarray(snaps["have"]), invalid,
                               topic)
    dups = ex.duplicate_events(np.asarray(snaps["have"]),
                               np.asarray(snaps["mesh"]),
                               cfg.offsets, topic)
    peers = ex.peer_events(cfg.offsets, n, fault_schedule=sched)
    rpcs = ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic)
    return ex.merge_event_streams(base, meshes, rejects, dups, peers,
                                  rpcs)


@pytest.mark.slow
def test_full_faulted_run_exports_all_13_types_and_replays(tmp_path):
    """THE acceptance pin: one faulted gossipsub run exports every one
    of the reference's 13 TraceEvent types; written with
    write_pb_trace, read back via interop.replay, the event stream
    alone reconstructs the simulator's final possession AND mesh."""
    from go_libp2p_pubsub_tpu.interop import replay as rp

    run = faulted_run()
    (cfg, sched, params, out, snaps, rsnaps, topic, origin, ticks,
     invalid, peer_topic, n, m, T) = run
    merged = all_13_events(run)
    got = {TraceType.NAMES[e.type] for e in merged}
    assert got == set(TraceType.NAMES.values())        # 13/13
    path = tmp_path / "full13.pb"
    write_pb_trace(str(path), merged)
    evs = rp.load_pb_trace(str(path))
    assert len(evs) == len(merged)
    have_rt = rp.possession_from_trace(evs, n, m)
    hw = np.asarray(out.have)
    have_sim = np.zeros((n, m), dtype=bool)
    for j in range(m):
        w, b = divmod(j, 32)
        have_sim[:, j] = (hw[w] >> np.uint32(b)) & 1
    np.testing.assert_array_equal(have_rt, have_sim)
    mesh_rt = rp.mesh_from_trace(evs, cfg.offsets, n)
    np.testing.assert_array_equal(mesh_rt, np.asarray(out.mesh))


@pytest.mark.slow
def test_rpc_stream_aggregates_equal_telemetry_counters():
    """On a fault-free unscored run, the per-RPC stream's per-tick
    aggregates equal the telemetry counters EXACTLY: two independent
    observers (host-side RPC reconstruction vs in-scan reductions) of
    the same protocol."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.interop import export as ex
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        gossip_run_rpc_snapshots, tree_copy)

    n, t, m, T = 200, 2, 8, 14
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    rng = np.random.default_rng(4)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 6, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    peer_topic = (np.arange(n) % t).astype(np.int64)
    _, frames = tl.telemetry_run(
        params, tree_copy(state), T,
        make_gossip_step(cfg, telemetry=tl.TelemetryConfig(
            wire=False, scores=False, mesh=False)))
    arrs = tl.frames_to_arrays(frames)
    _, rsnaps = gossip_run_rpc_snapshots(
        params, tree_copy(state), T,
        make_gossip_step(cfg, rpc_probe=True))
    rsnaps = {k: np.asarray(v) for k, v in rsnaps.items()}
    events = ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic)
    agg = {k: np.zeros(T, dtype=np.int64) for k in
           ("msgs", "ihave_rpcs", "ihave_ids", "iwant_rpcs",
            "iwant_ids", "graft", "prune")}
    n_send = n_recv = 0
    for e in events:
        if e.type == TraceType.RECV_RPC:
            n_recv += 1
            continue
        if e.type != TraceType.SEND_RPC:
            continue
        n_send += 1
        k = e.timestamp // 10**9
        meta = e.send_rpc.meta
        agg["msgs"][k] += len(meta.messages or ())
        c = meta.control
        if c is not None:
            for ih in (c.ihave or ()):
                agg["ihave_rpcs"][k] += 1
                agg["ihave_ids"][k] += len(ih.message_ids)
            for iw in (c.iwant or ()):
                agg["iwant_rpcs"][k] += 1
                agg["iwant_ids"][k] += len(iw.message_ids)
            agg["graft"][k] += len(c.graft or ())
            agg["prune"][k] += len(c.prune or ())
    assert n_send == n_recv > 0       # healthy edges pair up exactly
    np.testing.assert_array_equal(
        agg["msgs"], arrs["payload_sent"] + arrs["iwant_ids_served"])
    np.testing.assert_array_equal(agg["ihave_rpcs"], arrs["ihave_rpcs"])
    np.testing.assert_array_equal(agg["ihave_ids"], arrs["ihave_ids"])
    np.testing.assert_array_equal(agg["iwant_rpcs"], arrs["iwant_rpcs"])
    np.testing.assert_array_equal(agg["iwant_ids"],
                                  arrs["iwant_ids_requested"])
    np.testing.assert_array_equal(agg["graft"], arrs["graft_sends"])
    np.testing.assert_array_equal(agg["prune"], arrs["prune_sends"])


@pytest.mark.slow
def test_rpc_stream_drop_rpc_under_faults():
    """Fault-masked edges emit DROP_RPC: with link loss and churn the
    stream carries drops; dead senders attempt nothing (no event with
    a down peer_id while down)."""
    from go_libp2p_pubsub_tpu.interop import export as ex

    run = faulted_run()
    (cfg, sched, params, out, snaps, rsnaps, topic, origin, ticks,
     invalid, peer_topic, n, m, T) = run
    events = ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic)
    drops = [e for e in events if e.type == TraceType.DROP_RPC]
    assert drops
    down = {(5, k) for k in range(3, 8)} | {(11, k) for k in range(2, 12)}
    for e in events:
        p = int(e.peer_id[4:])
        k = e.timestamp // 10**9
        assert (p, k) not in down, (p, k, e.type)


def test_rpc_stream_captures_flood_publish():
    """Round 11 (the fixed round-10 refusal): with WithFloodPublish, a
    publisher's due messages ride SEND_RPCs to EVERY subscribed
    candidate above the publish threshold — far beyond its mesh
    degree — and flood-only edges carry exactly the due publishes."""
    from go_libp2p_pubsub_tpu.interop import export as ex
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        ScoreSimConfig, gossip_run_rpc_snapshots)

    n, t, m, T = 200, 2, 4, 6
    cfg = GossipSimConfig(offsets=make_gossip_offsets(t, 16, n, seed=4),
                          n_topics=t)
    sc = ScoreSimConfig(flood_publish=True)
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    origin = np.array([10, 11, 24, 37])
    topic = (origin % t).astype(np.int64)
    ticks = np.array([2, 2, 3, 3], dtype=np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks,
                                    score_cfg=sc)
    peer_topic = (np.arange(n) % t).astype(np.int64)
    _, rsnaps = gossip_run_rpc_snapshots(
        params, state, T, make_gossip_step(cfg, sc, rpc_probe=True))
    rsnaps = {k: np.asarray(v) for k, v in rsnaps.items()}
    events = ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic)
    mid = {msg_id(j): j for j in range(m)}
    for j, (o, pt) in enumerate(zip(origin, ticks)):
        sends = [e for e in events
                 if e.type == TraceType.SEND_RPC
                 and e.peer_id == b"sim-%d" % o
                 and e.timestamp // 10**9 == int(pt)
                 and any(mid.get(mm.message_id) == j
                         for mm in (e.send_rpc.meta.messages or ()))]
        # flood: every subscribed candidate gets a copy at the publish
        # tick — with C=16 and ~half the ring in-topic that is well
        # above the mesh bound Dhi
        assert len(sends) > cfg.d_hi, (j, len(sends))


def test_peer_events_churn_semantics():
    """ADD_PEER at tick 0 for live circulant partners; REMOVE_PEER by
    live observers when a peer goes down; symmetric re-ADD on rejoin."""
    import go_libp2p_pubsub_tpu.models.faults as fl
    from go_libp2p_pubsub_tpu.interop import export as ex

    n, offs = 12, (1, -1)
    sched = fl.FaultSchedule(n_peers=n, horizon=10,
                             down_intervals=((3, 2, 5),), seed=0)
    events = ex.peer_events(offs, n, fault_schedule=sched)
    adds0 = [(int(e.peer_id[4:]), int(e.add_peer.peer_id[4:]))
             for e in events
             if e.type == TraceType.ADD_PEER and e.timestamp == 0]
    assert len(adds0) == n * 2                    # full live ring
    removes = [(e.timestamp // 10**9, int(e.peer_id[4:]),
                int(e.remove_peer.peer_id[4:])) for e in events
               if e.type == TraceType.REMOVE_PEER]
    assert sorted(removes) == [(2, 2, 3), (2, 4, 3)]
    readds = [(e.timestamp // 10**9, int(e.peer_id[4:]),
               int(e.add_peer.peer_id[4:])) for e in events
              if e.type == TraceType.ADD_PEER and e.timestamp > 0]
    assert sorted(readds) == [(5, 2, 3), (5, 3, 2), (5, 3, 4),
                              (5, 4, 3)]


@pytest.mark.slow
def test_tracestat_frames_percentiles_and_check_gate(tmp_path):
    """tracestat prefers histogram frames for latency percentiles,
    reports 13/13 coverage, and the --check gate passes against its
    own report, fails on a doctored regression baseline, and exits 2
    on an empty frames sidecar."""
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.interop import export as ex

    run = faulted_run()
    (cfg, sched, params, out, snaps, rsnaps, topic, origin, ticks,
     invalid, peer_topic, n, m, T) = run
    merged = all_13_events(run)
    trace = tmp_path / "full13.pb"
    write_pb_trace(str(trace), merged)
    # frames sidecar from the same sim config (telemetry run)
    tcfg = tl.TelemetryConfig(latency_hist=True, latency_buckets=16)
    subs = np.zeros((n, cfg.n_topics), dtype=bool)
    subs[np.arange(n), np.arange(n) % cfg.n_topics] = True
    p3, s3 = make_gossip_sim(cfg, subs, topic, origin, ticks,
                             fault_schedule=sched)
    _, counts, frames = tl.telemetry_run_curve(
        p3, s3, T, make_gossip_step(cfg, telemetry=tcfg), m)
    fr_path = tmp_path / "frames.json"
    ex.write_telemetry_frames(str(fr_path), frames, tcfg,
                              counts=np.asarray(counts),
                              publish_tick=ticks, msg_topic=topic)
    r = _run_tracestat([trace], extra=("--frames", str(fr_path),
                                       "--json"))
    assert r.returncode == 0, r.stderr
    rep = json.loads(r.stdout)
    assert rep["coverage"]["covered"] == 13
    assert rep["latency_ticks"]["source"] == "frames"
    assert rep["latency_ticks"]["p99"] is not None
    assert "latency_by_topic_ticks" in rep
    base = tmp_path / "OBS_base.json"
    base.write_text(json.dumps(rep))
    r2 = _run_tracestat([trace], extra=("--frames", str(fr_path),
                                        "--check", str(base)))
    assert r2.returncode == 0, r2.stderr
    # doctored regression baseline: tighter p99 -> gate trips
    doctored = dict(rep)
    doctored["latency_ticks"] = dict(rep["latency_ticks"])
    doctored["latency_ticks"]["p99"] = -5
    bad = tmp_path / "OBS_bad.json"
    bad.write_text(json.dumps(doctored))
    r3 = _run_tracestat([trace], extra=("--frames", str(fr_path),
                                        "--check", str(bad)))
    assert r3.returncode == 1
    assert "latency regression" in r3.stderr
    # coverage regression: drop an event type from the trace
    few = [e for e in merged if e.type != TraceType.DROP_RPC]
    part = tmp_path / "partial.pb"
    write_pb_trace(str(part), few)
    r4 = _run_tracestat([part], extra=("--frames", str(fr_path),
                                       "--check", str(base)))
    assert r4.returncode == 1
    assert "coverage regression" in r4.stderr
    assert "DROP_RPC" in r4.stderr
    # empty frames sidecar: documented exit 2
    empty = tmp_path / "empty.json"
    empty.write_text("")
    r5 = _run_tracestat([trace], extra=("--frames", str(empty)))
    assert r5.returncode == 2
    assert "empty frames file" in r5.stderr
    # histogram-free frames: also exit 2
    nohist = tmp_path / "nohist.json"
    nohist.write_text(json.dumps({"ns_per_tick": 10**9}))
    r6 = _run_tracestat([trace], extra=("--frames", str(nohist)))
    assert r6.returncode == 2
    assert "latency_hist" in r6.stderr


def test_rpc_probe_paired_topics_lifted():
    """Round 13 (the lifted refusal): paired-topic overlays are
    rpc_probe-supported — the probe snapshot carries the per-slot
    masks and the exporter reconstructs per-slot GRAFT/PRUNE topics,
    slot-merged payload RPCs, and a slot-split IHAVE whose ids match
    the message table's topic slots exactly."""
    from collections import Counter

    import pytest

    from go_libp2p_pubsub_tpu.interop import export as ex
    from go_libp2p_pubsub_tpu.models.gossipsub import (
        gossip_run_rpc_snapshots, tree_copy)

    n, t, m, T = 120, 4, 8, 10
    cfg = GossipSimConfig(
        offsets=make_gossip_offsets(t, 16, n, seed=3, paired=True),
        n_topics=t, paired_topics=True)
    rng = np.random.default_rng(3)
    subs = np.zeros((n, t), dtype=bool)
    own = np.arange(n) % t
    subs[np.arange(n), own] = True
    subs[np.arange(n), (own + t // 2) % t] = True
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    ticks = rng.integers(0, 4, m).astype(np.int32)
    params, state = make_gossip_sim(cfg, subs, topic, origin, ticks)
    step = make_gossip_step(cfg, rpc_probe=True)
    out, rsnaps = gossip_run_rpc_snapshots(params, tree_copy(state),
                                           T, step)
    rsnaps = {k: np.asarray(v) for k, v in rsnaps.items()}
    for key in ("fwd_b", "graft_b", "prune_b", "fresh_a", "fresh_b"):
        assert key in rsnaps, key
    peer_topic = own.astype(np.int64)
    peer_topic_b = ((own + t // 2) % t).astype(np.int64)
    # paired snapshots without peer_topic_b are rejected by name
    with pytest.raises(ValueError, match="peer_topic_b"):
        ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic)
    events = ex.rpc_events(
        rsnaps, cfg.offsets, topic, peer_topic,
        peer_topic_b=peer_topic_b,
        slot_b_words=np.asarray(params.slot_b_words))
    sends = [e for e in events if e.type == TraceType.SEND_RPC]
    recvs = [e for e in events if e.type == TraceType.RECV_RPC]
    assert len(sends) == len(recvs) > 0   # fault-free: all pair up

    def popcnt(arr):
        return int(np.unpackbits(
            np.ascontiguousarray(arr).view(np.uint8)).sum())

    # per-slot GRAFT/PRUNE counts in the stream == the probe masks,
    # with each entry carrying its OWN slot's topic
    g_top = Counter()
    p_top = Counter()
    msgs_total = 0
    for e in sends:
        meta = e.send_rpc.meta
        msgs_total += len(meta.messages or ())
        c = meta.control
        if c is None:
            continue
        for gm in (c.graft or ()):
            g_top[gm.topic] += 1
        for pm in (c.prune or ()):
            p_top[pm.topic] += 1
    # topic labels come from each sender's two slots; totals match
    assert sum(g_top.values()) == popcnt(rsnaps["graft"]) + \
        popcnt(rsnaps["graft_b"])
    assert sum(p_top.values()) == popcnt(rsnaps["prune"]) + \
        popcnt(rsnaps["prune_b"])
    # slot-A and slot-B topics BOTH appear in the control stream
    topics_seen = set(g_top) | set(p_top)
    assert any(tp in topics_seen
               for tp in {f"topic-{x}" for x in range(t // 2)})
    assert any(tp in topics_seen
               for tp in {f"topic-{x}" for x in range(t // 2, t)})
    # payload coverage: the slot-merged RPC messages count equals the
    # per-edge fresh_a/fresh_b popcounts over the attempted edges
    expect = 0
    C = len(cfg.offsets)
    for k in range(T):
        fa_any = np.zeros(n, dtype=bool)
        fb_any = np.zeros(n, dtype=bool)
        for w in range(rsnaps["fresh_a"].shape[1]):
            fa_any |= rsnaps["fresh_a"][k, w] != 0
            fb_any |= rsnaps["fresh_b"][k, w] != 0
        for c2 in range(C):
            bit = np.uint32(1) << np.uint32(c2)
            f_e = ((rsnaps["fwd"][k] & bit) != 0) & fa_any
            fb_e = ((rsnaps["fwd_b"][k] & bit) != 0) & fb_any
            for p in np.flatnonzero(f_e | fb_e):
                if f_e[p]:
                    expect += popcnt(rsnaps["fresh_a"][k, :, p])
                if fb_e[p]:
                    expect += popcnt(rsnaps["fresh_b"][k, :, p])
    # sends also include IWANT-served payloads; the mesh-forward part
    # must be covered exactly
    assert msgs_total >= expect > 0
    # the ihave split respects slot_b_words: rebuild the exporter's
    # classification and verify against the message table
    slot_b = np.asarray(params.slot_b_words)
    second = ((np.arange(n) % t) + t // 2) % t
    for e in sends:
        c = e.send_rpc.meta.control
        if c is None or not c.ihave:
            continue
        p = int(e.peer_id[4:])
        for ih in c.ihave:
            want_b = ih.topic == f"topic-{int(second[p])}" and \
                ih.topic != f"topic-{int(own[p])}"
            for mid_b in ih.message_ids:
                j = next(jj for jj in range(m)
                         if msg_id(jj) == mid_b)
                on_b = bool((int(slot_b[j // 32, p])
                             >> (j % 32)) & 1)
                assert on_b == want_b, (p, j, ih.topic)
