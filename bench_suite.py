#!/usr/bin/env python
"""Benchmark suite: all five BASELINE.md configs, one JSON line each.

(`bench.py` remains the single-line flagship bench the driver runs; this
suite is the full matrix for tracking all baseline configs.)

  floodsub_hosts   20 real in-proc hosts, 1 topic, protocol core
                   (mirrors /root/reference/floodsub_test.go
                   TestBasicFloodsub: dense topology, every host
                   publishes, every host receives) — msgs delivered/sec
                   through real varint-delimited frames
  randomsub_10k    10k sim peers, 1 topic, sqrt fanout — heartbeats/s
  gossipsub_v10    100k sim peers, 10 topics, no scoring — heartbeats/s
  gossipsub_v11    1M (TPU) / 100k (CPU) peers, 100 topics, scoring +
                   gater — heartbeats/s (same as bench.py)
  gossipsub_v11_adversarial
                   same + 20% sybils running the IHAVE broken-promise
                   spam AND the IWANT retransmission flood —
                   heartbeats/s, gated on honest-traffic delivery and
                   the retransmission-cutoff load bound
  gossipsub_telemetry
                   the flagship config run telemetry-off vs
                   telemetry-on (models/telemetry.py) — a throughput
                   row each (the observation cost, measured) plus the
                   control-overhead row (control bytes / payload
                   bytes, the GossipSub paper's headline number)
  gossipsub_v11_churn_kernel / gossipsub_telemetry_kernel
                   the same faulted / observed workloads through the
                   pallas receive kernel (round 9: in-kernel fault
                   masks + telemetry tallies), each also measuring
                   the KERNEL-path mask/observation overhead and
                   alias-paired to its XLA row for pick_bench_path
  gossipsub_tournament
                   round 11: the attack x defense product ({clean,
                   spam, eclipse, byzantine, cold_restart} x
                   {reference, weak, hardened} score knobs) as ONE
                   batched dispatch, worst-case honest delivery per
                   defense + /tmp artifact for the tourneystat gate
  gossipsub_invariants / gossipsub_invariants_kernel
                   round 11: the in-scan runtime invariant checker's
                   measured overhead, checker-off vs checker-on, on
                   both execution paths
  gossipsub_sweepd / gossipsub_sweepd_kernel
                   round 12: the config-as-data sweep engine
                   (tools/sweepd.py on models/knobs.py SimKnobs) —
                   >= 20 DISTINCT protocol/fault/attack configs
                   served from ONE compiled executable
                   (compile-counter asserted), heterogeneous-config
                   wall-clock vs the same-shape seed-batch row, and
                   the /tmp artifact for the sweepstat gate; the
                   kernel twin serves sequentially through the pallas
                   step (no vmap rule) with the same zero-recompile
                   counter, alias-paired to the XLA row
  gossipsub_pipelined
                   round 13: the event-driven-time sweep
                   (models/delays.py) — delay_base {1, 2, 4} (+ a
                   jittered point) through ONE knob-batched compiled
                   executable at 100k peers with the K=8 delay line
                   and the device latency histogram on; commits the
                   delivery-latency percentile curves (DELAY_r13.json
                   / the delaystat gate, measure_all step 4f) — the
                   pipelined-gossip picture vs the one-hop baseline
  gossipsub_multichip
                   round 14: whole-sim scale-out over the ``peers``
                   mesh axis (parallel/sharded.py) — the 1M D-scaling
                   curve (D in {1, 2, 4, 8}: warm wall-clock, one
                   compile per D, boundary-collective census from the
                   compiled HLO, final-state digest BIT-IDENTICAL to
                   D=1) plus the 10M-peer flagship row at max D;
                   /tmp artifact for the shardstat gate (measure_all
                   step 4g), ``hardware_queued``-tagged when run on
                   the CPU virtual mesh
  gossipsub_serving
                   round 18: the fault-tolerant multi-tenant front
                   end (go_libp2p_pubsub_tpu/serving) under load —
                   Zipf shape popularity / Poisson arrivals through
                   the shape-bucketed LRU executable cache (compile
                   count == traced bucket count, evictions free), an
                   overload burst with explicit rejection rows, a
                   SIGKILL-mid-long-scenario + journal-replay restart
                   resumed to the bit-identical digest, and the
                   traced-vs-AOT (jax.export) cold-start race; /tmp
                   artifact for the servestat gate (measure_all
                   step 4k)
  gossipsub_resident
                   round 16: the tick-resident megakernel
                   (make_fused_window) — T=8 ticks per pallas
                   dispatch with the carry resident in VMEM, digest
                   bit-identical to the per-tick kernel, ONE compile,
                   plus the analytic per-tick HBM ledger (100k/1M
                   points, VMEM-budget verdicts); /tmp artifact for
                   the residentstat gate (measure_all step 4i)

Usage: python bench_suite.py [config ...]   (default: all)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

import numpy as np

from go_libp2p_pubsub_tpu.utils.artifacts import write_json_atomic


def emit(metric, value, unit, baseline=None, extra=None):
    line = {"metric": metric, "value": round(value, 2), "unit": unit}
    if baseline:
        line["vs_baseline"] = round(value / baseline, 4)
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)


# -- 1. protocol core: 20 in-proc hosts ------------------------------------

def bench_floodsub_hosts():
    from go_libp2p_pubsub_tpu.core import InProcNetwork, create_floodsub
    from go_libp2p_pubsub_tpu.core.testing import (
        dense_connect, get_hosts, settle)

    async def run():
        net = InProcNetwork()
        hosts = get_hosts(net, 20)
        psubs = [await create_floodsub(h) for h in hosts]
        subs = []
        for ps in psubs:
            topic = await ps.join("bench")
            subs.append(await topic.subscribe())
        await dense_connect(hosts)
        await settle(0.2)
        n_rounds = 10
        t0 = time.perf_counter()
        delivered = 0
        for r in range(n_rounds):
            for i, ps in enumerate(psubs):
                topic = await ps.join("bench")
                await topic.publish(f"msg {r} {i}".encode())
                for sub in subs:
                    msg = await asyncio.wait_for(sub.next(), 10)
                    assert msg.data.endswith(f"{r} {i}".encode())
                    delivered += 1
        dt = time.perf_counter() - t0
        for ps in psubs:
            await ps.close()
        await net.close()
        return delivered / dt

    rate = asyncio.run(run())
    emit("floodsub_20hosts_deliveries_per_sec", rate, "msgs/s")


# -- shared sim scaffolding -------------------------------------------------

def _subs_matrix(n, t):
    subs = np.zeros((n, t), dtype=bool)
    subs[np.arange(n), np.arange(n) % t] = True
    return subs


def _msgs(rng, n, t, m, horizon):
    topic = rng.integers(0, t, m)
    origin = rng.integers(0, n // t, m) * t + topic
    tick = np.sort(rng.integers(0, horizon, m)).astype(np.int32)
    return topic, origin, tick


def bench_randomsub_10k():
    import jax
    import go_libp2p_pubsub_tpu.models.randomsub as rs

    n, t, m, C = 10_000, 1, 32, 128
    rng = np.random.default_rng(0)
    cfg = rs.RandomSubSimConfig(
        offsets=rs.make_randomsub_offsets(t, C, n, seed=0), n_topics=t)
    warmup, T, reps = 50, 100, 3
    horizon = warmup + T * reps
    topic, origin, tick = _msgs(rng, n, t, m, horizon - 30)
    params, state = rs.make_randomsub_sim(cfg, _subs_matrix(n, t), topic,
                                          origin, tick, dense=True)
    params = jax.device_put(params)
    step = rs.make_randomsub_dense_step(cfg)  # MXU path at small N
    state = rs.randomsub_run(params, jax.device_put(state), warmup, step)
    _ = int(np.asarray(state.tick))
    t0 = time.perf_counter()
    for _r in range(reps):
        state = rs.randomsub_run(params, state, T, step)
        _ = int(np.asarray(state.tick))
    dt = time.perf_counter() - t0
    reach = np.asarray(rs.reach_counts(params, state))
    assert (reach == n).all(), reach[:8]  # all publishes are >=30 ticks old
    emit("randomsub_10kpeers_heartbeats_per_sec", T * reps / dt,
         "heartbeats/s")


def _bench_gossip(metric, n, t, score_cfg, sybil_frac=None,
                  gate_honest=False, baseline=None, paired=False,
                  kernel=False, px_candidates=None, with_direct=False,
                  shared_sybil_ips=False, replicas=None):
    """replicas=B runs B independent replica sims (mesh seeds 0..B-1)
    stacked on a leading axis through ONE gossip_run_batch dispatch per
    timed block — the amortized-replica row (metric should carry a
    ``_batched{B}`` tag; value = replica-heartbeats/s, B x the ticks of
    one trajectory per wall-clock second).  XLA path only: the pallas
    kernel has no vmap rule."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    if replicas is not None and kernel:
        raise ValueError("batched replicas: XLA path only (no vmap "
                         "rule for the pallas kernel)")
    m, C = 32, 16
    warmup, T, reps = 100, 100, 3
    horizon = warmup + T * reps
    rng = np.random.default_rng(0)
    # GOSSIP_BENCH_BLOCK: kernel block size override — the paired
    # kernel holds ~2x the per-block VMEM state of the clean one, so a
    # VMEM-limited chip may need 4096 there
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    n_named = n   # the config's nominal peer count, pre-kernel-rounding
    if kernel:
        # kernel coverage: the full config matrix (paired, attacks,
        # PX, shared-IP gater, direct peers — all parity-pinned)

        # the pallas step wants n divisible by the u8 tile alignment
        # (4096) and the block (aligned-wrap plan) — round UP so the
        # simulated network is never smaller than the named config
        import math
        quantum = math.lcm(t, 4096, block)
        n = -(-n // quantum) * quantum
    # sybil flags are drawn AFTER any kernel rounding of n
    sybil = (np.random.default_rng(7).random(n) < sybil_frac
             if sybil_frac is not None else None)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0, paired=paired),
        n_topics=t, paired_topics=paired)
    topic, origin, tick = _msgs(rng, n, t, m, horizon)
    if sybil is not None and gate_honest:
        # honest origins only, so the delivery gate is meaningful
        honest_ids = np.flatnonzero(~sybil)
        pick = honest_ids[rng.integers(0, len(honest_ids), m)]
        topic = (pick % t).astype(topic.dtype)
        origin = pick
    # the timed loop carries protocol state only: final reach (counted
    # from the packed possession words) is the delivery gate, so the
    # int16 [W, 32, N] first-tick delivery records stay out of the
    # benchmark — hop curves come from the validation runs, not the bench
    subs = _subs_matrix(n, t)
    if paired:
        # overlapping membership: every peer in BOTH its pair topics
        subs[np.arange(n), (np.arange(n) % t + t // 2) % t] = True
    extra = {}
    if with_direct:
        # a sparse operator-pinned direct overlay: ~n/1009 peers get a
        # direct edge on candidate pair (0, cinv[0]); symmetric by
        # construction (edge marked iff EITHER endpoint is pinned)
        f = (np.arange(n) % 1009) == 0
        de = np.zeros((n, C), dtype=bool)
        for c_ in (0, cfg.cinv[0]):
            de[:, c_] = f | np.roll(f, -int(cfg.offsets[c_]))
        extra["direct_edges"] = de
    if px_candidates is not None:
        extra["px_candidates"] = px_candidates
    if shared_sybil_ips and sybil is not None:
        # sybil clusters behind shared addresses: P6 colocation and the
        # gater's per-IP grouping are live (peer_gater.go:119-151)
        ip = np.arange(n)
        sid = np.flatnonzero(sybil)
        ip[sid] = n + np.arange(len(sid)) // 4
        extra["peer_ip"] = ip
    sim_kw = dict(score_cfg=score_cfg, sybil=sybil,
                  track_first_tick=False,
                  pad_to_block=(block if kernel else None), **extra)
    if replicas is None:
        params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                           tick, **sim_kw)
        run = gs.gossip_run
    else:
        builds = [gs.make_gossip_sim(cfg, subs, topic, origin, tick,
                                     seed=r, **sim_kw)
                  for r in range(replicas)]
        params = gs.stack_trees([b[0] for b in builds])
        state = gs.stack_trees([b[1] for b in builds])
        run = gs.gossip_run_batch
    params = jax.device_put(params)
    # invariant: pad_to_block == receive_block (the kernel plan checks)
    step = gs.make_gossip_step(cfg, score_cfg, receive_block=block)
    state = run(params, jax.device_put(state), warmup, step)
    sub_np = np.asarray(params.subscribed)
    deg = np.asarray(gs.mesh_degrees(state))[sub_np]
    if sybil is not None:
        # broadcast sybil over the replica axis if batched
        syb_cand = (sybil if replicas is None
                    else np.broadcast_to(sybil, sub_np.shape))
        deg = deg[~syb_cand[sub_np]]
    assert deg.mean() >= cfg.d_lo, f"mesh failed to form: mean {deg.mean()}"
    t0 = time.perf_counter()
    for _r in range(reps):
        state = run(params, state, T, step)
        _ = int(np.asarray(state.tick).reshape(-1)[0])
    dt = time.perf_counter() - t0
    settled = tick < horizon - 30
    members = np.arange(n) % t
    for i in ([None] if replicas is None else range(replicas)):
        p_i = params if i is None else gs.index_trees(params, i)
        s_i = state if i is None else gs.index_trees(state, i)
        if gate_honest and sybil is not None:
            honest = ~sybil
            reach = np.asarray(gs.reach_counts_from_have(p_i, s_i,
                                                         mask=honest))
            if paired:
                member_of = lambda tau: ((members == tau)  # noqa: E731
                                         | ((members + t // 2) % t == tau))
            else:
                member_of = lambda tau: members == tau  # noqa: E731
            want = np.array([(honest & member_of(topic[j])).sum()
                             for j in range(m)])
        else:
            reach = np.asarray(gs.reach_counts_from_have(p_i, s_i))
            want = np.full(m, (2 * n // t) if paired else (n // t))
        ok = reach[settled] == want[settled]
        assert ok.all(), (reach[settled][~ok], want[settled][~ok])
        if s_i.iwant_serves is not None:
            # IWANT-flood containment gate (gossipsub_spam_test.go:24),
            # DERIVED bound: the flood accrual only fires while
            # s < retrans * padv, so after the add
            # s' <= (s - ceil(s/H)) + padv < retrans * padv + padv
            #    = (retrans + 1) * padv,
            # and padv (the partner's advertised window) <= 32 * W ids —
            # every edge's ledger stays under (retrans + 1) * 32W exactly,
            # no overshoot fudge.  True peers only: pad-lane ledger rows of
            # the kernel path carry garbage (see iwant_serve_level).
            n_t = p_i.n_true if p_i.n_true is not None else n
            serves = np.asarray(s_i.iwant_serves)[:, :n_t]
            per_edge_cap = ((cfg.gossip_retransmission + 1) * 32
                            * p_i.origin_words.shape[0])
            assert serves.max() < per_edge_cap, serves.max()
    rate = T * reps * (1 if replicas is None else replicas) / dt
    name = metric.format(n=n)
    emit(name, rate, "heartbeats/s", baseline=baseline)
    if "_kernel" in name:
        # downstream exact-name consumers (dashboards, the driver's
        # flagship-row scrape) key on the plain HISTORICAL metric name
        # — which carries the nominal peer count, not the kernel's
        # lcm-rounded one — so format the alias with the pre-rounding
        # n.  Tagged alias_of so the path picker never mistakes it for
        # an XLA measurement (tools/pick_bench_path.py skips alias
        # rows).
        emit(metric.replace("_kernel", "").format(n=n_named), rate,
             "heartbeats/s", baseline=baseline,
             extra={"alias_of": name})


def bench_gossipsub_v10():
    _bench_gossip("gossipsub_v10_100kpeers_10topics_heartbeats_per_sec",
                  100_000, 10, None)


def bench_gossipsub_v11():
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    # the 10k hb/s BASELINE.md target is defined for this config (v5e-8)
    # kernel path needs the TPU mosaic lowering — never on CPU hosts
    kernel = (os.environ.get("GOSSIP_BENCH_KERNEL", "0") == "1"
              and on_accel)
    _bench_gossip("gossipsub_v11_{n}peers_100topics"
                  + ("_kernel" if kernel else "") + "_heartbeats_per_sec",
                  n, 100, gs.ScoreSimConfig(), baseline=10_000.0,
                  kernel=kernel)


def bench_gossipsub_v11_batched():
    """Amortized replica execution: B independent flagship-config
    replicas (distinct mesh seeds, same topology/messages) advanced by
    ONE vmapped scan with a donated batch carry (gossip_run_batch) —
    the replica-sweep workload of the statistical validation tools
    (tools/validate_curves.py chunks).  Value is replica-heartbeats/s:
    B x the single-run tick count per wall-clock second, so the row
    divided by the plain gossipsub_v11 row is the amortization factor.
    GOSSIP_BENCH_REPLICAS overrides B (default 4)."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    B = int(os.environ.get("GOSSIP_BENCH_REPLICAS", "4"))
    _bench_gossip(
        "gossipsub_v11_{n}peers_100topics_batched" + str(B)
        + "_heartbeats_per_sec",
        n, 100, gs.ScoreSimConfig(), baseline=10_000.0, replicas=B)


def bench_gossipsub_v11_multitopic():
    """1M peers with OVERLAPPING topic membership (paired-topic mode:
    every peer subscribes two topics and keeps a mesh per topic, so the
    per-topic score sum and TopicScoreCap are live — the network is no
    longer T disjoint layers)."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    kernel = (os.environ.get("GOSSIP_BENCH_KERNEL", "0") == "1"
              and on_accel)
    _bench_gossip(
        "gossipsub_v11_multitopic_{n}peers_100topics_2per_peer"
        + ("_kernel" if kernel else "") + "_heartbeats_per_sec",
        n, 100, gs.ScoreSimConfig(topic_score_cap=50.0), paired=True,
        baseline=10_000.0, kernel=kernel)


def bench_gossipsub_v11_adversarial():
    """20% sybils running BOTH gossip-repair attacks at once: IHAVE
    broken-promise spam (gossipsub_spam_test.go:135) and the IWANT
    retransmission flood (gossipsub_spam_test.go:24).  Gated on full
    honest delivery and on the retransmission cutoff's served-load
    bound.  GOSSIP_BENCH_KERNEL=1 runs it on the pallas kernel path
    (the in-kernel attack accrual is parity-pinned)."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    kernel = (os.environ.get("GOSSIP_BENCH_KERNEL", "0") == "1"
              and on_accel)
    _bench_gossip(
        "gossipsub_v11_adversarial_{n}peers_20pct_sybil"
        + ("_kernel" if kernel else "") + "_heartbeats_per_sec",
        n, 100, gs.ScoreSimConfig(sybil_ihave_spam=True,
                                  sybil_iwant_spam=True),
        sybil_frac=0.2, gate_honest=True, baseline=10_000.0,
        kernel=kernel)


def bench_gossipsub_v11_everything():
    """The EVERYTHING-ON flagship: overlapping topic membership (paired
    meshes + TopicScoreCap) + PX candidate rotation + operator-pinned
    direct peers + sybil clusters behind shared IPs (P6 + per-IP gater
    grouping) running BOTH gossip-repair attacks — the full feature set
    active at once, as the reference router runs it by construction
    (gossipsub.go:197-297).  Gated on full honest delivery."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    kernel = (os.environ.get("GOSSIP_BENCH_KERNEL", "0") == "1"
              and on_accel)
    _bench_gossip(
        "gossipsub_v11_everything_{n}peers"
        + ("_kernel" if kernel else "") + "_heartbeats_per_sec",
        n, 100, gs.ScoreSimConfig(topic_score_cap=50.0,
                                  sybil_ihave_spam=True,
                                  sybil_iwant_spam=True),
        sybil_frac=0.2, gate_honest=True, paired=True,
        px_candidates=14, with_direct=True, shared_sybil_ips=True,
        baseline=10_000.0, kernel=kernel)


def bench_gossipsub_v11_churn():
    """Degradation under faults (models/faults.py): 10% of peers cycle
    down/up in staggered waves, every link drops 2% of ticks, and one
    30-heartbeat partition splits the network in half mid-run.  XLA
    path (the kernel twin is gossipsub_v11_churn_kernel).  Emits
    THREE rows: throughput under churn, the delivery-under-churn
    fraction, and the partition-heal recovery time (ticks from heal
    to 99% reachability for a publish still inside the IHAVE window
    at heal — the OPTIMUMP2P-style headline metric)."""
    import jax
    import go_libp2p_pubsub_tpu.models.faults as fl
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.models._delivery import recovery_ticks

    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    t = 100
    m, C = 32, 16
    warmup, T = 100, 150
    horizon = warmup + T
    part_start, heal = warmup + 20, warmup + 50
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    score_cfg = gs.ScoreSimConfig()
    # messages: most spread through the run; the last four published
    # 2 ticks before heal from partition side 0 (the recovery probes)
    topic, origin, tick = _msgs(rng, n, t, m, horizon - 40)
    grp = (np.arange(n) < n // 2).astype(np.int64)
    probe = np.arange(m - 4, m)
    tick[probe] = heal - 2
    origin[probe] = (origin[probe] % (n // 2 // t)) * t + topic[probe]
    # churn: 10% of peers down for one of three staggered 20-tick waves
    # — all rejoined by warmup+35, BEFORE the recovery probes publish
    # (a peer down across a publish misses it forever once it ages out
    # of the mcache window, so late churn would cap reachability below
    # the 99% recovery threshold; that loss is the delivery-fraction
    # row's business, the recovery row isolates the partition)
    victims = np.flatnonzero(rng.random(n) < 0.10)
    ivs = [(int(p), warmup + 5 + int(p % 3) * 5,
            warmup + 25 + int(p % 3) * 5) for p in victims]
    sched = fl.FaultSchedule(
        n_peers=n, horizon=horizon, down_intervals=ivs, drop_prob=0.02,
        partition_group=grp, partition_windows=[(part_start, heal)],
        seed=1)
    subs = _subs_matrix(n, t)
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, tick, score_cfg=score_cfg,
        track_first_tick=False, fault_schedule=sched)
    params = jax.device_put(params)
    step = gs.make_gossip_step(cfg, score_cfg)
    state = gs.gossip_run(params, jax.device_put(state), warmup, step)
    _ = int(np.asarray(state.tick))
    t0 = time.perf_counter()
    state, counts = gs.gossip_run_curve(params, state, T, step, m)
    counts = np.asarray(counts)
    dt = time.perf_counter() - t0
    want = np.full(m, n // t, dtype=np.float32)
    # final delivered fraction from the possession words (the per-tick
    # curve only covers the measured window; warmup-era publishes
    # delivered most of their copies before it)
    reach = np.asarray(gs.reach_counts_from_have(params, state))
    # the recovery probes belong to the recovery row, not the churn
    # delivery average (_msgs already bounds every tick < horizon - 40)
    settled = np.ones(m, dtype=bool)
    settled[probe] = False
    churn_frac = float((reach[settled] / want[settled]).mean())
    # per-tick counts start at warmup: index heal by (heal - warmup)
    rec = np.asarray(recovery_ticks(counts, heal - warmup, want,
                                    frac=0.99))[probe]
    rec_ok = rec[rec >= 0]
    emit(f"gossipsub_v11_churn_{n}peers_heartbeats_per_sec", T / dt,
         "heartbeats/s",
         extra={"faults": "10pct_churn+2pct_loss+partition"})
    emit(f"gossipsub_v11_churn_{n}peers_delivery_fraction",
         churn_frac, "fraction",
         extra={"messages": int(settled.sum()),
                "faults": "10pct_churn+2pct_loss+partition"})
    assert churn_frac > 0.80, (
        f"delivery collapsed under churn: {churn_frac}")
    assert len(rec_ok), "no partition probe recovered"
    emit(f"gossipsub_v11_partition_recovery_ticks_{n}peers",
         float(np.median(rec_ok)), "ticks",
         extra={"probes": int(len(rec)),
                "recovered": int(len(rec_ok)),
                "threshold": 0.99})


def bench_gossipsub_v11_churn_kernel():
    """gossipsub_v11_churn through the pallas receive kernel (round 9:
    fault masks thread through the kernel's VMEM pass).  Mosaic on
    TPU; CPU hosts run the kernel in interpret mode — the on/off
    RATIO is the measurement there, not absolute speed.  Emits the
    faulted kernel throughput row plus a fault-free kernel run of the
    same shape, so the KERNEL-path fault-mask overhead is itself
    measured (the XLA path's was ~15% at 100k CPU, PERF_NOTES r7/r9),
    and an alias row pairing the kernel measurement to the plain
    churn metric name (tagged alias_of — pick_bench_path skips it)."""
    import math
    import jax
    import go_libp2p_pubsub_tpu.models.faults as fl
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    on_accel = jax.devices()[0].platform != "cpu"
    n_named = 1_000_000 if on_accel else 100_000
    t = 100
    m, C = 32, 16
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    quantum = math.lcm(t, 4096, block)
    n = -(-n_named // quantum) * quantum
    warmup, T = 100, 150
    horizon = warmup + T
    part_start, heal = warmup + 20, warmup + 50
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    score_cfg = gs.ScoreSimConfig()
    topic, origin, tick = _msgs(rng, n, t, m, horizon - 40)
    grp = (np.arange(n) < n // 2).astype(np.int64)
    victims = np.flatnonzero(rng.random(n) < 0.10)
    ivs = [(int(p), warmup + 5 + int(p % 3) * 5,
            warmup + 25 + int(p % 3) * 5) for p in victims]
    sched = fl.FaultSchedule(
        n_peers=n, horizon=horizon, down_intervals=ivs, drop_prob=0.02,
        partition_group=grp, partition_windows=[(part_start, heal)],
        seed=1)
    subs = _subs_matrix(n, t)
    rates = {}
    frac = None
    for mode in ("faulted", "clean"):
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, tick, score_cfg=score_cfg,
            track_first_tick=False, pad_to_block=block,
            fault_schedule=(sched if mode == "faulted" else None))
        params = jax.device_put(params)
        step = gs.make_gossip_step(cfg, score_cfg, receive_block=block,
                                   receive_interpret=not on_accel)
        state = gs.gossip_run(params, jax.device_put(state), warmup,
                              step)
        _ = int(np.asarray(state.tick))
        t0 = time.perf_counter()
        state = gs.gossip_run(params, state, T, step)
        _ = int(np.asarray(state.tick))
        rates[mode] = T / (time.perf_counter() - t0)
        if mode == "faulted":
            reach = np.asarray(gs.reach_counts_from_have(params, state))
            frac = float((reach / float(n // t)).mean())
            assert frac > 0.80, (
                f"delivery collapsed under churn (kernel): {frac}")
    overhead = 100.0 * (rates["clean"] / rates["faulted"] - 1.0)
    name = f"gossipsub_v11_churn_kernel_{n}peers_heartbeats_per_sec"
    emit(name, rates["faulted"], "heartbeats/s",
         extra={"faults": "10pct_churn+2pct_loss+partition",
                "fault_mask_overhead_pct": round(overhead, 1),
                "kernel_fault_free_hbps": round(rates["clean"], 2),
                "delivery_fraction": round(frac, 3),
                "interpret": not on_accel})
    emit(f"gossipsub_v11_churn_{n_named}peers_heartbeats_per_sec",
         rates["faulted"], "heartbeats/s", extra={"alias_of": name})


def bench_gossipsub_telemetry_kernel():
    """Kernel twin of gossipsub_telemetry: the flagship v1.1 config
    through the pallas kernel telemetry-OFF vs telemetry-ON (the
    round-9 in-kernel counter tallies), a throughput row each so the
    KERNEL-path observation cost is measured (the XLA path's was ~51%
    at 100k CPU, PERF_NOTES r8), plus the control-overhead row —
    each alias-paired to its XLA metric name for pick_bench_path
    (alias rows are tagged and skipped by the picker)."""
    import math
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    on_accel = jax.devices()[0].platform != "cpu"
    n_named = 1_000_000 if on_accel else 100_000
    t = 100
    m, C = 32, 16
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    quantum = math.lcm(t, 4096, block)
    n = -(-n_named // quantum) * quantum
    # interpret-mode CPU fallback is ~2 orders slower than XLA: one
    # timed window there, the usual three on hardware
    warmup, T = 100, 100
    reps = 3 if on_accel else 1
    horizon = warmup + T * reps
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    score_cfg = gs.ScoreSimConfig()
    topic, origin, tick = _msgs(rng, n, t, m, horizon)
    subs = _subs_matrix(n, t)
    tcfg = tl.TelemetryConfig()
    rates = {}
    tel_totals = None
    for mode in ("off", "on"):
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, tick, score_cfg=score_cfg,
            track_first_tick=False, pad_to_block=block)
        params = jax.device_put(params)
        state = jax.device_put(state)
        step = gs.make_gossip_step(
            cfg, score_cfg, receive_block=block,
            receive_interpret=not on_accel,
            telemetry=(tcfg if mode == "on" else None))
        if mode == "off":
            state = gs.gossip_run(params, state, warmup, step)
            _ = int(np.asarray(state.tick))
            t0 = time.perf_counter()
            for _r in range(reps):
                state = gs.gossip_run(params, state, T, step)
                _ = int(np.asarray(state.tick))
            rates[mode] = T * reps / (time.perf_counter() - t0)
        else:
            state, _fr = tl.telemetry_run(params, state, warmup, step)
            _ = int(np.asarray(state.tick))
            t0 = time.perf_counter()
            window_frames = []
            for _r in range(reps):
                state, fr = tl.telemetry_run(params, state, T, step)
                _ = int(np.asarray(state.tick))
                window_frames.append(tl.summarize_frames(fr))
            rates[mode] = T * reps / (time.perf_counter() - t0)
            tel_totals = {
                k: sum(s[k] for s in window_frames)
                for k in ("bytes_payload", "bytes_control",
                          "payload_sent", "ihave_ids",
                          "iwant_ids_served", "graft_sends",
                          "prune_sends")}
    overhead = 100.0 * (rates["off"] / rates["on"] - 1.0)
    for mode in ("off", "on"):
        extra = {"interpret": not on_accel}
        if mode == "on":
            extra["telemetry_overhead_pct"] = round(overhead, 1)
        name = (f"gossipsub_v11_telemetry_{mode}_kernel_{n}peers"
                "_heartbeats_per_sec")
        emit(name, rates[mode], "heartbeats/s", extra=extra)
        emit(f"gossipsub_v11_telemetry_{mode}_{n_named}peers"
             "_heartbeats_per_sec", rates[mode], "heartbeats/s",
             extra={"alias_of": name})
    ratio = (tel_totals["bytes_control"] / tel_totals["bytes_payload"]
             if tel_totals["bytes_payload"] > 0 else 0.0)
    name = (f"gossipsub_v11_control_overhead_kernel_{n}peers"
            "_bytes_ratio")
    emit(name, ratio, "control_bytes/payload_bytes",
         extra={k: round(v, 1) for k, v in tel_totals.items()})
    emit(f"gossipsub_v11_control_overhead_{n_named}peers_bytes_ratio",
         ratio, "control_bytes/payload_bytes",
         extra={"alias_of": name})


def bench_gossipsub_telemetry():
    """Observation cost + the GossipSub paper's headline overhead
    number: the flagship v1.1 config run telemetry-OFF and
    telemetry-ON (models/telemetry.py full frame, XLA path — the
    kernel twin is gossipsub_telemetry_kernel), one throughput row
    each so the observation cost is itself measured, plus the
    control-overhead row (control bytes / payload bytes, estimated
    from the pb/rpc.py framing constants) summed over the ON run's
    measured window."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    on_accel = jax.devices()[0].platform != "cpu"
    n = 1_000_000 if on_accel else 100_000
    t = 100
    m, C = 32, 16
    warmup, T, reps = 100, 100, 3
    horizon = warmup + T * reps
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    score_cfg = gs.ScoreSimConfig()
    topic, origin, tick = _msgs(rng, n, t, m, horizon)
    subs = _subs_matrix(n, t)
    tcfg = tl.TelemetryConfig()
    rates = {}
    tel_totals = None
    for mode in ("off", "on"):
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, tick, score_cfg=score_cfg,
            track_first_tick=False)
        params = jax.device_put(params)
        state = jax.device_put(state)
        if mode == "off":
            step = gs.make_gossip_step(cfg, score_cfg)
            state = gs.gossip_run(params, state, warmup, step)
            deg = np.asarray(gs.mesh_degrees(state))[
                np.asarray(params.subscribed)]
            assert deg.mean() >= cfg.d_lo, f"no mesh: {deg.mean()}"
            _ = int(np.asarray(state.tick))
            t0 = time.perf_counter()
            for _r in range(reps):
                state = gs.gossip_run(params, state, T, step)
                _ = int(np.asarray(state.tick))
            rates[mode] = T * reps / (time.perf_counter() - t0)
        else:
            step = gs.make_gossip_step(cfg, score_cfg, telemetry=tcfg)
            state, _fr = tl.telemetry_run(params, state, warmup, step)
            _ = int(np.asarray(state.tick))
            t0 = time.perf_counter()
            window_frames = []
            for _r in range(reps):
                state, fr = tl.telemetry_run(params, state, T, step)
                _ = int(np.asarray(state.tick))
                window_frames.append(tl.summarize_frames(fr))
            rates[mode] = T * reps / (time.perf_counter() - t0)
            tel_totals = {
                k: sum(s[k] for s in window_frames)
                for k in ("bytes_payload", "bytes_control",
                          "payload_sent", "ihave_ids",
                          "iwant_ids_served", "graft_sends",
                          "prune_sends")}
    emit(f"gossipsub_v11_telemetry_off_{n}peers_heartbeats_per_sec",
         rates["off"], "heartbeats/s")
    emit(f"gossipsub_v11_telemetry_on_{n}peers_heartbeats_per_sec",
         rates["on"], "heartbeats/s",
         extra={"telemetry_overhead_pct": round(
             100.0 * (rates["off"] / rates["on"] - 1.0), 1)})
    ratio = (tel_totals["bytes_control"] / tel_totals["bytes_payload"]
             if tel_totals["bytes_payload"] > 0 else 0.0)
    emit(f"gossipsub_v11_control_overhead_{n}peers_bytes_ratio",
         ratio, "control_bytes/payload_bytes",
         extra={k: round(v, 1) for k, v in tel_totals.items()})


def bench_gossipsub_tournament():
    """Attack × defense tournament (round 11): the full {clean, spam,
    eclipse, byzantine, cold_restart} x {reference, weak, hardened}
    product as ONE batched dispatch (models/tournament.py on
    stack_trees + vmap; defense knobs are traced ScoreKnobs operands,
    so the grid shares one compiled step).  Every cell is
    invariant-armed — the bench asserts zero runtime violations.

    The shape is FIXED (20k peers, 20 topics, 150 ticks) on every
    platform so the committed TOURNEY_r12.json baseline gates CPU and
    TPU passes alike; tools/tourneystat.py --check compares the
    reference-defense worst-case delivery fraction written to
    /tmp/gossipsub_tournament.json.  Round 12: the defense axis gains
    the auto-TUNED point (models/tournament.py tune_defense — the
    coordinate-descent product of the recompile-free knob dispatch),
    measured every pass alongside reference/weak/hardened."""
    from go_libp2p_pubsub_tpu.models.tournament import run_tournament

    n, t, m, T = 20_000, 20, 24, 150
    t0 = time.perf_counter()
    rep = run_tournament(n, t, m, T, seed=0)
    dt = time.perf_counter() - t0
    rep["round"] = 12
    rep["tuned_vs_reference_delta"] = round(
        rep["worst_case"]["tuned"]["delivery_fraction"]
        - rep["worst_case"]["reference"]["delivery_fraction"], 4)
    write_json_atomic("/tmp/gossipsub_tournament.json", rep)
    emit(f"gossipsub_tournament_{n}peers_replica_heartbeats_per_sec",
         rep["replicas"] * T / dt, "heartbeats/s",
         extra={"cells": rep["replicas"], "ticks": T,
                "wall_s": round(dt, 1)})
    for dname, w in rep["worst_case"].items():
        emit(f"gossipsub_tournament_worst_case_delivery_{dname}",
             w["delivery_fraction"], "fraction",
             extra={"attack": w["attack"]})
    ecl = {r["defense"]: r.get("eclipse_takeover")
           for r in rep["rows"] if r["attack"] == "eclipse"}
    emit("gossipsub_tournament_eclipse_takeover_reference",
         ecl.get("reference", 0.0), "fraction",
         extra={"weak": ecl.get("weak"),
                "hardened": ecl.get("hardened")})
    assert rep["invariant_violations"] == 0, rep["rows"]


def _bench_invariants(kernel: bool):
    """Shared body of the invariant-overhead benches: the flagship
    v1.1 config run checker-OFF vs checker-ON (all three groups), one
    throughput row each — the round-11 observation-cost measurement
    (PERF_NOTES).  The state trajectory is bit-identical either way
    (pinned by tests/test_invariants.py); only the cost is at stake
    here."""
    import math
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.invariants as iv

    on_accel = jax.devices()[0].platform != "cpu"
    n_named = 1_000_000 if on_accel else 100_000
    t = 100
    m, C = 32, 16
    n = n_named
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    if kernel:
        quantum = math.lcm(t, 4096, block)
        n = -(-n_named // quantum) * quantum
    # interpret-mode CPU fallback is ~2 orders slower than XLA: a
    # short window there (the overhead RATIO is the measurement)
    warmup, T = (100, 100) if (on_accel or not kernel) else (30, 50)
    horizon = warmup + T
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    score_cfg = gs.ScoreSimConfig()
    topic, origin, tick = _msgs(rng, n, t, m, horizon)
    subs = _subs_matrix(n, t)
    rates = {}
    report = None
    for mode in ("off", "on"):
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, tick, score_cfg=score_cfg,
            track_first_tick=False,
            pad_to_block=(block if kernel else None))
        if mode == "on":
            state = iv.attach(state)
        params = jax.device_put(params)
        step = gs.make_gossip_step(
            cfg, score_cfg,
            invariants=(iv.InvariantConfig() if mode == "on"
                        else None),
            **(dict(receive_block=block,
                    receive_interpret=not on_accel) if kernel
               else {}))
        state = gs.gossip_run(params, jax.device_put(state), warmup,
                              step)
        _ = int(np.asarray(state.tick))
        t0 = time.perf_counter()
        state = gs.gossip_run(params, state, T, step)
        _ = int(np.asarray(state.tick))
        rates[mode] = T / (time.perf_counter() - t0)
        if mode == "on":
            report = iv.report(state)
            assert report["bits"] == 0, report
    overhead = 100.0 * (rates["off"] / rates["on"] - 1.0)
    suffix = "_kernel" if kernel else ""
    for mode in ("off", "on"):
        extra = {"interpret": kernel and not on_accel}
        if mode == "on":
            extra.update(invariant_overhead_pct=round(overhead, 1),
                         violations=report["bits"])
        name = (f"gossipsub_v11_invariants_{mode}{suffix}_{n}peers"
                "_heartbeats_per_sec")
        emit(name, rates[mode], "heartbeats/s", extra=extra)
        if kernel:
            emit(f"gossipsub_v11_invariants_{mode}_{n_named}peers"
                 "_heartbeats_per_sec", rates[mode], "heartbeats/s",
                 extra={"alias_of": name})


def bench_gossipsub_invariants():
    """Invariant-check overhead on the XLA path (round 11)."""
    _bench_invariants(kernel=False)


def bench_gossipsub_invariants_kernel():
    """Invariant-check overhead on the pallas-kernel path: the checker
    is a pure epilogue readout of the kernel's outputs, so the fast
    path needs no in-kernel changes (mosaic on TPU; interpret on CPU
    where the on/off RATIO is the measurement)."""
    _bench_invariants(kernel=True)


def _trace_export_run(kernel: bool):
    """Shared body of the trace-export benches: one faulted 100k-peer
    gossipsub run (publish burst + mesh formation inside the probe
    window), all six exporter streams -> the 13-type merged trace,
    written in the reference pb format.  Returns row extras.

    Artifacts land at /tmp/gossipsub_trace_export.pb and
    /tmp/gossipsub_trace_export_frames.json — measure_all.sh runs
    ``tracestat --check OBS_r10.json`` over them right after this
    bench (the committed baseline; both execution paths produce the
    SAME trace bit-for-bit, so the gate is path-independent)."""
    import jax
    import go_libp2p_pubsub_tpu.models.faults as fl
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.interop import export as ex

    on_accel = jax.devices()[0].platform != "cpu"
    n, t, m, C = 100_000, 100, 16, 16
    T, T_rpc = 6, 2
    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=0), n_topics=t)
    sc = gs.ScoreSimConfig()
    topic, origin, tick = _msgs(rng, n, t, m, 4)
    subs = _subs_matrix(n, t)
    # two sybil origins publish validation-failing traffic so the
    # exported stream carries REJECT_MESSAGE — full 13/13 coverage in
    # the committed OBS_r10.json ratchet
    invalid = np.zeros(m, dtype=bool)
    invalid[:2] = True
    sybil = np.zeros(n, dtype=bool)
    sybil[origin[:2]] = True
    victims = np.flatnonzero(rng.random(n) < 0.002)
    sched = fl.FaultSchedule(
        n_peers=n, horizon=T,
        down_intervals=[(int(p), 1 + int(p % 2), 4 + int(p % 2))
                        for p in victims],
        drop_prob=0.02, seed=1)
    peer_topic = (np.arange(n) % t).astype(np.int64)
    kw = dict(pad_to_block=128) if kernel else {}
    step_kw = (dict(receive_block=128,
                    receive_interpret=not on_accel) if kernel
               else dict(use_pallas_receive=False))
    params, state = gs.make_gossip_sim(
        cfg, subs, topic, origin, tick, score_cfg=sc, sybil=sybil,
        msg_invalid=invalid, fault_schedule=sched, **kw)
    params = jax.device_put(params)
    state = jax.device_put(state)
    tcfg = tl.TelemetryConfig(latency_hist=True, latency_buckets=16)
    t0 = time.perf_counter()
    out, counts, frames = tl.telemetry_run_curve(
        params, gs.tree_copy(state), T,
        gs.make_gossip_step(cfg, sc, telemetry=tcfg, **step_kw), m)
    _, snaps = gs.gossip_run_acq_snapshots(
        params, gs.tree_copy(state), T,
        gs.make_gossip_step(cfg, sc, **step_kw))
    _, rsnaps = gs.gossip_run_rpc_snapshots(
        params, state, T_rpc,
        gs.make_gossip_step(cfg, sc, rpc_probe=True, **step_kw))
    have_s = np.asarray(snaps["have"])[:, :, :n]
    mesh_s = np.asarray(snaps["mesh"])[:, :n]
    rsnaps = {k: np.asarray(v) for k, v in rsnaps.items()}
    collect_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ftm = np.asarray(gs.first_tick_matrix(out, m))[:n]
    merged = ex.merge_event_streams(
        ex.events_from_sim(ftm, topic, origin, tick,
                           fault_schedule=sched,
                           peer_topic=peer_topic),
        ex.mesh_trace_events(mesh_s, cfg.offsets, peer_topic),
        ex.reject_events(have_s, invalid, topic),
        ex.duplicate_events(have_s, mesh_s, cfg.offsets, topic),
        ex.peer_events(cfg.offsets, n, fault_schedule=sched),
        ex.rpc_events(rsnaps, cfg.offsets, topic, peer_topic,
                      n_true=n))
    export_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    path = "/tmp/gossipsub_trace_export.pb"
    ex.write_pb_trace(path, merged)
    ex.write_telemetry_frames(
        "/tmp/gossipsub_trace_export_frames.json", frames, tcfg,
        counts=np.asarray(counts), publish_tick=tick, msg_topic=topic)
    write_s = time.perf_counter() - t0
    n_events = len(merged)
    n_bytes = os.path.getsize(path)
    types = {e.type for e in merged}
    assert len(types) == 13, f"only {len(types)} event types"
    return dict(n_events=n_events, bytes_total=n_bytes,
                collect_s=round(collect_s, 2),
                export_s=round(export_s, 2),
                write_s=round(write_s, 2))


def bench_gossipsub_trace_export():
    """Full-fidelity trace pipeline cost at 100k peers (round 10):
    device collection (telemetry frames + acq/mesh snapshots + the
    per-edge RPC probe) then the host-side 13-type export, measured
    as events/sec and bytes/event in the reference pb format."""
    x = _trace_export_run(kernel=False)
    name = "gossipsub_trace_export_100000peers"
    dt = x["export_s"] + x["write_s"]
    emit(f"{name}_events_per_sec", x["n_events"] / dt, "events/s",
         extra=x)
    emit(f"{name}_bytes_per_event",
         x["bytes_total"] / x["n_events"], "bytes/event")


def bench_gossipsub_trace_export_kernel():
    """Kernel twin of gossipsub_trace_export (alias_of-paired like the
    round-9 rows): the same collectors and host export with the sim
    advanced by the pallas receive path — proving the fast path feeds
    the full trace pipeline, and costing its collection side."""
    x = _trace_export_run(kernel=True)
    name = "gossipsub_trace_export_100000peers"
    dt = x["export_s"] + x["write_s"]
    emit(f"{name}_events_per_sec_kernel", x["n_events"] / dt,
         "events/s", extra={**x, "alias_of": f"{name}_events_per_sec"})
    emit(f"{name}_bytes_per_event_kernel",
         x["bytes_total"] / x["n_events"], "bytes/event",
         extra={"alias_of": f"{name}_bytes_per_event"})


def bench_gossipsub_sweepd():
    """The sweep engine's serving row (round 12): one resident
    SweepServer (tools/sweepd.py) compiles ONE executable for a fixed
    10k x 10t shape, then serves 24 DISTINCT protocol/fault/attack
    scenario configs — knob points across the degree family,
    gossip_factor, backoff, defense weights, link-loss rates, churn,
    and three attack formations — through the batched knob dispatch.
    Asserts the compile counter stays at 1 (>= 20 configs per
    compile) and that the heterogeneous sweep's wall-clock stays
    within 2x of a same-shape seed-only batch sweep (the seed batch
    runs FIRST and pays the one compile, so the ratio compares
    steady-state serving).  Writes /tmp/gossipsub_sweepd.json for
    ``sweepstat --check`` (measure_all step 4e)."""
    from tools.sweepd import SweepServer

    n, t, m, ticks, B = 10_000, 10, 16, 60, 6
    srv = SweepServer(n=n, t=t, m=m, ticks=ticks, batch=B, seed=0)

    # seed-batch reference: 24 replicas of the REFERENCE config
    # differing only in seed — the round-6 amortized-replica workload,
    # through the same engine (pays the single compile)
    seed_reqs = [{"id": f"seed{i}", "seed": i} for i in range(24)]
    w0 = srv.wall_s
    seed_rows = srv.submit(seed_reqs)
    seed_wall = srv.wall_s - w0
    assert all(r["ok"] for r in seed_rows), seed_rows

    # the heterogeneous sweep: 24 distinct configs across the full
    # knob surface (protocol degrees, gossip coverage, backoff,
    # defense weights), fault rates, churn, and attack formations
    sweep_reqs = [
        {"id": "ref", "seed": 0},
        {"id": "d4", "knobs": {"d": 4, "d_lo": 3, "d_hi": 8}},
        {"id": "d8", "knobs": {"d": 8, "d_lo": 6, "d_hi": 12}},
        {"id": "d10", "knobs": {"d": 10, "d_lo": 8, "d_hi": 14,
                                "d_score": 6, "d_out": 3}},
        {"id": "lazy3", "knobs": {"d_lazy": 3}},
        {"id": "lazy12", "knobs": {"d_lazy": 12}},
        {"id": "gf05", "knobs": {"gossip_factor": 0.05}},
        {"id": "gf50", "knobs": {"gossip_factor": 0.5}},
        {"id": "gf90", "knobs": {"gossip_factor": 0.9}},
        {"id": "bo5", "knobs": {"backoff_ticks": 5}},
        {"id": "bo120", "knobs": {"backoff_ticks": 120}},
        {"id": "ttl10", "knobs": {"fanout_ttl_ticks": 10}},
        {"id": "retrans1", "knobs": {"gossip_retransmission": 1}},
        {"id": "loss02", "drop_prob": 0.02},
        {"id": "loss10", "drop_prob": 0.10},
        {"id": "loss20churn", "drop_prob": 0.20, "churn": True},
        {"id": "churn", "churn": True},
        {"id": "spam", "attack": "spam", "attack_frac": 0.15},
        {"id": "spam_hard", "attack": "spam", "attack_frac": 0.15,
         "knobs": {"behaviour_penalty_weight": -40.0,
                   "gossip_threshold": -2.0}},
        {"id": "eclipse", "attack": "eclipse", "attack_frac": 0.15},
        {"id": "eclipse_hard", "attack": "eclipse",
         "attack_frac": 0.15,
         "knobs": {"behaviour_penalty_weight": -40.0}},
        {"id": "byz", "attack": "byzantine", "attack_frac": 0.1},
        {"id": "byz_weak", "attack": "byzantine", "attack_frac": 0.1,
         "knobs": {"invalid_message_deliveries_weight": 0.0}},
        {"id": "kitchen_sink", "drop_prob": 0.05, "churn": True,
         "attack": "spam", "attack_frac": 0.1,
         "knobs": {"d": 8, "d_lo": 6, "d_hi": 12,
                   "gossip_factor": 0.4,
                   "behaviour_penalty_weight": -20.0}},
    ]
    w0 = srv.wall_s
    rows = srv.submit(sweep_reqs)
    sweep_wall = srv.wall_s - w0
    assert all(r["ok"] for r in rows), [r for r in rows
                                        if not r["ok"]]
    viol = sum(r.get("inv_bits", 0) != 0 for r in rows)
    assert viol == 0, rows
    compiles = srv.compiles()
    assert compiles == 1, f"engine recompiled: {compiles} executables"
    assert len(sweep_reqs) >= 20
    ratio = sweep_wall / seed_wall if seed_wall else None
    # the acceptance contract, enforced HERE too (sweepstat re-checks
    # the committed artifact): heterogeneous configs must cost no
    # more than 2x the same-shape seed-only batch
    assert ratio is None or ratio <= 2.0, (
        f"heterogeneous sweep {ratio:.2f}x the seed-batch wall")
    stats = srv.stats()
    art = {
        "round": 12,
        "shape": stats["shape"],
        "configs_served": len(sweep_reqs),
        "batches": stats["batches"],
        "compiles": compiles,
        "configs_per_compile": len(sweep_reqs) / compiles,
        "sweep_wall_s": round(sweep_wall, 2),
        "seed_batch_wall_s": round(seed_wall, 2),
        "sweep_vs_seed_ratio": (round(ratio, 3)
                                if ratio is not None else None),
        "replica_hbps": round(
            len(sweep_reqs) * ticks / sweep_wall, 2),
        "scenario_ids": [r["id"] for r in sweep_reqs],
        "rows": rows,
    }
    write_json_atomic("/tmp/gossipsub_sweepd.json", art)
    emit(f"gossipsub_sweepd_{n}peers_replica_heartbeats_per_sec",
         art["replica_hbps"], "heartbeats/s",
         extra={"configs": len(sweep_reqs), "compiles": compiles,
                "batches": stats["batches"],
                "sweep_vs_seed_ratio": art["sweep_vs_seed_ratio"]})
    emit("gossipsub_sweepd_configs_per_compile",
         art["configs_per_compile"], "configs/compile")


def bench_gossipsub_sweepd_kernel():
    """Kernel twin of gossipsub_sweepd: the pallas step has no vmap
    rule, so the kernel server proves the OTHER half of the claim —
    scenarios served SEQUENTIALLY through one compiled mosaic (CPU:
    interpret) executable with the knob scalars as SMEM operands,
    compile counter still 1 across distinct configs.  Alias-paired to
    the XLA row for pick_bench_path (alias rows are tagged and
    skipped by the picker)."""
    import jax
    from tools.sweepd import SweepServer

    on_accel = jax.devices()[0].platform != "cpu"
    n, t, m, ticks = 512, 4, 8, 12
    srv = SweepServer(n=n, t=t, m=m, ticks=ticks, batch=1,
                      kernel=True, receive_block=128,
                      interpret=not on_accel, seed=0)
    reqs = [
        {"id": "ref"},
        {"id": "d5", "knobs": {"d": 5, "d_hi": 9}},
        {"id": "gf40", "knobs": {"gossip_factor": 0.4,
                                 "backoff_ticks": 6}},
        {"id": "hard", "knobs": {"behaviour_penalty_weight": -40.0,
                                 "graylist_threshold": -60.0}},
        {"id": "loss", "drop_prob": 0.05, "churn": True},
        {"id": "spam", "attack": "spam", "attack_frac": 0.1},
    ]
    t0 = time.perf_counter()
    rows = srv.submit(reqs)
    dt = time.perf_counter() - t0
    assert all(r["ok"] for r in rows), rows
    assert srv.compiles() == 1, srv.compiles()
    name = f"gossipsub_sweepd_kernel_{n}peers_configs_per_compile"
    emit(name, len(reqs) / srv.compiles(), "configs/compile",
         extra={"configs": len(reqs), "interpret": not on_accel,
                "wall_s": round(dt, 1)})
    emit("gossipsub_sweepd_configs_per_compile",
         len(reqs) / srv.compiles(), "configs/compile",
         extra={"alias_of": name})


def bench_gossipsub_pipelined():
    """Round 13: the pipelined-gossip regime (models/delays.py,
    ROADMAP direction 3; "The Algorithm of Pipelined Gossiping" /
    OPTIMUMP2P, PAPERS.md).  ONE knob-batched dispatch sweeps the
    heartbeat/RTT ratio — delay_base in {1, 2, 4} plus a jittered
    point — over the 100k v1.1 config with the K=8 delay line and the
    device-side latency histogram on.  The ``base1`` row is the
    one-hop pre-delay baseline (bit-identical to the round-12 step,
    pinned by tests/test_delays.py); the delayed rows commit the
    FIRST genuinely multi-bucket delivery-latency percentile curves.
    The pipelined picture: per-hop delay stretches the latency
    distribution ~linearly (p50/p99 ≈ base x the one-hop curve)
    while the pipeline keeps delivering (delivery fraction holds) —
    the delay sweep itself compiles ONE executable (delay_base/
    delay_jitter are traced SimKnobs leaves).  Writes
    /tmp/gossipsub_pipelined.json for ``delaystat --check``
    (measure_all step 4f)."""
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.histutil import hist_percentiles
    from go_libp2p_pubsub_tpu.models.delays import DelayConfig

    n, t, m, ticks, K = 100_000, 100, 24, 48, 8
    rng = np.random.default_rng(0)
    subs = _subs_matrix(n, t)
    topic, origin, pub = _msgs(rng, n, t, m, 8)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=7), n_topics=t)
    sc = gs.ScoreSimConfig()
    tcfg = tl.TelemetryConfig(counters=False, wire=False, mesh=False,
                              scores=False, faults=False,
                              latency_hist=True, latency_buckets=ticks)
    dc = DelayConfig(base=1, jitter=0, k_slots=K)
    points = [("base1", {"delay_base": 1}),
              ("base2", {"delay_base": 2}),
              ("base4", {"delay_base": 4}),
              ("base4j2", {"delay_base": 4, "delay_jitter": 2})]
    builds = [gs.make_gossip_sim(subs=subs, msg_topic=topic,
                                 msg_origin=origin,
                                 msg_publish_tick=pub, seed=3,
                                 cfg=cfg, score_cfg=sc, delays=dc,
                                 track_first_tick=False,
                                 sim_knobs=kv)
              for _, kv in points]
    params = gs.stack_trees([p for p, _ in builds])
    state = gs.stack_trees([s for _, s in builds])
    step = gs.make_gossip_step(cfg, sc, telemetry=tcfg)
    runner = tl.telemetry_run_batch
    cache0 = runner._cache_size()
    t0 = time.perf_counter()
    state_b, frames = runner(params, state, ticks, step)
    jax.block_until_ready(state_b.have)
    dt = time.perf_counter() - t0
    compiles = runner._cache_size() - cache0
    hists = np.asarray(
        tl.frames_to_arrays(frames)["latency_hist"]).sum(0)  # [B, L]
    reach = np.asarray(jax.vmap(
        lambda p, s: gs.reach_counts_from_have(p, s))(params,
                                                      state_b))
    per_topic = n // t
    rows = []
    for i, (rid, kv) in enumerate(points):
        lat = hist_percentiles(hists[i])
        rows.append({
            "id": rid,
            "delay_base": int(kv.get("delay_base", 1)),
            "delay_jitter": int(kv.get("delay_jitter", 0)),
            "delivery_fraction": round(
                float(reach[i].mean()) / per_topic, 4),
            "latency": lat,
            "hist": [int(c) for c in hists[i]],
        })
    base_row = rows[0]
    for row in rows:
        # the pipelined contract, enforced HERE too (delaystat
        # re-checks the committed artifact): delay stretches latency,
        # it must not lose traffic
        assert (row["delivery_fraction"]
                >= base_row["delivery_fraction"] - 0.05), rows
        if row["delay_base"] > 1:
            assert sum(1 for c in row["hist"] if c) >= 2, row
    assert compiles <= 1, f"delay sweep recompiled: {compiles}"
    art = {
        "round": 13,
        "shape": {"n": n, "t": t, "m": m, "ticks": ticks,
                  "k_slots": K},
        "compiles": int(compiles),
        "wall_s": round(dt, 2),
        "replica_hbps": round(len(points) * ticks / dt, 2),
        "rows": rows,
    }
    write_json_atomic("/tmp/gossipsub_pipelined.json", art)
    name = f"gossipsub_pipelined_{n}peers_replica_heartbeats_per_sec"
    emit(name, art["replica_hbps"], "heartbeats/s",
         extra={"points": [r["id"] for r in rows],
                "compiles": int(compiles),
                "p99_by_base": {r["id"]: r["latency"]["p99"]
                                for r in rows}})
    emit("gossipsub_pipelined_p99_stretch_base4",
         rows[2]["latency"]["p99"]
         / max(base_row["latency"]["p99"], 1), "x",
         extra={"base1_p99": base_row["latency"]["p99"],
                "base4_p99": rows[2]["latency"]["p99"]})


def bench_gossipsub_multichip():
    """Round 14: whole-sim multi-chip scale-out (parallel/sharded.py,
    ROADMAP direction 1).  The ENTIRE scan carry — possession words,
    per-edge counters, mesh/backoff, scores — runs sharded over the
    ``peers`` mesh axis via the carry-pinned runner (no per-tick
    resharding; the circulant rolls lower to boundary collectives).
    Two deliverables, both into /tmp/gossipsub_multichip.json for the
    ``shardstat --check`` gate (measure_all step 4g):

    * the D-scaling curve at the 1M v1.1-shape config — per D in
      {1, 2, 4, 8} the warm wall-clock, compile count (must be 1),
      the boundary-collective census from the compiled HLO of a
      probe-shape twin, and BIT-IDENTITY of the final state digest
      against the D=1 row (the sharding layer is a layout contract);
    * the 10M-peer flagship row at max D.  On the CPU virtual mesh
      (``--xla_force_host_platform_device_count``) the artifact is
      tagged ``hardware_queued`` — the real-mesh row lands via the
      tpu_watch protocol when the relay next recovers.

    Shapes are env-tunable (GOSSIP_MULTICHIP_N /
    GOSSIP_MULTICHIP_FLAGSHIP_N; FLAGSHIP_N=0 skips the 10M row)."""
    import hashlib

    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    n = int(os.environ.get("GOSSIP_MULTICHIP_N", 1_000_000))
    n_flag = int(os.environ.get("GOSSIP_MULTICHIP_FLAGSHIP_N",
                                10_000_000))
    t, m, ticks, n_probe = 10, 24, 8, 4096
    ndev = len(jax.devices())
    Ds = [d for d in (1, 2, 4, 8) if d <= ndev]

    def build(n_, t_, m_):
        rng = np.random.default_rng(0)
        cfg = gs.GossipSimConfig(
            offsets=gs.make_gossip_offsets(t_, 16, n_, seed=7),
            n_topics=t_)
        sc = gs.ScoreSimConfig()
        subs = _subs_matrix(n_, t_)
        topic, origin, pub = _msgs(rng, n_, t_, m_, 3)
        params, state = gs.make_gossip_sim(
            cfg, subs, topic, origin, pub, seed=3, score_cfg=sc,
            track_first_tick=False)
        return cfg, sc, params, state

    def digest(out):
        h = hashlib.sha256()
        for leaf in (out.have, out.mesh, out.backoff, out.tick):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    cfg, sc, params, state = build(n, t, m)
    step = gs.make_gossip_step(cfg, sc)
    pcfg, psc, pparams, pstate = build(n_probe, t, m)
    pstep = gs.make_gossip_step(pcfg, psc)

    rows, ref_digest = [], None
    for D in Ds:
        mesh = pm.make_mesh(D)
        params_s, state_s, sh = ps.shard_sim(
            params, gs.tree_copy(state), mesh, n)
        cache0 = ps.sharded_gossip_run._cache_size()
        t0 = time.perf_counter()
        out = ps.sharded_gossip_run(params_s, state_s, ticks, step, sh)
        jax.block_until_ready(out.have)
        cold = time.perf_counter() - t0
        # warm twin from a fresh (donated-away) carry
        _, state_s, _ = ps.shard_sim(params, gs.tree_copy(state),
                                     mesh, n)
        t0 = time.perf_counter()
        out = ps.sharded_gossip_run(params_s, state_s, ticks, step, sh)
        jax.block_until_ready(out.have)
        dt = time.perf_counter() - t0
        compiles = ps.sharded_gossip_run._cache_size() - cache0
        # boundary-collective census on the probe-shape twin (same
        # step structure; lowering the 1M program again would just
        # recompile it)
        pp, st, psh = ps.shard_sim(pparams, gs.tree_copy(pstate),
                                   mesh, n_probe)
        hlo = ps.sharded_gossip_run.lower(
            pp, st, ticks, pstep, psh).compile().as_text()
        coll = ps.collective_stats(hlo)
        dg = digest(out)
        if ref_digest is None:
            ref_digest = dg
        rows.append({
            "id": f"D{D}", "devices": D, "n": n,
            "compiles": int(compiles),
            "wall_s": round(dt, 3), "cold_s": round(cold, 2),
            "heartbeats_per_sec": round(ticks / dt, 3),
            "peer_ticks_per_sec": round(n * ticks / dt, 1),
            "bit_identical": dg == ref_digest, "digest": dg,
            "collectives": {k: v for k, v in coll.items()
                            if k != "total_bytes"},
            "collective_bytes": coll["total_bytes"],
            "probe_n": n_probe,
        })
        assert compiles == 1, (D, compiles)
        assert dg == ref_digest, (D, dg, ref_digest)
        if D > 1:
            # the whole-sim carry really partitions: boundary
            # collectives must appear once the mesh has >1 shard
            assert coll["total_bytes"] > 0, (D, coll)

    if n_flag:
        D = Ds[-1]
        mesh = pm.make_mesh(D)
        fcfg, fsc, fparams, fstate = build(n_flag, t, m)
        fstep = gs.make_gossip_step(fcfg, fsc)
        fparams_s, fstate_s, fsh = ps.shard_sim(fparams, fstate,
                                                mesh, n_flag)
        t0 = time.perf_counter()
        fout = ps.sharded_gossip_run(fparams_s, fstate_s, ticks,
                                     fstep, fsh)
        jax.block_until_ready(fout.have)
        fdt = time.perf_counter() - t0
        rows.append({
            "id": "flagship", "devices": D, "n": n_flag,
            "wall_s": round(fdt, 2),
            "heartbeats_per_sec": round(ticks / fdt, 3),
            "peer_ticks_per_sec": round(n_flag * ticks / fdt, 1),
            "digest": digest(fout),
        })

    backend = jax.default_backend()
    art = {
        "round": 14,
        "platform": backend,
        "n_devices": ndev,
        "hardware_queued": backend != "tpu",
        "shape": {"n": n, "t": t, "m": m, "ticks": ticks,
                  "flagship_n": n_flag},
        "rows": rows,
    }
    write_json_atomic("/tmp/gossipsub_multichip.json", art)
    emit(f"gossipsub_multichip_{n}peers_peer_ticks_per_sec",
         rows[len(Ds) - 1]["peer_ticks_per_sec"], "peer-ticks/s",
         extra={"devices": Ds[-1], "compiles_per_D": 1,
                "bit_identical": all(r.get("bit_identical", True)
                                     for r in rows),
                "collective_bytes_probe":
                    rows[len(Ds) - 1]["collective_bytes"]})
    if n_flag:
        emit(f"gossipsub_multichip_flagship_{n_flag}peers"
             "_heartbeats_per_sec",
             rows[-1]["heartbeats_per_sec"], "heartbeats/s",
             extra={"devices": rows[-1]["devices"],
                    "platform": backend,
                    "hardware_queued": backend != "tpu"})


def bench_gossipsub_checkpoint():
    """Round 15: preemption-tolerant execution
    (parallel/checkpoint.py).  The tick horizon splits into S segments
    of one lax.scan each with the FULL carry snapshotted (CRC-verified,
    atomic) between segments; scan splitting is exact, so every row
    must reproduce the single-scan digest BIT-IDENTICALLY.  Rows into
    /tmp/gossipsub_checkpoint.json for the ``ckptstat --check`` gate
    (measure_all step 4h):

    * ``single``        the uninterrupted one-scan reference;
    * ``segmented_S2`` / ``segmented_S4``  the segmented runner at
      S in {2, 4} — digest, wall-clock (overhead vs single), compile
      count (equal segments must share ONE executable), snapshot
      bytes on disk;
    * ``kill_resume``   a run interrupted via the deferred-SIGTERM
      machinery (request_stop -> CheckpointInterrupt after the
      in-flight segment flushes) and resumed from its snapshot;
    * ``shard_restore`` saved under a shard_sim placement at D=4 and
      resumed at D=8 (the D->D' restore contract) — skipped (and the
      artifact tagged) when fewer than 8 devices are visible.

    Shapes are env-tunable (GOSSIP_CKPT_N / GOSSIP_CKPT_TICKS);
    snapshots live under GOSSIP_CKPT_DIR (default
    /tmp/gossip_ckpt_bench, wiped per row)."""
    import hashlib
    import shutil

    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    n = int(os.environ.get("GOSSIP_CKPT_N", 1_000_000))
    ticks = int(os.environ.get("GOSSIP_CKPT_TICKS", 8))
    base_dir = os.environ.get("GOSSIP_CKPT_DIR", "/tmp/gossip_ckpt_bench")
    t, m = 10, 24
    ndev = len(jax.devices())

    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, 16, n, seed=7), n_topics=t)
    sc = gs.ScoreSimConfig()
    subs = _subs_matrix(n, t)
    topic, origin, pub = _msgs(rng, n, t, m, 3)

    def build():
        return gs.make_gossip_sim(cfg, subs, topic, origin, pub,
                                  seed=3, score_cfg=sc,
                                  track_first_tick=False)

    def digest(out):
        h = hashlib.sha256()
        for leaf in (out.have, out.mesh, out.backoff, out.tick):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    def fresh_dir(name):
        d = os.path.join(base_dir, name)
        shutil.rmtree(d, ignore_errors=True)
        return d

    fp = ck.config_fingerprint(cfg, sc)
    step = gs.make_gossip_step(cfg, sc)
    params, state = build()

    t0 = time.perf_counter()
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    t0 = time.perf_counter()   # warm
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    wall_single = time.perf_counter() - t0
    ref = digest(out)
    rows = [{"id": "single", "n": n, "wall_s": round(wall_single, 3),
             "digest": ref, "bit_identical": True}]

    for S in (2, 4):
        # cold pass: counts the compiles (equal segments must share
        # ONE executable); warm pass in a fresh dir times the honest
        # overhead — segment dispatch + snapshot I/O, compile excluded
        d = fresh_dir(f"S{S}")
        ckc = ck.CheckpointConfig(directory=d, every=max(ticks // S, 1),
                                  fingerprint=fp)
        cache0 = gs.gossip_run._cache_size()
        out = ck.ckpt_gossip_run(params, gs.tree_copy(state), ticks,
                                 step, ckc)
        jax.block_until_ready(out.have)
        compiles = gs.gossip_run._cache_size() - cache0
        d = fresh_dir(f"S{S}")
        ckc = ck.CheckpointConfig(directory=d, every=max(ticks // S, 1),
                                  fingerprint=fp)
        t0 = time.perf_counter()
        out = ck.ckpt_gossip_run(params, gs.tree_copy(state), ticks,
                                 step, ckc)
        jax.block_until_ready(out.have)
        dt = time.perf_counter() - t0
        snap_bytes = sum(
            os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))
        dg = digest(out)
        rows.append({
            "id": f"segmented_S{S}", "n": n, "segments": S,
            "every": ckc.every, "wall_s": round(dt, 3),
            "overhead_x": round(dt / wall_single, 2),
            "compiles": int(compiles),
            "snapshot_bytes": int(snap_bytes),
            "digest": dg, "bit_identical": dg == ref,
        })
        assert dg == ref, (S, dg, ref)

    # kill-resume: the deferred-stop machinery interrupts after the
    # first flushed segment; the SAME call then resumes to completion
    d = fresh_dir("kill")
    ckc = ck.CheckpointConfig(directory=d, every=max(ticks // 4, 1),
                              fingerprint=fp)
    ck.request_stop()
    interrupted = False
    try:
        ck.ckpt_gossip_run(params, gs.tree_copy(state), ticks, step,
                           ckc)
    except ck.CheckpointInterrupt as e:
        interrupted = True
        ticks_done = e.ticks_done
    ck.clear_stop()
    out = ck.ckpt_gossip_run(params, gs.tree_copy(state), ticks, step,
                             ckc)
    jax.block_until_ready(out.have)
    dg = digest(out)
    rows.append({
        "id": "kill_resume", "n": n, "every": ckc.every,
        "interrupted": interrupted,
        "resumed_from_tick": ticks_done if interrupted else None,
        "wall_s": 0.0, "digest": dg, "bit_identical": dg == ref,
    })
    assert interrupted and dg == ref, (interrupted, dg, ref)

    # D->D' restore: save sharded at D_save, resume at D_resume
    if ndev >= 2:
        d_save = 4 if ndev >= 8 else ndev // 2
        d_resume = 8 if ndev >= 8 else ndev
        d = fresh_dir("shard")
        ckc = ck.CheckpointConfig(directory=d, every=max(ticks // 2, 1),
                                  fingerprint=fp)
        mesh_s = pm.make_mesh(d_save)
        p_s, s_s, sh_s = ps.shard_sim(params, gs.tree_copy(state),
                                      mesh_s, n)
        ck.request_stop()
        try:
            ck.ckpt_sharded_gossip_run(p_s, s_s, ticks, step, sh_s,
                                       ckc)
        except ck.CheckpointInterrupt:
            pass
        ck.clear_stop()
        mesh_r = pm.make_mesh(d_resume)
        p_r, s_r, sh_r = ps.shard_sim(params, gs.tree_copy(state),
                                      mesh_r, n)
        out = ck.ckpt_sharded_gossip_run(p_r, s_r, ticks, step, sh_r,
                                         ckc)
        jax.block_until_ready(out.have)
        dg = digest(out)
        rows.append({
            "id": "shard_restore", "n": n,
            "devices_save": d_save, "devices_resume": d_resume,
            "wall_s": 0.0, "digest": dg, "bit_identical": dg == ref,
        })
        assert dg == ref, (d_save, d_resume, dg, ref)

    shutil.rmtree(base_dir, ignore_errors=True)
    backend = jax.default_backend()
    art = {
        "round": 15,
        "platform": backend,
        "n_devices": ndev,
        "hardware_queued": backend != "tpu",
        "shape": {"n": n, "t": t, "m": m, "ticks": ticks},
        "rows": rows,
    }
    write_json_atomic("/tmp/gossipsub_checkpoint.json", art)
    emit(f"gossipsub_checkpoint_{n}peers_segment_overhead_x",
         rows[2]["overhead_x"], "x single-scan",
         extra={"segments": 4, "compiles": rows[2]["compiles"],
                "bit_identical": all(r["bit_identical"] for r in rows),
                "kill_resume_ok": rows[3]["bit_identical"],
                "rows": len(rows)})


def bench_gossipsub_resident():
    """Round 16: the tick-resident gossip megakernel
    (make_fused_window / gossip_run_fused).  One pallas dispatch per
    T=8-tick window with the whole per-shard carry resident in VMEM
    across grid steps, vs the per-tick kernel staging the carry
    through HBM every tick.  Three contracts, one artifact
    (/tmp/gossipsub_resident.json for the ``residentstat --check``
    gate, measure_all step 4i):

    * BIT-IDENTITY: the fused trajectory's final-state digest must
      equal the per-tick kernel's (residency is a scheduling change,
      never an arithmetic one);
    * ONE COMPILE: the whole fused run is one executable
      (compile-counter asserted) — windows re-dispatch, never
      re-trace;
    * the BYTE LEDGER: analytic per-tick HBM bytes
      (ops/pallas/receive.fused_working_set_bytes — the pallas body
      is opaque to XLA's bytes-accessed counter) for the bench shape
      plus the 100k/1M ledger points, with the VMEM working set and
      the budget verdict per point (1M refuses: the carry is past the
      96MB budget — the refusal is part of the record).

    Mosaic on TPU; CPU hosts run both paths in interpret mode, where
    the digest/compile/ledger rows are the measurement and wall-clock
    is indicative only.  Shape env-tunable via GOSSIP_RESIDENT_N
    (must be a multiple of lcm(block, 1024))."""
    import hashlib

    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.ops.pallas.receive import (
        FUSED_ALIGN, fused_working_set_bytes)

    on_accel = jax.devices()[0].platform != "cpu"
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    n = int(os.environ.get("GOSSIP_RESIDENT_N",
                           1_048_576 if on_accel else 131_072))
    assert n % block == 0 and n % FUSED_ALIGN == 0, (n, block)
    t, m, C = 10, 24, 16
    Tw = 8          # fused window length; >= 5x needs the T=8 window
    ticks = Tw * 2

    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=7), n_topics=t)
    subs = _subs_matrix(n, t)
    topic, origin, pub = _msgs(rng, n, t, m, ticks // 2)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, pub,
                                       seed=3, pad_to_block=block)
    params = jax.device_put(params)

    def digest(s):
        h = hashlib.sha256()
        for leaf in (s.have, s.recent, s.mesh, s.fanout, s.last_pub,
                     s.backoff, s.tick):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    # per-tick kernel reference: same padded layout, same block plan
    step = gs.make_gossip_step(cfg, None, receive_block=block,
                               receive_interpret=not on_accel)
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    t0 = time.perf_counter()
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    wall_unfused = time.perf_counter() - t0
    ref = digest(out)
    rows = [{"id": "unfused_kernel", "n": n, "ticks": ticks,
             "wall_s": round(wall_unfused, 3),
             "heartbeats_per_sec": round(ticks / wall_unfused, 2),
             "digest": ref, "bit_identical": True}]

    # fused window: T=8 ticks per pallas dispatch, carry resident
    window = gs.make_fused_window(cfg, None, ticks_fused=Tw,
                                  receive_block=block,
                                  receive_interpret=not on_accel,
                                  on_refusal="raise")
    reason = window.capability(params, state)
    assert reason is None, reason
    cache0 = gs.gossip_run_fused._cache_size()
    out = gs.gossip_run_fused(params, gs.tree_copy(state), ticks,
                              window)
    jax.block_until_ready(out.have)
    compiles = gs.gossip_run_fused._cache_size() - cache0
    t0 = time.perf_counter()
    out = gs.gossip_run_fused(params, gs.tree_copy(state), ticks,
                              window)
    jax.block_until_ready(out.have)
    wall_fused = time.perf_counter() - t0
    dg = digest(out)
    rows.append({
        "id": f"fused_T{Tw}", "n": n, "ticks": ticks,
        "ticks_fused": Tw, "wall_s": round(wall_fused, 3),
        "heartbeats_per_sec": round(ticks / wall_fused, 2),
        "compiles": int(compiles),
        "digest": dg, "bit_identical": dg == ref,
    })
    assert dg == ref, (dg, ref)
    assert compiles == 1, f"fused run recompiled: {compiles}"

    # analytic HBM/VMEM ledger: the bench shape + the 100k and 1M
    # points (W=1 at m<=32; hg is the config default)
    from go_libp2p_pubsub_tpu.models.gossipsub import FUSED_VMEM_BUDGET
    W = (m + 31) // 32
    hg = cfg.history_gossip
    ledger = []
    for n_l in sorted({102_400, n, 1_048_576}):
        ws = fused_working_set_bytes(C, W, hg, n_l, ticks=Tw)
        red = (ws["unfused_hbm_bytes_per_tick"]
               / max(ws["hbm_bytes_per_tick"], 1.0))
        ledger.append({
            "n": n_l, "ticks_fused": Tw,
            "carry_bytes_per_peer": ws["carry_bytes_per_peer"],
            "vmem_bytes": int(ws["vmem_bytes"]),
            "vmem_budget_bytes": int(FUSED_VMEM_BUDGET),
            "fits": ws["vmem_bytes"] <= FUSED_VMEM_BUDGET,
            "unfused_hbm_bytes_per_tick":
                int(ws["unfused_hbm_bytes_per_tick"]),
            "fused_hbm_bytes_per_tick": int(ws["hbm_bytes_per_tick"]),
            "hbm_reduction_x": round(red, 2),
        })

    backend = jax.default_backend()
    art = {
        "round": 16,
        "platform": backend,
        "hardware_queued": backend != "tpu",
        "interpret": not on_accel,
        "shape": {"n": n, "t": t, "m": m, "C": C, "ticks": ticks,
                  "ticks_fused": Tw, "block": block},
        "rows": rows,
        "ledger": ledger,
    }
    write_json_atomic("/tmp/gossipsub_resident.json", art)
    bench_point = next(e for e in ledger if e["n"] == n)
    emit(f"gossipsub_resident_{n}peers_hbm_reduction_x",
         bench_point["hbm_reduction_x"], "x per-tick HBM bytes",
         extra={"ticks_fused": Tw, "compiles": int(compiles),
                "bit_identical": dg == ref,
                "fused_hbps": rows[1]["heartbeats_per_sec"],
                "unfused_hbps": rows[0]["heartbeats_per_sec"],
                "interpret": not on_accel})


def bench_gossipsub_resident_sharded():
    """Round 17: the SHARDED tick-resident megakernel — VMEM residency
    x multi-chip sharding composed (make_fused_window(shard_mesh=...)
    / sharded_gossip_run_fused).  Under shard_map each shard runs ONE
    resident pallas dispatch per T=8-tick window whose in-kernel
    remote DMAs carry the ring-halo boundary words between grid ticks;
    the per-SHARD carry never leaves VMEM inside the window.  Four
    contracts, one artifact (/tmp/gossipsub_resident_sharded.json for
    the ``residentstat --check --sharded`` gate, measure_all step 4j):

    * BIT-IDENTITY ACROSS D: the fused-sharded trajectory's final
      digest at every D in {2, 4} must equal the single-device
      per-tick kernel's (the halo exchange is a scheduling change,
      never an arithmetic one);
    * ONE COMPILE PER D: each fused-sharded run is one executable —
      windows re-dispatch, never re-trace;
    * the r16 LEDGER carried forward unchanged (no coverage shrink);
    * the MULTIPLICATIVE row: the per-(n, D) fits table
      (fused_working_set_bytes with real circulant offsets — the halo
      reach is offset geometry) including the headline 1M point,
      REFUSED at D=1 and FITS by D=8 with multiplicative saving =
      fused HBM reduction x the D-way carry partition.

    Mosaic + real ICI DMAs on TPU; CPU hosts run the same program on
    the virtual mesh in interpret mode (set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``), where
    digest/compile/ledger rows are the measurement, wall-clock is
    indicative only, and the artifact is tagged ``hardware_queued``."""
    import hashlib

    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.ops.pallas.receive import (
        FUSED_ALIGN, FUSED_SHARD_TILE, fused_working_set_bytes)
    from go_libp2p_pubsub_tpu.parallel import mesh as pm
    from go_libp2p_pubsub_tpu.parallel import sharded as ps

    on_accel = jax.devices()[0].platform != "cpu"
    ndev = len(jax.devices())
    block = int(os.environ.get("GOSSIP_BENCH_BLOCK", "8192"))
    n = int(os.environ.get("GOSSIP_RESIDENT_N",
                           1_048_576 if on_accel else 131_072))
    assert n % block == 0 and n % FUSED_ALIGN == 0, (n, block)
    t, m, C = 10, 24, 16
    Tw = 8
    ticks = Tw * 2
    Ds = [d for d in (2, 4) if d <= ndev and n % d == 0
          and (n // d) % FUSED_SHARD_TILE == 0]

    rng = np.random.default_rng(0)
    cfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(t, C, n, seed=7), n_topics=t)
    subs = _subs_matrix(n, t)
    topic, origin, pub = _msgs(rng, n, t, m, ticks // 2)
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin, pub,
                                       seed=3, pad_to_block=block)
    params = jax.device_put(params)

    def digest(s):
        h = hashlib.sha256()
        for leaf in (s.have, s.recent, s.mesh, s.fanout, s.last_pub,
                     s.backoff, s.tick):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()[:16]

    # single-device per-tick kernel: the arithmetic reference
    step = gs.make_gossip_step(cfg, None, receive_block=block,
                               receive_interpret=not on_accel)
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    t0 = time.perf_counter()
    out = gs.gossip_run(params, gs.tree_copy(state), ticks, step)
    jax.block_until_ready(out.have)
    wall_unfused = time.perf_counter() - t0
    ref = digest(out)
    rows = [{"id": "unfused_kernel", "n": n, "ticks": ticks,
             "wall_s": round(wall_unfused, 3),
             "heartbeats_per_sec": round(ticks / wall_unfused, 2),
             "digest": ref, "bit_identical": True}]

    # single-chip fused window: the residency baseline the sharded
    # rows multiply against
    window = gs.make_fused_window(cfg, None, ticks_fused=Tw,
                                  receive_block=block,
                                  receive_interpret=not on_accel,
                                  on_refusal="raise")
    reason = window.capability(params, state)
    assert reason is None, reason
    cache0 = gs.gossip_run_fused._cache_size()
    out = gs.gossip_run_fused(params, gs.tree_copy(state), ticks,
                              window)
    jax.block_until_ready(out.have)
    compiles = gs.gossip_run_fused._cache_size() - cache0
    t0 = time.perf_counter()
    out = gs.gossip_run_fused(params, gs.tree_copy(state), ticks,
                              window)
    jax.block_until_ready(out.have)
    wall_fused = time.perf_counter() - t0
    dg = digest(out)
    rows.append({
        "id": f"fused_T{Tw}", "n": n, "ticks": ticks,
        "ticks_fused": Tw, "wall_s": round(wall_fused, 3),
        "heartbeats_per_sec": round(ticks / wall_fused, 2),
        "compiles": int(compiles),
        "digest": dg, "bit_identical": dg == ref,
    })
    assert dg == ref, (dg, ref)
    assert compiles == 1, f"fused run recompiled: {compiles}"

    # fused-sharded rows: the composition under test
    for D in Ds:
        mesh = pm.make_mesh(D)
        win = gs.make_fused_window(cfg, None, ticks_fused=Tw,
                                   receive_block=block,
                                   receive_interpret=not on_accel,
                                   shard_mesh=mesh, shard_axis="peers",
                                   on_refusal="raise")
        reason = win.capability(params, state)
        assert reason is None, (D, reason)
        params_s, state_s, sh = ps.shard_sim(
            params, gs.tree_copy(state), mesh, n)
        cache0 = ps.sharded_gossip_run_fused._cache_size()
        out = ps.sharded_gossip_run_fused(params_s, state_s, ticks,
                                          win, sh)
        jax.block_until_ready(out.have)
        compiles = ps.sharded_gossip_run_fused._cache_size() - cache0
        # warm twin from a fresh (donated-away) carry
        _, state_s, _ = ps.shard_sim(params, gs.tree_copy(state),
                                     mesh, n)
        t0 = time.perf_counter()
        out = ps.sharded_gossip_run_fused(params_s, state_s, ticks,
                                          win, sh)
        jax.block_until_ready(out.have)
        dt = time.perf_counter() - t0
        dg = digest(out)
        rows.append({
            "id": f"fused_sharded_D{D}", "n": n, "devices": D,
            "ticks": ticks, "ticks_fused": Tw,
            "wall_s": round(dt, 3),
            "heartbeats_per_sec": round(ticks / dt, 2),
            "compiles": int(compiles),
            "digest": dg, "bit_identical": dg == ref,
        })
        assert dg == ref, (D, dg, ref)
        assert compiles == 1, (D, compiles)

    # the r16 ledger, carried forward unchanged (coverage gate), plus
    # the per-(n, D) fits table with real circulant offsets — the
    # halo reach and the tailored ctrl segments are offset geometry,
    # not just magnitudes
    from go_libp2p_pubsub_tpu.models.gossipsub import FUSED_VMEM_BUDGET
    W = (m + 31) // 32
    hg = cfg.history_gossip
    ledger = []
    for n_l in sorted({102_400, n, 1_048_576}):
        ws = fused_working_set_bytes(C, W, hg, n_l, ticks=Tw)
        red = (ws["unfused_hbm_bytes_per_tick"]
               / max(ws["hbm_bytes_per_tick"], 1.0))
        ledger.append({
            "n": n_l, "ticks_fused": Tw,
            "carry_bytes_per_peer": ws["carry_bytes_per_peer"],
            "vmem_bytes": int(ws["vmem_bytes"]),
            "vmem_budget_bytes": int(FUSED_VMEM_BUDGET),
            "fits": ws["vmem_bytes"] <= FUSED_VMEM_BUDGET,
            "unfused_hbm_bytes_per_tick":
                int(ws["unfused_hbm_bytes_per_tick"]),
            "fused_hbm_bytes_per_tick": int(ws["hbm_bytes_per_tick"]),
            "hbm_reduction_x": round(red, 2),
        })

    fits_table = []
    for n_l in sorted({102_400, n, 1_048_576}):
        offs_l = gs.make_gossip_offsets(t, C, n_l, seed=7)
        for D in (1, 2, 4, 8):
            if n_l % D or (n_l // D) % FUSED_SHARD_TILE:
                continue
            try:
                ws = fused_working_set_bytes(
                    C, W, hg, n_l, ticks=Tw, devices=D,
                    offsets=(offs_l if D > 1 else None))
            except ValueError as e:
                fits_table.append({"n": n_l, "devices": D,
                                   "ticks_fused": Tw,
                                   "refused": str(e)})
                continue
            red = (ws["unfused_hbm_bytes_per_tick"]
                   / max(ws["hbm_bytes_per_tick"], 1.0))
            fits_table.append({
                "n": n_l, "devices": D, "ticks_fused": Tw,
                "vmem_bytes": int(ws["vmem_bytes"]),
                "vmem_budget_bytes": int(FUSED_VMEM_BUDGET),
                "fits": ws["vmem_bytes"] <= FUSED_VMEM_BUDGET,
                "boundary_bytes_per_tick":
                    int(ws.get("boundary_bytes_per_tick", 0)),
                "hbm_reduction_x": round(red, 2),
                "multiplicative_x": round(red * D, 2),
            })

    # the headline row: 1M peers, which the single-chip budget
    # REFUSES, composes to FITS once the ring splits the carry —
    # with margin by D=8
    m_pts = {e["devices"]: e for e in fits_table
             if e["n"] == 1_048_576 and "fits" in e}
    head = m_pts[8]
    assert head["fits"], head
    assert not m_pts[1]["fits"], m_pts[1]
    multiplicative = {
        "n": 1_048_576, "devices": 8, "ticks_fused": Tw,
        "hbm_reduction_x": head["hbm_reduction_x"],
        "multiplicative_x": head["multiplicative_x"],
        "fits_by_devices": {str(d): bool(e["fits"])
                            for d, e in sorted(m_pts.items())},
        "first_fits_devices": min(d for d, e in m_pts.items()
                                  if e["fits"]),
    }

    backend = jax.default_backend()
    art = {
        "round": 17,
        "platform": backend,
        "n_devices": ndev,
        "hardware_queued": backend != "tpu",
        "interpret": not on_accel,
        "shape": {"n": n, "t": t, "m": m, "C": C, "ticks": ticks,
                  "ticks_fused": Tw, "block": block, "devices": Ds},
        "rows": rows,
        "ledger": ledger,
        "fits_table": fits_table,
        "multiplicative": multiplicative,
    }
    write_json_atomic("/tmp/gossipsub_resident_sharded.json", art)
    emit(f"gossipsub_resident_sharded_{n}peers_multiplicative_x",
         multiplicative["multiplicative_x"],
         "x per-tick single-chip HBM",
         extra={"ticks_fused": Tw, "devices": Ds,
                "first_fits_devices":
                    multiplicative["first_fits_devices"],
                "bit_identical": all(r["bit_identical"]
                                     for r in rows),
                "interpret": not on_accel})


_SERVE_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
from go_libp2p_pubsub_tpu.serving import FrontendConfig, ScenarioFrontend
fe = ScenarioFrontend(FrontendConfig(
    batch=2, max_buckets=2, long_ticks={long_ticks},
    ckpt_dir={ckpt_dir!r}, ckpt_every={every},
    server_kw={{"seed": 0}}))
lines = [{line!r}] if {first} else []
fe.serve_lines(lines, sys.stdout, journal={journal!r})
"""

_SERVE_COLD_CHILD = r"""
import json, sys, time
t0 = time.perf_counter()
sys.path.insert(0, {repo!r})
from go_libp2p_pubsub_tpu.serving import FrontendConfig, ScenarioFrontend
fe = ScenarioFrontend(FrontendConfig(
    batch=4, max_buckets=4, aot_dir={aot!r}, server_kw={{"seed": 0}}))
first = None
rows = []
for n, t, m, ticks in ((256, 2, 8, 16), (128, 2, 4, 8)):
    for i in range(4):
        fe.admit({{"id": f"c-n{{n}}-ticks{{ticks}}-{{i}}", "n": n,
                   "t": t, "m": m, "ticks": ticks, "seed": i}})
    rows += fe.drain()
    if first is None:
        first = time.perf_counter() - t0
st = fe.stats()
print(json.dumps({{
    "cold": True, "first_result_s": round(first, 3),
    "total_s": round(time.perf_counter() - t0, 3),
    "compiles": st["compiles"], "aot_loads": st["aot_loads"],
    "aot_exports": st["aot_exports"],
    "traced_buckets": st["traced_buckets"],
    "rows": [[r.get("id"), r.get("delivery_fraction"),
              r.get("honest_delivery_fraction")] for r in rows],
}}), flush=True)
"""


def bench_gossipsub_serving():
    """Round 18: the fault-tolerant multi-tenant front end
    (go_libp2p_pubsub_tpu/serving) under generated load.  Four phases,
    one artifact (/tmp/gossipsub_serving.json) for the ``servestat
    --check`` gate (measure_all step 4k):

    * ``load``          GOSSIP_SERVE_REQS (default 2000) requests with
      Zipf-popular shapes over a 5-shape pool (max_buckets=4, so the
      cold shape cycles through LRU eviction) and Poisson arrivals
      paced at GOSSIP_SERVE_RPS (default 400/s); a slice carries tight
      deadlines (named timeout rows) and elevated priority.  Reports
      throughput, p50/p99 queue latency, and the headline contract:
      compile count == distinct traced bucket shapes (evictions and
      rebuilds add ZERO compiles).
    * ``overload``      a burst into a queue_cap=32 front end
      dispatching every 4th arrival: admissions past the cap come back
      as EXPLICIT ``overloaded`` rejection rows; the accounting
      identity (admitted == served + errors + timeouts + transient +
      queued + parked) proves nothing was silently dropped.
    * ``kill_recovery`` a subprocess serving one LONG scenario
      (ckpt-segmented) is SIGKILLed mid-run after >= 2 snapshots; a
      restarted server replays the CRC'd journal, resumes from the
      snapshot, and must land on the BIT-IDENTICAL digest of an
      uninterrupted reference run.
    * ``cold_start``    time-to-first-result for a fresh process,
      traced-and-exported vs AOT-loaded (jax.export blobs keyed on
      bucket spec + config fingerprint): the AOT pass must reach full
      bucket coverage with ZERO compiles and bit-identical rows."""
    import io
    import signal
    import subprocess
    import tempfile
    import zlib

    import jax
    from go_libp2p_pubsub_tpu.serving import (FrontendConfig,
                                              ScenarioFrontend)

    n_reqs = int(os.environ.get("GOSSIP_SERVE_REQS", 2000))
    rps = float(os.environ.get("GOSSIP_SERVE_RPS", 400.0))
    kill_ticks = int(os.environ.get("GOSSIP_SERVE_KILL_TICKS", 400))
    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="gossip_serve_bench_")

    # -- load phase: Zipf shapes, Poisson arrivals ---------------------
    pool = [(256, 2, 8, 16), (128, 2, 4, 8), (256, 4, 8, 16),
            (64, 2, 4, 8), (256, 2, 8, 24)]
    zipf_a = 1.1
    w = np.array([1.0 / (r + 1) ** zipf_a for r in range(len(pool))])
    w /= w.sum()
    rng = np.random.default_rng(18)
    shape_ix = rng.choice(len(pool), size=n_reqs, p=w)
    gaps = rng.exponential(1.0 / rps, size=n_reqs)
    fe = ScenarioFrontend(FrontendConfig(
        max_buckets=4, batch=8, queue_cap=max(4 * n_reqs, 4096),
        server_kw={"seed": 0}))
    rows = []
    t_load = time.perf_counter()
    t_next = t_load
    for i in range(n_reqs):
        t_next += gaps[i]
        lag = t_next - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        n, t, m, ticks = pool[shape_ix[i]]
        req = {"id": f"r{i}", "n": n, "t": t, "m": m, "ticks": ticks,
               "seed": int(i % 64)}
        if i % 20 == 0:
            req["deadline_s"] = 0.05    # tight: times out under backlog
        if i % 10 == 0:
            req["priority"] = 1
        rej = fe.admit(req)
        if rej is not None:
            rows.append(rej)
        rows.extend(fe.dispatch_ready())
    rows.extend(fe.drain())
    load_wall = time.perf_counter() - t_load
    st = fe.stats()
    assert st["admitted"] == n_reqs and st["queued"] == 0, st
    assert (st["served"] + st["errors"] + st["timeouts"]
            + st["transient_failures"]) == n_reqs, st
    assert st["compiles"] == st["traced_buckets"] == len(pool), st
    assert st["evictions"] > 0, st     # the pool outnumbers the cap
    ok_rows = [r for r in rows if r.get("ok") and "queue_s" in r]
    assert all(r.get("inv_bits", 0) == 0 for r in ok_rows)
    q = np.array([r["queue_s"] for r in ok_rows])
    load = {
        "admitted": st["admitted"], "served": st["served"],
        "errors": st["errors"], "timeouts": st["timeouts"],
        "transient_failures": st["transient_failures"],
        "queued": st["queued"], "parked": st["parked"],
        "rejected_overload": st["rejected_overload"],
        "retries": st["retries"],
        "throughput_rps": round(st["served"] / load_wall, 2),
        "p50_queue_s": round(float(np.percentile(q, 50)), 4),
        "p99_queue_s": round(float(np.percentile(q, 99)), 4),
        "wall_s": round(load_wall, 2),
        "device_s": st["device_s"],
        "evictions": st["evictions"],
    }

    # -- overload phase: burst into a tiny admission cap ---------------
    # arrivals outrun service on purpose: one dispatch (<= one batch
    # of 8) per 16 admissions, so the queue crosses the cap and stays
    # there — admissions past it must come back as named rejections
    fe2 = ScenarioFrontend(FrontendConfig(
        max_buckets=2, batch=8, queue_cap=32, server_kw={"seed": 0}))
    over_rows = []
    for i in range(300):
        rej = fe2.admit({"id": f"o{i}", "n": 256, "t": 2, "m": 8,
                         "ticks": 16, "seed": int(i % 16)})
        if rej is not None:
            over_rows.append(rej)
        if i % 16 == 15:
            over_rows.extend(fe2.dispatch_ready())
    over_rows.extend(fe2.drain())
    st2 = fe2.stats()
    assert st2["rejected_overload"] > 0, st2
    assert all(r.get("overloaded") and "overloaded:" in r["error"]
               for r in over_rows if not r.get("ok")
               and not r.get("timeout")), over_rows
    assert (st2["admitted"] + st2["rejected_overload"] == 300
            and st2["queued"] == 0), st2
    overload = {
        "requests": 300, "queue_cap": 32,
        "admitted": st2["admitted"], "served": st2["served"],
        "errors": st2["errors"], "timeouts": st2["timeouts"],
        "transient_failures": st2["transient_failures"],
        "queued": st2["queued"], "parked": st2["parked"],
        "rejected_overload": st2["rejected_overload"],
        "reject_rate": round(st2["rejected_overload"] / 300, 4),
    }

    # -- kill recovery: SIGKILL mid-long-scenario, restart, digest ----
    kill_req = {"id": "kill1", "n": 256, "t": 2, "m": 8,
                "ticks": kill_ticks, "seed": 1}
    raw = json.dumps(kill_req, sort_keys=True)
    ckpt_dir = os.path.join(work, "ckpt")
    journal = os.path.join(work, "serve.journal")
    snapdir = os.path.join(
        ckpt_dir, f"kill1-{zlib.crc32(raw.encode()):08x}")
    env = dict(os.environ, JAX_PLATFORMS=jax.default_backend())
    long_ticks = kill_ticks // 2

    def kill_child(first):
        script = _SERVE_KILL_CHILD.format(
            repo=repo, long_ticks=long_ticks, ckpt_dir=ckpt_dir,
            every=2, line=raw, first=int(first), journal=journal)
        return subprocess.Popen([sys.executable, "-c", script],
                                stdout=subprocess.PIPE, text=True,
                                env=env)

    child = kill_child(first=True)
    deadline = time.time() + 300
    while time.time() < deadline:
        if (os.path.isdir(snapdir)
                and sum(f.endswith(".ckpt")
                        for f in os.listdir(snapdir)) >= 2):
            break
        if child.poll() is not None:
            raise AssertionError(
                "kill child finished before it could be killed: "
                + (child.communicate()[0] or ""))
        time.sleep(0.01)
    else:
        raise AssertionError("kill child never produced snapshots")
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=60)

    # the uninterrupted reference (different snapshot dir, different
    # segmentation — the digest must not depend on either)
    fe_ref = ScenarioFrontend(FrontendConfig(
        batch=2, max_buckets=2, long_ticks=long_ticks,
        ckpt_dir=os.path.join(work, "ckpt_ref"),
        ckpt_every=max(kill_ticks // 2, 1), server_kw={"seed": 0}))
    buf = io.StringIO()
    fe_ref.serve_lines([raw], buf)
    ref_row = next(json.loads(ln) for ln in buf.getvalue().splitlines()
                   if json.loads(ln).get("long"))
    assert ref_row["ok"], ref_row

    restart = kill_child(first=False)
    out, _ = restart.communicate(timeout=600)
    assert restart.returncode == 0, out
    parsed = [json.loads(ln) for ln in out.splitlines()]
    res_row = next(r for r in parsed if r.get("long"))
    res_stats = next(r for r in parsed if r.get("stats"))
    assert res_row["resumed"], res_row
    match = res_row["digest"] == ref_row["digest"]
    assert match, (res_row, ref_row)
    kill_recovery = {
        "ticks": kill_ticks, "sigkill": True,
        "admitted": res_stats["admitted"],
        "served": res_stats["served"],
        "errors": res_stats["errors"],
        "timeouts": res_stats["timeouts"],
        "transient_failures": res_stats["transient_failures"],
        "queued": res_stats["queued"], "parked": res_stats["parked"],
        "resumed": res_stats["long_resumed"],
        "digest": res_row["digest"], "digest_match": match,
    }

    # -- cold start: traced+exported vs AOT-loaded ---------------------
    aot_dir = os.path.join(work, "aot")

    def cold_child():
        script = _SERVE_COLD_CHILD.format(repo=repo, aot=aot_dir)
        r = subprocess.run([sys.executable, "-c", script],
                           capture_output=True, text=True, env=env,
                           timeout=900)
        assert r.returncode == 0, r.stderr
        return next(json.loads(ln) for ln in r.stdout.splitlines()
                    if json.loads(ln).get("cold"))

    traced = cold_child()     # empty cache: traces + exports blobs
    aot = cold_child()        # warm cache: loads blobs, zero compiles
    assert traced["compiles"] == traced["aot_exports"] == 2, traced
    assert aot["compiles"] == 0 and aot["aot_loads"] == 2, aot
    assert aot["rows"] == traced["rows"], (traced, aot)
    cold_start = {
        "buckets": 2,
        "traced_s": traced["first_result_s"],
        "traced_total_s": traced["total_s"],
        "aot_s": aot["first_result_s"],
        "aot_total_s": aot["total_s"],
        "speedup_x": round(traced["total_s"] / aot["total_s"], 2),
        "aot_compiles": aot["compiles"],
        "aot_loads": aot["aot_loads"],
        "bit_identical": aot["rows"] == traced["rows"],
    }

    import shutil
    shutil.rmtree(work, ignore_errors=True)
    backend = jax.default_backend()
    art = {
        "round": 18,
        "platform": backend,
        "hardware_queued": backend != "tpu",
        "requests": n_reqs,
        "zipf_a": zipf_a,
        "arrival_rps": rps,
        "shape_pool": [f"n{p[0]}-t{p[1]}-m{p[2]}-ticks{p[3]}"
                       for p in pool],
        "compiles": st["compiles"],
        "traced_buckets": st["traced_buckets"],
        "bucket_count": st["bucket_count"],
        "evictions": st["evictions"],
        "load": load,
        "overload": overload,
        "kill_recovery": kill_recovery,
        "cold_start": cold_start,
        "rows": [
            dict({"id": "load"}, **load),
            dict({"id": "overload"}, **overload),
            dict({"id": "kill_recovery"}, **kill_recovery),
            dict({"id": "cold_start"}, **cold_start),
        ],
    }
    write_json_atomic("/tmp/gossipsub_serving.json", art)
    emit("gossipsub_serving_throughput_rps", load["throughput_rps"],
         "requests/s",
         extra={"requests": n_reqs, "compiles": st["compiles"],
                "buckets": st["traced_buckets"],
                "p99_queue_s": load["p99_queue_s"],
                "reject_rate": overload["reject_rate"],
                "kill_recovery_ok": match,
                "cold_speedup_x": cold_start["speedup_x"]})
    emit("gossipsub_serving_cold_start_aot_s", cold_start["aot_s"],
         "s to first result",
         extra={"traced_s": cold_start["traced_s"],
                "aot_compiles": cold_start["aot_compiles"]})


def bench_gossipsub_metrics():
    """Round 19: the service observability plane under concurrent
    load.  Three phases, one artifact (/tmp/gossipsub_metrics.json)
    for the ``obsstat --check`` gate (measure_all step 4l):

    * ``fleet``   a real ``sweepd --multi --socket --metrics-port 0``
      subprocess served by tools/loadgen.py's multi-process client
      fleet while the parent scrapes /metrics.json MID-FLIGHT — every
      scrape, including ones taken while requests are queued between
      the fleet's concurrent connections, must satisfy the accounting
      identity (admitted == served + errors + timeouts + transient +
      queued + parked); the closing stats/metrics verbs cross-check
      the scrape against the front end's own counters field by field,
      and /trace.json must come back as loadable Chrome trace JSON.
    * ``spans``   an in-process front end driven through served /
      timed-out / overload-rejected requests: distinct trace count ==
      admissions (rejections never get a trace), every admitted trace
      reaches a terminal event, zero open spans and zero dropped
      events after the drain, and the exported trace file round-trips
      through json.load.
    * ``delay_parity``  the round-19 refusal lift, measured: identity
      delays (DelayConfig(1, 0, 1)) with counters armed vs the
      pre-delay step — max |diff| over all 11 counter fields must be
      0 — while a real delay spread shows the counters still flow."""
    import socket as sk
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import jax
    from go_libp2p_pubsub_tpu.serving import (FrontendConfig,
                                              ScenarioFrontend)
    from tools.loadgen import run_fleet

    procs = int(os.environ.get("GOSSIP_METRICS_PROCS", 3))
    per_proc = int(os.environ.get("GOSSIP_METRICS_REQS", 6))
    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="gossip_metrics_bench_")
    sock_path = os.path.join(work, "sweepd.sock")
    env = dict(os.environ, JAX_PLATFORMS=jax.default_backend())

    # -- fleet phase: live server, concurrent clients, live scrapes ---
    child = subprocess.Popen(
        [sys.executable, "-m", "tools.sweepd", "--multi",
         "--socket", sock_path, "--metrics-port", "0",
         "--batch", "2", "--peers", "64", "--topics", "1",
         "--msgs", "2", "--ticks", "4", "--max-buckets", "4"],
        cwd=repo, env=env, stderr=subprocess.PIPE, text=True)
    base_url = None
    try:
        for line in child.stderr:
            if "metrics at " in line:
                base_url = (line.strip().split("metrics at ", 1)[1]
                            .rsplit("/metrics", 1)[0])
            if "listening on" in line:
                break
        assert base_url, "sweepd never announced its metrics endpoint"
        threading.Thread(target=child.stderr.read,
                         daemon=True).start()

        ident_keys = ("served_total", "errors_total",
                      "deadline_timeouts_total",
                      "transient_failures_total", "queue_depth",
                      "parked")

        def scrape() -> dict:
            with urllib.request.urlopen(base_url + "/metrics.json",
                                        timeout=5) as r:
                fams = [json.loads(ln) for ln in
                        r.read().decode().splitlines()]
            vals = {}
            for fam in fams:
                if fam["kind"] == "histogram" or not fam["samples"]:
                    vals.setdefault(fam["name"], 0)
                    continue
                s = fam["samples"][0]
                if not s["labels"]:
                    vals[fam["name"]] = s["value"]
            admitted = vals.get("pubsub_serving_admitted_total", 0)
            accounted = sum(vals.get("pubsub_serving_" + k, 0)
                            for k in ident_keys)
            return dict(
                {k: vals.get("pubsub_serving_" + k, 0)
                 for k in ident_keys},
                admitted=admitted, accounted=accounted,
                identity_ok=admitted == accounted)

        fleet_box = {}

        def drive():
            fleet_box["out"] = run_fleet(
                sock_path, procs=procs, requests_per_proc=per_proc,
                connect_timeout_s=30.0)

        fleet_th = threading.Thread(target=drive)
        fleet_th.start()
        scrapes = [dict(scrape(), mid_flight=True)]
        while fleet_th.is_alive():
            time.sleep(0.25)
            scrapes.append(dict(scrape(), mid_flight=True))
        fleet_th.join()
        scrapes.append(dict(scrape(), mid_flight=False))
        fleet = fleet_box["out"]
        assert not fleet["worker_failures"], fleet["worker_failures"]
        sent = fleet["requests_sent"]
        assert len(fleet["rows"]) == sent, (len(fleet["rows"]), sent)
        assert all(s["identity_ok"] for s in scrapes), scrapes

        # cross-check: the line-protocol stats row vs the scrape,
        # field by field, on one quiet connection
        with sk.socket(sk.AF_UNIX, sk.SOCK_STREAM) as s:
            s.connect(sock_path)
            with s.makefile("r") as rf, s.makefile("w") as wf:
                wf.write('{"cmd": "stats"}\n{"cmd": "metrics"}\n')
                wf.flush()
                s.shutdown(sk.SHUT_WR)
                proto = [json.loads(ln) for ln in rf if ln.strip()]
        stats_row = next(r for r in proto if r.get("stats"))
        met_row = next(r for r in proto if r.get("metrics"))
        fam_map = {f["name"]: f for f in met_row["families"]}

        def fam_val(name):
            smp = fam_map["pubsub_" + name]["samples"]
            return smp[0]["value"] if smp else 0

        pairs = {"admitted": "serving_admitted_total",
                 "served": "serving_served_total",
                 "errors": "serving_errors_total",
                 "timeouts": "serving_deadline_timeouts_total",
                 "transient_failures":
                     "serving_transient_failures_total",
                 "rejected_overload":
                     "serving_overload_rejected_total",
                 "retries": "serving_retries_total",
                 "queued": "serving_queue_depth",
                 "parked": "serving_parked"}
        cross = {k: {"stats": stats_row[k], "scrape": fam_val(v)}
                 for k, v in pairs.items()}
        cross_match = all(v["stats"] == v["scrape"]
                          for v in cross.values())
        spans_live = met_row["spans"]
        spans_match = (spans_live["traces"] == stats_row["admitted"]
                       == sent)
        assert cross_match, cross
        assert spans_match, (spans_live, stats_row["admitted"], sent)

        with urllib.request.urlopen(base_url + "/trace.json",
                                    timeout=5) as r:
            trace = json.loads(r.read().decode())
        assert trace["traceEvents"], "empty live Chrome trace"
        trace_events = len(trace["traceEvents"])
    finally:
        child.terminate()
        try:
            child.wait(timeout=30)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait(timeout=30)

    fleet_phase = {
        "procs": procs, "requests_sent": sent,
        "rows_received": len(fleet["rows"]), "ok": fleet["ok"],
        "error_rows": fleet["errors"], "rps": fleet["rps"],
        "wall_s": fleet["wall_s"], "scrape_count": len(scrapes),
        "mid_flight_scrapes": sum(1 for s in scrapes
                                  if s["mid_flight"]),
        "identity_ok": all(s["identity_ok"] for s in scrapes),
        "cross_match": cross_match, "spans_match": spans_match,
        "trace_events": trace_events,
    }

    # -- span phase: served / timed-out / rejected, in-process --------
    fe = ScenarioFrontend(FrontendConfig(
        max_buckets=2, batch=2, queue_cap=6, server_kw={"seed": 0}))
    span_rows = []
    for i in range(10):
        req = {"id": f"s{i}", "n": 64, "t": 1, "m": 2, "ticks": 4,
               "seed": i}
        if i in (4, 5):
            req["deadline_s"] = 0.0    # culled at the next dispatch
        rej = fe.admit(req)
        if rej is not None:
            span_rows.append(rej)
        if i % 4 == 3:
            time.sleep(0.01)
            span_rows.extend(fe.dispatch_ready(force=True))
    span_rows.extend(fe.drain())
    st = fe.stats()
    summ = fe.obs.spans.summary()
    trace_path = "/tmp/gossipsub_metrics_trace.json"
    fe.obs.spans.write_chrome_trace(trace_path)
    with open(trace_path) as f:
        exported = json.load(f)
    rejected = sum(1 for r in span_rows if r.get("overloaded"))
    span_phase = {
        "requests": 10, "admitted": st["admitted"],
        "served": st["served"], "timeouts": st["timeouts"],
        "rejected_overload": st["rejected_overload"],
        "traces": summ["traces"], "terminal": summ["terminal"],
        "open_spans": summ["open_spans"],
        "dropped_events": summ["dropped_events"],
        "phases": summ["phases"],
        "exported_events": len(exported["traceEvents"]),
        "trace_path": trace_path,
    }
    assert summ["traces"] == st["admitted"] == 10 - rejected, span_phase
    assert summ["terminal"] == st["admitted"], span_phase
    assert summ["open_spans"] == 0 == summ["dropped_events"], span_phase
    assert st["timeouts"] > 0, span_phase

    # -- delay parity: the lifted counters-group refusal, measured ----
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.models.delays import DelayConfig

    fields = ("payload_sent", "ihave_rpcs", "ihave_ids", "iwant_rpcs",
              "iwant_ids_requested", "iwant_ids_served", "graft_sends",
              "prune_sends", "dup_suppressed", "bytes_payload",
              "bytes_control")
    pn, pt, pm, pticks = 64, 2, 4, 6
    subs = np.zeros((pn, pt), dtype=bool)
    subs[np.arange(pn), np.arange(pn) % pt] = True
    rng = np.random.default_rng(0)
    ptopic = rng.integers(0, pt, pm)
    porigin = rng.integers(0, pn // pt, pm) * pt + ptopic
    ptks = np.zeros(pm, dtype=np.int32)
    pcfg = gs.GossipSimConfig(
        offsets=gs.make_gossip_offsets(pt, 8, pn, seed=1),
        n_topics=pt, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
        d_lazy=2, backoff_ticks=8)
    psc = gs.ScoreSimConfig()

    def counter_totals(delays):
        kw = dict(score_cfg=psc, delays=delays)
        if delays is not None:
            kw["delays_counters"] = True
        params, state = gs.make_gossip_sim(pcfg, subs, ptopic,
                                           porigin, ptks, **kw)
        step = gs.make_gossip_step(pcfg, psc,
                                   telemetry=tl.TelemetryConfig())
        out = []
        for _ in range(pticks):
            state, _d, frame = step(params, state)
            out.append(np.array([np.asarray(getattr(frame, f)).sum()
                                 for f in fields], dtype=np.int64))
        return np.stack(out)

    t0 = time.perf_counter()
    ref = counter_totals(None)
    idn = counter_totals(DelayConfig(base=1, jitter=0, k_slots=1))
    spread = counter_totals(DelayConfig(base=2, jitter=1, k_slots=4))
    parity_s = time.perf_counter() - t0
    max_abs_diff = int(np.abs(ref - idn).max())
    delay_parity = {
        "fields": len(fields), "ticks": pticks,
        "max_abs_diff": max_abs_diff,
        "identity_counter_total": int(idn.sum()),
        "delayed_counter_total": int(spread.sum()),
        "wall_s": round(parity_s, 2),
    }
    assert max_abs_diff == 0, delay_parity
    assert spread.sum() > 0, delay_parity

    import shutil
    shutil.rmtree(work, ignore_errors=True)
    backend = jax.default_backend()
    art = {
        "round": 19,
        "platform": backend,
        "hardware_queued": backend != "tpu",
        "fleet": fleet_phase,
        "scrapes": scrapes,
        "cross_check": cross,
        "spans": span_phase,
        "delay_parity": delay_parity,
        "rows": [
            dict({"id": "fleet"}, **fleet_phase),
            dict({"id": "spans"}, **span_phase),
            dict({"id": "delay_parity"}, **delay_parity),
        ],
    }
    write_json_atomic("/tmp/gossipsub_metrics.json", art)
    emit("gossipsub_metrics_fleet_rps", fleet["rps"], "requests/s",
         extra={"procs": procs, "requests": sent,
                "mid_flight_scrapes":
                    fleet_phase["mid_flight_scrapes"],
                "identity_ok": fleet_phase["identity_ok"],
                "cross_match": cross_match,
                "trace_events": trace_events})
    emit("gossipsub_metrics_delay_parity_diff", float(max_abs_diff),
         "counter units",
         extra={"fields": len(fields),
                "delayed_counter_total":
                    delay_parity["delayed_counter_total"]})


BENCHES = {
    "floodsub_hosts": bench_floodsub_hosts,
    "randomsub_10k": bench_randomsub_10k,
    "gossipsub_v10": bench_gossipsub_v10,
    "gossipsub_v11": bench_gossipsub_v11,
    "gossipsub_v11_batched": bench_gossipsub_v11_batched,
    "gossipsub_v11_multitopic": bench_gossipsub_v11_multitopic,
    "gossipsub_v11_adversarial": bench_gossipsub_v11_adversarial,
    "gossipsub_v11_everything": bench_gossipsub_v11_everything,
    "gossipsub_v11_churn": bench_gossipsub_v11_churn,
    "gossipsub_v11_churn_kernel": bench_gossipsub_v11_churn_kernel,
    "gossipsub_telemetry": bench_gossipsub_telemetry,
    "gossipsub_telemetry_kernel": bench_gossipsub_telemetry_kernel,
    "gossipsub_trace_export": bench_gossipsub_trace_export,
    "gossipsub_trace_export_kernel": bench_gossipsub_trace_export_kernel,
    "gossipsub_tournament": bench_gossipsub_tournament,
    "gossipsub_invariants": bench_gossipsub_invariants,
    "gossipsub_invariants_kernel": bench_gossipsub_invariants_kernel,
    "gossipsub_sweepd": bench_gossipsub_sweepd,
    "gossipsub_sweepd_kernel": bench_gossipsub_sweepd_kernel,
    "gossipsub_pipelined": bench_gossipsub_pipelined,
    "gossipsub_multichip": bench_gossipsub_multichip,
    "gossipsub_checkpoint": bench_gossipsub_checkpoint,
    "gossipsub_resident": bench_gossipsub_resident,
    "gossipsub_resident_sharded": bench_gossipsub_resident_sharded,
    "gossipsub_serving": bench_gossipsub_serving,
    "gossipsub_metrics": bench_gossipsub_metrics,
}


def main():
    # Deferred SIGTERM/SIGINT (round 15, op-note #2): a preempted
    # suite finishes the in-flight segment/bench, flushes what it has,
    # and exits 0 — ``timeout -k`` never SIGKILLs a mid-operation TPU
    # client.  Segmented runs snapshot via CheckpointInterrupt; plain
    # benches stop cleanly at the next bench boundary.
    from go_libp2p_pubsub_tpu.parallel import checkpoint as _ck
    _ck.install_kill_handlers()
    which = sys.argv[1:] or list(BENCHES)
    for name in which:
        try:
            BENCHES[name]()
        except _ck.CheckpointInterrupt as e:
            print(json.dumps({"metric": f"{name}_interrupted",
                              "resume_snapshot": e.path,
                              "ticks_done": e.ticks_done}), flush=True)
            return
        if _ck.stop_requested():
            print(json.dumps({"metric": "suite_stopped_after",
                              "bench": name}), flush=True)
            return


if __name__ == "__main__":
    main()
