#!/usr/bin/env python
"""residentstat: inspect a tick-resident megakernel bench artifact and
gate the round-16 residency contract (and, with ``--sharded``, the
round-17 residency-x-sharding composition) against a committed
baseline.

    python tools/residentstat.py /tmp/gossipsub_resident.json
    python tools/residentstat.py /tmp/gossipsub_resident.json \
        --check RESIDENT_r16.json [--min-reduction 5.0]
    python tools/residentstat.py /tmp/gossipsub_resident_sharded.json \
        --sharded --check RESIDENT_r17.json

Prints the round-16 table: the per-tick kernel row vs the fused
T-tick-window row (wall-clock, digest, compile count) and the analytic
byte ledger (per-tick HBM bytes unfused vs fused, the VMEM working set
and its budget verdict, at the bench shape plus the 100k/1M points).
The contract being gated is the round-16 tentpole: the fused
trajectory is BIT-IDENTICAL to the per-tick kernel's, the whole fused
run is ONE compiled executable, and everywhere the resident carry fits
the VMEM budget at >= 100k peers the per-tick HBM traffic drops by at
least --min-reduction x (the ledger is analytic —
ops/pallas/receive.fused_working_set_bytes — because the pallas body
is opaque to XLA's bytes-accessed counter).

With ``--sharded`` the round-17 contract is gated on top: the
artifact must carry at least one ``fused_sharded_D*`` row (each
digest-identical to the per-tick reference and ONE compile — the
in-kernel halo exchange is a scheduling change), the per-(n, devices)
``fits_table``, and the ``multiplicative`` headline object whose 1M
point flips from NOT-fitting at D=1 to FITTING at D=8 (the
composition's reason to exist); --check additionally refuses
fits-table coverage shrink, a fitting baseline point going REFUSED,
and a shrinking multiplicative saving.

Exit codes (tracestat/tourneystat/sweepstat/delaystat/shardstat/
ckptstat convention):

  0  clean
  1  regression: fused digest differing from the per-tick kernel row
     (residency changed the arithmetic), a fused run that compiled
     more than one executable (re-trace per window), a fitting
     >= 100k-peer ledger point under --min-reduction x, a --sharded
     1M flip that no longer flips, or (with --check) a baseline
     row/ledger/fits-table point missing from the current artifact, a
     baseline-true bit_identical or fits flag going false, or a
     reduction/multiplicative shrinking vs the committed baseline
  2  unusable input: missing/unparseable artifact, no rows, no
     unfused reference row, no fused row, an empty byte ledger, or
     (with --sharded) no fused-sharded row, no fits_table, or no
     multiplicative object
"""

from __future__ import annotations

import argparse
import json
import sys


def load(path: str, prog: str = "residentstat") -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{prog}: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)
    rows = obj.get("rows") if isinstance(obj, dict) else None
    if not rows or not isinstance(rows, list):
        print(f"{prog}: {path} carries no rows", file=sys.stderr)
        raise SystemExit(2)
    if not any(isinstance(r, dict) and r.get("id") == "unfused_kernel"
               for r in rows):
        print(f"{prog}: {path} has no per-tick kernel reference row — "
              "fused bit-identity has no reference", file=sys.stderr)
        raise SystemExit(2)
    if not any(isinstance(r, dict)
               and str(r.get("id", "")).startswith("fused_")
               for r in rows):
        print(f"{prog}: {path} has no fused-window row", file=sys.stderr)
        raise SystemExit(2)
    if not obj.get("ledger"):
        print(f"{prog}: {path} carries no byte ledger — the residency "
              "win is unmeasured", file=sys.stderr)
        raise SystemExit(2)
    return obj


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="residentstat",
                                 description=__doc__)
    ap.add_argument("artifact")
    ap.add_argument("--check", metavar="BASELINE",
                    help="committed baseline artifact to gate against")
    ap.add_argument("--min-reduction", type=float, default=5.0,
                    help="minimum per-tick HBM-bytes reduction (x) at "
                         "every fitting >= 100k-peer ledger point "
                         "(default 5.0 — the round-16 acceptance bar)")
    ap.add_argument("--sharded", action="store_true",
                    help="gate the round-17 residency-x-sharding "
                         "composition: fused_sharded_D* rows, the "
                         "per-(n, devices) fits table, and the 1M "
                         "multiplicative flip")
    ns = ap.parse_args(argv)

    cur = load(ns.artifact)
    rows = [r for r in cur["rows"] if isinstance(r, dict)]
    unfused = next(r for r in rows if r.get("id") == "unfused_kernel")
    shape = cur.get("shape", {})
    print(f"tick-resident megakernel: {shape.get('n')} peers x "
          f"{shape.get('t')} topics, {shape.get('ticks')} ticks in "
          f"T={shape.get('ticks_fused')} windows, "
          f"platform={cur.get('platform')}"
          f"{' (interpret)' if cur.get('interpret') else ''}"
          f"{', hardware row queued' if cur.get('hardware_queued') else ''}")
    for r in rows:
        extra = ""
        if r.get("compiles") is not None:
            extra += f"  compiles={r['compiles']}"
        if r.get("heartbeats_per_sec") is not None:
            extra += f"  {r['heartbeats_per_sec']} hb/s"
        print(f"  {r['id']:<16s} wall={r.get('wall_s', 0):.3f}s "
              f"digest={r.get('digest')} "
              f"bit_identical={r.get('bit_identical')}{extra}")
    ledger = [e for e in cur["ledger"] if isinstance(e, dict)]
    for e in ledger:
        verdict = ("FITS" if e.get("fits")
                   else "REFUSED (past VMEM budget)")
        print(f"  ledger n={e['n']:>8d}: "
              f"{e.get('unfused_hbm_bytes_per_tick', 0) / 1e6:9.1f} MB"
              f" -> {e.get('fused_hbm_bytes_per_tick', 0) / 1e6:8.1f}"
              f" MB /tick ({e.get('hbm_reduction_x')}x)  "
              f"vmem={e.get('vmem_bytes', 0) / 1e6:.1f} MB {verdict}")

    fits_table = [e for e in cur.get("fits_table", [])
                  if isinstance(e, dict)]
    mult = cur.get("multiplicative")
    if ns.sharded:
        if not any(str(r.get("id", "")).startswith("fused_sharded_D")
                   for r in rows):
            print("residentstat: --sharded artifact has no "
                  "fused_sharded_D* row — the composition is "
                  "unmeasured", file=sys.stderr)
            return 2
        if not fits_table or not isinstance(mult, dict):
            print("residentstat: --sharded artifact carries no "
                  "fits_table/multiplicative — the per-(n, devices) "
                  "ledger is missing", file=sys.stderr)
            return 2
        for e in fits_table:
            if "refused" in e:
                print(f"  fits n={e['n']:>8d} D={e['devices']}: "
                      f"REFUSED by name ({e['refused'][:64]}...)")
                continue
            print(f"  fits n={e['n']:>8d} D={e['devices']}: "
                  f"vmem={e.get('vmem_bytes', 0) / 1e6:6.1f} MB "
                  f"{'FITS   ' if e.get('fits') else 'REFUSED'} "
                  f"halo={e.get('boundary_bytes_per_tick', 0) / 1e6:.1f}"
                  f" MB/tick  {e.get('hbm_reduction_x')}x -> "
                  f"{e.get('multiplicative_x')}x multiplicative")
        print(f"  multiplicative: n={mult.get('n')} "
              f"D={mult.get('devices')} "
              f"{mult.get('multiplicative_x')}x "
              f"(first fits at D={mult.get('first_fits_devices')})")

    rc = 0
    for r in rows:
        if r["id"] == "unfused_kernel":
            continue
        if r.get("digest") != unfused.get("digest") \
                or not r.get("bit_identical"):
            print(f"residentstat: {r['id']} digest {r.get('digest')} "
                  f"!= per-tick kernel {unfused.get('digest')} — "
                  "residency changed the trajectory", file=sys.stderr)
            rc = 1
        if r.get("compiles") is not None and r["compiles"] > 1:
            print(f"residentstat: {r['id']} compiled {r['compiles']} "
                  "executables — fused windows must share ONE "
                  "(re-trace per window regression)", file=sys.stderr)
            rc = 1
    for e in ledger:
        if (e.get("fits") and e.get("n", 0) >= 100_000
                and e.get("hbm_reduction_x", 0.0) < ns.min_reduction):
            print(f"residentstat: ledger n={e['n']} reduction "
                  f"{e.get('hbm_reduction_x')}x under the "
                  f"{ns.min_reduction}x bar — the resident window no "
                  "longer amortizes the carry traffic",
                  file=sys.stderr)
            rc = 1
    if ns.sharded:
        fbd = mult.get("fits_by_devices", {})
        if fbd.get("1") is not False or fbd.get("8") is not True:
            print("residentstat: the 1M multiplicative flip is gone — "
                  f"fits_by_devices={fbd} (want the D=1 carry past "
                  "the budget and the D=8 per-shard carry fitting)",
                  file=sys.stderr)
            rc = 1

    if ns.check:
        base = load(ns.check)
        base_rows = {r["id"]: r for r in base["rows"]
                     if isinstance(r, dict)}
        cur_ids = {r["id"] for r in rows}
        missing = set(base_rows) - cur_ids
        if missing:
            print("residentstat: row coverage shrank vs baseline: "
                  f"missing {sorted(missing)}", file=sys.stderr)
            rc = 1
        for rid, ref in sorted(base_rows.items()):
            r = next((x for x in rows if x["id"] == rid), None)
            if r is None:
                continue
            if ref.get("bit_identical") and not r.get("bit_identical"):
                print(f"residentstat: {rid} was bit_identical in the "
                      "baseline and no longer is", file=sys.stderr)
                rc = 1
            verdict = "OK" if r.get("bit_identical") else "REGRESSED"
            print(f"check: {rid} bit_identical="
                  f"{r.get('bit_identical')} vs baseline "
                  f"{ref.get('bit_identical')} -> {verdict}")
        base_ledger = {e["n"]: e for e in base.get("ledger", [])
                       if isinstance(e, dict)}
        cur_ledger = {e["n"]: e for e in ledger}
        lmissing = set(base_ledger) - set(cur_ledger)
        if lmissing:
            print("residentstat: ledger coverage shrank vs baseline: "
                  f"missing n={sorted(lmissing)}", file=sys.stderr)
            rc = 1
        for n_l, ref in sorted(base_ledger.items()):
            e = cur_ledger.get(n_l)
            if e is None:
                continue
            got = e.get("hbm_reduction_x", 0.0)
            want = ref.get("hbm_reduction_x", 0.0)
            if ref.get("fits") and got < want:
                print(f"residentstat: ledger n={n_l} reduction "
                      f"{got}x shrank vs baseline {want}x — carry "
                      "bytes grew or the window shortened",
                      file=sys.stderr)
                rc = 1
            print(f"check: ledger n={n_l} {got}x vs baseline {want}x "
                  f"-> {'OK' if not ref.get('fits') or got >= want else 'REGRESSED'}")
        if ns.sharded:
            base_ft = {(e["n"], e["devices"]): e
                       for e in base.get("fits_table", [])
                       if isinstance(e, dict)}
            cur_ft = {(e["n"], e["devices"]): e for e in fits_table}
            fmissing = set(base_ft) - set(cur_ft)
            if fmissing:
                print("residentstat: fits-table coverage shrank vs "
                      f"baseline: missing (n, D)={sorted(fmissing)}",
                      file=sys.stderr)
                rc = 1
            for key, ref in sorted(base_ft.items()):
                e = cur_ft.get(key)
                if e is None or "refused" in ref:
                    continue
                if ref.get("fits") and not e.get("fits"):
                    print(f"residentstat: fits n={key[0]} D={key[1]} "
                          "fit in the baseline and no longer does — "
                          "the per-shard working set grew past the "
                          "budget", file=sys.stderr)
                    rc = 1
                got = e.get("multiplicative_x", 0.0)
                want = ref.get("multiplicative_x", 0.0)
                if ref.get("fits") and got < want:
                    print(f"residentstat: fits n={key[0]} D={key[1]} "
                          f"multiplicative {got}x shrank vs baseline "
                          f"{want}x", file=sys.stderr)
                    rc = 1
            bm = base.get("multiplicative") or {}
            got = (mult or {}).get("multiplicative_x", 0.0)
            want = bm.get("multiplicative_x", 0.0)
            print(f"check: multiplicative {got}x vs baseline {want}x "
                  f"-> {'OK' if got >= want else 'REGRESSED'}")
            if got < want:
                print(f"residentstat: the headline multiplicative "
                      f"saving {got}x shrank vs baseline {want}x",
                      file=sys.stderr)
                rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
