#!/usr/bin/env python
"""loadgen: a multi-process client fleet for the sweepd socket server
(round 19).

Forks ``--procs`` worker processes, each holding its OWN connection to
a ``sweepd --socket`` (round 19's thread-per-connection loop serves
them concurrently against the one resident server), each writing
``--requests`` JSON request lines and reading result rows until the
server's EOF drain.  The parent merges every worker's rows and reports
the fleet totals: requests sent, terminal rows received, error rows,
and requests/second over the fleet wall clock.

Row accounting across a concurrent fleet: the front end's dispatch
batches mix requests from different connections, and a drain triggered
on one connection emits rows for requests admitted on another — so
PER-WORKER row counts vary, but the fleet TOTAL of terminal rows
equals the total of requests sent (the no-silent-drop identity,
observed from the client side).  bench_suite's ``gossipsub_metrics``
bench drives this fleet while scraping ``--metrics-port`` mid-flight.

    python tools/loadgen.py /tmp/sweepd.sock --procs 4 --requests 8

Import-light on purpose (stdlib only, no jax): the fleet is the
CLIENT side.  ``run_fleet`` is the embeddable face.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import socket
import sys
import time

sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]

__all__ = ["run_fleet", "main"]


def _default_request(worker: int, i: int) -> dict:
    """A small short-path request; ids are fleet-unique so rows can be
    joined back to their request no matter which connection emitted
    them."""
    return {"id": f"w{worker}-r{i}", "n": 64, "t": 1, "m": 2,
            "ticks": 4, "seed": (worker * 1_000_003 + i) % 2**31}


def _connect(path: str, timeout_s: float) -> socket.socket:
    deadline = time.monotonic() + timeout_s
    while True:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.connect(path)
            return sock
        except (FileNotFoundError, ConnectionRefusedError):
            sock.close()
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


def _worker(path: str, worker: int, n_requests: int, make_request,
            connect_timeout_s: float, queue) -> None:
    rows: list = []
    err = None
    try:
        sock = _connect(path, connect_timeout_s)
        with sock, sock.makefile("r") as rf, sock.makefile("w") as wf:
            for i in range(n_requests):
                wf.write(json.dumps(make_request(worker, i)) + "\n")
            wf.flush()
            # half-close: the server sees EOF, drains (rows for
            # requests still queued — possibly admitted on OTHER
            # connections — come back here), and closes
            sock.shutdown(socket.SHUT_WR)
            for line in rf:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    except Exception as e:  # graftlint: ignore[broad-except]
        # any worker failure is surfaced in the parent's summary
        err = f"{e.__class__.__name__}: {e}"
    queue.put({"worker": worker, "rows": rows, "error": err})


def run_fleet(socket_path: str, *, procs: int = 4,
              requests_per_proc: int = 8, make_request=None,
              connect_timeout_s: float = 10.0) -> dict:
    """Drive ``procs`` forked clients, ``requests_per_proc`` requests
    each, against a listening sweepd socket.  Returns the merged
    summary: ``rows`` (every terminal row the fleet received, fleet
    order unspecified), ``stats_rows`` (one final counters row per
    connection), ``ok``/``errors`` row counts, ``worker_failures``,
    and ``rps`` over the fleet wall clock."""
    if procs < 1 or requests_per_proc < 1:
        raise ValueError(
            f"loadgen: procs={procs} and requests_per_proc="
            f"{requests_per_proc} must both be >= 1")
    make_request = make_request or _default_request
    ctx = mp.get_context("fork")
    queue = ctx.Queue()
    t0 = time.perf_counter()
    workers = [
        ctx.Process(target=_worker,
                    args=(socket_path, w, requests_per_proc,
                          make_request, connect_timeout_s, queue),
                    daemon=True)
        for w in range(procs)
    ]
    for p in workers:
        p.start()
    results = [queue.get() for _ in workers]
    for p in workers:
        p.join(timeout=30)
    wall = time.perf_counter() - t0

    rows, stats_rows, failures = [], [], []
    for res in sorted(results, key=lambda r: r["worker"]):
        if res["error"]:
            failures.append({"worker": res["worker"],
                             "error": res["error"]})
        for row in res["rows"]:
            (stats_rows if row.get("stats") else rows).append(row)
    ok = sum(1 for r in rows if r.get("ok"))
    sent = procs * requests_per_proc
    return {
        "procs": procs,
        "requests_sent": sent,
        "rows": rows,
        "stats_rows": stats_rows,
        "ok": ok,
        "errors": len(rows) - ok,
        "worker_failures": failures,
        "wall_s": round(wall, 3),
        "rps": round(sent / wall, 2) if wall else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="loadgen", description=__doc__)
    ap.add_argument("socket", help="sweepd --socket path")
    ap.add_argument("--procs", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per worker process")
    ap.add_argument("--connect-timeout", type=float, default=10.0)
    ns = ap.parse_args(argv)
    out = run_fleet(ns.socket, procs=ns.procs,
                    requests_per_proc=ns.requests,
                    connect_timeout_s=ns.connect_timeout)
    summary = {k: v for k, v in out.items()
               if k not in ("rows", "stats_rows")}
    summary["rows_received"] = len(out["rows"])
    print(json.dumps(summary, indent=2))
    # client-side no-silent-drop check: every request sent came back
    # as exactly one terminal row somewhere in the fleet
    if summary["rows_received"] != out["requests_sent"] \
            or out["worker_failures"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
