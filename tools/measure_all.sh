#!/bin/bash
# One recovery-day measurement pass: strictly sequential TPU processes,
# generous timeouts (never kill mid-run unless truly wedged).
#
# Ordered so the highest-value artifacts land FIRST — the tunnel has
# died mid-session twice (PERF_NOTES operational notes), so a pass that
# aborts halfway should still leave the kernel-identity artifact and
# the flagship bench number behind.  The log is copied into the repo
# after every step for the same reason.
set -u
cd /root/repo
log=/tmp/measure_all.log
: > "$log"
sync_log() { cp "$log" /root/repo/MEASURE_RECOVERY.log; }
trap sync_log EXIT
port_open() {
  (exec 3<>/dev/tcp/127.0.0.1/"${AXON_PROBE_PORT:-8082}") 2>/dev/null \
    && exec 3>&- 3<&-
}
run() {
  local t="$1"; shift
  # MEASURE_DEADLINE (epoch secs): stop starting new TPU steps near the
  # driver's own end-of-round bench window — two concurrent TPU clients
  # wedge the tunnel (PERF_NOTES operational notes)
  if [ "$(date +%s)" -gt "${MEASURE_DEADLINE:-9999999999}" ]; then
    echo "!! measurement deadline passed — leaving the chip free" \
      | tee -a "$log"
    sync_log
    exit 3
  fi
  echo "=== $* ===" | tee -a "$log"
  timeout -k 30 "$t" "$@" 2>&1 | grep -v WARNING | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "--- rc=$rc ---" | tee -a "$log"
  sync_log
  # the relay has died mid-session twice; once it's gone every further
  # step just burns its full timeout against a dead backend — abort,
  # the watcher re-arms and reruns the pass from the top on recovery
  if ! port_open; then
    echo "!! relay port closed — aborting measurement pass" | tee -a "$log"
    sync_log
    exit 2
  fi
}
# 1. hardware kernel-identity artifact (small run, judge deliverable)
run 1800 python tools/kernel_identity.py 200000 KERNEL_IDENTITY_r05.json
# 2. the flagship driver metric — forced-XLA so the pass ALWAYS
# produces a plain flagship row for pick_bench_path to compare against
# (a committed kernel pin would otherwise make bench.py emit only the
# _kernel row and the picker would clear a still-valid pin)
run 1800 env GOSSIP_BENCH_KERNEL=0 python bench.py
# 3. XLA vs kernel timing at 1M (decides the default path)
run 2700 python tools/bench_kernel.py 1000000 xla kernel kernela
run 2700 python tools/bench_kernel.py 1000000 kernela --noroll
# 4. the bench-suite rows, both paths
run 2700 python bench_suite.py gossipsub_v10 gossipsub_v11_multitopic \
    gossipsub_v11_adversarial gossipsub_v11_everything
run 2700 env GOSSIP_BENCH_KERNEL=1 python bench_suite.py gossipsub_v11 \
    gossipsub_v11_adversarial gossipsub_v11_multitopic \
    gossipsub_v11_everything
# 5. GSPMD overhead + diagnostics
run 1800 python tools/bench_sharded.py
run 1800 python tools/bench_micro.py 1000000 100
run 1800 python tools/profile_trace.py 1000000 xla
echo DONE | tee -a "$log"
