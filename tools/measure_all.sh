#!/bin/bash
# One recovery-day measurement pass: strictly sequential TPU processes,
# generous timeouts (never kill mid-run unless truly wedged).
set -u
cd /root/repo
log=/tmp/measure_all.log
: > "$log"
run() {
  echo "=== $* ===" | tee -a "$log"
  timeout -k 10 1800 "$@" 2>&1 | grep -v WARNING | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "--- rc=$rc ---" | tee -a "$log"
}
run python tools/bench_kernel.py 1000000 xla kernel kernela
run python tools/bench_kernel.py 1000000 kernela --noroll
run python tools/kernel_identity.py 200000 KERNEL_IDENTITY_r05.json
run python tools/bench_sharded.py
run python tools/bench_micro.py 1000000 100
run python tools/profile_trace.py 1000000 xla
run python bench.py
run python bench_suite.py gossipsub_v10 gossipsub_v11_multitopic \
    gossipsub_v11_adversarial gossipsub_v11_everything
run env GOSSIP_BENCH_KERNEL=1 python bench_suite.py gossipsub_v11 \
    gossipsub_v11_adversarial gossipsub_v11_multitopic \
    gossipsub_v11_everything
echo DONE | tee -a "$log"
