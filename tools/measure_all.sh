#!/bin/bash
# One recovery-day measurement pass: strictly sequential TPU processes,
# generous timeouts (never kill mid-run unless truly wedged).
#
# Ordered so the highest-value artifacts land FIRST — the tunnel has
# died mid-session twice (PERF_NOTES operational notes), so a pass that
# aborts halfway should still leave the kernel-identity artifact and
# the flagship bench number behind.  The log is copied into the repo
# after every step for the same reason.
#
# Round 15: the pass is RESUMABLE.  Every completed bench step is
# journaled (keyed on the git HEAD it ran under); when the watcher
# re-arms after an abort it reruns this script, which skips the
# already-completed steps instead of restarting from step 0 — and the
# segmented checkpoint bench additionally resumes mid-run from its own
# snapshots (parallel/checkpoint.py).  The cheap CPU gates re-run on
# every resume (their /tmp artifacts survive the completed steps).
# The journal lives in /tmp on purpose: a reboot clears it together
# with the artifacts it vouches for.
set -u
cd /root/repo
log=/tmp/measure_all.log
: > "$log"
sync_log() { cp "$log" /root/repo/MEASURE_RECOVERY.log; }
trap sync_log EXIT
journal=/tmp/measure_all.steps
head_sha=$(git rev-parse HEAD 2>/dev/null || echo none)
if [ -f "$journal" ] && [ "$(head -n1 "$journal" 2>/dev/null)" = "$head_sha" ]; then
  echo "=== resuming measure chain: $(grep -c '^done ' "$journal") step(s)" \
       "already completed under $head_sha ===" | tee -a "$log"
else
  printf '%s\n' "$head_sha" > "$journal"
fi
step_done() { echo "done $1" >> "$journal"; }
step_skip() { grep -qx "done $1" "$journal"; }
port_open() {
  (exec 3<>/dev/tcp/127.0.0.1/"${AXON_PROBE_PORT:-8082}") 2>/dev/null \
    && exec 3>&- 3<&-
}
# Relay-death handling: the relay has died mid-session twice, and once
# it is gone every further step just burns its full timeout against a
# dead backend.  Instead of aborting the whole pass on the first
# failure, wait for the relay to come back with CAPPED EXPONENTIAL
# BACKOFF (30s doubling to a 480s cap, ~25 min total), logging each
# retry; only when the budget is exhausted abort the pass (the watcher
# re-arms and reruns it — resuming from the journal, not from step 0).
wait_for_relay() {
  local delay=30 attempt=0
  while [ "$attempt" -lt 7 ]; do
    if port_open; then
      [ "$attempt" -gt 0 ] && \
        echo "!! relay back after $attempt retries" | tee -a "$log"
      return 0
    fi
    attempt=$((attempt + 1))
    echo "!! relay port closed — retry #$attempt in ${delay}s" \
      | tee -a "$log"
    sync_log
    sleep "$delay"
    delay=$((delay * 2))
    [ "$delay" -gt 480 ] && delay=480
    if [ "$(date +%s)" -gt "${MEASURE_DEADLINE:-9999999999}" ]; then
      echo "!! deadline passed while waiting for relay" | tee -a "$log"
      return 1
    fi
  done
  return 1
}
# run <step-id> <timeout> cmd...: journaled TPU step.  KILL_GRACE (the
# ``timeout -k`` window, default 30s) is sized per step so a SIGTERMed
# client can finish its in-flight segment and flush its snapshot —
# SIGKILLing a mid-operation TPU client is exactly the op-note #2
# tunnel-wedge failure mode.
run() {
  local id="$1" t="$2"; shift 2
  if step_skip "$id"; then
    echo "=== skip $id ($*) — completed earlier this pass ===" \
      | tee -a "$log"
    return 0
  fi
  # MEASURE_DEADLINE (epoch secs): stop starting new TPU steps near the
  # driver's own end-of-round bench window — two concurrent TPU clients
  # wedge the tunnel (PERF_NOTES operational notes)
  if [ "$(date +%s)" -gt "${MEASURE_DEADLINE:-9999999999}" ]; then
    echo "!! measurement deadline passed — leaving the chip free" \
      | tee -a "$log"
    sync_log
    exit 3
  fi
  echo "=== $* ===" | tee -a "$log"
  timeout -k "${KILL_GRACE:-30}" "$t" "$@" 2>&1 | grep -v WARNING | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "--- rc=$rc ---" | tee -a "$log"
  sync_log
  if ! port_open; then
    if ! wait_for_relay; then
      echo "!! relay stayed dead — aborting measurement pass" \
        | tee -a "$log"
      sync_log
      exit 2
    fi
    # the relay died DURING the step above, so its artifact may be
    # truncated: re-run that one step once on the recovered relay
    echo "=== retrying after relay recovery: $* ===" | tee -a "$log"
    timeout -k "${KILL_GRACE:-30}" "$t" "$@" 2>&1 | grep -v WARNING | tee -a "$log"
    rc=${PIPESTATUS[0]}
    echo "--- retry rc=$rc ---" | tee -a "$log"
    sync_log
    # flapping relay: if it died AGAIN during the retry, abort the
    # pass now rather than letting the next step burn its full
    # timeout against a dead backend (the watcher re-arms with its
    # own backoff and reruns the pass — journal intact)
    if ! port_open; then
      echo "!! relay died again during the retry — aborting pass" \
        | tee -a "$log"
      sync_log
      exit 2
    fi
  fi
  [ "$rc" -eq 0 ] && step_done "$id"
  return 0
}
# 0. lint preflight (CPU-only, seconds): a measurement pass burning
# chip-hours from a tree that doesn't even lint is a wasted window —
# fail fast before the first TPU step (tools/lint.sh: pinned ruff
# config, stdlib fallback where ruff isn't installed)
echo "=== lint preflight ===" | tee -a "$log"
bash tools/lint.sh 2>&1 | tee -a "$log"
if [ "${PIPESTATUS[0]}" -ne 0 ]; then
  echo "!! lint preflight failed — fix findings before measuring" \
    | tee -a "$log"
  sync_log
  exit 4
fi
# 0.5. graftlint preflight (CPU-only, ~1 min): the JAX-specific static
# suite — AST rules, the abstract-eval audit over the full simulator
# config matrix (no sim executed), the config thread-or-refuse
# contracts, and the capability-lattice plan audit (every lattice cell
# must PLAN or REFUSE exactly as models/plan.py says).  Exactly the
# silent regressions (f64 promotion, dropped donation, kernel-contract
# drift, refusal-string drift) that would waste the chip window.
echo "=== graftlint preflight ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python -m tools.graftlint 2>&1 | tee -a "$log"
if [ "${PIPESTATUS[0]}" -ne 0 ]; then
  echo "!! graftlint preflight failed — fix findings before measuring" \
    | tee -a "$log"
  sync_log
  exit 4
fi
# 0.6. capability-matrix gate (CPU-only): emit the planner's verdict
# over the whole lattice and diff against the committed golden matrix.
# A PLAN->REFUSE flip or a refusal-string drift is a regression (a
# REFUSE->PLAN lift is a note — capability only grows).
echo "=== planstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python -m tools.graftlint --emit-matrix \
    > /tmp/plan_matrix.json 2>>"$log"
env JAX_PLATFORMS=cpu python tools/planstat.py /tmp/plan_matrix.json \
    --check PLAN_r19.json 2>&1 | tee -a "$log"
plrc=${PIPESTATUS[0]}
if [ "$plrc" -eq 2 ]; then
  echo "!! planstat gate failed — unusable capability matrix (emit" \
      "crashed or schema drift?)" | tee -a "$log"
  sync_log
  exit 15
elif [ "$plrc" -ne 0 ]; then
  echo "!! planstat gate failed — a lattice cell regressed" \
      "PLAN->REFUSE, a refusal string drifted from the golden" \
      "matrix, or a cell failed to classify" | tee -a "$log"
  sync_log
  exit 15
fi
# 1. hardware kernel-identity artifact (small run, judge deliverable)
run s1 1800 python tools/kernel_identity.py 200000 KERNEL_IDENTITY_r05.json
# 2. the flagship driver metric — forced-XLA so the pass ALWAYS
# produces a plain flagship row for pick_bench_path to compare against
# (a committed kernel pin would otherwise make bench.py emit only the
# _kernel row and the picker would clear a still-valid pin)
run s2 1800 env GOSSIP_BENCH_KERNEL=0 python bench.py
# 3. XLA vs kernel timing at 1M (decides the default path)
run s3a 2700 python tools/bench_kernel.py 1000000 xla kernel kernela
run s3b 2700 python tools/bench_kernel.py 1000000 kernela --noroll
# 4. the bench-suite rows, both paths
run s4 2700 python bench_suite.py gossipsub_v10 gossipsub_v11_multitopic \
    gossipsub_v11_adversarial gossipsub_v11_everything
run s4k 2700 env GOSSIP_BENCH_KERNEL=1 python bench_suite.py gossipsub_v11 \
    gossipsub_v11_adversarial gossipsub_v11_multitopic \
    gossipsub_v11_everything
# 4b. faulted + observed runs on the kernel path (round 9): the
# kernel-path fault-mask and telemetry overheads, measured on mosaic
run s4b 2700 python bench_suite.py gossipsub_v11_churn_kernel \
    gossipsub_telemetry_kernel
# 4c. trace pipeline (round 10): 13-type export throughput on both
# paths, then the tracestat regression gate over the artifacts the
# bench just wrote (coverage must stay 13/13 and device-histogram p99
# within 1 tick of the committed OBS_r10.json baseline)
run s4c 2700 python bench_suite.py gossipsub_trace_export \
    gossipsub_trace_export_kernel
echo "=== tracestat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/tracestat.py \
    /tmp/gossipsub_trace_export.pb \
    --frames /tmp/gossipsub_trace_export_frames.json \
    --check OBS_r10.json 2>&1 | tee -a "$log"
if [ "${PIPESTATUS[0]}" -ne 0 ]; then
  echo "!! tracestat gate failed — trace coverage or p99 regressed" \
    | tee -a "$log"
  sync_log
  exit 5
fi
# 4d. adversarial tournament + invariant overhead (round 11): the
# attack x defense sweep in one dispatch, then the tourneystat gate
# over the artifact the bench just wrote (worst-case honest delivery
# under reference score params must stay within slack of the
# committed TOURNEY_r11.json; any runtime invariant violation fails),
# plus the invariant-checker overhead rows on both execution paths
run s4d 2700 python bench_suite.py gossipsub_tournament \
    gossipsub_invariants gossipsub_invariants_kernel
echo "=== tourneystat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/tourneystat.py \
    /tmp/gossipsub_tournament.json \
    --check TOURNEY_r12.json 2>&1 | tee -a "$log"
trc=${PIPESTATUS[0]}
if [ "$trc" -eq 2 ]; then
  echo "!! tourneystat gate failed — unusable tournament artifact" \
      "(bench crashed or wrote a truncated file?)" | tee -a "$log"
  sync_log
  exit 6
elif [ "$trc" -ne 0 ]; then
  echo "!! tourneystat gate failed — worst-case delivery regressed" \
      "or a cell reported an invariant violation" | tee -a "$log"
  sync_log
  exit 6
fi
# 4e. sweep engine (round 12): the resident scenario server's serving
# row — >= 20 distinct protocol/fault/attack configs from ONE compiled
# executable, heterogeneous sweep within 2x of the seed-batch row —
# plus the kernel-path sequential twin, then the sweepstat gate over
# the artifact the bench just wrote (configs-per-compile and
# throughput vs the committed SWEEP_r12.json)
run s4e 2700 python bench_suite.py gossipsub_sweepd gossipsub_sweepd_kernel
echo "=== sweepstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/sweepstat.py \
    /tmp/gossipsub_sweepd.json \
    --check SWEEP_r12.json 2>&1 | tee -a "$log"
src=${PIPESTATUS[0]}
if [ "$src" -eq 2 ]; then
  echo "!! sweepstat gate failed — unusable sweep artifact" \
      "(bench crashed or wrote a truncated file?)" | tee -a "$log"
  sync_log
  exit 7
elif [ "$src" -ne 0 ]; then
  echo "!! sweepstat gate failed — configs-per-compile or sweep" \
      "throughput regressed" | tee -a "$log"
  sync_log
  exit 7
fi
# 4f. event-driven time (round 13): the pipelined-gossip sweep — the
# heartbeat/RTT ratio (delay_base/delay_jitter knobs) swept through
# ONE compiled executable over the 100k v1.1 config with the K-slot
# delay line, committing the first multi-bucket delivery-latency
# percentile curves — then the delaystat gate over the artifact the
# bench just wrote (p99 within slack of the committed DELAY_r13.json,
# delivery fraction holding, zero recompiles across delay points)
run s4f 2700 python bench_suite.py gossipsub_pipelined
echo "=== delaystat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/delaystat.py \
    /tmp/gossipsub_pipelined.json \
    --check DELAY_r13.json 2>&1 | tee -a "$log"
drc=${PIPESTATUS[0]}
if [ "$drc" -eq 2 ]; then
  echo "!! delaystat gate failed — unusable delay-sweep artifact" \
      "(bench crashed, or a delayed row's histogram is degenerate?)" \
      | tee -a "$log"
  sync_log
  exit 8
elif [ "$drc" -ne 0 ]; then
  echo "!! delaystat gate failed — delivery-latency p99 or delivery" \
      "fraction regressed past slack" | tee -a "$log"
  sync_log
  exit 8
fi
# 4g. multi-chip scale-out (round 14): the whole-sim carry sharded
# over the ``peers`` mesh axis — the 1M D-scaling curve (one compile
# per D, boundary-collective census, final-state digest bit-identical
# to D=1) plus the 10M-peer flagship row at max D — then the shardstat
# gate over the artifact the bench just wrote (bit-identity, compile
# counts, collective presence, and throughput vs the committed
# MULTICHIP_r14.json)
run s4g 3600 python bench_suite.py gossipsub_multichip
echo "=== shardstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/shardstat.py \
    /tmp/gossipsub_multichip.json \
    --check MULTICHIP_r14.json 2>&1 | tee -a "$log"
shrc=${PIPESTATUS[0]}
if [ "$shrc" -eq 2 ]; then
  echo "!! shardstat gate failed — unusable multichip artifact" \
      "(bench crashed, or no D-scaling curve?)" | tee -a "$log"
  sync_log
  exit 9
elif [ "$shrc" -ne 0 ]; then
  echo "!! shardstat gate failed — sharded trajectory diverged from" \
      "single-device, a mesh recompiled, or throughput regressed" \
      | tee -a "$log"
  sync_log
  exit 9
fi
# 4h. preemption-tolerant execution (round 15): the segmented-scan
# checkpoint rows — segmented(S in {2,4}) digests BIT-IDENTICAL to the
# single scan, the kill-resume row (deferred SIGTERM -> snapshot ->
# resume), and the sharded D=4 save -> D=8 resume row — then the
# ckptstat gate over the artifact the bench just wrote (resume
# bit-identity, recompile-per-segment, segment overhead vs the
# committed CKPT_r15.json).  KILL_GRACE=120: a SIGTERMed bench gets
# two minutes to finish the in-flight 1M segment and flush its
# snapshot before timeout escalates to SIGKILL.
KILL_GRACE=120 run s4h 2700 python bench_suite.py gossipsub_checkpoint
echo "=== ckptstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/ckptstat.py \
    /tmp/gossipsub_checkpoint.json \
    --check CKPT_r15.json 2>&1 | tee -a "$log"
ckrc=${PIPESTATUS[0]}
if [ "$ckrc" -eq 2 ]; then
  echo "!! ckptstat gate failed — unusable checkpoint artifact" \
      "(bench crashed or wrote a truncated file?)" | tee -a "$log"
  sync_log
  exit 10
elif [ "$ckrc" -ne 0 ]; then
  echo "!! ckptstat gate failed — resume bit-identity broke, a" \
      "segment recompiled, or snapshot overhead passed slack" \
      | tee -a "$log"
  sync_log
  exit 10
fi
# 4i. tick-resident megakernel (round 16): the fused T=8 window —
# digest BIT-IDENTICAL to the per-tick kernel, ONE compiled
# executable across windows, and the analytic per-tick HBM ledger
# (>= 5x reduction at every fitting >= 100k-peer point) — then the
# residentstat gate over the artifact the bench just wrote, vs the
# committed RESIDENT_r16.json
run s4i 2700 python bench_suite.py gossipsub_resident
echo "=== residentstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/residentstat.py \
    /tmp/gossipsub_resident.json \
    --check RESIDENT_r16.json 2>&1 | tee -a "$log"
rsrc=${PIPESTATUS[0]}
if [ "$rsrc" -eq 2 ]; then
  echo "!! residentstat gate failed — unusable resident artifact" \
      "(bench crashed, or no byte ledger?)" | tee -a "$log"
  sync_log
  exit 11
elif [ "$rsrc" -ne 0 ]; then
  echo "!! residentstat gate failed — fused trajectory diverged from" \
      "the per-tick kernel, a window re-traced, or the HBM reduction" \
      "fell under the 5x bar" | tee -a "$log"
  sync_log
  exit 11
fi
# 4j. sharded tick-resident megakernel (round 17): the fused window
# with in-kernel ring-halo exchange under shard_map — digest
# BIT-IDENTICAL to the single-device per-tick kernel at every D in
# {2, 4}, ONE compile per D, the per-(n, devices) fits table with
# real circulant offsets, and the 1M multiplicative flip (REFUSED at
# D=1 -> FITS at D=8) — then the residentstat --sharded gate vs the
# committed RESIDENT_r17.json.  The virtual mesh comes from the env
# here (CPU hosts; on TPU the real mesh is jax.devices()).
run s4j 2700 env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python bench_suite.py gossipsub_resident_sharded
echo "=== residentstat --sharded --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/residentstat.py \
    /tmp/gossipsub_resident_sharded.json \
    --sharded --check RESIDENT_r17.json 2>&1 | tee -a "$log"
rssrc=${PIPESTATUS[0]}
if [ "$rssrc" -eq 2 ]; then
  echo "!! residentstat --sharded gate failed — unusable sharded" \
      "resident artifact (bench crashed, no fused_sharded rows, or" \
      "no fits table?)" | tee -a "$log"
  sync_log
  exit 12
elif [ "$rssrc" -ne 0 ]; then
  echo "!! residentstat --sharded gate failed — a fused-sharded" \
      "trajectory diverged from the per-tick kernel, a window" \
      "re-traced, the 1M flip is gone, or the multiplicative saving" \
      "shrank" | tee -a "$log"
  sync_log
  exit 12
fi
# 4k. fault-tolerant multi-tenant serving (round 18): the
# shape-bucketed front end under Zipf/Poisson load — compile count ==
# traced bucket count (LRU evictions free), explicit overload
# rejection rows (no silent drops: the accounting identity), the
# SIGKILL-mid-long-scenario journal-replay restart resumed to the
# BIT-IDENTICAL digest, and the traced-vs-AOT (jax.export) cold-start
# race — then the servestat gate over the artifact the bench just
# wrote, vs the committed SERVE_r18.json.  KILL_GRACE=120: a SIGTERMed
# bench drains its queue and parks interrupted long scenarios before
# timeout escalates.  (s4k is the kernel flagship run above — this
# step runs as s4sv.)
KILL_GRACE=120 run s4sv 2700 python bench_suite.py gossipsub_serving
echo "=== servestat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/servestat.py \
    /tmp/gossipsub_serving.json \
    --check SERVE_r18.json 2>&1 | tee -a "$log"
svrc=${PIPESTATUS[0]}
if [ "$svrc" -eq 2 ]; then
  echo "!! servestat gate failed — unusable serving artifact (bench" \
      "crashed, no summary rows, or no compile counter?)" \
      | tee -a "$log"
  sync_log
  exit 13
elif [ "$svrc" -ne 0 ]; then
  echo "!! servestat gate failed — the front end recompiled past its" \
      "bucket count, dropped a request silently, stopped rejecting" \
      "under overload, broke kill-recovery bit-identity, or fell" \
      "below the baseline throughput/latency floor" | tee -a "$log"
  sync_log
  exit 13
fi
# 4l. service observability plane (round 19): the metrics/spans bench
# — a real ``sweepd --multi --socket --metrics-port`` subprocess under
# tools/loadgen.py's multi-process client fleet with MID-FLIGHT
# /metrics.json scrapes (every scrape must satisfy the accounting
# identity), the stats-vs-scrape cross-check over one connection, the
# Chrome-trace span ledger (traces == admissions, one terminal event
# each), and the delay-armed device-counter parity rows (the lifted
# counters-group refusal: DelayConfig(1,0,1) bit-identical to the
# undelayed counters) — then the obsstat gate over the artifact the
# bench just wrote, vs the committed METRICS_r19.json
run s4l 2700 python bench_suite.py gossipsub_metrics
echo "=== obsstat --check gate ===" | tee -a "$log"
env JAX_PLATFORMS=cpu python tools/obsstat.py \
    /tmp/gossipsub_metrics.json \
    --check METRICS_r19.json 2>&1 | tee -a "$log"
obrc=${PIPESTATUS[0]}
if [ "$obrc" -eq 2 ]; then
  echo "!! obsstat gate failed — unusable metrics artifact (bench" \
      "crashed, no scrape rows, or no span summary?)" | tee -a "$log"
  sync_log
  exit 14
elif [ "$obrc" -ne 0 ]; then
  echo "!! obsstat gate failed — a scrape broke the accounting" \
      "identity, the span ledger lost a request, the fleet dropped a" \
      "row, delay-armed counter parity broke, or fleet throughput" \
      "fell below the baseline floor" | tee -a "$log"
  sync_log
  exit 14
fi
# 5. GSPMD overhead + diagnostics
run s5a 1800 python tools/bench_sharded.py
run s5b 1800 python tools/bench_micro.py 1000000 100
run s5c 1800 python tools/profile_trace.py 1000000 xla
rm -f "$journal"
echo DONE | tee -a "$log"
