#!/bin/bash
# Patient TPU-tunnel watcher (PERF_NOTES operational discipline):
#  - cheap TCP probe of the axon relay port every 240 s (NOT a JAX client,
#    so it cannot hold or wedge the remote device grant);
#  - once the port listens, ONE short jax.devices() probe;
#  - on success, run the full measurement pass (tools/measure_all.sh) and
#    auto-commit the artifacts it writes into the repo.
# Strictly one TPU client at a time; a flock guard keeps a second watcher
# copy (the round-4 "stray probe loops" hazard) from ever starting.
set -u
cd /root/repo
exec 9>/tmp/tpu_watch.lock
if ! flock -n 9; then
  echo "[watch] another watcher holds /tmp/tpu_watch.lock — exiting" >&2
  exit 1
fi
log=/tmp/tpu_watch.log
port="${AXON_PROBE_PORT:-8082}"

# PERF_NOTES operational note #2: background probe loops OUTLIVE their
# shell wrappers — a stale `jax.devices()` probe left over from a dead
# watcher is a live TPU client, and two concurrent clients wedge the
# axon tunnel.  Hunt them down (ps match on the probe command) before
# starting ANY new TPU client of our own.  Only the known probe
# command is targeted — never arbitrary python/jax processes (a
# measurement pass mid-flight must not be SIGTERM'd, note #2's other
# lesson).
hunt_stale_probes() {
  local pids pid
  pids=$(ps -eo pid=,args= \
         | grep -F 'import jax; print(jax.devices())' \
         | grep -v grep | awk '{print $1}')
  for pid in $pids; do
    [ "$pid" = "$$" ] && continue
    echo "[watch] killing stale TPU probe pid $pid (pre-client hunt," \
         "op-note #2)" | tee -a "$log"
    kill "$pid" 2>/dev/null
  done
  if [ -n "$pids" ]; then
    sleep 2   # give the dying client a beat to release its grant
  fi
}
# hard stop for ALL watcher TPU activity (probes included): leave the
# chip free for the driver's own end-of-round bench run
export MEASURE_DEADLINE="${MEASURE_DEADLINE:-$(date -d '2026-07-31 14:10 UTC' +%s)}"
echo "[watch] start $(date -u +%H:%M:%S) probing 127.0.0.1:$port" | tee -a "$log"
n=0
# after an aborted measurement pass (relay died mid-pass) the watcher
# RE-ARMS with capped exponential backoff instead of giving up or
# hammering: 60s doubling to a 1920s cap, reset on any completed pass.
# The steady-state probe cadence stays 240s.
retry_delay=60
retry_count=0
while true; do
  if [ "$(date +%s)" -gt "$MEASURE_DEADLINE" ]; then
    echo "[watch] deadline passed — exiting (chip left to the driver)" \
      | tee -a "$log"
    exit 0
  fi
  n=$((n + 1))
  if (exec 3<>/dev/tcp/127.0.0.1/"$port") 2>/dev/null; then
    exec 3>&- 3<&- 2>/dev/null
    echo "[watch] attempt $n: port open $(date -u +%H:%M:%S)" | tee -a "$log"
    hunt_stale_probes
    if timeout -k 10 300 python -c "import jax; print(jax.devices())" \
        >>"$log" 2>&1; then
      echo "[watch] backend up — running measure_all $(date -u +%H:%M:%S)" \
        | tee -a "$log"
      hunt_stale_probes   # measure_all is a new TPU client too
      touch /tmp/measure_pass_start
      bash tools/measure_all.sh >>"$log" 2>&1
      mrc=$?
      echo "[watch] measure_all rc=$mrc $(date -u +%H:%M:%S)" | tee -a "$log"
      if [ "$mrc" -eq 0 ]; then
        bash tools/measure_variants.sh >>"$log" 2>&1
        echo "[watch] variants finished $(date -u +%H:%M:%S)" | tee -a "$log"
      fi
      # commit only artifacts this pass actually (re)wrote — a stale
      # KERNEL_IDENTITY json from an aborted earlier pass must not be
      # relabeled as this capture.
      # Run the path picker ONLY after a COMPLETED pass: an aborted one
      # (relay death mid-pass, deadline) lacks the forced-XLA flagship
      # row, and the picker must not judge — let alone clear — a
      # hardware-measured pin from half a log (advisor r5)
      if [ "$mrc" -eq 0 ]; then
        python tools/pick_bench_path.py >>"$log" 2>&1
      else
        echo "[watch] pass aborted (rc=$mrc) — skipping pick_bench_path" \
          | tee -a "$log"
      fi
      fresh=$(find KERNEL_IDENTITY_r05.json MEASURE_RECOVERY.log \
              MEASURE_VARIANTS.log \
              -newer /tmp/measure_pass_start 2>/dev/null)
      [ -n "$fresh" ] && git add $fresh
      # -A so a pin the picker just DELETED is staged too
      git add -A -- BENCH_CONFIG.json 2>/dev/null
      if ! git diff --cached --quiet; then
        git commit -m "Hardware recovery capture: measure_all artifacts" \
          >>"$log" 2>&1 || true
      fi
      # pass aborted on a relay death: keep watching — a later
      # recovery reruns measure_all, which RESUMES from its step
      # journal (/tmp/measure_all.steps, keyed on git HEAD): completed
      # bench steps are skipped, and the segmented checkpoint bench
      # additionally resumes mid-run from its own snapshots, so an
      # abort costs the in-flight step, never the pass so far.
      # Back off exponentially (capped) so a flapping relay is not
      # hammered with full measurement passes; each retry is logged.
      [ "$mrc" -eq 0 ] && exit 0
      retry_count=$((retry_count + 1))
      echo "[watch] pass aborted — retry #$retry_count in ${retry_delay}s" \
        | tee -a "$log"
      sleep "$retry_delay" 9>&-
      retry_delay=$((retry_delay * 2))
      [ "$retry_delay" -gt 1920 ] && retry_delay=1920
      continue
    fi
    echo "[watch] attempt $n: port open but backend probe failed" \
      | tee -a "$log"
  else
    echo "[watch] attempt $n: port closed $(date -u +%H:%M:%S)" >>"$log"
  fi
  sleep 240 9>&-   # don't leak the lock fd into the sleep child
done
