#!/usr/bin/env python
"""Dump the optimized HLO of the scanned v1.1 step and summarize the
named fusions (to map profiler trace names -> source ops).

Usage: python tools/dump_hlo.py [n] [xla|kernel] [fusion-name ...]
With fusion names: print those computations in full.  Without: print a
one-line op-mix summary per >=16-op fusion.
"""

from __future__ import annotations

import re
import sys
from collections import Counter

sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]
sys.path.insert(0, "tools")  # graftlint: ignore[sys-path-insert]

from go_libp2p_pubsub_tpu.utils.artifacts import write_text_atomic  # noqa: E402

from bench_kernel import build  # noqa: E402


def main():
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    which = sys.argv[2] if len(sys.argv) > 2 else "xla"
    want = sys.argv[3:]
    kw = {}
    pad = 8192 if which == "kernel" else None
    if which == "kernel":
        kw = dict(receive_block=8192)
    cfg, sc, params, state = build(n, pad_block=pad)
    step = gs.make_gossip_step(cfg, sc, **kw)

    def run(params, state):
        return gs.gossip_run(params, state, 100, step)

    txt = jax.jit(run).lower(params, state).compile().as_text()
    write_text_atomic("/tmp/step_hlo.txt", txt)
    print(f"HLO: {len(txt.splitlines())} lines -> /tmp/step_hlo.txt")

    # split computations
    comps = {}
    cur = None
    for line in txt.splitlines():
        if line.strip().endswith("{") and ("fused_computation" in line
                                           or line.startswith("%")
                                           or "ENTRY" in line):
            name = line.strip().split()[0].lstrip("%")
            cur = name
            comps[cur] = []
        elif cur is not None:
            if line.strip() == "}":
                cur = None
            else:
                comps[cur].append(line)

    if want:
        # fusion.N in the trace corresponds to the instruction name;
        # find its computation via the fusion instruction line
        for w in want:
            pat = re.compile(rf"%?{re.escape(w)}\s*=.*calls=%?([\w.\-]+)")
            for line in txt.splitlines():
                m = pat.search(line)
                if m:
                    print("=" * 70)
                    print(line.strip()[:300])
                    body = comps.get(m.group(1), [])
                    for b in body:
                        print(b[:240])
                    break
        return

    # summary: op mix for each fusion instruction
    for line in txt.splitlines():
        m = re.search(
            r"%?([\w.\-]+) = (\S+) fusion\((.*?)\), kind=(\S+), "
            r"calls=%?([\w.\-]+)", line)
        if not m:
            continue
        name, shape, _args, kind, comp = m.groups()
        body = comps.get(comp, [])
        ops = Counter()
        for b in body:
            mo = re.match(r"\s*%?[\w.\-]+ = \S+ ([\w\-]+)\(", b)
            if mo:
                ops[mo.group(1)] += 1
        if sum(ops.values()) < 10:
            continue
        top = ", ".join(f"{k}x{v}" for k, v in ops.most_common(8))
        print(f"{name:28s} {shape:24s} {kind:18s} {top}")


if __name__ == "__main__":
    main()
