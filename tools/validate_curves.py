#!/usr/bin/env python
"""BASELINE.md metric 2: reachability-vs-hops curves, core vs sim,
averaged over many independent runs.

The CI gates (tests/test_interop_replay.py) compare SINGLE core runs
against the deterministic sim under a wide envelope (0.075) because one
60-host asyncio cluster carries ±0.02 of run-to-run timing noise.  The
BASELINE claim ("curves matching within 1%") is a statement about MEAN
curves, so this tool runs K independent (topology, publishers, mesh
seed) samples on BOTH sides, averages, and records the achieved
per-hop delta as a committed artifact.

Replica execution is BATCHED (the round-5 n=120 sweep's binding cost
was K separate Python-loop gossip_run calls, each recompiling the step
for its own topology): replicas are grouped into chunks of B that
share a topology — publishers and mesh seed stay per-replica — and
each chunk advances as ONE gossip_run_batch dispatch of the vmapped
step with a donated carry.  B is chosen from the peer count so the
batched carry fits the memory budget (see _pick_chunk; override with
--batch).  Per replica the batched trajectory is bit-identical to the
sequential one, so --sequential (the automatic fallback when B=1)
iterates the SAME spec list one run at a time and produces identical
mean curves — it exists for A/B validation and as the escape hatch on
memory-starved hosts.

CPU-only (the core is asyncio; the sim runs fine on the CPU backend).

Usage: python tools/validate_curves.py [K] [out.json] [n]
                                       [--batch B] [--sequential]
                                       [--sim-only] [--degradation]
                                       [--telemetry]

--sim-only skips the asyncio core side entirely: it times and reports
just the sim replica sweep (the perf-comparison mode recorded in
PERF_NOTES.md).

--degradation runs the FAULT-INJECTION sweep instead (sim only, gossip
repair enabled): the same K-replica batch at several link-drop levels
with 10% churn overlapping the publish tick (models/faults.py),
recording the mean reachability curve and final delivered fraction per
level — the graceful-degradation artifact.

--telemetry runs the TELEMETRY timeline sweep instead (sim only,
gossip repair enabled, models/telemetry.py full frame): the same
K-replica batch through telemetry_run_batch, dumping the per-tick
replica-mean timeline of the protocol counters (payload copies, IHAVE
ids, gossip pulls, GRAFT/PRUNE, duplicates, mesh degree, estimated
wire bytes) plus the whole-run control-overhead ratio — the
observability artifact.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]

from go_libp2p_pubsub_tpu.utils.artifacts import write_json_atomic  # noqa: E402
#   (script-style tool, documented to run from the repo root)

# cap on replicas per shared-topology chunk: keeps >= 2 distinct
# topologies in a default K=10..12 sweep (topology is one of the three
# randomness dimensions the mean averages over)
MAX_CHUNK = 6


def _pick_chunk(n_peers: int, k: int, budget_bytes: int) -> int:
    """Chunk size B from the peer count: how many replica carries fit
    the memory budget at once.

    Per-replica carry estimate for the curve config (no scoring), from
    the GossipState layout: mesh/fanout/last_pub/gates [N] words,
    backoff i16 [C, N], have + recent u32 [(1 + Hg) * W, N], first_tick
    i16 [W, 32, N] — first_tick dominates.  W = 1 (M = 24 ids), C = 8,
    Hg = 3 here; the formula keeps the symbolic form so larger sweeps
    scale it honestly.
    """
    C, W, HG = 8, 1, 3
    per_replica = n_peers * (4 * 4          # mesh/fanout/last_pub/gate
                             + 2 * C        # backoff i16
                             + 4 * W * (1 + HG)   # have + recent
                             + 2 * W * 32)  # first_tick i16
    b = int(budget_bytes // max(per_replica, 1))
    return max(1, min(k, b, MAX_CHUNK))


def _make_specs(K: int, B: int, n: int, C: int, M: int):
    """The K replica specs, chunked: chunk j (replicas j*B .. j*B+B-1)
    shares topology seed 3+j; publishers (rng 100+k) and the sim's mesh
    seed (k) stay per-replica.  The sequential fallback iterates the
    same list, so both paths average the same trajectories."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    chunks = []
    for j in range(0, (K + B - 1) // B):
        members = []
        offsets = gs.make_gossip_offsets(1, C, n, seed=3 + j)
        for k in range(j * B, min((j + 1) * B, K)):
            rng = np.random.default_rng(100 + k)
            members.append({
                "k": k,
                "publishers": list(rng.integers(0, n, M)),
                "seed": k,
            })
        chunks.append({"topo_seed": 3 + j, "offsets": offsets,
                       "members": members})
    return chunks


def _sim_sweep(chunks, n: int, M: int, HOPS: int, sequential: bool):
    """Run every replica's sim trajectory; returns ({k: (mean_curve,
    mesh_degree)}, fell_back).  Batched: one gossip_run_batch per
    chunk.  Sequential: one gossip_run per replica, same specs.
    ``fell_back`` is True when ANY chunk had to drop from the batched
    path to the per-replica loop — the committed artifact's mode tag
    must reflect that, or the recorded timing would impersonate the
    batched path."""
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    subs = np.ones((n, 1), dtype=bool)
    out = {}
    fell_back = False
    for chunk in chunks:
        cfg = gs.GossipSimConfig(
            offsets=chunk["offsets"], n_topics=1, d=3, d_lo=2, d_hi=6,
            d_score=2, d_out=1, d_lazy=0, gossip_factor=0.0)
        step = gs.make_gossip_step(cfg, None)
        specs = [dict(subs=subs, msg_topic=np.zeros(M, np.int64),
                      msg_origin=np.array(m["publishers"]),
                      msg_publish_tick=np.full(M, 90, np.int32),
                      seed=m["seed"])
                 for m in chunk["members"]]
        if not (sequential or len(specs) == 1):
            try:
                params_b, state_b = gs.stack_sims(cfg, specs)
                fin_b = gs.gossip_run_batch(params_b, state_b, 110, step)
                for i, m in enumerate(chunk["members"]):
                    out[m["k"]] = _replica_stats(
                        gs, gs.index_trees(params_b, i),
                        gs.index_trees(fin_b, i), HOPS, n)
                continue
            except Exception as e:  # graftlint: ignore[broad-except]
                # OOM / backend refusal — deliberately broad: the
                # per-replica loop is always available and identical
                fell_back = True
                print(f"batched chunk failed ({type(e).__name__}: "
                      f"{e}); falling back to the sequential loop",
                      file=sys.stderr)
        for m, spec in zip(chunk["members"], specs):
            params, state = gs.make_gossip_sim(cfg, **spec)
            fin = gs.gossip_run(params, state, 110, step)
            out[m["k"]] = _replica_stats(gs, params, fin, HOPS, n)
    return out, fell_back


DEGRADATION_LEVELS = (0.0, 0.05, 0.15)


def _degradation_sweep(chunks, n, M, HOPS, sequential, out_path,
                       mode="?"):
    """Fault-level sweep over the SAME replica specs as the curve
    sweep: for each link-drop level, every replica additionally churns
    10% of its peers down across the publish tick.  Batched exactly
    like _sim_sweep (stack_sims -> one gossip_run_batch per chunk;
    fault schedules ride the stacked params with per-replica seeds).
    Writes the per-level mean curves + final delivered fraction and
    prints a one-line summary."""
    import time as _time

    import go_libp2p_pubsub_tpu.models.faults as fl
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import mean_reach_fraction

    subs = np.ones((n, 1), dtype=bool)
    t0 = _time.perf_counter()
    per_level_curves = {level: [] for level in DEGRADATION_LEVELS}
    fell_back = False
    # chunks OUTER, levels inner: the jitted scanned step is keyed on
    # the step closure (static argnum), so one make_gossip_step per
    # chunk serves all levels — levels only change array contents
    for chunk in chunks:
        # gossip repair ON (unlike the core-comparison config):
        # fault recovery IS the mechanism under test
        cfg = gs.GossipSimConfig(
            offsets=chunk["offsets"], n_topics=1, d=3, d_lo=2,
            d_hi=6, d_score=2, d_out=1)
        step = gs.make_gossip_step(cfg, None)
        for level in DEGRADATION_LEVELS:
            curves = per_level_curves[level]

            def sched(k):
                rng = np.random.default_rng(1000 + k)
                victims = np.flatnonzero(rng.random(n) < 0.10)
                return fl.FaultSchedule(
                    n_peers=n, horizon=110,
                    down_intervals=[(int(p), 85, 100) for p in victims],
                    drop_prob=level, seed=k)

            specs = [dict(subs=subs, msg_topic=np.zeros(M, np.int64),
                          msg_origin=np.array(m["publishers"]),
                          msg_publish_tick=np.full(M, 90, np.int32),
                          seed=m["seed"],
                          fault_schedule=sched(m["k"]))
                     for m in chunk["members"]]
            fins = None
            if not (sequential or len(specs) == 1):
                try:
                    params_b, state_b = gs.stack_sims(cfg, specs)
                    fin_b = gs.gossip_run_batch(params_b, state_b, 110,
                                                step)
                    fins = [(gs.index_trees(params_b, i),
                             gs.index_trees(fin_b, i))
                            for i in range(len(specs))]
                except Exception as e:  # graftlint: ignore[broad-except]
                    # OOM / backend refusal — deliberately broad; the
                    # per-replica loop is identical (see _sim_sweep)
                    fell_back = True
                    print(f"batched degradation chunk failed "
                          f"({type(e).__name__}: {e}); falling back "
                          "to the sequential loop", file=sys.stderr)
            if fins is None:
                fins = []
                for spec in specs:
                    p_, s_ = gs.make_gossip_sim(cfg, **spec)
                    fins.append((p_, gs.gossip_run(p_, s_, 110, step)))
            for p_, f_ in fins:
                curves.append(mean_reach_fraction(
                    np.asarray(gs.reach_by_hops(p_, f_, HOPS)), n))
    levels = {}
    for level in DEGRADATION_LEVELS:
        mean = np.mean(per_level_curves[level], axis=0)
        levels[str(level)] = {
            "mean_curve": [round(float(x), 4) for x in mean],
            "final_delivered_fraction": round(float(mean[-1]), 4),
        }
        print(f"level {level}: final fraction {mean[-1]:.4f}",
              file=sys.stderr)
    dt = _time.perf_counter() - t0
    if fell_back:
        # timing (at least partly) the per-replica loop's — the
        # artifact must not attribute it to the batched path
        mode += "+seq-fallback"
    report = {
        "config": {"n_hosts": n, "msgs_per_run": M,
                   "runs_per_level": sum(len(c["members"])
                                         for c in chunks),
                   "churn": "10% peers down ticks [85, 100)",
                   "publish_tick": 90, "mode": mode},
        "hops": HOPS,
        "levels": levels,
        "sweep_seconds": round(dt, 3),
    }
    write_json_atomic(out_path, report)
    print(json.dumps({
        "degradation_levels": list(levels),
        "final_fractions": [levels[k]["final_delivered_fraction"]
                            for k in levels],
        "mode": mode,
        "sweep_seconds": report["sweep_seconds"]}))


TELEMETRY_FIELDS = ("payload_sent", "ihave_ids", "iwant_ids_served",
                    "graft_sends", "prune_sends", "dup_suppressed",
                    "mesh_deg_mean", "bytes_payload", "bytes_control")


def _telemetry_sweep(chunks, n, M, sequential, out_path, mode="?"):
    """Per-tick telemetry timeline over the SAME replica specs as the
    curve sweep (gossip repair ON, full TelemetryFrame): one
    telemetry_run_batch per chunk — frames come back [T, B] and are
    averaged across replicas per tick.  Writes the timeline artifact
    with the whole-run control/payload byte totals and prints a
    one-line summary."""
    import time as _time

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.telemetry as tl

    subs = np.ones((n, 1), dtype=bool)
    TICKS = 110
    tcfg = tl.TelemetryConfig()
    t0 = _time.perf_counter()
    per_field = {f: [] for f in TELEMETRY_FIELDS}   # replica [T] rows
    fell_back = False
    for chunk in chunks:
        cfg = gs.GossipSimConfig(
            offsets=chunk["offsets"], n_topics=1, d=3, d_lo=2,
            d_hi=6, d_score=2, d_out=1)
        step = gs.make_gossip_step(cfg, None, telemetry=tcfg)
        specs = [dict(subs=subs, msg_topic=np.zeros(M, np.int64),
                      msg_origin=np.array(m["publishers"]),
                      msg_publish_tick=np.full(M, 90, np.int32),
                      seed=m["seed"])
                 for m in chunk["members"]]
        arrs = None
        if not (sequential or len(specs) == 1):
            try:
                params_b, state_b = gs.stack_sims(cfg, specs)
                _, fr_b = tl.telemetry_run_batch(params_b, state_b,
                                                 TICKS, step)
                arrs = tl.frames_to_arrays(fr_b)      # each [T, B]
                for i in range(len(specs)):
                    for f in TELEMETRY_FIELDS:
                        per_field[f].append(
                            np.asarray(arrs[f][:, i], dtype=np.float64))
            except Exception as e:  # graftlint: ignore[broad-except]
                # OOM / backend refusal — deliberately broad; the
                # per-replica loop is identical (see _sim_sweep)
                fell_back = True
                print(f"batched telemetry chunk failed "
                      f"({type(e).__name__}: {e}); falling back to "
                      "the sequential loop", file=sys.stderr)
                arrs = None
        if arrs is None:
            for spec in specs:
                p_, s_ = gs.make_gossip_sim(cfg, **spec)
                _, fr = tl.telemetry_run(p_, s_, TICKS, step)
                fa = tl.frames_to_arrays(fr)          # each [T]
                for f in TELEMETRY_FIELDS:
                    per_field[f].append(
                        np.asarray(fa[f], dtype=np.float64))
    dt = _time.perf_counter() - t0
    if fell_back:
        mode += "+seq-fallback"
    timeline = {f: [round(float(x), 3)
                    for x in np.mean(per_field[f], axis=0)]
                for f in TELEMETRY_FIELDS}
    bp = float(np.sum(per_field["bytes_payload"]))
    bc = float(np.sum(per_field["bytes_control"]))
    report = {
        "config": {"n_hosts": n, "msgs_per_run": M,
                   "runs": len(per_field["payload_sent"]),
                   "publish_tick": 90, "mode": mode},
        "ticks": TICKS,
        "mean_timeline": timeline,
        "bytes_payload_total": round(bp, 1),
        "bytes_control_total": round(bc, 1),
        "control_overhead_ratio": round(bc / bp, 4) if bp else 0.0,
        "sweep_seconds": round(dt, 3),
    }
    write_json_atomic(out_path, report)
    print(json.dumps({
        "telemetry_runs": report["config"]["runs"],
        "control_overhead_ratio": report["control_overhead_ratio"],
        "mode": mode,
        "sweep_seconds": report["sweep_seconds"]}))


def _replica_stats(gs, params, fin, HOPS, n):
    from go_libp2p_pubsub_tpu.interop import mean_reach_fraction

    mean = mean_reach_fraction(
        np.asarray(gs.reach_by_hops(params, fin, HOPS)), n)
    deg = float(np.asarray(gs.mesh_degrees(fin)).mean())
    return mean, deg


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, reach_by_hops_from_trace,
        run_core_gossipsub)

    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("K", nargs="?", type=int, default=10)
    ap.add_argument("out", nargs="?", default="CURVES_r05.json")
    ap.add_argument("n", nargs="?", type=int, default=60)
    ap.add_argument("--batch", type=int, default=None,
                    help="override the chunk size heuristic")
    ap.add_argument("--sequential", action="store_true",
                    help="per-replica fallback over the same specs")
    ap.add_argument("--sim-only", action="store_true",
                    help="skip the asyncio core side; time the sim "
                         "replica sweep only")
    ap.add_argument("--degradation", action="store_true",
                    help="fault-injection sweep (churn + link-drop "
                         "levels) instead of the core comparison")
    ap.add_argument("--telemetry", action="store_true",
                    help="telemetry timeline sweep (per-tick protocol "
                         "counters + control-overhead artifact) "
                         "instead of the core comparison")
    ns = ap.parse_args()
    batch_override = ns.batch
    sequential = ns.sequential
    sim_only = ns.sim_only
    K, out_path, n = ns.K, ns.out, ns.n
    C, M = 8, 24
    HOPS = 12 if n <= 60 else 16

    import os
    budget = int(os.environ.get("GOSSIP_CURVE_MEM_BUDGET",
                                str(1 << 30)))
    B = batch_override or _pick_chunk(n, K, budget)
    chunks = _make_specs(K, B, n, C, M)
    mode = "sequential" if (sequential or B == 1) else f"batched{B}"
    if ns.degradation:
        if out_path == "CURVES_r05.json":    # the core-mode default
            out_path = "DEGRADATION_r07.json"
        print(f"degradation sweep: K={K} chunk={B} mode={mode} "
              f"levels={DEGRADATION_LEVELS}", file=sys.stderr)
        _degradation_sweep(chunks, n, M, HOPS, sequential, out_path,
                           mode=mode)
        return
    if ns.telemetry:
        if out_path == "CURVES_r05.json":    # the core-mode default
            out_path = "TELEMETRY_r08.json"
        print(f"telemetry sweep: K={K} chunk={B} mode={mode}",
              file=sys.stderr)
        _telemetry_sweep(chunks, n, M, sequential, out_path, mode=mode)
        return
    print(f"sim sweep: K={K} chunk={B} mode={mode}", file=sys.stderr)

    t0 = time.perf_counter()
    sim_stats, fell_back = _sim_sweep(chunks, n, M, HOPS, sequential)
    sim_seconds = time.perf_counter() - t0
    if fell_back:
        # the timing below is (at least partly) the per-replica loop's
        # — the artifact must not attribute it to the batched path
        mode += "+seq-fallback"
    print(f"sim sweep: {sim_seconds:.2f}s ({mode})", file=sys.stderr)

    sim_curves, core_curves = [], []
    degrees = []
    incomplete = 0
    for chunk in chunks:
        for m in chunk["members"]:
            k = m["k"]
            sim_mean, sim_deg = sim_stats[k]
            if sim_mean[-1] != 1.0:
                # with gossip repair OFF (the curve-comparison setting)
                # an unlucky settled mesh can disconnect a peer — the
                # exact failure mode gossip exists to repair.  Drop the
                # pair.
                incomplete += 1
                print(f"run {k}: sim mesh incomplete (no gossip "
                      "repair), dropped", file=sys.stderr)
                continue
            if sim_only:
                degrees.append((sim_deg, sim_deg))
                sim_curves.append(sim_mean)
                continue

            # mean mesh degree DRIVES spread speed: curves are only
            # comparable when the two meshes settled to the same degree
            # (the CI gate requires |core_deg - sim_deg| < 0.6 for the
            # same reason); under-warmed core clusters sit mid-GRAFT-
            # burst with inflated degrees and systematically faster
            # curves
            core_mean = core_deg = None
            for warm_s, settle_s in ((2.0, 1.2), (3.5, 2.0), (5.0, 2.5)):
                run = run_core_gossipsub(chunk["offsets"], n,
                                         m["publishers"],
                                         warm_s=warm_s,
                                         settle_s=settle_s)
                cm = mean_reach_fraction(
                    reach_by_hops_from_trace(run, HOPS + 1), n)
                cd = float(np.mean(run.extra["mesh_degrees"]))
                if cm[-1] == 1.0 and abs(cd - sim_deg) < 0.6:
                    core_mean, core_deg = cm, cd
                    break
            if core_mean is None:
                incomplete += 1       # drop the PAIR, keep sides matched
                print(f"run {k}: core incomplete/degree-mismatched "
                      f"(core_deg {cd:.2f} vs sim {sim_deg:.2f}), "
                      "dropped", file=sys.stderr)
                continue
            degrees.append((core_deg, sim_deg))
            sim_curves.append(sim_mean)
            # sim hop h aligns with core hop h+1 (the sim's publish tick
            # includes the first forwarding hop)
            core_curves.append(core_mean[1:HOPS + 1])
            print(f"run {k}: ok (deg core {core_deg:.2f} "
                  f"sim {sim_deg:.2f})", flush=True)

    sim_avg = np.mean(sim_curves, axis=0)
    report = {
        "config": {"n_hosts": n, "C": C, "msgs_per_run": M,
                   "runs": len(sim_curves), "dropped": incomplete,
                   "chunk": B, "mode": mode},
        "sim_sweep_seconds": round(sim_seconds, 3),
        "hops": HOPS,
        "sim_mean_curve": [round(float(x), 4) for x in sim_avg],
    }
    if sim_only:
        report["mean_mesh_degree"] = {
            "sim": round(float(np.mean([d[1] for d in degrees])), 3)}
        summary = {"runs": len(sim_curves), "mode": mode,
                   "sim_sweep_seconds": report["sim_sweep_seconds"]}
    else:
        core_avg = np.mean(core_curves, axis=0)
        delta = np.abs(core_avg - sim_avg)
        report.update({
            "mean_mesh_degree": {
                "core": round(float(np.mean([d[0] for d in degrees])), 3),
                "sim": round(float(np.mean([d[1] for d in degrees])), 3)},
            "core_mean_curve": [round(float(x), 4) for x in core_avg],
            "abs_delta_per_hop": [round(float(x), 4) for x in delta],
            "max_abs_delta": round(float(delta.max()), 4),
            "mean_abs_delta": round(float(delta.mean()), 4),
        })
        summary = {"curves_max_abs_delta": report["max_abs_delta"],
                   "curves_mean_abs_delta": report["mean_abs_delta"],
                   "runs": len(sim_curves)}
    write_json_atomic(out_path, report)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
