#!/usr/bin/env python
"""BASELINE.md metric 2: reachability-vs-hops curves, core vs sim,
averaged over many independent runs.

The CI gates (tests/test_interop_replay.py) compare SINGLE core runs
against the deterministic sim under a wide envelope (0.075) because one
60-host asyncio cluster carries ±0.02 of run-to-run timing noise.  The
BASELINE claim ("curves matching within 1%") is a statement about MEAN
curves, so this tool runs K independent (topology, publishers, mesh
seed) samples on BOTH sides, averages, and records the achieved
per-hop delta as a committed artifact.

CPU-only (the core is asyncio; the sim runs fine on the CPU backend).

Usage: python tools/validate_curves.py [K] [out.json]
"""

from __future__ import annotations

import json
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    from go_libp2p_pubsub_tpu.interop import (
        mean_reach_fraction, reach_by_hops_from_trace,
        run_core_gossipsub)

    K = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    out_path = sys.argv[2] if len(sys.argv) > 2 else "CURVES_r05.json"
    n = int(sys.argv[3]) if len(sys.argv) > 3 else 60
    C, M = 8, 24
    HOPS = 12 if n <= 60 else 16

    sim_curves, core_curves = [], []
    degrees = []
    incomplete = 0
    for k in range(K):
        offsets = gs.make_gossip_offsets(1, C, n, seed=3 + k)
        rng = np.random.default_rng(100 + k)
        publishers = list(rng.integers(0, n, M))

        cfg = gs.GossipSimConfig(
            offsets=offsets, n_topics=1, d=3, d_lo=2, d_hi=6,
            d_score=2, d_out=1, d_lazy=0, gossip_factor=0.0)
        subs = np.ones((n, 1), dtype=bool)
        params, state = gs.make_gossip_sim(
            cfg, subs, np.zeros(M, np.int64), np.array(publishers),
            np.full(M, 90, np.int32), seed=k)
        out = gs.gossip_run(params, state, 110,
                            gs.make_gossip_step(cfg, None))
        sim_mean = mean_reach_fraction(
            np.asarray(gs.reach_by_hops(params, out, HOPS)), n)
        if sim_mean[-1] != 1.0:
            # with gossip repair OFF (the curve-comparison setting) an
            # unlucky settled mesh can disconnect a peer — the exact
            # failure mode gossip exists to repair.  Drop the pair.
            incomplete += 1
            print(f"run {k}: sim mesh incomplete (no gossip repair), "
                  "dropped", file=sys.stderr)
            continue
        sim_deg = float(np.asarray(gs.mesh_degrees(out)).mean())

        # mean mesh degree DRIVES spread speed: curves are only
        # comparable when the two meshes settled to the same degree
        # (the CI gate requires |core_deg - sim_deg| < 0.6 for the
        # same reason); under-warmed core clusters sit mid-GRAFT-burst
        # with inflated degrees and systematically faster curves
        core_mean = core_deg = None
        for warm_s, settle_s in ((2.0, 1.2), (3.5, 2.0), (5.0, 2.5)):
            run = run_core_gossipsub(offsets, n, publishers,
                                     warm_s=warm_s, settle_s=settle_s)
            cm = mean_reach_fraction(
                reach_by_hops_from_trace(run, HOPS + 1), n)
            cd = float(np.mean(run.extra["mesh_degrees"]))
            if cm[-1] == 1.0 and abs(cd - sim_deg) < 0.6:
                core_mean, core_deg = cm, cd
                break
        if core_mean is None:
            incomplete += 1       # drop the PAIR, keep sides matched
            print(f"run {k}: core incomplete/degree-mismatched "
                  f"(core_deg {cd:.2f} vs sim {sim_deg:.2f}), dropped",
                  file=sys.stderr)
            continue
        degrees.append((core_deg, sim_deg))
        sim_curves.append(sim_mean)
        # sim hop h aligns with core hop h+1 (the sim's publish tick
        # includes the first forwarding hop)
        core_curves.append(core_mean[1:HOPS + 1])
        print(f"run {k}: ok (deg core {core_deg:.2f} sim {sim_deg:.2f})",
              flush=True)

    sim_avg = np.mean(sim_curves, axis=0)
    core_avg = np.mean(core_curves, axis=0)
    delta = np.abs(core_avg - sim_avg)
    report = {
        "config": {"n_hosts": n, "C": C, "msgs_per_run": M,
                   "runs": len(sim_curves), "dropped": incomplete},
        "mean_mesh_degree": {
            "core": round(float(np.mean([d[0] for d in degrees])), 3),
            "sim": round(float(np.mean([d[1] for d in degrees])), 3)},
        "hops": HOPS,
        "sim_mean_curve": [round(float(x), 4) for x in sim_avg],
        "core_mean_curve": [round(float(x), 4) for x in core_avg],
        "abs_delta_per_hop": [round(float(x), 4) for x in delta],
        "max_abs_delta": round(float(delta.max()), 4),
        "mean_abs_delta": round(float(delta.mean()), 4),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"curves_max_abs_delta": report["max_abs_delta"],
                      "curves_mean_abs_delta": report["mean_abs_delta"],
                      "runs": len(sim_curves)}))


if __name__ == "__main__":
    main()
