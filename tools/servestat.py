#!/usr/bin/env python
"""servestat: inspect a serving-front-end bench artifact and gate the
fault-tolerance claims against a committed baseline.

    python tools/servestat.py /tmp/gossipsub_serving.json
    python tools/servestat.py /tmp/gossipsub_serving.json \
        --check SERVE_r18.json [--rps-slack 0.5] [--p99-slack 3.0]

Prints the load/overload/kill-recovery/cold-start summary rows.
Exit codes (tracestat/tourneystat --check convention):

  0  clean
  1  regression: compile count != traced bucket count (the
     multi-tenant zero-recompile claim), a request unaccounted for
     (served + errors + timeouts + rejections + queued must equal
     admissions — silent drops are the one unforgivable failure), an
     overload phase that produced NO explicit rejection rows, a
     kill-recovery digest mismatch (a resumed long scenario must be
     bit-identical), an AOT cold start that still compiled, or (with
     --check) throughput dropping more than ``--rps-slack`` below /
     p99 queue latency growing more than ``--p99-slack`` above the
     committed baseline
  2  unusable input: missing/unparseable artifact, no summary rows,
     or no compile counter (the bucketed-compile claim can't be
     checked)
"""

from __future__ import annotations

import argparse
import json
import sys


def load(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"servestat: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)
    if not obj.get("rows"):
        print(f"servestat: {path} carries no summary rows",
              file=sys.stderr)
        raise SystemExit(2)
    if "compiles" not in obj or obj.get("compiles") is None:
        print(f"servestat: {path} carries no compile counter — the "
              "bucketed zero-recompile claim cannot be checked",
              file=sys.stderr)
        raise SystemExit(2)
    return obj


def _accounted(phase: dict) -> bool:
    """The no-silent-drop identity: every admitted request ends in
    exactly one terminal bucket, and rejections were never admitted."""
    return (phase.get("admitted", 0)
            == phase.get("served", 0) + phase.get("errors", 0)
            + phase.get("timeouts", 0)
            + phase.get("transient_failures", 0)
            + phase.get("queued", 0) + phase.get("parked", 0))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="servestat",
                                 description=__doc__)
    ap.add_argument("artifact")
    ap.add_argument("--check", metavar="BASELINE",
                    help="committed baseline artifact to gate against")
    ap.add_argument("--rps-slack", type=float, default=0.5,
                    help="allowed fractional throughput drop vs "
                         "baseline (default 0.5; CPU/TPU passes share "
                         "one artifact schema)")
    ap.add_argument("--p99-slack", type=float, default=3.0,
                    help="allowed p99 queue-latency growth factor vs "
                         "baseline (default 3.0x — queue latency is "
                         "load-shaped, gate loosely)")
    ns = ap.parse_args(argv)

    cur = load(ns.artifact)
    rc = 0
    for row in cur["rows"]:
        bits = " ".join(f"{k}={v}" for k, v in row.items()
                        if k != "id")
        print(f"  {str(row.get('id')):<18s} {bits}")
    print(f"compiles={cur['compiles']} "
          f"traced_buckets={cur.get('traced_buckets')} "
          f"bucket_count={cur.get('bucket_count')}")

    load_p = cur.get("load", {})
    if cur["compiles"] != cur.get("traced_buckets"):
        print(f"servestat: compile count {cur['compiles']} != traced "
              f"bucket count {cur.get('traced_buckets')} — the "
              "front end recompiled (or double-counted) an executable",
              file=sys.stderr)
        rc = 1
    for name in ("load", "overload", "kill_recovery"):
        phase = cur.get(name)
        if phase and not _accounted(phase):
            print(f"servestat: {name} phase lost requests: admitted="
                  f"{phase.get('admitted')} vs served="
                  f"{phase.get('served')} errors={phase.get('errors')}"
                  f" timeouts={phase.get('timeouts')} transient="
                  f"{phase.get('transient_failures')} queued="
                  f"{phase.get('queued')} parked={phase.get('parked')}"
                  " — a silent drop", file=sys.stderr)
            rc = 1
    over = cur.get("overload", {})
    if over and not over.get("rejected_overload"):
        print("servestat: the overload phase produced no explicit "
              "rejection rows — backpressure is not engaging (or "
              "drops are silent)", file=sys.stderr)
        rc = 1
    kill = cur.get("kill_recovery", {})
    if kill and not kill.get("digest_match"):
        print("servestat: kill-recovery digest mismatch — a resumed "
              "long scenario is NOT bit-identical to the "
              "uninterrupted run", file=sys.stderr)
        rc = 1
    cold = cur.get("cold_start", {})
    if cold and cold.get("aot_compiles", 0) != 0:
        print(f"servestat: the AOT cold start compiled "
              f"{cold['aot_compiles']} executable(s) — the exported "
              "blobs are not being served", file=sys.stderr)
        rc = 1

    if ns.check:
        base = load(ns.check)
        b_load = base.get("load", {})
        rps_cur, rps_base = (load_p.get("throughput_rps"),
                             b_load.get("throughput_rps"))
        if rps_cur is not None and rps_base:
            floor = rps_base * (1.0 - ns.rps_slack)
            verdict = "OK" if rps_cur >= floor else "REGRESSED"
            print(f"check: throughput_rps {rps_cur:.2f} vs baseline "
                  f"{rps_base:.2f} (floor {floor:.2f}) -> {verdict}")
            if rps_cur < floor:
                rc = 1
        p99_cur, p99_base = (load_p.get("p99_queue_s"),
                             b_load.get("p99_queue_s"))
        if p99_cur is not None and p99_base:
            ceil = p99_base * ns.p99_slack
            verdict = "OK" if p99_cur <= ceil else "REGRESSED"
            print(f"check: p99_queue_s {p99_cur:.3f} vs baseline "
                  f"{p99_base:.3f} (ceiling {ceil:.3f}) -> {verdict}")
            if p99_cur > ceil:
                rc = 1
        if (base.get("bucket_count")
                and cur.get("bucket_count", 0)
                < base["bucket_count"]):
            print("servestat: bucket coverage shrank vs baseline: "
                  f"{cur.get('bucket_count')} < "
                  f"{base['bucket_count']}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
