#!/usr/bin/env python
"""sweepstat: inspect a sweep-engine bench artifact and gate
regressions against a committed baseline.

    python tools/sweepstat.py /tmp/gossipsub_sweepd.json
    python tools/sweepstat.py /tmp/gossipsub_sweepd.json \
        --check SWEEP_r12.json [--ratio-slack 2.0] [--hbps-slack 0.5]

Prints the per-scenario delivery table and the serving counters.
Exit codes (tracestat/tourneystat --check convention):

  0  clean
  1  regression: a failed or invariant-violating scenario row, fewer
     configs served per compile than the baseline (the engine started
     recompiling), the heterogeneous-sweep wall-clock exceeding the
     same-shape seed-batch row by more than the 2x contract, or (with
     --check) replica throughput dropping more than ``--hbps-slack``
     below the committed baseline
  2  unusable input: missing/unparseable artifact, no scenario rows,
     or no compile counter (the zero-recompile claim can't be checked)
"""

from __future__ import annotations

import argparse
import json
import sys


def load(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"sweepstat: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)
    if not obj.get("rows"):
        print(f"sweepstat: {path} carries no scenario rows",
              file=sys.stderr)
        raise SystemExit(2)
    if not obj.get("compiles"):
        print(f"sweepstat: {path} carries no compile counter — the "
              "zero-recompile claim cannot be checked", file=sys.stderr)
        raise SystemExit(2)
    return obj


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="sweepstat",
                                 description=__doc__)
    ap.add_argument("artifact")
    ap.add_argument("--check", metavar="BASELINE",
                    help="committed baseline artifact to gate against")
    ap.add_argument("--ratio-slack", type=float, default=2.0,
                    help="max heterogeneous-sweep / seed-batch "
                         "wall-clock ratio (default 2.0 — the "
                         "acceptance contract)")
    ap.add_argument("--hbps-slack", type=float, default=0.5,
                    help="allowed fractional replica-throughput drop "
                         "vs baseline (default 0.5; CPU/TPU passes "
                         "share one artifact schema)")
    ns = ap.parse_args(argv)

    cur = load(ns.artifact)
    rc = 0
    shape = cur.get("shape", {})
    print(f"sweepd: {shape.get('n')} peers x {shape.get('t')} topics, "
          f"{cur.get('configs_served')} configs in "
          f"{cur.get('batches')} batches of {shape.get('batch')}, "
          f"{shape.get('ticks')} ticks")
    for row in cur["rows"]:
        if not row.get("ok"):
            print(f"  {str(row.get('id')):<16s} FAILED: "
                  f"{row.get('error')}")
            continue
        extra = ""
        if row.get("inv_bits", 0):
            extra = (f"  INVARIANT-VIOLATION bits="
                     f"{row['inv_bits']:#x} first="
                     f"{row.get('inv_first')}")
        print(f"  {str(row.get('id')):<16s} "
              f"honest_delivery={row['honest_delivery_fraction']:.4f}"
              f"{extra}")
    print(f"compiles={cur['compiles']} configs_per_compile="
          f"{cur.get('configs_per_compile')} replica_hbps="
          f"{cur.get('replica_hbps')} sweep_vs_seed_ratio="
          f"{cur.get('sweep_vs_seed_ratio')}")

    bad = [r for r in cur["rows"]
           if not r.get("ok") or r.get("inv_bits", 0)]
    if bad:
        print(f"sweepstat: {len(bad)} scenario row(s) failed or "
              "violated invariants", file=sys.stderr)
        rc = 1
    ratio = cur.get("sweep_vs_seed_ratio")
    if ratio is not None and ratio > ns.ratio_slack:
        print(f"sweepstat: heterogeneous sweep is {ratio:.2f}x the "
              f"seed-batch wall-clock (> {ns.ratio_slack}x contract)",
              file=sys.stderr)
        rc = 1

    if ns.check:
        base = load(ns.check)
        cpc_cur = cur.get("configs_per_compile", 0)
        cpc_base = base.get("configs_per_compile", 0)
        if cpc_cur < cpc_base:
            print(f"sweepstat: configs-per-compile regressed: "
                  f"{cpc_cur} < baseline {cpc_base} (the engine is "
                  "recompiling across scenarios)", file=sys.stderr)
            rc = 1
        hb_cur, hb_base = (cur.get("replica_hbps"),
                           base.get("replica_hbps"))
        if hb_cur is not None and hb_base:
            floor = hb_base * (1.0 - ns.hbps_slack)
            verdict = "OK" if hb_cur >= floor else "REGRESSED"
            print(f"check: replica_hbps {hb_cur:.2f} vs baseline "
                  f"{hb_base:.2f} (floor {floor:.2f}) -> {verdict}")
            if hb_cur < floor:
                rc = 1
        missing = (set(map(str, base.get("scenario_ids", [])))
                   - set(str(r.get("id")) for r in cur["rows"]))
        if missing:
            print("sweepstat: scenario coverage shrank vs baseline: "
                  f"missing {sorted(missing)}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
