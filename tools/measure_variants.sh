#!/bin/bash
# Kernel-schedule variant sweep — runs AFTER tools/measure_all.sh so the
# baseline numbers land first.  Strictly sequential TPU processes; each
# variant is one process (GOSSIP_KERNEL_SLOTS is read at import).
# Identity at every swept depth/block is pinned by the interpret-mode
# suite (tests/test_pallas_receive.py, run at slots 2/4/8).
set -u
cd /root/repo
log=/tmp/measure_variants.log
: > "$log"
sync_log() { cp "$log" /root/repo/MEASURE_VARIANTS.log; }
trap sync_log EXIT
port_open() {
  (exec 3<>/dev/tcp/127.0.0.1/"${AXON_PROBE_PORT:-8082}") 2>/dev/null \
    && exec 3>&- 3<&-
}
run() {
  if [ "$(date +%s)" -gt "${MEASURE_DEADLINE:-9999999999}" ]; then
    echo "!! measurement deadline passed — leaving the chip free" \
      | tee -a "$log"
    sync_log
    exit 3
  fi
  echo "=== $* ===" | tee -a "$log"
  timeout -k 30 2700 "$@" 2>&1 | grep -v WARNING | tee -a "$log"
  echo "--- rc=${PIPESTATUS[0]} ---" | tee -a "$log"
  sync_log
  # same abort-on-relay-death logic as measure_all.sh: once the relay
  # port is gone every further variant just burns its full 2700 s
  # timeout against a dead backend (~3 h for the sweep) — abort; the
  # watcher re-arms and a later recovery reruns the pass
  if ! port_open; then
    echo "!! relay port closed — aborting variant sweep" | tee -a "$log"
    sync_log
    exit 2
  fi
}
# prefetch-depth sweep at the default block
run env GOSSIP_KERNEL_SLOTS=8 python tools/bench_kernel.py 1000000 kernela
run env GOSSIP_KERNEL_SLOTS=2 python tools/bench_kernel.py 1000000 kernela
# block-size sweep at the default depth
run env GOSSIP_BENCH_BLOCK=4096 python tools/bench_kernel.py 1000000 kernela
run env GOSSIP_BENCH_BLOCK=16384 python tools/bench_kernel.py 1000000 kernela
echo DONE | tee -a "$log"
