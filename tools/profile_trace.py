#!/usr/bin/env python
"""Capture a jax.profiler trace of the scanned v1.1 step and print the
top device ops by total time.

Usage: python tools/profile_trace.py [n] [xla|kernel] [out_dir]
"""

from __future__ import annotations

import glob
import gzip
import json
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, ".")  # graftlint: ignore[sys-path-insert]
sys.path.insert(0, "tools")  # graftlint: ignore[sys-path-insert]

from bench_kernel import build  # noqa: E402


def main():
    import jax
    import go_libp2p_pubsub_tpu.models.gossipsub as gs

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    which = sys.argv[2] if len(sys.argv) > 2 else "xla"
    out = sys.argv[3] if len(sys.argv) > 3 else "/tmp/jaxtrace"
    kw = {}
    pad = None
    if which == "kernel":
        pad = 8192
        kw = dict(receive_block=8192)
    cfg, sc, params, state = build(n, pad_block=pad)
    step = gs.make_gossip_step(cfg, sc, **kw)
    state = gs.gossip_run(params, state, 100, step)
    _ = int(np.asarray(state.tick))
    with jax.profiler.trace(out):
        state = gs.gossip_run(params, state, 50, step)
        _ = int(np.asarray(state.tick))

    paths = sorted(glob.glob(out + "/**/*.trace.json.gz", recursive=True))
    if not paths:
        raise SystemExit(f"no trace under {out}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    # device-track events only: keep events whose pid is a device track
    # (name contains TPU/device); fall back to all complete events
    pids = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pids[ev["pid"]] = ev["args"].get("name", "")
    dev_pids = {p for p, nm in pids.items()
                if "TPU" in nm or "/device" in nm.lower()}
    tot = defaultdict(float)
    cnt = defaultdict(int)
    for ev in trace["traceEvents"]:
        if ev.get("ph") != "X":
            continue
        if dev_pids and ev.get("pid") not in dev_pids:
            continue
        tot[ev["name"]] += ev.get("dur", 0)
        cnt[ev["name"]] += 1
    items = sorted(tot.items(), key=lambda kv: -kv[1])
    grand = sum(tot.values())
    print(f"pids: { {p: pids.get(p, '?') for p in dev_pids} }")
    print(f"total device-op time: {grand / 1e3:.2f} ms over 50 ticks "
          f"({grand / 1e3 / 50:.3f} ms/tick)")
    for name, us in items[:40]:
        print(f"{us / 50:9.1f} us/tick  x{cnt[name] // 50:<4d} {name[:90]}")


if __name__ == "__main__":
    main()
