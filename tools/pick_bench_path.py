#!/usr/bin/env python
"""Pick the driver bench's execution path from measured results.

Scans MEASURE_RECOVERY.log for the flagship v1.1 rows (the metric
carries a ``_kernel`` tag when the pallas path ran, bench_suite.py)
and writes BENCH_CONFIG.json {"kernel": true} iff the kernel path
measurably beat the XLA path on hardware — bench.py then defaults the
driver's unattended end-of-round run to the winner.

A pin is only CLEARED on a COMPLETED losing comparison: both
comparable 1M rows present and the kernel failing the margin.  A log
missing either row (aborted pass, CPU-fallback flagship, relay death)
is not evidence the pin is stale — the last hardware-measured decision
stands (advisor r5).  Alias rows (bench_suite re-emitting a kernel
measurement under the plain historical name, tagged "alias_of") are
skipped: they are kernel numbers and must not impersonate XLA ones.

Usage: python tools/pick_bench_path.py [log=MEASURE_RECOVERY.log]
"""

from __future__ import annotations

import json
import os
import re
import sys

# the tool runs from arbitrary cwds (tpu_watch, tests) — anchor the
# repo root on the script location, not the working directory
sys.path.insert(0, os.path.dirname(os.path.dirname(  # graftlint: ignore[sys-path-insert]
    os.path.abspath(__file__))))

from go_libp2p_pubsub_tpu.utils.artifacts import write_json_atomic  # noqa: E402

# 7+ digit peer counts only: the 1M-scale TPU rows (1000000 plain /
# 1024000 kernel-padded).  The CPU-fallback row (100000 peers) is a
# 10x-smaller problem and must not enter the comparison.
ROW = re.compile(r'^\{.*"metric": "(gossipsub_v11_\d{7,}peers_100topics'
                 r'(_kernel)?_heartbeats_per_sec)"')


def main():
    log = sys.argv[1] if len(sys.argv) > 1 else "MEASURE_RECOVERY.log"
    xla, kern = [], []
    try:
        with open(log) as f:
            for line in f:
                m = ROW.match(line.strip())
                if not m:
                    continue
                try:
                    row = json.loads(line)
                    val = float(row["value"])
                except (ValueError, KeyError, TypeError):
                    continue   # truncated/garbled row (killed bench)
                if "alias_of" in row:
                    continue   # kernel value re-emitted under the
                    #            plain name for exact-name consumers
                (kern if m.group(2) else xla).append(val)
    except OSError as e:
        print(f"pick_bench_path: no log ({e}); leaving config untouched")
        return
    best_x = max(xla, default=None)
    best_k = max(kern, default=None)
    print(f"pick_bench_path: xla={best_x} kernel={best_k} (hb/s)")
    cfg = "BENCH_CONFIG.json"
    if best_x is None or best_k is None:
        # an incomplete comparison (aborted pass / CPU-fallback
        # flagship) is not evidence either way: keep whatever the last
        # completed hardware comparison decided
        print("pick_bench_path: missing a comparable 1M row — "
              "leaving any existing pin untouched")
        return
    # require a real margin: path choice should not flap on noise
    if best_k > 1.02 * best_x:
        write_json_atomic(cfg, {"kernel": True,
                                "measured_xla_hbs": best_x,
                                "measured_kernel_hbs": best_k},
                          indent=None)
        print("pick_bench_path: kernel path pinned")
    elif os.path.exists(cfg):
        # a COMPLETED comparison the kernel lost: the pin is genuinely
        # stale
        os.remove(cfg)
        print("pick_bench_path: stale kernel pin cleared")


if __name__ == "__main__":
    main()
