"""Abstract-eval audit: trace every simulator runner over the declared
config matrix and check compile-time invariants — without executing a
single sim tick.

The two seed-breaking jax-pin drifts fixed in PR 2 (``jax.lax.
reduce_or`` removed, ``pltpu.CompilerParams`` renamed) and the silent
regressions the 1M-peer hardware benches cannot afford (f64 promotion,
a dropped donation doubling the resident carry, a host callback
sneaking into the scan) are all visible in the jaxpr / lowered HLO.
This pass builds tiny sims for every combination of the DECLARED
matrix —

    3 simulators x telemetry {off,on} x faults {off,on}
                 x {sequential,batched}            (all three)
    gossipsub additionally x XLA {combined,split}  (force_split)

plus the round-10 VARIANT cases (sequential):

    floodsub  variant=gather  x telemetry x faults   (table path)
    randomsub variant=dense   x telemetry x faults   (MXU path)
    gossipsub variant=rpc     x telemetry, faults on (rpc_probe
              step + gossip_run_rpc_snapshots)
    gossipsub variant=hist    x faults, scored, all three histogram
              groups on (latency/degree/score bucket tallies)

— and for each case runs ``jax.make_jaxpr`` over the real runner
(scan included) plus ``.lower`` on the jitted entry point.  Checks:

- **no-64bit**: no float64/int64/uint64/complex128 aval anywhere in
  the jaxpr (recursively through pjit/scan/vmap sub-jaxprs).
- **no-widening-convert**: no ``convert_element_type`` whose target is
  a 64-bit dtype (the specific drift mode of a silent f64 promotion).
- **no-host-callback**: no callback/infeed/outfeed primitive — the
  scan must stay device-resident.
- **donation**: the lowered module aliases EVERY state-carry leaf to
  an output (``tf.aliasing_output`` per donated buffer) — donation
  declared in Python but dropped in lowering would silently double
  resident memory.
- **const-budget**: captured (closure) constants across all
  sub-jaxprs stay under ``CONST_BUDGET_BYTES`` — a step closure that
  captures a peer-sized array ships it once per compilation and hides
  it from the donation accounting.

Everything here is trace/lower only: building the tiny sims executes
ordinary array constructors, but auditing never runs a step
(tests/test_graftlint.py pins that with a backend-compile guard).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: peer count / topics / messages / candidates for the audit sims —
#: big enough to be structurally honest (W=1 word, C=8 ring), small
#: enough that a full-matrix trace stays in seconds
N, T, M, C = 80, 2, 6, 8
TICKS = 3
BATCH = 2
CONST_BUDGET_BYTES = 1 << 20

_64BIT = ("float64", "int64", "uint64", "complex128")


@dataclass
class AuditCase:
    sim: str                 # gossipsub | floodsub | randomsub
    split: bool              # gossipsub XLA formulation axis
    telemetry: bool
    faults: bool
    batched: bool
    variant: str = ""        # "" | gather | dense | rpc | hist | ...
    trace: object = field(repr=False, default=None)   # () -> ClosedJaxpr
    lower: object = field(repr=False, default=None)   # () -> lowered text
    n_carry_leaves: int = 0
    #: primitives that MUST appear in the traced jaxpr (round 14: the
    #: shard_map kernel dispatch asserts its boundary collectives —
    #: halo ppermutes + telemetry psum — are actually present)
    expect_primitives: tuple = ()

    @property
    def name(self) -> str:
        return (f"{self.sim}"
                f"{'-' + self.variant if self.variant else ''}"
                f"{'-split' if self.split else ''}"
                f"{'-tel' if self.telemetry else ''}"
                f"{'-faults' if self.faults else ''}"
                f"{'-batched' if self.batched else '-seq'}")


def declared_matrix() -> list[dict]:
    """The full audited combination set, as data (tests assert
    build_cases covers exactly this)."""
    out = []
    for sim in ("gossipsub", "floodsub", "randomsub"):
        splits = (False, True) if sim == "gossipsub" else (False,)
        for split in splits:
            for tel in (False, True):
                for faults in (False, True):
                    for batched in (False, True):
                        out.append(dict(sim=sim, split=split,
                                        telemetry=tel, faults=faults,
                                        batched=batched, variant=""))
    # round-10 variant cases: the newly-threaded table/MXU paths, the
    # rpc_probe snapshot runner, and the histogram frame groups — all
    # sequential (the base matrix already proves the batched axis)
    for tel in (False, True):
        for faults in (False, True):
            out.append(dict(sim="floodsub", split=False, telemetry=tel,
                            faults=faults, batched=False,
                            variant="gather"))
            out.append(dict(sim="randomsub", split=False, telemetry=tel,
                            faults=faults, batched=False,
                            variant="dense"))
    for tel in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=tel,
                        faults=True, batched=False, variant="rpc"))
    for faults in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=True,
                        faults=faults, batched=False, variant="hist"))
    # round-11 variant cases: the in-scan invariant checker (gossip on
    # both fault axes; flood/randomsub check their delivery subset
    # faulted), and the attack surface — eclipse + byzantine + traced
    # defense knobs + cold-restart churn under ONE step, sequential
    # plus the batched tournament runner
    for faults in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=faults, batched=False, variant="inv"))
    out.append(dict(sim="floodsub", split=False, telemetry=False,
                    faults=True, batched=False, variant="inv"))
    out.append(dict(sim="randomsub", split=False, telemetry=False,
                    faults=True, batched=False, variant="inv"))
    for batched in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=True, batched=batched,
                        variant="attack"))
    # round-12 knob cases: the config-as-data surface — per-replica
    # SimKnobs protocol points (degree family + gossip_factor +
    # backoff + defense weights + the traced fault drop rate) through
    # the sequential step and the knob-batched sweep runner
    # (gossip_run_knob_batch), donation + no-64-bit on the stacked
    # scalar operands
    for batched in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=True, batched=batched,
                        variant="knobs"))
    # round-13 variant cases: event-driven time (models/delays.py) —
    # delayed gossip through the combined path (sequential faulted +
    # knob-batched over HETEROGENEOUS delay points) and the split
    # path (separate mesh/gossip delay lines), delayed flood and
    # randomsub through the source-ring replay; donation + no-64-bit
    # must hold on the new [K, ...] delay-line carries
    for batched in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=True, batched=batched,
                        variant="delays"))
    out.append(dict(sim="gossipsub", split=True, telemetry=False,
                    faults=False, batched=False, variant="delays"))
    out.append(dict(sim="floodsub", split=False, telemetry=False,
                    faults=True, batched=False, variant="delays"))
    out.append(dict(sim="randomsub", split=False, telemetry=False,
                    faults=True, batched=False, variant="delays"))
    # round-19 delay-armed counter cases: the telemetry counters
    # group threads under delays (send tallies in delay_exchange,
    # arrival accounting off the dequeued adv_line/gsp_line observer
    # lines) — combined faulted, split, and the flood/randomsub
    # source-ring replay, all counter+wire-armed
    out.append(dict(sim="gossipsub", split=False, telemetry=True,
                    faults=True, batched=False, variant="delays"))
    out.append(dict(sim="gossipsub", split=True, telemetry=True,
                    faults=False, batched=False, variant="delays"))
    out.append(dict(sim="floodsub", split=False, telemetry=True,
                    faults=True, batched=False, variant="delays"))
    out.append(dict(sim="randomsub", split=False, telemetry=True,
                    faults=True, batched=False, variant="delays"))
    # round-14 variant cases: the whole-sim multi-chip surface
    # (parallel/sharded.py) — the carry-pinned GSPMD runner sequential
    # (faulted + delayed) and knob-batched, plus the shard_map kernel
    # dispatch: streamed (halo ppermutes + telemetry psum asserted in
    # the jaxpr) and delayed (the round-14 lift: no halo, arrival
    # words ride as sharded blocked operands).  Donation and the
    # 64-bit ban must hold across the sharding boundary.
    for batched in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=True, batched=batched,
                        variant="sharded"))
    out.append(dict(sim="gossipsub", split=False, telemetry=True,
                    faults=True, batched=False,
                    variant="sharded-kernel"))
    out.append(dict(sim="gossipsub", split=False, telemetry=False,
                    faults=True, batched=False,
                    variant="sharded-kernel-delays"))
    # round-15 segmented checkpoint cases: a checkpointed run is the
    # SAME jitted runner dispatched once per segment
    # (parallel/checkpoint.segment_dispatch), so every compile-time
    # invariant must hold at the SPLIT horizon too — donation
    # preserved across the segment boundary, no 64-bit avals, and no
    # host callback smuggled into a segment by the snapshot machinery
    # (snapshots are strictly between-dispatch host I/O)
    for batched in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=True, batched=batched, variant="ckpt"))
    out.append(dict(sim="floodsub", split=False, telemetry=False,
                    faults=True, batched=False, variant="ckpt"))
    # round-16 tick-resident fused window cases: the resident
    # multi-tick pallas dispatch (whole carry donated into the
    # windowed scan, no 64-bit avals anywhere in the fused kernel's
    # seeding/tick arithmetic)
    for faults in (False, True):
        out.append(dict(sim="gossipsub", split=False, telemetry=False,
                        faults=faults, batched=False, variant="fused"))
    # round-17 fused-sharded cases: the COMPOSED dispatch — one
    # resident pallas invocation per shard inside shard_map whose
    # in-kernel remote DMAs (dma_start/dma_wait) carry the ring-halo
    # boundary between grid ticks.  No ppermute may be needed (the
    # boundary never leaves the kernel); telemetry frames must psum
    # across the mesh; donation and the 64-bit ban must hold through
    # the shard_map boundary.
    for telemetry in (False, True):
        for faults in (False, True):
            out.append(dict(sim="gossipsub", split=False,
                            telemetry=telemetry, faults=faults,
                            batched=False, variant="fused-sharded"))
    return out


def _sim_inputs(n_topics: int, seed: int = 0):
    import numpy as np
    subs = np.zeros((N, n_topics), dtype=bool)
    subs[np.arange(N), np.arange(N) % n_topics] = True
    rng = np.random.default_rng(seed)
    topic = rng.integers(0, n_topics, M)
    origin = rng.integers(0, N // n_topics, M) * n_topics + topic
    ticks = np.zeros(M, dtype=np.int32)
    return subs, topic, origin, ticks


def audit_fault_schedule(seed: int = 0):
    """A schedule exercising all three fault classes within TICKS."""
    import numpy as np
    from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
    return FaultSchedule(
        n_peers=N, horizon=max(TICKS, 4),
        down_intervals=((0, 0, 2), (3, 1, 3)),
        drop_prob=0.1,
        partition_group=(np.arange(N) % 2).astype(np.int32),
        partition_windows=((1, 3),),
        seed=seed)


def build_cases() -> list[AuditCase]:
    """Build (params, state, step, runner) for every declared combo.
    This phase executes ordinary array builders; the returned cases'
    ``trace``/``lower`` thunks never execute anything."""
    import jax
    import go_libp2p_pubsub_tpu.models.floodsub as fs
    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    import go_libp2p_pubsub_tpu.models.invariants as iv
    import go_libp2p_pubsub_tpu.models.randomsub as rs
    import go_libp2p_pubsub_tpu.models.telemetry as tl
    from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets

    tcfg = tl.TelemetryConfig()
    cases = []
    for combo in declared_matrix():
        sim = combo["sim"]
        variant = combo.get("variant", "")
        tel = tcfg if combo["telemetry"] else None
        fsched = (audit_fault_schedule() if combo["faults"] else None)
        b = combo["batched"]

        if variant == "gather":
            # flood GATHER table path (round 10): symmetric nbrs table
            # equivalent to the circulant ring, faults compiled against
            # the table (compile_faults_gather)
            import numpy as np
            offs = tuple(int(o) for o in
                         make_circulant_offsets(T, C, N, seed=1))
            nbrs = np.stack([(np.arange(N) + o) % N for o in offs],
                            axis=1)
            mask = np.ones_like(nbrs, dtype=bool)
            subs, topic, origin, ticks = _sim_inputs(T)
            params, state = fs.make_flood_sim(
                nbrs, mask, subs, None, topic, origin, ticks,
                fault_schedule=fsched)
            core = fs.make_gather_step_core(telemetry=tel)
            runner = (tl.telemetry_run_curve if tel
                      else fs.flood_run_curve)
            args, statics = (params, state, TICKS, core, M), (2, 3, 4)

        elif variant == "dense":
            # randomsub DENSE MXU path (round 10): all-pairs adjacency,
            # faults via compile_faults_dense (canonical-pair coins)
            rcfg = rs.RandomSubSimConfig(
                offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
                n_topics=T, d=3)
            subs, topic, origin, ticks = _sim_inputs(T)
            params, state = rs.make_randomsub_sim(
                rcfg, subs, topic, origin, ticks, dense=True,
                fault_schedule=fsched)
            step = rs.make_randomsub_dense_step(rcfg, telemetry=tel)
            runner = tl.telemetry_run if tel else rs.randomsub_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "rpc":
            # per-edge RPC probe runner (round 10): the snapshot scan
            # that feeds interop.export.rpc_events
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            subs, topic, origin, ticks = _sim_inputs(T)
            params, state = gs.make_gossip_sim(
                cfg, subs, topic, origin, ticks, seed=0,
                fault_schedule=fsched)
            step = gs.make_gossip_step(cfg, telemetry=tel,
                                       rpc_probe=True)
            runner = gs.gossip_run_rpc_snapshots
            args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "inv":
            # the in-scan invariant checker (round 11): gossipsub runs
            # every group on a scored sim; flood/randomsub their
            # delivery subset.  States are invariant-armed.
            icfg = iv.InvariantConfig()
            subs, topic, origin, ticks = _sim_inputs(T)
            if sim == "gossipsub":
                cfg = gs.GossipSimConfig(
                    offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                    n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2,
                    d_out=1, d_lazy=2, backoff_ticks=8)
                sc = gs.ScoreSimConfig()
                params, state = gs.make_gossip_sim(
                    cfg, subs, topic, origin, ticks, seed=0,
                    score_cfg=sc, fault_schedule=fsched)
                state = iv.attach(state)
                step = gs.make_gossip_step(cfg, sc, invariants=icfg)
                runner = gs.gossip_run
                args, statics = (params, state, TICKS, step), (2, 3)
            elif sim == "floodsub":
                offs = tuple(int(o) for o in
                             make_circulant_offsets(T, C, N, seed=1))
                params, state = fs.make_flood_sim(
                    None, None, subs, None, topic, origin, ticks,
                    fault_schedule=fsched, fault_offsets=offs)
                state = iv.attach(state)
                core = fs.make_circulant_step_core(offs,
                                                   invariants=icfg)
                runner = fs.flood_run_curve
                args, statics = ((params, state, TICKS, core, M),
                                 (2, 3, 4))
            else:   # randomsub
                rcfg = rs.RandomSubSimConfig(
                    offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
                    n_topics=T, d=3)
                params, state = rs.make_randomsub_sim(
                    rcfg, subs, topic, origin, ticks,
                    fault_schedule=fsched)
                state = iv.attach(state)
                step = rs.make_randomsub_step(rcfg, invariants=icfg)
                runner = rs.randomsub_run
                args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "attack":
            # the round-11 attack surface under ONE step: eclipse +
            # byzantine + both spam behaviors compiled in, traced
            # defense knobs, cold-restart churn — sequential and
            # through the batched tournament runner
            import dataclasses
            import numpy as np
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            sc = gs.ScoreSimConfig(
                sybil_ihave_spam=True, sybil_iwant_spam=True,
                sybil_eclipse=True, byzantine_mutation=True)
            subs, topic, origin, ticks = _sim_inputs(T)

            def build_attack(r):
                sched = dataclasses.replace(audit_fault_schedule(r),
                                            cold_restart=True)
                return gs.make_gossip_sim(
                    cfg, subs, topic, origin, ticks, seed=r,
                    score_cfg=sc,
                    sybil=(np.arange(N) % 11) == 0,
                    eclipse_sybil=(np.arange(N) % 11) == 1,
                    eclipse_victim=(np.arange(N) % 11) == 2,
                    byzantine=(np.arange(N) % 11) == 3,
                    score_knobs={"behaviour_penalty_weight": -20.0},
                    fault_schedule=sched)

            step = gs.make_gossip_step(cfg, sc)
            if b:
                builds = [build_attack(r) for r in range(BATCH)]
                params = gs.stack_trees([p for p, _ in builds])
                state = gs.stack_trees([s for _, s in builds])
                runner = gs.gossip_run_tournament
            else:
                params, state = build_attack(0)
                runner = gs.gossip_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "knobs":
            # the round-12 sweep surface: HETEROGENEOUS SimKnobs
            # points (distinct degree/coverage/backoff/defense/fault
            # values per replica) under one step — the scenario-server
            # workload (tools/sweepd.py)
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            sc = gs.ScoreSimConfig()
            subs, topic, origin, ticks = _sim_inputs(T)

            def build_knob(r):
                return gs.make_gossip_sim(
                    cfg, subs, topic, origin, ticks, seed=r,
                    score_cfg=sc,
                    fault_schedule=audit_fault_schedule(r),
                    sim_knobs={"d": 3 + r, "d_lazy": 2 + r,
                               "gossip_factor": 0.25 + 0.25 * r,
                               "backoff_ticks": 8 + r,
                               "drop_prob": 0.05 * (r + 1),
                               "behaviour_penalty_weight":
                                   -10.0 * (r + 1)})

            step = gs.make_gossip_step(cfg, sc)
            if b:
                builds = [build_knob(r) for r in range(BATCH)]
                params = gs.stack_trees([p for p, _ in builds])
                state = gs.stack_trees([s for _, s in builds])
                runner = gs.gossip_run_knob_batch
            else:
                params, state = build_knob(0)
                runner = gs.gossip_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "delays":
            # round-13 event-driven time: the K-slot delay lines ride
            # the donated state carry; the batched case sweeps
            # HETEROGENEOUS delay knob points through the knob runner
            from go_libp2p_pubsub_tpu.models.delays import DelayConfig
            dc = DelayConfig(base=2, jitter=1, k_slots=4)
            subs, topic, origin, ticks = _sim_inputs(T)
            if sim == "gossipsub":
                cfg = gs.GossipSimConfig(
                    offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                    n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2,
                    d_out=1, d_lazy=2, backoff_ticks=8)
                sc = gs.ScoreSimConfig()
                split = combo["split"]

                def build_delay(r):
                    return gs.make_gossip_sim(
                        cfg, subs, topic, origin, ticks, seed=r,
                        score_cfg=sc, delays=dc, delays_split=split,
                        delays_counters=tel is not None,
                        fault_schedule=(audit_fault_schedule(r)
                                        if fsched else None),
                        sim_knobs=({"delay_base": 1 + r,
                                    "delay_jitter": r} if b
                                   else None))

                step = gs.make_gossip_step(cfg, sc, telemetry=tel,
                                           force_split=split)
                if b:
                    builds = [build_delay(r) for r in range(BATCH)]
                    params = gs.stack_trees([p for p, _ in builds])
                    state = gs.stack_trees([s for _, s in builds])
                    runner = gs.gossip_run_knob_batch
                else:
                    params, state = build_delay(0)
                    runner = tl.telemetry_run if tel else gs.gossip_run
                args, statics = (params, state, TICKS, step), (2, 3)
            elif sim == "floodsub":
                offs = tuple(int(o) for o in
                             make_circulant_offsets(T, C, N, seed=1))
                params, state = fs.make_flood_sim(
                    None, None, subs, None, topic, origin, ticks,
                    fault_schedule=fsched, fault_offsets=offs,
                    delays=dc)
                core = fs.make_circulant_step_core(offs, telemetry=tel)
                runner = (tl.telemetry_run_curve if tel
                          else fs.flood_run_curve)
                args, statics = ((params, state, TICKS, core, M),
                                 (2, 3, 4))
            else:   # randomsub
                rcfg = rs.RandomSubSimConfig(
                    offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
                    n_topics=T, d=3)
                params, state = rs.make_randomsub_sim(
                    rcfg, subs, topic, origin, ticks,
                    fault_schedule=fsched, delays=dc)
                step = rs.make_randomsub_step(rcfg, telemetry=tel)
                runner = tl.telemetry_run if tel else rs.randomsub_run
                args, statics = (params, state, TICKS, step), (2, 3)

        elif variant == "sharded":
            # round-14 whole-sim GSPMD sharding: the carry-pinned
            # runners over a 2-shard CPU mesh (1-shard when the host
            # exposes a single CPU device — the trace is identical),
            # with the full composition live: faults + delays +
            # (batched) heterogeneous knob points
            from go_libp2p_pubsub_tpu.models.delays import DelayConfig
            from go_libp2p_pubsub_tpu.parallel import mesh as pmesh
            from go_libp2p_pubsub_tpu.parallel import sharded as psh
            mesh = pmesh.make_mesh(devices=jax.devices("cpu")[:2])
            dc = DelayConfig(base=2, jitter=1, k_slots=4)
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            sc = gs.ScoreSimConfig()
            subs, topic, origin, ticks = _sim_inputs(T)

            def build_shard(r):
                return gs.make_gossip_sim(
                    cfg, subs, topic, origin, ticks, seed=r,
                    score_cfg=sc, delays=dc,
                    fault_schedule=audit_fault_schedule(r),
                    sim_knobs=({"delay_base": 1 + r,
                                "gossip_factor": 0.25 + 0.25 * r}
                               if b else None))

            step = gs.make_gossip_step(cfg, sc)
            if b:
                builds = [build_shard(r) for r in range(BATCH)]
                params = gs.stack_trees([p for p, _ in builds])
                state = gs.stack_trees([s for _, s in builds])
                params, state, shardings = psh.shard_sim(
                    params, state, mesh, N)
                runner = psh.sharded_gossip_run_knob_batch
            else:
                params, state = build_shard(0)
                params, state, shardings = psh.shard_sim(
                    params, state, mesh, N)
                runner = psh.sharded_gossip_run
            args = (params, state, TICKS, step, shardings)
            statics = (2, 3, 4)

        elif variant in ("sharded-kernel", "sharded-kernel-delays"):
            # round-14 shard_map kernel dispatch, traced at the real
            # divisibility shape (n = D * block, no pad lanes).  The
            # streamed case must show the halo collective-permutes and
            # the telemetry psum IN THE JAXPR; the delayed case is the
            # lifted round-14 path — no halo (arrival words are
            # per-receiver blocked operands), shard_map still present.
            import numpy as np
            from go_libp2p_pubsub_tpu.models.delays import DelayConfig
            from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
            from go_libp2p_pubsub_tpu.parallel import mesh as pmesh
            mesh = pmesh.make_mesh(devices=jax.devices("cpu")[:2])
            D = mesh.shape[pmesh.PEER_AXIS]
            kb = 1024            # contracts.KERNEL_BLOCK
            n_k = D * kb
            delayed = variant.endswith("delays")
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, n_k, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            sc = gs.ScoreSimConfig()
            subs_k = np.zeros((n_k, T), dtype=bool)
            subs_k[np.arange(n_k), np.arange(n_k) % T] = True
            rng = np.random.default_rng(0)
            topic_k = rng.integers(0, T, M)
            origin_k = rng.integers(0, n_k // T, M) * T + topic_k
            ticks_k = np.zeros(M, dtype=np.int32)
            sched = FaultSchedule(
                n_peers=n_k, horizon=max(TICKS, 4),
                down_intervals=((0, 0, 2), (3, 1, 3)),
                drop_prob=0.1, seed=0)
            params, state = gs.make_gossip_sim(
                cfg, subs_k, topic_k, origin_k, ticks_k, seed=0,
                score_cfg=sc, fault_schedule=sched,
                pad_to_block=kb,
                delays=(DelayConfig(base=2, jitter=1, k_slots=4)
                        if delayed else None))
            step = gs.make_gossip_step(
                cfg, sc, receive_block=kb, receive_interpret=True,
                shard_mesh=mesh,
                telemetry=(tl.TelemetryConfig() if combo["telemetry"]
                           else None))
            runner = tl.telemetry_run if combo["telemetry"] \
                else gs.gossip_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif variant in ("fused", "fused-sharded"):
            # round-16 tick-resident fused window, traced at the fused
            # alignment shape (n_true == n_pad, n % 1024 == 0 — the
            # shared N=80 can never take the resident path).  The
            # resident case must donate the whole carry into the
            # windowed dispatch with no 64-bit avals in the in-kernel
            # tick/seed arithmetic.  The round-17 fused-sharded case
            # is the COMPOSED dispatch: capability must ACCEPT, and
            # the traced program must be the shard_map of one resident
            # pallas call per shard with the in-kernel remote-DMA halo
            # (dma_start/dma_wait) — no ppermute boundary collectives.
            import numpy as np
            from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
            sharded_f = variant == "fused-sharded"
            if sharded_f:
                from go_libp2p_pubsub_tpu.parallel import mesh as pmesh
                from go_libp2p_pubsub_tpu.parallel import sharded as psh
                mesh_f = pmesh.make_mesh(devices=jax.devices("cpu")[:2])
                D_f = mesh_f.shape[pmesh.PEER_AXIS]
            else:
                mesh_f, D_f = None, 1
            kb = 1024            # contracts.KERNEL_BLOCK == FUSED_ALIGN
            n_f = D_f * kb
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, n_f, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            subs_f = np.zeros((n_f, T), dtype=bool)
            subs_f[np.arange(n_f), np.arange(n_f) % T] = True
            rng = np.random.default_rng(0)
            topic_f = rng.integers(0, T, M)
            origin_f = rng.integers(0, n_f // T, M) * T + topic_f
            ticks_f = np.zeros(M, dtype=np.int32)
            sched = (FaultSchedule(
                n_peers=n_f, horizon=4,
                down_intervals=((0, 0, 2), (3, 1, 3)),
                drop_prob=0.1, seed=0) if combo["faults"] else None)
            params, state = gs.make_gossip_sim(
                cfg, subs_f, topic_f, origin_f, ticks_f, seed=0,
                fault_schedule=sched, pad_to_block=kb)
            window = gs.make_fused_window(
                cfg, None, ticks_fused=2, receive_block=kb,
                receive_interpret=True, shard_mesh=mesh_f,
                telemetry=(tl.TelemetryConfig() if combo["telemetry"]
                           else None),
                on_refusal="raise")
            reason = window.capability(params, state)
            assert reason is None, reason
            if sharded_f:
                params, state, sh_f = psh.shard_sim(
                    params, state, mesh_f, n_f)
                runner = psh.sharded_gossip_run_fused
                args = (params, state, 4, window, sh_f)
                statics = (2, 3, 4)
            else:
                runner = gs.gossip_run_fused
                args, statics = (params, state, 4, window), (2, 3)

        elif variant == "ckpt":
            # round-15 segmented checkpoint runners: trace the engine's
            # dispatch table at the 2-segment split horizon with the
            # full composition live (faults + delays; the batched case
            # is the knob-batch segment).  The snapshot I/O itself is
            # host-side between dispatches — nothing of it may appear
            # in the traced segment.
            from go_libp2p_pubsub_tpu.models.delays import DelayConfig
            from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
            dispatch = ck.segment_dispatch()
            seg = max(1, TICKS // 2)
            subs, topic, origin, ticks = _sim_inputs(T)
            if sim == "gossipsub":
                cfg = gs.GossipSimConfig(
                    offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                    n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2,
                    d_out=1, d_lazy=2, backoff_ticks=8)
                sc = gs.ScoreSimConfig()
                dc = DelayConfig(base=2, jitter=1, k_slots=4)
                step = gs.make_gossip_step(cfg, sc)

                def build_ck(r):
                    return gs.make_gossip_sim(
                        cfg, subs, topic, origin, ticks, seed=r,
                        score_cfg=sc, delays=dc,
                        fault_schedule=audit_fault_schedule(r))

                if b:
                    builds = [build_ck(r) for r in range(BATCH)]
                    params = gs.stack_trees([p for p, _ in builds])
                    state = gs.stack_trees([s for _, s in builds])
                    runner = dispatch["gossipsub-batch"]
                else:
                    params, state = build_ck(0)
                    runner = dispatch["gossipsub"]
                args, statics = (params, state, seg, step), (2, 3)
            else:   # floodsub
                offs = tuple(int(o) for o in
                             make_circulant_offsets(T, C, N, seed=1))
                params, state = fs.make_flood_sim(
                    None, None, subs, None, topic, origin, ticks,
                    fault_schedule=fsched, fault_offsets=offs)
                step_fn = fs.make_circulant_flood_step(offs)
                runner = dispatch["floodsub"]
                args, statics = (params, state, seg, step_fn), (2, 3)

        elif variant == "hist":
            # all three histogram groups live (score_hist needs a
            # scored sim)
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            sc = gs.ScoreSimConfig()
            tel_h = tl.TelemetryConfig(latency_hist=True,
                                       degree_hist=True,
                                       score_hist=True)
            subs, topic, origin, ticks = _sim_inputs(T)
            params, state = gs.make_gossip_sim(
                cfg, subs, topic, origin, ticks, seed=0, score_cfg=sc,
                fault_schedule=fsched)
            step = gs.make_gossip_step(cfg, sc, telemetry=tel_h)
            runner = tl.telemetry_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif sim == "gossipsub":
            cfg = gs.GossipSimConfig(
                offsets=gs.make_gossip_offsets(T, C, N, seed=1),
                n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
                d_lazy=2, backoff_ticks=8)
            step = gs.make_gossip_step(cfg, force_split=combo["split"],
                                       telemetry=tel)
            subs, topic, origin, ticks = _sim_inputs(T)
            spec = dict(subs=subs, msg_topic=topic, msg_origin=origin,
                        msg_publish_tick=ticks)
            if b:
                specs = [dict(spec, seed=r,
                              fault_schedule=(audit_fault_schedule(r)
                                              if fsched else None))
                         for r in range(BATCH)]
                params, state = gs.stack_sims(cfg, specs)
                runner = (tl.telemetry_run_batch if tel
                          else gs.gossip_run_batch)
            else:
                params, state = gs.make_gossip_sim(
                    cfg, seed=0, fault_schedule=fsched, **spec)
                runner = tl.telemetry_run if tel else gs.gossip_run
            args, statics = (params, state, TICKS, step), (2, 3)

        elif sim == "floodsub":
            offs = tuple(int(o) for o in
                         make_circulant_offsets(T, C, N, seed=1))
            subs, topic, origin, ticks = _sim_inputs(T)

            def build_flood(sched):
                return fs.make_flood_sim(
                    None, None, subs, None, topic, origin, ticks,
                    fault_schedule=sched, fault_offsets=offs)

            if b:
                builds = [build_flood(audit_fault_schedule(r)
                                      if fsched else None)
                          for r in range(BATCH)]
                params = fs.stack_trees([p for p, _ in builds])
                state = fs.stack_trees([s for _, s in builds])
                if tel:
                    core = fs.make_circulant_step_core(offs,
                                                       telemetry=tel)
                    runner, args, statics = (
                        tl.telemetry_run_batch,
                        (params, state, TICKS, core), (2, 3))
                else:
                    step_fn = fs.make_circulant_flood_step(offs)
                    runner, args, statics = (
                        fs.flood_run_batch,
                        (params, state, TICKS, step_fn), (2, 3))
            else:
                params, state = build_flood(fsched)
                core = fs.make_circulant_step_core(offs, telemetry=tel)
                runner = (tl.telemetry_run_curve if tel
                          else fs.flood_run_curve)
                args, statics = (params, state, TICKS, core, M), (2, 3, 4)

        else:   # randomsub
            rcfg = rs.RandomSubSimConfig(
                offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
                n_topics=T, d=3)
            step = rs.make_randomsub_step(rcfg, telemetry=tel)
            subs, topic, origin, ticks = _sim_inputs(T)

            def build_rsub(sched):
                return rs.make_randomsub_sim(
                    rcfg, subs, topic, origin, ticks,
                    fault_schedule=sched)

            if b:
                builds = [build_rsub(audit_fault_schedule(r)
                                     if fsched else None)
                          for r in range(BATCH)]
                params = rs.stack_trees([p for p, _ in builds])
                state = rs.stack_trees([s for _, s in builds])
                runner = (tl.telemetry_run_batch if tel
                          else rs.randomsub_run_batch)
            else:
                params, state = build_rsub(fsched)
                runner = tl.telemetry_run if tel else rs.randomsub_run
            args, statics = (params, state, TICKS, step), (2, 3)

        case = AuditCase(**combo)
        case.n_carry_leaves = len(jax.tree_util.tree_leaves(state))
        if variant == "sharded-kernel":
            case.expect_primitives = ("shard_map", "ppermute", "psum")
        elif variant == "sharded-kernel-delays":
            # the lifted delay path needs NO halo — but the dispatch
            # must still be the shard_map one
            case.expect_primitives = ("shard_map",)
        elif variant == "fused-sharded":
            # round 17: the composed dispatch — one resident pallas
            # call per shard under shard_map, the ring-halo boundary
            # carried by in-kernel remote DMAs between grid ticks
            # (no ppermute: the boundary never leaves the kernel);
            # telemetry tallies psum across the mesh
            case.expect_primitives = ("shard_map", "pallas_call",
                                      "dma_start", "dma_wait")
            if combo["telemetry"]:
                case.expect_primitives += ("psum",)
        # late-binding via default args: the thunks must be pure
        # trace/lower closures over THIS combo's objects
        case.trace = (lambda r=runner, a=args, s=statics:
                      jax.make_jaxpr(r, static_argnums=s)(*a))
        case.lower = (lambda r=runner, a=args:
                      r.lower(*a).as_text())
        cases.append(case)
    return cases


# --------------------------------------------------------------------------
# Jaxpr walking + the checks
# --------------------------------------------------------------------------


def _iter_eqns(jaxpr):
    """Every eqn in a (Closed)Jaxpr, recursively through sub-jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for val in eqn.params.values():
            subs = val if isinstance(val, (tuple, list)) else (val,)
            for sub in subs:
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub)


def _iter_consts(jaxpr):
    """Captured constants, recursively (ClosedJaxpr.consts at every
    nesting level)."""
    consts = getattr(jaxpr, "consts", None)
    if consts:
        yield from consts
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        for val in eqn.params.values():
            subs = val if isinstance(val, (tuple, list)) else (val,)
            for sub in subs:
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _iter_consts(sub)


def audit_case(case: AuditCase) -> list[str]:
    """Problem strings for one case (empty = clean)."""
    problems = []
    closed = case.trace()

    dtypes = set()
    prims_seen = set()
    for eqn in _iter_eqns(closed):
        prim = eqn.primitive.name
        prims_seen.add(prim)
        if "callback" in prim or prim in ("infeed", "outfeed"):
            problems.append(
                f"{case.name}: no-host-callback: primitive '{prim}' "
                "in the traced runner")
        if prim == "convert_element_type":
            dst = str(eqn.params.get("new_dtype"))
            if dst in _64BIT:
                problems.append(
                    f"{case.name}: no-widening-convert: "
                    f"convert_element_type -> {dst}")
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                dtypes.add(str(aval.dtype))
    bad = sorted(d for d in dtypes if d in _64BIT)
    if bad:
        problems.append(
            f"{case.name}: no-64bit: {', '.join(bad)} aval(s) in the "
            "traced runner")

    missing = [p for p in case.expect_primitives
               if p not in prims_seen]
    if missing:
        problems.append(
            f"{case.name}: expected-collectives: primitive(s) "
            f"{', '.join(missing)} absent from the traced runner — "
            "the sharded dispatch lost its boundary collectives")

    const_bytes = sum(getattr(c, "nbytes", 0)
                      for c in _iter_consts(closed))
    if const_bytes > CONST_BUDGET_BYTES:
        problems.append(
            f"{case.name}: const-budget: {const_bytes} bytes of "
            f"captured constants > {CONST_BUDGET_BYTES}")

    lowered = case.lower()
    aliased, nargs = _aliased_args(lowered)
    # every runner donates exactly its state carry, which flattens to
    # the LAST n_carry_leaves entry-function arguments (params leaves
    # first) — so the aliased set must be exactly that trailing range.
    # A bare occurrence count would let aliasing on OTHER buffers mask
    # a dropped state donation.  Multi-device (sharded) lowerings
    # record donation as ``jax.buffer_donor`` instead of
    # ``tf.aliasing_output`` (aliasing is resolved at compile time,
    # after GSPMD fixes the output shardings) — _aliased_args accepts
    # either marker.
    expect = set(range(nargs - case.n_carry_leaves, nargs))
    if aliased != expect:
        problems.append(
            f"{case.name}: donation: aliased/donor args "
            f"{sorted(aliased)} != the state-carry args "
            f"{sorted(expect)} — the donated carry is not (exactly) "
            "the aliased buffer set")
    return problems


def _aliased_args(lowered: str) -> tuple[set, int]:
    """(indices of @main arguments carrying tf.aliasing_output OR
    jax.buffer_donor — the multi-device donation marker — plus the
    total argument count) from the lowered StableHLO text.

    Parsed by splitting the signature at each ``%argN:`` rather than
    by an attr-dict regex: sharded lowerings carry ``mhlo.sharding``
    attr strings with NESTED braces ("{devices=[2]<=[2]}"), which a
    flat ``\\{[^{}]*\\}`` match silently skips."""
    m = re.search(r"func\.func public @main\((.*?)\)\s*->", lowered,
                  re.S)
    if m is None:
        return set(), 0
    sig = m.group(1)
    nargs = len(set(re.findall(r"%arg(\d+):", sig)))
    aliased = set()
    for part in re.split(r"(?=%arg\d+:)", sig):
        am = re.match(r"%arg(\d+):", part)
        if am and ("tf.aliasing_output" in part
                   or "jax.buffer_donor" in part):
            aliased.add(int(am.group(1)))
    return aliased, nargs


def run_audit(cases=None, log=None) -> list[str]:
    """The whole matrix; returns all problems (empty = clean)."""
    if cases is None:
        cases = build_cases()
    problems = []
    for case in cases:
        probs = audit_case(case)
        if log is not None:
            log(f"  audit {case.name}: "
                f"{'OK' if not probs else f'{len(probs)} problem(s)'}")
        problems.extend(probs)
    return problems
