"""Per-line graftlint suppressions and per-file scope directives.

Suppressions are deliberately NOT ``noqa``: a graftlint finding is a
repo-specific contract violation, and silencing one must be a
separate, auditable decision from silencing a generic style rule.
The syntax (in a real comment — string literals and docstrings that
merely QUOTE a pragma are ignored, the file is tokenized)::

    some_code()  # graftlint: ignore[rule-name]
    other_code()  # graftlint: ignore[rule-a,rule-b]
    anything()   # graftlint: ignore

A bare ``ignore`` silences every rule on that line; the bracketed form
silences only the named rules (the audit-friendly form — prefer it).
``grep -rn "graftlint: ignore"`` lists every suppression.

Fixture files (and any file whose on-disk location does not reflect
the scope its rules should be checked under) may pin their scope with
a file-level directive: a comment that starts its own line, anywhere
in the file::

    # graftlint: scope=model
"""

from __future__ import annotations

import io
import re
import tokenize

_IGNORE_RE = re.compile(
    r"#\s*graftlint:\s*ignore(?:\[([A-Za-z0-9_\-, ]+)\])?")
_SCOPE_RE = re.compile(r"^#\s*graftlint:\s*scope=([a-z]+)\s*$")

#: scopes a file may claim / be classified into
SCOPES = ("model", "core", "service", "tools", "tests", "other")


def _comments(src: str):
    """(line, column, text) of every real COMMENT token — tokenizing
    (rather than regexing raw lines) is what keeps directives quoted
    inside string literals or docstrings from being honored."""
    try:
        toks = tokenize.generate_tokens(io.StringIO(src).readline)
        return [(t.start[0], t.start[1], t.string) for t in toks
                if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparseable source: the AST pass reports it separately
        return []


def pragma_lines(src: str) -> dict[int, frozenset[str] | None]:
    """Map line number -> suppressed rule names (None = all rules)."""
    out: dict[int, frozenset[str] | None] = {}
    for line, _col, text in _comments(src):
        m = _IGNORE_RE.search(text)
        if m:
            names = m.group(1)
            out[line] = (None if names is None else frozenset(
                n.strip() for n in names.split(",") if n.strip()))
    return out


def validate_pragmas(src: str, known) -> list[tuple[int, str]]:
    """(line, name) for every bracketed ignore naming a rule not in
    ``known``.  A typo'd name is a suppression that guards NOTHING
    while looking auditable — round 19 rejects it by name instead of
    silently accepting it (``pragma_lines`` itself stays parse-only so
    docs and tests can use placeholder names)."""
    out: list[tuple[int, str]] = []
    for line, _col, text in _comments(src):
        m = _IGNORE_RE.search(text)
        if m is None or m.group(1) is None:
            continue
        for name in m.group(1).split(","):
            name = name.strip()
            if name and name not in known:
                out.append((line, name))
    return out


def suppressed(pragmas: dict, line: int, rule: str) -> bool:
    if line not in pragmas:
        return False
    names = pragmas[line]
    return names is None or rule in names


def scope_override(src: str) -> str | None:
    """The file's ``# graftlint: scope=...`` directive, if any (a
    comment that starts its own line).  A directive naming an unknown
    scope raises ValueError carrying a ``lineno`` attribute
    (check_file converts it into a located finding rather than
    crashing the run)."""
    for line, col, text in _comments(src):
        if col != 0:
            continue          # trailing comments are not directives
        m = _SCOPE_RE.match(text.strip())
        if m:
            scope = m.group(1)
            if scope not in SCOPES:
                err = ValueError(
                    f"unknown graftlint scope directive {scope!r} "
                    f"(one of {SCOPES})")
                err.lineno = line
                raise err
            return scope
    return None
