"""Exhaustive capability-lattice audit: every cell PLANS or REFUSES.

The round-20 static pass behind the capability planner
(``models/plan.py``).  It enumerates the full feature lattice — all
six execution paths crossed with the feature axes (faults, telemetry,
scores, delays and their armed observer/probe lines, knobs, attacks,
PX/direct overlays, padding/alignment, fused residency, the sharded
fused composition, checkpoint segmentation, and the serving surface)
— and cross-checks EVERY cell's planner verdict against reality:

- a **PLAN** cell must trace (``jax.make_jaxpr`` on the real step /
  window / runner, never executing a tick — enforced by the same
  backend-compile guard the jaxpr audit's tests pin) and its jaxpr
  must contain the plan's declared primitives and none of its
  forbidden ones (e.g. the sharded fused composition must carry
  ``shard_map`` + ``dma_start``/``dma_wait`` and must NOT fall back
  to the ``ppermute`` halo);
- a **REFUSE** cell must raise the planner's EXACT named string, as
  the planner's exception class, from the real entry point;
- a cell whose verdict is neither, or that lacks its trace/provoke
  arm, is an audit failure — 100% of the lattice classifies.

``capability_matrix()`` serializes the verdicts as the golden matrix
(``PLAN_r19.json``, gated by ``tools/planstat.py --check``);
``matrix_markdown()`` renders the README capability table from the
same verdicts, so the prose can never drift from the planner.

Cells marked ``fast`` form the seconds-scale preflight subset
(``--plan-fast`` / tools/lint.sh / tier-1 tests); the full sweep runs
in graftlint's default suite and measure_all.sh step 0.5.
"""

from __future__ import annotations

import dataclasses

from .contracts import C, KERNEL_BLOCK, M, N, T

MATRIX_SCHEMA = "plan-matrix-v1"
MATRIX_ROUND = 19


@dataclasses.dataclass(frozen=True)
class Cell:
    """One lattice cell.  ``build()`` returns a dict with ``verdict``
    (the planner's ExecutionPlan | Refusal) plus the arm that proves
    it: ``trace`` (() -> ClosedJaxpr, PLAN cells) or ``provoke``
    (() -> None that must raise, REFUSE cells)."""

    id: str
    path: str                # lattice path / composition family
    feature: str
    build: object
    fast: bool = False


# --------------------------------------------------------------------------
# Build helpers (lazy jax imports; shapes distinct per concern)
# --------------------------------------------------------------------------


def _gossip_build(n=N, pad=None, paired=False, offsets=None, **kw):
    import numpy as np

    import go_libp2p_pubsub_tpu.models.gossipsub as gs
    cfg = gs.GossipSimConfig(
        offsets=(offsets if offsets is not None
                 else gs.make_gossip_offsets(T, C, n, seed=1,
                                             paired=paired)),
        n_topics=T, paired_topics=paired, d=3, d_lo=2, d_hi=6,
        d_score=2, d_out=1, d_lazy=2, backoff_ticks=8)
    subs = np.zeros((n, T), dtype=bool)
    own = np.arange(n) % T
    subs[np.arange(n), own] = True
    if paired:
        subs[np.arange(n), (own + T // 2) % T] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, n // T, M) * T + topic
    ticks = np.zeros(M, dtype=np.int32)
    if pad is not None:
        kw["pad_to_block"] = pad
    params, state = gs.make_gossip_sim(cfg, subs, topic, origin,
                                       ticks, seed=0, **kw)
    return gs, cfg, params, state


def _sched(n=N, cold=False):
    from go_libp2p_pubsub_tpu.models.faults import FaultSchedule
    return FaultSchedule(n_peers=n, horizon=4,
                         down_intervals=((0, 0, 2), (3, 1, 3)),
                         drop_prob=0.1, cold_restart=cold, seed=0)


def _delay_cfg(k=4):
    from go_libp2p_pubsub_tpu.models.delays import DelayConfig
    return DelayConfig(base=1, jitter=1, k_slots=k)


def _trace_step(gs, cfg, params, state, sc=None, **step_kw):
    import jax
    step = gs.make_gossip_step(cfg, sc, **step_kw)
    return jax.make_jaxpr(step)(params, state)


def _eval_step(gs, cfg, params, state, sc=None, **step_kw):
    import jax
    step = gs.make_gossip_step(cfg, sc, **step_kw)
    jax.eval_shape(step, params, state)   # refusal cells: must raise


def _window(gs, cfg, sc=None, ticks=2, block=KERNEL_BLOCK, **kw):
    return gs.make_fused_window(cfg, sc, ticks_fused=ticks,
                                receive_block=block,
                                receive_interpret=True,
                                on_refusal="raise", **kw)


def _mesh(devices):
    import jax

    from go_libp2p_pubsub_tpu.parallel import mesh as pmesh
    return pmesh.make_mesh(devices=jax.devices("cpu")[:devices])


def _flood_inputs(n=N):
    import numpy as np
    subs = np.zeros((n, T), dtype=bool)
    subs[np.arange(n), np.arange(n) % T] = True
    rng = np.random.default_rng(0)
    topic = rng.integers(0, T, M)
    origin = rng.integers(0, n // T, M) * T + topic
    ticks = np.zeros(M, dtype=np.int32)
    return subs, topic, origin, ticks


def _circ_offsets():
    from go_libp2p_pubsub_tpu.ops.graph import make_circulant_offsets
    return tuple(int(o) for o in make_circulant_offsets(T, C, N,
                                                        seed=1))


def _gather_table():
    import numpy as np
    offs = _circ_offsets()
    nbrs = np.stack([(np.arange(N) + o) % N for o in offs], axis=1)
    return nbrs, np.ones_like(nbrs, dtype=bool)


# --------------------------------------------------------------------------
# The lattice
# --------------------------------------------------------------------------


def build_cells() -> list[Cell]:
    from go_libp2p_pubsub_tpu.models import plan as _plan

    cells: list[Cell] = []

    def cell(id, path, feature, fn, fast=False):
        cells.append(Cell(id, path, feature, fn, fast))

    # -- gossip-xla ---------------------------------------------------------

    def xla_plain():
        gs, cfg, params, state = _gossip_build()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state))
    cell("xla/plain", "gossip-xla", "plain", xla_plain, fast=True)

    def xla_faults():
        gs, cfg, params, state = _gossip_build(fault_schedule=_sched())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state))
    cell("xla/faults", "gossip-xla", "faults", xla_faults)

    def xla_telemetry():
        import go_libp2p_pubsub_tpu.models.telemetry as tl
        gs, cfg, params, state = _gossip_build()
        tcfg = tl.TelemetryConfig()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           telemetry=tcfg),
            trace=lambda: _trace_step(gs, cfg, params, state,
                                      telemetry=tcfg))
    cell("xla/telemetry", "gossip-xla", "telemetry", xla_telemetry)

    def xla_scored():
        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig()
        gs, cfg, params, state = _gossip_build(score_cfg=sc)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, sc, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state, sc))
    cell("xla/scored", "gossip-xla", "scored", xla_scored)

    def xla_delays():
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state))
    cell("xla/delays", "gossip-xla", "delays", xla_delays, fast=True)

    def xla_probe():
        gs, cfg, params, state = _gossip_build(fault_schedule=_sched())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           rpc_probe=True),
            trace=lambda: _trace_step(gs, cfg, params, state,
                                      rpc_probe=True))
    cell("xla/rpc-probe", "gossip-xla", "rpc-probe", xla_probe)

    def xla_delays_probe():
        # the round-20 lifted registry hole: delays x rpc_probe PLANS
        # when the probe delay line is armed at build
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg(),
                                               delays_probe=True)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           rpc_probe=True),
            trace=lambda: _trace_step(gs, cfg, params, state,
                                      rpc_probe=True))
    cell("xla/delays-rpc-probe", "gossip-xla", "delays+rpc-probe",
         xla_delays_probe, fast=True)

    def xla_delays_counters():
        import go_libp2p_pubsub_tpu.models.telemetry as tl
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg(),
                                               delays_counters=True)
        tcfg = tl.TelemetryConfig()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           telemetry=tcfg),
            trace=lambda: _trace_step(gs, cfg, params, state,
                                      telemetry=tcfg))
    cell("xla/delays-counters", "gossip-xla", "delays+counters",
         xla_delays_counters)

    def xla_delays_paired():
        gs, cfg, params, state = _gossip_build(paired=True)
        _, _, dparams, _ = _gossip_build(delays=_delay_cfg())
        grafted = params.replace(delays=dparams.delays)

        def provoke():
            from go_libp2p_pubsub_tpu.models.delays import DelayConfig
            _gossip_build(paired=True, delays=DelayConfig(1, 0, 1))
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, grafted, state),
            provoke=provoke)
    cell("xla/delays-paired", "gossip-xla", "delays+paired",
         xla_delays_paired)

    def xla_delays_probe_line():
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           rpc_probe=True),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       rpc_probe=True))
    cell("xla/delays-probe-line", "gossip-xla",
         "delays+rpc-probe, line unarmed", xla_delays_probe_line,
         fast=True)

    def xla_delays_counter_lines():
        import go_libp2p_pubsub_tpu.models.telemetry as tl
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg())
        tcfg = tl.TelemetryConfig()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           telemetry=tcfg),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       telemetry=tcfg))
    cell("xla/delays-counter-lines", "gossip-xla",
         "delays+counters, lines unarmed", xla_delays_counter_lines)

    def xla_delays_lines():
        gs, cfg, dparams, _ = _gossip_build(delays=_delay_cfg())
        _, _, _, pstate = _gossip_build()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, dparams, pstate),
            provoke=lambda: _eval_step(gs, cfg, dparams, pstate))
    cell("xla/delays-lines", "gossip-xla",
         "delayed params, undelayed state", xla_delays_lines)

    def xla_delays_split():
        gs, cfg, params, state = _gossip_build(delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           force_split=True),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       force_split=True))
    cell("xla/delays-split-line", "gossip-xla",
         "delays+split, line unarmed", xla_delays_split)

    def xla_probe_mixed():
        import numpy as np
        gs, cfg, params, state = _gossip_build(
            flood_proto=(np.arange(N) % 7) == 0)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           rpc_probe=True),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       rpc_probe=True))
    cell("xla/probe-mixed-protocol", "gossip-xla",
         "rpc-probe+flood-proto", xla_probe_mixed)

    def xla_padded():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_gossip_step(
                cfg, None, params, state, use_pallas_receive=False),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       use_pallas_receive=False))
    cell("xla/padded-state", "gossip-xla", "padded layout, XLA forced",
         xla_padded, fast=True)

    # -- gossip-kernel ------------------------------------------------------

    KSTEP = dict(receive_block=KERNEL_BLOCK, receive_interpret=True)

    def kernel_plain():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state, **KSTEP))
    cell("kernel/plain", "gossip-kernel", "plain", kernel_plain,
         fast=True)

    def kernel_faults():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               fault_schedule=_sched())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state, **KSTEP))
    cell("kernel/faults", "gossip-kernel", "faults", kernel_faults)

    def kernel_telemetry():
        import go_libp2p_pubsub_tpu.models.telemetry as tl
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK)
        tcfg = tl.TelemetryConfig()
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state,
                                           telemetry=tcfg),
            trace=lambda: _trace_step(gs, cfg, params, state,
                                      telemetry=tcfg, **KSTEP))
    cell("kernel/telemetry", "gossip-kernel", "telemetry",
         kernel_telemetry)

    def kernel_scored():
        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig()
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               score_cfg=sc)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, sc, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state, sc,
                                      **KSTEP))
    cell("kernel/scored", "gossip-kernel", "scored", kernel_scored)

    def kernel_delays():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, None, params, state),
            trace=lambda: _trace_step(gs, cfg, params, state, **KSTEP))
    cell("kernel/delays", "gossip-kernel", "delays", kernel_delays)

    def kernel_knob_iwant():
        import numpy as np

        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig(sybil_iwant_spam=True)
        gs, cfg, params, state = _gossip_build(
            pad=KERNEL_BLOCK, score_cfg=sc,
            sybil=(np.arange(N) % 5) == 0,
            sim_knobs={"gossip_retransmission": 3})
        return dict(
            verdict=_plan.plan_gossip_step(cfg, sc, params, state),
            provoke=lambda: _eval_step(gs, cfg, params, state, sc,
                                       **KSTEP))
    cell("kernel/knobs-iwant-spam", "gossip-kernel",
         "knobs+iwant-spam attack", kernel_knob_iwant)

    def kernel_delay_iwant():
        import numpy as np

        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig(sybil_iwant_spam=True)
        gs, cfg, params, state = _gossip_build(
            pad=KERNEL_BLOCK, score_cfg=sc,
            sybil=(np.arange(N) % 5) == 0, delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_gossip_step(cfg, sc, params, state),
            provoke=lambda: _eval_step(gs, cfg, params, state, sc,
                                       **KSTEP))
    cell("kernel/delays-iwant-spam", "gossip-kernel",
         "delays+iwant-spam attack", kernel_delay_iwant)

    def kernel_config():
        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig(mesh_message_deliveries_weight=-1.0)
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               score_cfg=sc)
        return dict(
            verdict=_plan.plan_gossip_step(cfg, sc, params, state),
            provoke=lambda: _eval_step(gs, cfg, params, state, sc,
                                       **KSTEP))
    cell("kernel/config-p3", "gossip-kernel", "P3 provenance scoring",
         kernel_config)

    def kernel_needs_pad():
        gs, cfg, params, state = _gossip_build()
        return dict(
            verdict=_plan.plan_gossip_step(
                cfg, None, params, state, use_pallas_receive=True),
            provoke=lambda: _eval_step(gs, cfg, params, state,
                                       use_pallas_receive=True))
    cell("kernel/needs-pad", "gossip-kernel",
         "unpadded layout, kernel forced", kernel_needs_pad, fast=True)

    # -- gossip-kernel-fused ------------------------------------------------

    def fused_plain():
        import jax
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            trace=lambda: jax.make_jaxpr(_window(gs, cfg))(params,
                                                           state))
    cell("fused/plain", "gossip-kernel-fused", "plain", fused_plain,
         fast=True)

    def fused_faults():
        import jax
        gs, cfg, params, state = _gossip_build(
            n=KERNEL_BLOCK, pad=KERNEL_BLOCK,
            fault_schedule=_sched(n=KERNEL_BLOCK))
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            trace=lambda: jax.make_jaxpr(_window(gs, cfg))(params,
                                                           state))
    cell("fused/faults", "gossip-kernel-fused", "faults", fused_faults)

    def fused_ckpt_aligned():
        import jax

        from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        ckpt = ck.CheckpointConfig(directory="/tmp/planaudit-ckpt",
                                   every=4)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            4, checkpoint=ckpt,
                                            ckpt_horizon=8),
            trace=lambda: jax.make_jaxpr(
                _window(gs, cfg, ticks=4))(params, state))
    cell("fused/ckpt-aligned", "gossip-kernel-fused",
         "checkpoint, aligned segments", fused_ckpt_aligned)

    def fused_window_zero():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            0),
            provoke=lambda: _window(gs, cfg, ticks=0))
    cell("fused/window", "gossip-kernel-fused", "zero-tick window",
         fused_window_zero)

    def fused_base_wrap():
        import numpy as np
        gs, cfg, params, state = _gossip_build(
            n=KERNEL_BLOCK, pad=KERNEL_BLOCK,
            flood_proto=(np.arange(KERNEL_BLOCK) % 7) == 0)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/kernel-config", "gossip-kernel-fused",
         "per-tick kernel refusal, fused-wrapped", fused_base_wrap)

    def fused_unpadded():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/unpadded", "gossip-kernel-fused", "unpadded layout",
         fused_unpadded)

    def fused_scored():
        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        sc = gsm.ScoreSimConfig()
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               score_cfg=sc)
        return dict(
            verdict=_plan.plan_fused_window(cfg, sc, params, state, 2),
            provoke=lambda: _window(gs, cfg, sc)(params, state))
    cell("fused/scored", "gossip-kernel-fused", "scored", fused_scored)

    def fused_paired():
        gs, cfg, params, state = _gossip_build(paired=True,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/paired", "gossip-kernel-fused", "paired topics",
         fused_paired)

    def fused_delays():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               delays=_delay_cfg())
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/delays", "gossip-kernel-fused", "delays", fused_delays)

    def fused_knobs():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               sim_knobs={"d": 4})
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/knobs", "gossip-kernel-fused", "traced knobs",
         fused_knobs)

    def fused_px():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               px_candidates=7)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/px", "gossip-kernel-fused", "PX rotation", fused_px)

    def fused_direct():
        import numpy as np

        import go_libp2p_pubsub_tpu.models.gossipsub as gsm
        cfg0 = gsm.GossipSimConfig(
            offsets=gsm.make_gossip_offsets(T, C, N, seed=1),
            n_topics=T, d=3, d_lo=2, d_hi=6, d_score=2, d_out=1,
            d_lazy=2, backoff_ticks=8)
        f = (np.arange(N) % 5) == 0
        de = np.zeros((N, C), dtype=bool)
        for c_ in (0, cfg0.cinv[0]):
            de[:, c_] = f | np.roll(f, -int(cfg0.offsets[c_]))
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK,
                                               direct_edges=de)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/direct", "gossip-kernel-fused", "direct peers",
         fused_direct)

    def fused_pad_mismatch():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg)(params, state))
    cell("fused/pad-mismatch", "gossip-kernel-fused",
         "pad lanes present", fused_pad_mismatch, fast=True)

    def fused_align():
        gs, cfg, params, state = _gossip_build(n=1152, pad=128)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2),
            provoke=lambda: _window(gs, cfg, block=128)(params, state))
    cell("fused/align", "gossip-kernel-fused", "ring off the u32 tile",
         fused_align)

    def fused_vmem():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(
                cfg, None, params, state, 2,
                vmem_budget_bytes=1 << 16),
            provoke=lambda: _window(
                gs, cfg, vmem_budget_bytes=1 << 16)(params, state))
    cell("fused/vmem", "gossip-kernel-fused", "carry past VMEM budget",
         fused_vmem)

    def fused_horizon():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)

        def provoke():
            gs.gossip_run_fused(params, state, 3, _window(gs, cfg))
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2, horizon=3),
            provoke=provoke)
    cell("fused/horizon", "gossip-kernel-fused", "indivisible horizon",
         fused_horizon)

    def fused_ckpt_boundary():
        from go_libp2p_pubsub_tpu.parallel import checkpoint as ck
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        ckpt = ck.CheckpointConfig(directory="/tmp/planaudit-ckpt",
                                   every=6)

        def provoke():
            ck.ckpt_gossip_run_fused(params, state, 8,
                                     _window(gs, cfg, ticks=4), ckpt)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            4, checkpoint=ckpt,
                                            ckpt_horizon=8),
            provoke=provoke)
    cell("fused/ckpt-boundary", "gossip-kernel-fused",
         "checkpoint boundary mid-window", fused_ckpt_boundary)

    # -- gossip-kernel-fused-sharded ----------------------------------------

    def sharded_plain():
        import jax

        from go_libp2p_pubsub_tpu.parallel import sharded as psh
        mesh = _mesh(2)
        n = 2 * KERNEL_BLOCK
        gs, cfg, params, state = _gossip_build(n=n, pad=KERNEL_BLOCK)
        verdict = _plan.plan_fused_window(cfg, None, params, state, 2,
                                          sharded=True, devices=2)
        # shard placement compiles device transfers — do it at build,
        # keep only the make_jaxpr under the backend-compile guard
        window = _window(gs, cfg, shard_mesh=mesh)
        p, s, sh = psh.shard_sim(params, state, mesh, n)
        return dict(
            verdict=verdict,
            trace=lambda: jax.make_jaxpr(
                lambda pp, ss: psh.sharded_gossip_run_fused(
                    pp, ss, 4, window, sh))(p, s))
    cell("sharded/plain", "gossip-kernel-fused-sharded", "plain, D=2",
         sharded_plain)

    def sharded_devices():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2, sharded=True,
                                            devices=1),
            provoke=lambda: _window(
                gs, cfg, shard_mesh=_mesh(1))(params, state))
    cell("sharded/devices", "gossip-kernel-fused-sharded",
         "degenerate 1-extent mesh", sharded_devices)

    def sharded_divisible():
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2, sharded=True,
                                            devices=3),
            provoke=lambda: _window(
                gs, cfg, shard_mesh=_mesh(3))(params, state))
    cell("sharded/divisible", "gossip-kernel-fused-sharded",
         "ring not divisible by D", sharded_divisible)

    def sharded_tile():
        gs, cfg, params, state = _gossip_build(n=1152, pad=64)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2, sharded=True,
                                            devices=2),
            provoke=lambda: _window(
                gs, cfg, block=64,
                shard_mesh=_mesh(2))(params, state))
    cell("sharded/tile", "gossip-kernel-fused-sharded",
         "shard splits a 128-lane tile", sharded_tile)

    def sharded_halo():
        offs = (2, -2, 4, -4, 6, -6, 600, -600)
        gs, cfg, params, state = _gossip_build(n=KERNEL_BLOCK,
                                               pad=KERNEL_BLOCK,
                                               offsets=offs)
        return dict(
            verdict=_plan.plan_fused_window(cfg, None, params, state,
                                            2, sharded=True,
                                            devices=2),
            provoke=lambda: _window(
                gs, cfg, shard_mesh=_mesh(2))(params, state))
    cell("sharded/halo", "gossip-kernel-fused-sharded",
         "halo reach spans the ring", sharded_halo)

    # -- mesh-less simulators -----------------------------------------------

    def flood_circ(faulted):
        def build():
            import jax

            import go_libp2p_pubsub_tpu.models.floodsub as fs
            offs = _circ_offsets()
            subs, topic, origin, ticks = _flood_inputs()
            sched = _sched() if faulted else None
            params, state = fs.make_flood_sim(
                None, None, subs, None, topic, origin, ticks,
                fault_schedule=sched, fault_offsets=offs)
            core = fs.make_circulant_step_core(offs)
            return dict(
                verdict=_plan.plan_circulant("flood-circulant",
                                             faults=sched),
                trace=lambda: jax.make_jaxpr(
                    lambda p, s: fs.flood_run_curve(p, s, 2, core,
                                                    M))(params, state))
        return build
    cell("flood-circulant/plain", "flood-circulant", "plain",
         flood_circ(False), fast=True)
    cell("flood-circulant/faults", "flood-circulant", "faults",
         flood_circ(True))

    def flood_circ_cold():
        import go_libp2p_pubsub_tpu.models.floodsub as fs
        sched = _sched(cold=True)
        offs = _circ_offsets()
        subs, topic, origin, ticks = _flood_inputs()

        def provoke():
            fs.make_flood_sim(None, None, subs, None, topic, origin,
                              ticks, fault_schedule=sched,
                              fault_offsets=offs)
        return dict(
            verdict=_plan.plan_circulant("flood-circulant",
                                         faults=sched),
            provoke=provoke)
    cell("flood-circulant/cold-restart", "flood-circulant",
         "cold-restart churn", flood_circ_cold, fast=True)

    def flood_gather(faulted):
        def build():
            import jax

            import go_libp2p_pubsub_tpu.models.floodsub as fs
            nbrs, mask = _gather_table()
            subs, topic, origin, ticks = _flood_inputs()
            sched = _sched() if faulted else None
            params, state = fs.make_flood_sim(
                nbrs, mask, subs, None, topic, origin, ticks,
                fault_schedule=sched)
            core = fs.make_gather_step_core()
            return dict(
                verdict=_plan.plan_circulant("flood-gather",
                                             faults=sched),
                trace=lambda: jax.make_jaxpr(
                    lambda p, s: fs.flood_run_curve(p, s, 2, core,
                                                    M))(params, state))
        return build
    cell("flood-gather/plain", "flood-gather", "plain",
         flood_gather(False))
    cell("flood-gather/faults", "flood-gather", "faults",
         flood_gather(True))

    def flood_gather_cold():
        import go_libp2p_pubsub_tpu.models.floodsub as fs
        nbrs, mask = _gather_table()
        sched = _sched(cold=True)
        subs, topic, origin, ticks = _flood_inputs()

        def provoke():
            fs.make_flood_sim(nbrs, mask, subs, None, topic, origin,
                              ticks, fault_schedule=sched)
        return dict(
            verdict=_plan.plan_circulant("flood-gather", faults=sched),
            provoke=provoke)
    cell("flood-gather/cold-restart", "flood-gather",
         "cold-restart churn", flood_gather_cold)

    def _rs_build(dense, faulted):
        import go_libp2p_pubsub_tpu.models.randomsub as rs
        rcfg = rs.RandomSubSimConfig(
            offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
            n_topics=T, d=3)
        subs, topic, origin, ticks = _flood_inputs()
        sched = _sched() if faulted else None
        params, state = rs.make_randomsub_sim(
            rcfg, subs, topic, origin, ticks, dense=dense,
            fault_schedule=sched)
        step = (rs.make_randomsub_dense_step(rcfg) if dense
                else rs.make_randomsub_step(rcfg))
        return rs, rcfg, params, state, step, sched

    def randomsub(dense, faulted):
        path = ("randomsub-dense" if dense else "randomsub-circulant")

        def build():
            import jax
            rs, rcfg, params, state, step, sched = _rs_build(dense,
                                                             faulted)
            return dict(
                verdict=_plan.plan_circulant(path, faults=sched),
                trace=lambda: jax.make_jaxpr(step)(params, state))
        return build
    cell("randomsub-circulant/plain", "randomsub-circulant", "plain",
         randomsub(False, False))
    cell("randomsub-circulant/faults", "randomsub-circulant", "faults",
         randomsub(False, True))
    cell("randomsub-dense/plain", "randomsub-dense", "plain",
         randomsub(True, False))
    cell("randomsub-dense/faults", "randomsub-dense", "faults",
         randomsub(True, True))

    def randomsub_cold(dense):
        path = ("randomsub-dense" if dense else "randomsub-circulant")

        def build():
            import go_libp2p_pubsub_tpu.models.randomsub as rs
            rcfg = rs.RandomSubSimConfig(
                offsets=rs.make_randomsub_offsets(T, C, N, seed=1),
                n_topics=T, d=3)
            sched = _sched(cold=True)
            subs, topic, origin, ticks = _flood_inputs()

            def provoke():
                rs.make_randomsub_sim(rcfg, subs, topic, origin,
                                      ticks, dense=dense,
                                      fault_schedule=sched)
            return dict(
                verdict=_plan.plan_circulant(path, faults=sched),
                provoke=provoke)
        return build
    cell("randomsub-circulant/cold-restart", "randomsub-circulant",
         "cold-restart churn", randomsub_cold(False))
    cell("randomsub-dense/cold-restart", "randomsub-dense",
         "cold-restart churn", randomsub_cold(True), fast=True)

    # -- serving ------------------------------------------------------------

    def serve_xla_batch():
        import jax
        import numpy as np
        gs, cfg, params, state = _gossip_build()
        verdict = _plan.plan_serving(kernel=False, batch=8, devices=0)

        def trace():
            step = gs.make_gossip_step(cfg)
            bp = jax.tree_util.tree_map(
                lambda x: np.broadcast_to(
                    np.asarray(x), (8,) + np.asarray(x).shape),
                (params, state))
            return jax.make_jaxpr(jax.vmap(step))(*bp)
        return dict(verdict=verdict, trace=trace)
    cell("serving/xla-batch", "serving", "batched XLA dispatch, b=8",
         serve_xla_batch, fast=True)

    def serve_kernel_seq():
        gs, cfg, params, state = _gossip_build(pad=KERNEL_BLOCK)
        return dict(
            verdict=_plan.plan_serving(kernel=True, batch=1,
                                       devices=0),
            trace=lambda: _trace_step(gs, cfg, params, state, **KSTEP))
    cell("serving/kernel-seq", "serving", "sequential kernel path",
         serve_kernel_seq, fast=True)

    def serve_refuse(batch, devices, feature, fast=False):
        def build():
            from tools.sweepd import server_capability

            def provoke():
                reason = server_capability(kernel=True, batch=batch,
                                           devices=devices)
                if reason:
                    raise ValueError(reason)
            return dict(
                verdict=_plan.plan_serving(kernel=True, batch=batch,
                                           devices=devices),
                provoke=provoke)
        cell(f"serving/{feature}", "serving", feature, build,
             fast=fast)
    serve_refuse(8, 0, "kernel-batch", fast=True)
    serve_refuse(1, 2, "kernel-devices", fast=True)

    return cells


# --------------------------------------------------------------------------
# The audit
# --------------------------------------------------------------------------


def audit_cell(cell: Cell) -> list[str]:
    """Problem strings for one cell (empty = verdict matches
    reality)."""
    import jax._src.compiler as _compiler

    from go_libp2p_pubsub_tpu.models import plan as _plan

    from .jaxpr_audit import _iter_eqns

    pre = f"planaudit {cell.id}:"
    try:
        ctx = cell.build()
    except Exception as e:  # graftlint: ignore[broad-except] — any cell failure becomes a named finding
        return [f"{pre} cell build failed: {type(e).__name__}: {e}"]
    verdict = ctx.get("verdict")

    if isinstance(verdict, _plan.ExecutionPlan):
        trace = ctx.get("trace")
        if trace is None:
            return [f"{pre} PLAN verdict but no trace arm — "
                    "unclassifiable cell"]
        compiled = []
        orig = _compiler.backend_compile

        def guard(*a, **kw):
            compiled.append(a)
            return orig(*a, **kw)

        _compiler.backend_compile = guard
        try:
            closed = trace()
        except Exception as e:  # graftlint: ignore[broad-except] — reported by name
            return [f"{pre} PLAN cell failed to trace: "
                    f"{type(e).__name__}: {e}"]
        finally:
            _compiler.backend_compile = orig
        problems = []
        if compiled:
            problems.append(
                f"{pre} PLAN trace reached the compiler "
                f"{len(compiled)} time(s) — must trace only")
        prims = {eqn.primitive.name for eqn in _iter_eqns(closed)}
        missing = [p for p in verdict.primitives if p not in prims]
        if missing:
            problems.append(
                f"{pre} declared primitives missing from the traced "
                f"jaxpr: {missing} (plan path {verdict.path})")
        banned = [p for p in verdict.forbidden if p in prims]
        if banned:
            problems.append(
                f"{pre} forbidden primitives present in the traced "
                f"jaxpr: {banned} (plan path {verdict.path})")
        return problems

    if isinstance(verdict, _plan.Refusal):
        provoke = ctx.get("provoke")
        if provoke is None:
            return [f"{pre} REFUSE verdict but no provoke arm — "
                    "unclassifiable cell"]
        try:
            provoke()
        except verdict.exc as e:
            if str(e) != verdict.message:
                return [f"{pre} refusal string drift — planner says "
                        f"{verdict.message!r}, entry point raised "
                        f"{str(e)!r}"]
            return []
        except Exception as e:  # graftlint: ignore[broad-except] — reported by name
            return [f"{pre} wrong exception class — planner says "
                    f"{verdict.exc.__name__}, entry point raised "
                    f"{type(e).__name__}: {e}"]
        return [f"{pre} planner refuses ({verdict.code}) but the "
                "entry point did not raise"]

    return [f"{pre} unclassifiable verdict {type(verdict).__name__} "
            "— planner must return ExecutionPlan or Refusal"]


def run_planaudit(cells=None, fast_only: bool = False,
                  log=None) -> list[str]:
    """The whole lattice; returns all problems (empty = clean)."""
    if cells is None:
        cells = build_cells()
    if fast_only:
        cells = [c for c in cells if c.fast]
    problems = []
    for cell in cells:
        probs = audit_cell(cell)
        if log is not None:
            log(f"  plan {cell.id}: "
                f"{'OK' if not probs else f'{len(probs)} problem(s)'}")
        problems.extend(probs)
    return problems


# --------------------------------------------------------------------------
# Matrix serialization (the PLAN_r19.json golden artifact + README)
# --------------------------------------------------------------------------


def capability_matrix(cells=None) -> dict:
    """The planner's verdict over every lattice cell, as data.  Builds
    the cells (host-side sims) but never traces or provokes — the
    audit proves the verdicts; this serializes them."""
    from go_libp2p_pubsub_tpu.models import plan as _plan

    if cells is None:
        cells = build_cells()
    rows = []
    for cell in cells:
        row = {"id": cell.id, "path": cell.path,
               "feature": cell.feature}
        try:
            verdict = cell.build().get("verdict")
        except Exception as e:  # graftlint: ignore[broad-except] — reported by name
            row.update(verdict="ERROR",
                       error=f"{type(e).__name__}: {e}")
            rows.append(row)
            continue
        if isinstance(verdict, _plan.ExecutionPlan):
            row.update(verdict="PLAN", plan_path=verdict.path,
                       primitives=list(verdict.primitives),
                       forbidden=list(verdict.forbidden))
        elif isinstance(verdict, _plan.Refusal):
            row.update(verdict="REFUSE", code=verdict.code,
                       message=verdict.message,
                       exc=verdict.exc.__name__)
        else:
            row.update(verdict="ERROR",
                       error=f"unclassifiable verdict "
                             f"{type(verdict).__name__}")
        rows.append(row)
    return {"schema": MATRIX_SCHEMA, "round": MATRIX_ROUND,
            "cells": rows}


def matrix_markdown(matrix: dict | None = None) -> str:
    """The README capability table, rendered FROM the planner's
    verdicts (never hand-edited)."""
    if matrix is None:
        matrix = capability_matrix()
    lines = [
        "| Cell | Feature | Verdict | Detail |",
        "| --- | --- | --- | --- |",
    ]
    for row in matrix["cells"]:
        if row["verdict"] == "PLAN":
            prims = ", ".join(row["primitives"]) or "XLA-only"
            detail = f"`{row['plan_path']}` ({prims})"
        elif row["verdict"] == "REFUSE":
            detail = f"`{row['code']}` ({row['exc']})"
        else:
            detail = row.get("error", "?")
        lines.append(f"| `{row['id']}` | {row['feature']} | "
                     f"{row['verdict']} | {detail} |")
    return "\n".join(lines)
