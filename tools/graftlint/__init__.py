"""graftlint: repo-specific static analysis for the TPU pubsub codebase.

Three passes, runnable standalone (``python -m tools.graftlint``) and
wired into the measurement preflight (tools/measure_all.sh step 0.5):

- **AST pass** (``astpass``, stdlib-only): JAX-shaped defect patterns
  that generic linters miss — Python branching on traced values inside
  step/scan bodies, ``np.*`` calls in traced code, jit-wrapped runners
  whose ``state`` carry is not donated, banned nondeterminism in model
  code, bare/broad excepts and ``sys.path`` mutation in tools.
- **Abstract-eval audit** (``jaxpr_audit``): traces every simulator
  runner over a declared config matrix (3 simulators x telemetry x
  faults x batched x XLA combined/split) with ``jax.make_jaxpr`` /
  ``.lower`` — never executing a sim tick — and asserts no 64-bit
  widening, no host callbacks, donation actually applied to the carry,
  and captured-constant size under budget.
- **Config-contract checker** (``contracts``): every field of
  GossipSimConfig / FaultSchedule / TelemetryConfig must be provably
  threaded into each execution path, explicitly refused there, or
  build-time-validated — driven by the machine-readable ``CONTRACT``
  declarations on the config dataclasses themselves.

Per-line suppressions: ``# graftlint: ignore[rule]`` (see ``pragmas``).
Rule catalog and how to extend it: tools/README.md.
"""

from .astpass import (  # noqa: F401
    Finding,
    RULES,
    check_file,
    iter_target_files,
    run_paths,
)
from .pragmas import pragma_lines, scope_override  # noqa: F401

__all__ = [
    "Finding",
    "RULES",
    "check_file",
    "iter_target_files",
    "run_paths",
    "pragma_lines",
    "scope_override",
]
