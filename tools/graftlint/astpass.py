"""The stdlib-``ast`` pass: JAX-shaped defect patterns by rule.

Rule catalog (scopes: model = go_libp2p_pubsub_tpu/{models,ops},
tools = tools/, any = every scanned file; see tools/README.md for the
full rationale and how to add a rule):

- ``traced-branch`` (any): a Python ``if``/``while``/``assert``/
  conditional expression whose test contains a ``jnp.``/``jax.``/
  ``lax.`` expression, inside a traced function.  Python control flow
  on traced values either fails at trace time (ConcretizationTypeError,
  the lucky case) or silently bakes one branch into the compiled step.
  Use ``jnp.where``/``lax.cond``.
- ``np-in-traced`` (any): a ``np.*``/``numpy.*`` call inside a traced
  function.  NumPy ops concretize tracers or run host-side at trace
  time; inside a scanned step that is either a trace error or a silent
  constant.  Use ``jnp``, or hoist the host computation to build time.
  (``np.float32``-style attribute *references* — dtypes — are fine.)
- ``missing-donate`` (any): a jit-decorated function with a parameter
  named ``state`` (the scan carry convention of every runner in this
  repo) whose ``donate_argnums`` does not cover it.  At 1M peers an
  undonated carry holds two GB-scale copies live (see gossip_run).
- ``nondeterminism`` (model): ``time``/``random`` imported or called in
  model code.  Sim trajectories must be a function of explicit seeds;
  wall-clock or global-RNG state in models silently breaks replica
  batching and bit-identity pins.
- ``bare-except`` (model, tools): ``except:`` swallows KeyboardInterrupt
  / SystemExit and hides the relay-death failure modes the tools are
  built to surface.  Name the exception class.
- ``broad-except`` (tools): ``except Exception`` in tools — legitimate
  only for the documented batched->sequential fallbacks; every use
  carries a per-line pragma so suppressions stay auditable.
- ``sys-path-insert`` (tools): module-level ``sys.path`` mutation.
  Grandfathered in the script-style tools (pragma'd); new tools should
  run as modules (``python -m tools.x``) instead.
- ``lock-discipline`` (service = obs/ + serving/): a PUBLIC method of
  a lock-owning class (one whose ``__init__`` assigns ``self._lock``
  or whose methods enter ``with ..._lock:``) mutating ``self``-rooted
  state outside a ``with ..._lock:`` / ``with ...atomic():`` block.
  MetricsRegistry / SpanRecorder state is scraped concurrently by the
  serving threads; an unguarded write races the accounting identity
  the obsstat gate pins.  Private ``_``-helpers follow the documented
  caller-holds-lock convention and are exempt; a class that merely
  USES someone else's ``atomic()`` (the frontend pattern) does not
  qualify.  Mutation-through-call (``.append(...)``) is out of static
  reach — the rule catches assignment/augassign/annassign writes.

A function is *traced* when (a) it is decorated with ``jax.jit`` /
``partial(jax.jit, ...)``, (b) its name is passed to ``lax.scan`` /
``while_loop`` / ``fori_loop`` / ``cond`` / ``switch`` / ``vmap`` /
``pallas_call`` in the same module, (c) it is a conventional step body
(``step``/``body``/``core``/``kernel``-named) nested inside a
``make_*`` factory, or (d) it is nested inside a traced function.
Static detection under-approximates real tracing (a function passed
through a variable is invisible); the fixture corpus pins exactly what
the pass promises to catch.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

from .pragmas import (pragma_lines, scope_override, suppressed,
                      validate_pragmas)

#: rule name -> (scopes it applies in, or None = any scope; summary)
RULES: dict[str, tuple[tuple[str, ...] | None, str]] = {
    "traced-branch": (
        None, "Python branch on a traced (jnp/jax) expression inside a "
              "traced function"),
    "np-in-traced": (
        None, "np.* call inside a traced function"),
    "missing-donate": (
        None, "jit-wrapped runner's 'state' carry not in donate_argnums"),
    "nondeterminism": (
        ("model",), "time/random (wall clock, global RNG) in model code"),
    "bare-except": (
        ("model", "tools"), "bare 'except:'"),
    "broad-except": (
        ("tools",), "'except Exception' in tools"),
    "sys-path-insert": (
        ("tools",), "module-level sys.path mutation in tools"),
    "lock-discipline": (
        ("service",), "public-method mutation of lock-owning shared "
                      "state outside 'with ..._lock' / 'atomic()'"),
}

EXCLUDE_DIRS = {"__pycache__", ".git"}

#: nested-function names conventionally traced inside make_* factories
_STEP_NAMES = {"step", "body", "core", "telemetry_core", "kernel",
               "vstep"}
#: call targets whose function-valued arguments are traced
_TRACING_CALLS = {"scan", "while_loop", "fori_loop", "cond", "switch",
                  "vmap", "pmap", "pallas_call", "checkpoint", "remat"}
_JAX_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu"}


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: graftlint[{self.rule}] " \
               f"{self.message}"


def classify_scope(path: Path, root: Path) -> str:
    """Scope from on-disk location (fixtures override via directive)."""
    try:
        parts = path.resolve().relative_to(root.resolve()).parts
    except ValueError:
        parts = path.parts
    if "models" in parts or "ops" in parts:
        return "model"
    if "obs" in parts or "serving" in parts:
        return "service"
    if "core" in parts:
        return "core"
    if parts and parts[0] == "tools":
        return "tools"
    if "tests" in parts:
        return "tests"
    return "other"


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this decorator expression wrap jax.jit?"""
    d = _dotted(node)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f in ("jax.jit", "jit"):
            return True
        if f in ("partial", "functools.partial"):
            return any(_dotted(a) in ("jax.jit", "jit")
                       for a in node.args)
    return False


def _jit_decorator(fn: ast.FunctionDef) -> ast.expr | None:
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return dec
    return None


def _donated_argnums(dec: ast.expr) -> tuple | None:
    """Literal donate_argnums/donate_argnames of a jit decorator, as a
    mixed tuple of ints (argnums) and strs (argnames); () when absent,
    None when present but not a literal (unverifiable -> skip)."""
    if not isinstance(dec, ast.Call):
        return ()
    out = []
    found = False
    for kw in dec.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        found = True
        v = kw.value
        elts = (v.elts if isinstance(v, (ast.Tuple, ast.List))
                else [v])
        for elt in elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, (int, str))):
                return None
            out.append(elt.value)
    return tuple(out) if found else ()


def _contains_jax_expr(node: ast.AST) -> ast.AST | None:
    """A jnp./jax./lax.-rooted subexpression inside ``node`` (the
    traced-value heuristic for branch tests), or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            d = _dotted(sub)
            if d is not None and d.split(".")[0] in _JAX_ROOTS:
                return sub
    return None


class _FileChecker:
    def __init__(self, path: Path, src: str, tree: ast.Module,
                 scope: str):
        self.path = path
        self.src = src
        self.tree = tree
        self.scope = scope
        self.pragmas = pragma_lines(src)
        self.findings: list[Finding] = []
        self.traced: set[ast.AST] = set()
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # -- plumbing ---------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str):
        scopes = RULES[rule][0]
        if scopes is not None and self.scope not in scopes:
            return
        line = getattr(node, "lineno", 0)
        if suppressed(self.pragmas, line, rule):
            return
        self.findings.append(
            Finding(str(self.path), line, rule, message))

    def _enclosing_functions(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cur
            cur = self._parents.get(cur)

    # -- traced-function discovery ---------------------------------------

    def _collect_traced(self):
        by_name: dict[str, list[ast.AST]] = {}
        funcs = [n for n in ast.walk(self.tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        for fn in funcs:
            by_name.setdefault(fn.name, []).append(fn)
            # (a) jit-decorated
            if _jit_decorator(fn) is not None:
                self.traced.add(fn)
            # (c) conventional step body inside a make_* factory
            elif fn.name in _STEP_NAMES and any(
                    f.name.startswith("make_")
                    for f in self._enclosing_functions(fn)):
                self.traced.add(fn)
        # (b) passed by name to a tracing call
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            d = _dotted(call.func)
            if d is None or d.split(".")[-1] not in _TRACING_CALLS:
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    self.traced.update(by_name[arg.id])
        # (d) functions nested inside traced functions
        changed = True
        while changed:
            changed = False
            for fn in funcs:
                if fn in self.traced:
                    continue
                if any(enc in self.traced
                       for enc in self._enclosing_functions(fn)):
                    self.traced.add(fn)
                    changed = True

    def _in_traced(self, node: ast.AST) -> ast.AST | None:
        for enc in self._enclosing_functions(node):
            if enc in self.traced:
                return enc
        return None

    # -- the rules --------------------------------------------------------

    def run(self) -> list[Finding]:
        self._collect_traced()
        self._check_lock_discipline()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.If, ast.While, ast.Assert,
                                 ast.IfExp)):
                self._check_traced_branch(node)
            elif isinstance(node, ast.Call):
                self._check_np_call(node)
                self._check_sys_path(node)
                self._check_nondet_call(node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._check_donation(node)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._check_nondet_import(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_except(node)
        self.findings.sort(key=lambda f: (f.line, f.rule))
        return self.findings

    def _check_traced_branch(self, node):
        fn = self._in_traced(node)
        if fn is None:
            return
        test = node.test
        hit = _contains_jax_expr(test)
        if hit is None:
            return
        kind = {ast.If: "if", ast.While: "while", ast.Assert: "assert",
                ast.IfExp: "conditional expression"}[type(node)]
        self._emit(
            "traced-branch", node,
            f"Python {kind} on traced expression "
            f"'{_dotted(hit) or 'jnp/jax value'}' inside traced "
            f"function '{fn.name}' — use jnp.where / lax.cond")

    def _check_np_call(self, node):
        fn = self._in_traced(node)
        if fn is None:
            return
        d = _dotted(node.func)
        if d is None or d.split(".")[0] not in ("np", "numpy"):
            return
        self._emit(
            "np-in-traced", node,
            f"'{d}(...)' inside traced function '{fn.name}' — numpy "
            "concretizes tracers / runs at trace time; use jnp or "
            "hoist to build time")

    def _check_donation(self, fn):
        dec = _jit_decorator(fn)
        if dec is None:
            return
        argnames = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if "state" not in argnames:
            return
        idx = argnames.index("state")
        donated = _donated_argnums(dec)
        if donated is None:       # non-literal donate spec: unverifiable
            return
        if idx not in donated and "state" not in donated:
            self._emit(
                "missing-donate", fn,
                f"jit-wrapped '{fn.name}' carries 'state' at arg {idx} "
                f"but donate_argnums={donated or '()'} does not donate "
                "it — an undonated carry keeps two full copies live")

    def _check_nondet_import(self, node):
        names = ([a.name for a in node.names]
                 if isinstance(node, ast.Import)
                 else [node.module or ""])
        for name in names:
            root = name.split(".")[0]
            if root in ("time", "random"):
                self._emit(
                    "nondeterminism", node,
                    f"import of '{root}' in model code — trajectories "
                    "must be functions of explicit seeds")

    def _check_nondet_call(self, node):
        d = _dotted(node.func)
        if d is None:
            return
        root = d.split(".")[0]
        if root in ("time", "random") and "." in d:
            self._emit(
                "nondeterminism", node,
                f"'{d}(...)' in model code — wall clock / global RNG "
                "is banned in models")

    def _check_except(self, node):
        if node.type is None:
            self._emit("bare-except", node,
                       "bare 'except:' — name the exception class "
                       "(swallows KeyboardInterrupt/SystemExit)")
            return
        # tuple handlers hide the same classes: except (Exception, X)
        elts = (node.type.elts if isinstance(node.type, ast.Tuple)
                else [node.type])
        names = {_dotted(e) for e in elts}
        if "BaseException" in names:
            # semantically a bare except (same swallowed interrupts) —
            # same rule, same scopes
            self._emit("bare-except", node,
                       "'except BaseException' — equivalent to a bare "
                       "'except:' (swallows KeyboardInterrupt/"
                       "SystemExit); name the failure class")
        elif "Exception" in names:
            self._emit(
                "broad-except", node,
                "'except Exception' in tools — catch the specific "
                "failure, or pragma the documented fallback")

    # -- lock discipline (service scope) ----------------------------------

    @staticmethod
    def _is_lock_guard(item: ast.withitem) -> bool:
        """``with <...>._lock:`` or ``with <...>.atomic():``."""
        ce = item.context_expr
        d = _dotted(ce)
        if d is not None and d.split(".")[-1] == "_lock":
            return True
        if isinstance(ce, ast.Call):
            f = _dotted(ce.func)
            return f is not None and f.split(".")[-1] == "atomic"
        return False

    def _class_owns_lock(self, cls: ast.ClassDef) -> bool:
        """Assigns ``self._lock`` or enters ``with ..._lock:``
        anywhere in its body.  Merely calling someone else's
        ``atomic()`` (the frontend pattern) does NOT qualify — the
        guarded state belongs to the registry, not the caller."""
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and t.attr == "_lock"
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        return True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    d = _dotted(item.context_expr)
                    if d is not None and d.split(".")[-1] == "_lock":
                        return True
        return False

    @staticmethod
    def _self_rooted(target: ast.AST) -> bool:
        node = target
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id == "self"

    def _guarded(self, node: ast.AST, method: ast.AST) -> bool:
        cur = self._parents.get(node)
        while cur is not None and cur is not method:
            if isinstance(cur, (ast.With, ast.AsyncWith)) and any(
                    self._is_lock_guard(i) for i in cur.items):
                return True
            cur = self._parents.get(cur)
        return False

    def _check_lock_discipline(self):
        scopes = RULES["lock-discipline"][0]
        if scopes is not None and self.scope not in scopes:
            return
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not self._class_owns_lock(cls):
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name.startswith("_"):
                    continue  # private helpers: caller holds the lock
                for node in ast.walk(method):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AugAssign):
                        targets = [node.target]
                    elif isinstance(node, ast.AnnAssign):
                        if node.value is None:   # bare annotation
                            continue
                        targets = [node.target]
                    else:
                        continue
                    flat = []
                    for t in targets:
                        flat.extend(t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t])
                    for t in flat:
                        if not self._self_rooted(t):
                            continue
                        if self._guarded(node, method):
                            continue
                        self._emit(
                            "lock-discipline", node,
                            f"'{cls.name}.{method.name}' mutates "
                            "self-rooted state of a lock-owning class "
                            "outside 'with ..._lock:' / "
                            "'with ...atomic():' — scrapes race the "
                            "write; take the lock (private _helpers "
                            "run under the caller's lock and are "
                            "exempt)")
                        break

    def _check_sys_path(self, node):
        d = _dotted(node.func)
        if d in ("sys.path.insert", "sys.path.append"):
            self._emit(
                "sys-path-insert", node,
                "sys.path mutation — run new tools as modules "
                "(python -m tools.x); existing script-style tools are "
                "pragma-grandfathered")


def check_file(path: Path, root: Path | None = None,
               src: str | None = None) -> list[Finding]:
    """All findings for one file (scope from path, or the file's
    ``# graftlint: scope=...`` directive)."""
    path = Path(path)
    root = Path(root) if root is not None else Path(".")
    if src is None:
        src = path.read_text(encoding="utf-8",
                             errors="surrogateescape")
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Finding(str(path), e.lineno or 0, "syntax",
                        f"unparseable file: {e.msg}")]
    try:
        scope = scope_override(src) or classify_scope(path, root)
    except ValueError as e:
        # a typo'd directive must be a located finding, not a crash
        return [Finding(str(path), getattr(e, "lineno", 0),
                        "scope-directive", str(e))]
    findings = _FileChecker(path, src, tree, scope).run()
    # a bracketed ignore naming an unknown rule suppresses NOTHING —
    # reject it by name (round 19) instead of silently accepting it
    for line, name in validate_pragmas(src, RULES):
        findings.append(Finding(
            str(path), line, "pragma-directive",
            f"unknown rule {name!r} in '# graftlint: ignore[...]' "
            f"pragma (one of: {', '.join(sorted(RULES))})"))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


def _is_seeded_fixture(path: Path) -> bool:
    """ONLY graftlint's own corpus is exempt — a directory merely
    NAMED fixtures elsewhere in the repo is ordinary code and stays
    under the tree-clean gate."""
    parts = path.parts
    return ("fixtures" in parts
            and parts[max(0, parts.index("fixtures") - 1)]
            == "graftlint")


def iter_target_files(root: Path, include_fixtures: bool = False):
    """The .py files a default run scans (the seeded-violation corpus
    excluded unless asked for — it exists to be dirty)."""
    for path in sorted(Path(root).rglob("*.py")):
        if any(part in EXCLUDE_DIRS for part in path.parts):
            continue
        if not include_fixtures and _is_seeded_fixture(path):
            continue
        yield path


def run_paths(paths, root: Path | None = None,
              include_fixtures: bool = False) -> list[Finding]:
    """AST pass over files and/or directories.  ``include_fixtures``
    scans the seeded-violation corpus too (self-test mode; default
    runs exclude it — fixtures exist to be dirty)."""
    root = Path(root) if root is not None else Path(".")
    findings: list[Finding] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in iter_target_files(p,
                                       include_fixtures=include_fixtures):
                findings.extend(check_file(f, root))
        else:
            findings.extend(check_file(p, root))
    return findings
