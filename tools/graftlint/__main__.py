"""CLI: ``python -m tools.graftlint [paths...] [options]``.

Default (no paths): the full suite over the repo — AST pass on every
.py file (fixtures excluded), then the abstract-eval audit over the
declared config matrix, then the config-contract checker, then the
capability-lattice plan audit (every lattice cell must PLAN or
REFUSE exactly as ``models/plan.py`` says).  Exit 0 = clean; exit 1 =
findings, each printed as ``path:line: graftlint[rule] message``
(AST) or a named audit/contract/planaudit problem.

With explicit paths, only the AST pass runs, on those paths (fixtures
included — that is how the seeded-violation corpus self-tests).

Options: ``--ast-only`` (skip the jax-importing passes — the fast
preflight subset), ``--no-audit``, ``--no-contracts``,
``--no-planaudit``, ``--plan-fast`` (planaudit's seconds-scale
lattice subset), ``--emit-matrix`` (print the planner's capability
matrix as plan-matrix-v1 JSON on stdout and exit — the PLAN_r19.json
/ tools/planstat.py artifact), ``--emit-matrix-md`` (same, rendered
as the README capability table), ``--list-rules``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .astpass import RULES, run_paths


def _force_cpu_jax() -> None:
    # running as `python -m tools.graftlint` implies the repo root
    # is already importable, so go_libp2p_pubsub_tpu resolves too.
    # Force the CPU backend (as tools/validate_curves.py does): the
    # trace/lower passes must run even when the TPU relay is down —
    # a static preflight must never be a second TPU client.  The
    # round-14 sharded audit cases want >= 2 CPU devices (they
    # degrade to a 1-shard mesh otherwise), so request a virtual
    # host mesh BEFORE jax initializes its backends.
    import os
    if "jax" not in sys.modules and \
            "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs for the AST pass (default: repo "
                         "root; explicit paths skip the jaxpr passes)")
    ap.add_argument("--ast-only", action="store_true",
                    help="AST pass only (no jax import)")
    ap.add_argument("--no-audit", action="store_true")
    ap.add_argument("--no-contracts", action="store_true")
    ap.add_argument("--no-planaudit", action="store_true")
    ap.add_argument("--plan-fast", action="store_true",
                    help="planaudit: fast lattice subset only")
    ap.add_argument("--emit-matrix", action="store_true",
                    help="print the capability matrix as JSON and "
                         "exit (no lint passes)")
    ap.add_argument("--emit-matrix-md", action="store_true",
                    help="print the capability matrix as the README "
                         "markdown table and exit")
    ap.add_argument("--list-rules", action="store_true")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for name, (scopes, desc) in RULES.items():
            where = ", ".join(scopes) if scopes else "any"
            print(f"{name:18s} [{where}] {desc}")
        return 0

    if ns.emit_matrix or ns.emit_matrix_md:
        _force_cpu_jax()
        import json

        from .planaudit import capability_matrix, matrix_markdown
        matrix = capability_matrix()
        if ns.emit_matrix_md:
            print(matrix_markdown(matrix))
        else:
            print(json.dumps(matrix, indent=2))
        bad = [r for r in matrix["cells"]
               if r["verdict"] not in ("PLAN", "REFUSE")]
        if bad:
            print(f"graftlint: {len(bad)} lattice cell(s) failed to "
                  f"classify: {[r['id'] for r in bad]}",
                  file=sys.stderr)
            return 1
        return 0

    # the repo root is the directory that contains this package's
    # parent (tools/) — robust to being run from anywhere
    root = Path(__file__).resolve().parents[2]
    explicit = bool(ns.paths)
    paths = ns.paths or [root]
    findings = run_paths(paths, root=root, include_fixtures=explicit)
    for f in findings:
        print(f)
    n_problems = len(findings)

    if not explicit and not ns.ast_only:
        _force_cpu_jax()
        if not ns.no_audit:
            from .jaxpr_audit import run_audit
            print("graftlint: abstract-eval audit over the declared "
                  "config matrix ...", file=sys.stderr)
            audit = run_audit(log=lambda s: print(s, file=sys.stderr))
            for p in audit:
                print(p)
            n_problems += len(audit)
        if not ns.no_contracts:
            from .contracts import check_contracts
            print("graftlint: config-contract checks ...",
                  file=sys.stderr)
            contracts = check_contracts(
                log=lambda s: print(s, file=sys.stderr))
            for p in contracts:
                print(p)
            n_problems += len(contracts)
        if not ns.no_planaudit:
            from .planaudit import run_planaudit
            subset = "fast lattice subset" if ns.plan_fast else \
                "full feature lattice"
            print(f"graftlint: capability plan audit ({subset}) ...",
                  file=sys.stderr)
            plans = run_planaudit(
                fast_only=ns.plan_fast,
                log=lambda s: print(s, file=sys.stderr))
            for p in plans:
                print(p)
            n_problems += len(plans)

    if n_problems:
        print(f"graftlint: {n_problems} finding(s)", file=sys.stderr)
        return 1
    print("graftlint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
